"""CPU (host) evaluation of expression trees over numpy/pyarrow data.

This plays the role CPU Spark plays for the reference plugin: the fallback
executor for anything the planner keeps off the device, and the independent
oracle the test suite compares device results against.  Implemented with
numpy object-level semantics (NOT by re-running the jax code on CPU), so a
bug in a device kernel cannot hide in a shared implementation.

Columns are (values: np.ndarray, valid: np.ndarray[bool]); strings use
object arrays holding str|None.
"""
from __future__ import annotations

import datetime
import math
from typing import Tuple

import numpy as np

from ..types import (BooleanType, DataType, DateType, DoubleType, FloatType,
                     IntegerType, LongType, StringType, TimestampType)
from . import expressions as E
from . import math as M
from . import strings as S
from . import datetime_exprs as D
from .aggregates import AggregateExpression
from .cast import Cast, _INT_RANGE

CpuCol = Tuple[np.ndarray, np.ndarray]  # (values, valid)


def table_to_cpu_cols(table):
    """pyarrow Table -> list of CpuCol following our device type mapping."""
    import pyarrow as pa
    import pyarrow.compute as pc
    from ..types import from_arrow
    cols = []
    for col in table.columns:
        arr = col.combine_chunks() if col.num_chunks != 1 else col.chunk(0)
        if pa.types.is_dictionary(arr.type):
            arr = arr.dictionary_decode()
        if pa.types.is_decimal(arr.type):
            arr = pc.cast(arr, pa.float64())
        dt = from_arrow(arr.type)
        valid = np.asarray(arr.is_valid()) if arr.null_count \
            else np.ones(len(arr), dtype=bool)
        if dt.is_string:
            vals = np.array(arr.to_pylist(), dtype=object)
        elif pa.types.is_date32(arr.type):
            vals = np.asarray(arr.view(pa.int32()).fill_null(0)
                              .to_numpy(zero_copy_only=False))
        elif pa.types.is_timestamp(arr.type):
            vals = np.asarray(pc.cast(arr, pa.timestamp("us", tz="UTC"))
                              .view(pa.int64()).fill_null(0)
                              .to_numpy(zero_copy_only=False))
        else:
            fill = False if pa.types.is_boolean(arr.type) else 0
            vals = np.asarray(arr.fill_null(fill)
                              .to_numpy(zero_copy_only=False)
                              .astype(dt.np_dtype))
        vals = _zero_invalid(vals, valid, dt)
        cols.append((vals, valid))
    return cols


def cpu_cols_to_table(cols, schema):
    import pyarrow as pa
    from ..types import to_arrow
    arrays = []
    for (vals, valid), f in zip(cols, schema):
        pylist = [None if not v else _to_py(x, f.dtype)
                  for x, v in zip(vals.tolist(), valid.tolist())]
        arrays.append(pa.array(pylist, type=to_arrow(f.dtype)))
    return pa.table(arrays, names=schema.names)


def _to_py(x, dt: DataType):
    if dt is DateType:
        return datetime.date(1970, 1, 1) + datetime.timedelta(days=int(x))
    if dt is TimestampType:
        return datetime.datetime(1970, 1, 1,
                                 tzinfo=datetime.timezone.utc) + \
            datetime.timedelta(microseconds=int(x))
    return x


def _zero_invalid(vals, valid, dt: DataType):
    if dt.is_string:
        out = vals.copy()
        out[~valid] = None
        return out
    out = vals.copy()
    out[~valid] = 0
    return out


def _const(n, value, dtype: DataType) -> CpuCol:
    if value is None:
        if dtype.is_string:
            return np.full(n, None, dtype=object), np.zeros(n, bool)
        return (np.zeros(n, dtype=dtype.np_dtype if dtype.np_dtype is not None
                         else np.int64), np.zeros(n, bool))
    if dtype.is_string:
        return np.full(n, value, dtype=object), np.ones(n, bool)
    return (np.full(n, value, dtype=dtype.np_dtype), np.ones(n, bool))


def cpu_eval(expr: E.Expression, cols, n: int) -> CpuCol:
    """Evaluate `expr` against input columns (list of CpuCol)."""

    def rec(e):
        return cpu_eval(e, cols, n)

    if isinstance(expr, E.BoundReference):
        return cols[expr.index]
    if isinstance(expr, E.Literal):
        return _const(n, expr.value, expr.dtype)
    if isinstance(expr, E.Alias):
        return rec(expr.child)
    if isinstance(expr, Cast):
        return _cpu_cast(rec(expr.child), expr.child.dtype, expr.to, n)
    if isinstance(expr, AggregateExpression):
        raise RuntimeError("aggregates evaluated by agg exec")

    t = type(expr).__name__

    # ---- arithmetic / comparison / logic ------------------------------
    if t in _BINOPS:
        lv, lm = rec(expr.left)
        rv, rm = rec(expr.right)
        return _BINOPS[t](expr, lv, lm, rv, rm)
    if t == "UnaryMinus":
        v, m = rec(expr.child)
        return -v, m
    if t == "UnaryPositive":
        return rec(expr.child)
    if t == "Abs":
        v, m = rec(expr.child)
        return np.abs(v), m
    if t == "BitwiseNot":
        v, m = rec(expr.child)
        return ~v, m
    if t == "Not":
        v, m = rec(expr.child)
        return ~v.astype(bool), m
    if t == "IsNull":
        v, m = rec(expr.child)
        return ~m, np.ones(n, bool)
    if t == "IsNotNull":
        v, m = rec(expr.child)
        return m.copy(), np.ones(n, bool)
    if t == "IsNaN":
        v, m = rec(expr.child)
        vals = np.zeros(n, bool)
        vals[m] = np.isnan(v[m].astype(np.float64))
        return vals, np.ones(n, bool)
    if t == "Coalesce":
        dt = expr.dtype
        out_v, out_m = rec(expr.children[0])
        if not dt.is_string:
            out_v = out_v.astype(dt.np_dtype)
        out_v = out_v.copy()
        out_m = out_m.copy()
        for ch in expr.children[1:]:
            v, m = rec(ch)
            if not dt.is_string:
                v = v.astype(dt.np_dtype)
            fill = ~out_m & m
            out_v[fill] = v[fill]
            out_m |= m
        return out_v, out_m
    if t == "NaNvl":
        lv, lm = rec(expr.left)
        rv, rm = rec(expr.right)
        use_r = np.isnan(lv.astype(np.float64))
        v = np.where(use_r, rv.astype(lv.dtype), lv)
        m = np.where(use_r, rm, lm)
        return v, m
    if t == "If":
        pv, pm = rec(expr.pred)
        tv, tm = rec(expr.then)
        ov, om = rec(expr.other)
        cond = pm & pv.astype(bool)
        if expr.dtype.is_string:
            v = np.where(cond, tv, ov)
        else:
            tt = expr.dtype.np_dtype
            v = np.where(cond, tv.astype(tt), ov.astype(tt))
        return v, np.where(cond, tm, om)
    if t == "CaseWhen":
        e = expr.else_value if expr.else_value is not None \
            else E.Literal(None, expr.dtype)
        out = e
        for p, val in reversed(expr.branches):
            out = E.If(p, val, out)
        return rec(out)
    if t in ("In", "InSet"):
        v, m = rec(expr.value)
        items = [i for i in expr.items if i is not None]
        has_null = len(items) != len(expr.items)
        hit = np.zeros(n, bool)
        for it in items:
            if expr.value.dtype.is_string:
                hit |= np.array([x == it for x in v], dtype=bool)
            elif expr.value.dtype.is_floating:
                hit |= v == it
            else:
                hit |= v == it
        valid = m & (hit | ~has_null) if has_null else m
        return hit, valid

    # ---- math ---------------------------------------------------------
    if t in _MATH_UNARY:
        v, m = rec(expr.child)
        x = v.astype(np.float64)
        with np.errstate(all="ignore"):
            if t in ("Log", "Log2", "Log10"):
                ok = x > 0
                fn = {"Log": np.log, "Log2": np.log2, "Log10": np.log10}[t]
                return fn(np.where(ok, x, 1.0)), m & ok
            if t == "Log1p":
                ok = x > -1
                return np.log1p(np.where(ok, x, 0.0)), m & ok
            return _MATH_UNARY[t](x), m
    if t == "Floor":
        v, m = rec(expr.child)
        if expr.child.dtype.is_floating:
            return np.floor(v).astype(np.int64), m
        return v, m
    if t == "Ceil":
        v, m = rec(expr.child)
        if expr.child.dtype.is_floating:
            return np.ceil(v).astype(np.int64), m
        return v, m
    if t == "Pow":
        lv, lm = rec(expr.left)
        rv, rm = rec(expr.right)
        with np.errstate(all="ignore"):
            return np.power(lv.astype(np.float64), rv.astype(np.float64)), \
                lm & rm
    if t == "Atan2":
        lv, lm = rec(expr.left)
        rv, rm = rec(expr.right)
        return np.arctan2(lv.astype(np.float64), rv.astype(np.float64)), \
            lm & rm

    if t in ("Round", "BRound"):
        v, m = rec(expr.child)
        sv, sm = rec(expr.scale)
        dt = expr.child.dtype
        if dt.is_integral:
            # python-int arithmetic per row: immune to 10**(-s) overflowing
            # the column dtype (Spark rounds away all digits -> 0)
            out = np.zeros(n, dtype=v.dtype)
            for i in range(n):
                s = int(sv[i])
                x = int(v[i])
                if s >= 0:
                    out[i] = x
                    continue
                p = 10 ** (-s)
                q, rem = divmod(abs(x), p)
                if t == "BRound":
                    up = rem * 2 > p or (rem * 2 == p and q % 2 != 0)
                else:
                    up = rem * 2 >= p
                r = (q + (1 if up else 0)) * p * (1 if x >= 0 else -1)
                info = np.iinfo(v.dtype)
                span = int(info.max) - int(info.min) + 1
                # Java intValue()/longValue() wrap on overflow, and so does
                # the device's fixed-width arithmetic
                out[i] = (r - info.min) % span + info.min
            return out, m & sm
        x = v.astype(np.float64)
        p = np.power(10.0, sv.astype(np.float64))
        scaled = x * p
        with np.errstate(all="ignore"):
            if t == "BRound":
                r = np.rint(scaled)
            else:
                r = np.trunc(scaled + np.where(scaled >= 0, 0.5, -0.5))
            out = np.where(np.isfinite(x), r / p, x)
        return out.astype(dt.np_dtype), m & sm
    if t == "Cot":
        v, m = rec(expr.child)
        with np.errstate(all="ignore"):
            return 1.0 / np.tan(v.astype(np.float64)), m
    if t == "Hypot":
        lv, lm = rec(expr.left)
        rv, rm = rec(expr.right)
        return np.hypot(lv.astype(np.float64), rv.astype(np.float64)), \
            lm & rm
    if t == "Logarithm":
        bv, bm = rec(expr.left)
        xv, xm = rec(expr.right)
        b = bv.astype(np.float64)
        x = xv.astype(np.float64)
        ok = (x > 0) & (b > 0)
        with np.errstate(all="ignore"):
            out = np.log(np.where(x > 0, x, 1.0)) \
                / np.log(np.where(b > 0, b, 2.0))
        return out, bm & xm & ok
    if t in ("Least", "Greatest"):
        dt = expr.dtype
        parts = [rec(c) for c in expr.children]
        acc_v = parts[0][0].astype(dt.np_dtype)
        acc_m = parts[0][1].copy()
        for pv, pm in parts[1:]:
            v = pv.astype(dt.np_dtype)
            if dt.is_floating:
                vk = np.where(np.isnan(v), np.inf, v)
                ak = np.where(np.isnan(acc_v), np.inf, acc_v)
                vn, an = np.isnan(v), np.isnan(acc_v)
                if t == "Least":
                    better = (vk < ak) | (~vn & an)
                else:
                    better = (vk > ak) | (vn & ~an)
            else:
                better = (v < acc_v) if t == "Least" else (v > acc_v)
            take = pm & (~acc_m | better)
            acc_v = np.where(take, v, acc_v)
            acc_m = acc_m | pm
        return acc_v, acc_m
    if t == "Murmur3Hash":
        h = np.full(n, expr.seed, dtype=np.int32)
        for ch in expr.children:
            v, m = rec(ch)
            h = _np_spark_hash(v, m, ch.dtype, h)
        return h, np.ones(n, bool)

    # ---- strings ------------------------------------------------------
    if isinstance(expr, (S._StringUnary, S.Substring, S.Concat,
                         S.StartsWith, S.EndsWith, S.Contains, S.Like,
                         S.StringLocate, S.StringReplace, S._PadBase,
                         S.StringRepeat, S.SubstringIndex,
                         S.RegExpReplace)):
        return _cpu_string(expr, rec, n)

    # ---- datetime -----------------------------------------------------
    if isinstance(expr, (D._DatePart, D._DateArith, D.UnixTimestamp,
                         D.FromUnixTime, D.TimeAdd, D.TimeSub, D.AddMonths,
                         D.MonthsBetween, D.TruncDate, D.NextDay)):
        return _cpu_datetime(expr, rec, n)

    if t == "AtLeastNNonNulls":
        count = np.zeros(n, dtype=np.int32)
        for ch in expr.children:
            v, m = rec(ch)
            ok = m.copy()
            if ch.dtype.is_floating:
                with np.errstate(all="ignore"):
                    ok &= ~np.isnan(v.astype(np.float64))
            count += ok.astype(np.int32)
        return count >= expr.n, np.ones(n, bool)
    if t == "NormalizeNaNAndZero":
        v, m = rec(expr.child)
        if expr.child.dtype.is_floating:
            v = np.where(v == 0, np.zeros((), v.dtype), v)
        return v, m
    if t == "KnownFloatingPointNormalized":
        return rec(expr.child)
    if t == "InputFileName":
        from .expressions import current_input_file
        out = np.empty(n, dtype=object)
        out[:] = current_input_file()[0]
        return out, np.ones(n, bool)
    if t in ("InputFileBlockStart", "InputFileBlockLength"):
        from .expressions import current_input_file
        slot = 1 if t == "InputFileBlockStart" else 2
        return (np.full(n, current_input_file()[slot], dtype=np.int64),
                np.ones(n, bool))
    if t == "SparkPartitionID":
        return np.full(n, expr.partition_id, dtype=np.int32), np.ones(n, bool)
    if t == "MonotonicallyIncreasingID":
        base = expr.partition_id << 33
        return base + np.arange(n, dtype=np.int64), np.ones(n, bool)

    raise NotImplementedError(f"cpu_eval: {t}")


def _jvm_mod(l, r):
    return l - r * (np.sign(l) * np.sign(r) * (np.abs(l) // np.abs(r)))


def _promote_np(expr, lv, rv):
    from ..types import promote
    t = promote(expr.left.dtype, expr.right.dtype)
    return lv.astype(t.np_dtype), rv.astype(t.np_dtype)


def _arith(fn):
    def run(expr, lv, lm, rv, rm):
        lv, rv = _promote_np(expr, lv, rv)
        with np.errstate(all="ignore"):
            return fn(lv, rv), lm & rm
    return run


def _cpu_divide(expr, lv, lm, rv, rm):
    l = lv.astype(np.float64)
    r = rv.astype(np.float64)
    nz = r != 0.0
    with np.errstate(all="ignore"):
        return np.where(nz, l, 1.0) / np.where(nz, r, 1.0), lm & rm & nz


def _cpu_intdiv(expr, lv, lm, rv, rm):
    l = lv.astype(np.int64)
    r = rv.astype(np.int64)
    nz = r != 0
    rs = np.where(nz, r, 1)
    q = np.sign(l) * np.sign(rs) * (np.abs(l) // np.abs(rs))
    return q, lm & rm & nz


def _cpu_rem(expr, lv, lm, rv, rm):
    lv, rv = _promote_np(expr, lv, rv)
    if np.issubdtype(lv.dtype, np.floating):
        nz = rv != 0.0
        return np.fmod(lv, np.where(nz, rv, 1.0)), lm & rm & nz
    nz = rv != 0
    return _jvm_mod(lv, np.where(nz, rv, 1)), lm & rm & nz


def _cpu_pmod(expr, lv, lm, rv, rm):
    v, m = _cpu_rem(expr, lv, lm, rv, rm)
    lv2, rv2 = _promote_np(expr, lv, rv)
    safe = np.where(rv2 != 0, rv2, 1)
    if np.issubdtype(v.dtype, np.floating):
        v = np.where(v < 0, np.fmod(v + safe, safe), v)
    else:
        v = np.where(v < 0, _jvm_mod(v + safe, safe), v)
    return v, m


def _cmp_vals(expr, lv, rv):
    if expr.left.dtype.is_string:
        return lv, rv
    if expr.left.dtype.is_numeric and expr.right.dtype.is_numeric:
        return _promote_np(expr, lv, rv)
    return lv, rv


def _cpu_eq(lv, rv, str_side):
    if str_side:
        return np.array([a == b for a, b in zip(lv, rv)], dtype=bool)
    if np.issubdtype(lv.dtype, np.floating):
        return (lv == rv) | (np.isnan(lv) & np.isnan(rv))
    return lv == rv


def _cpu_lt(lv, rv, str_side):
    if str_side:
        return np.array([(a is not None and b is not None and a < b)
                         for a, b in zip(lv, rv)], dtype=bool)
    if np.issubdtype(lv.dtype, np.floating):
        return np.where(np.isnan(lv), False, np.where(np.isnan(rv), True,
                                                      lv < rv))
    return lv < rv


def _comparison(kind):
    def run(expr, lv, lm, rv, rm):
        s = expr.left.dtype.is_string
        lv2, rv2 = _cmp_vals(expr, lv, rv)
        if kind == "eq":
            out = _cpu_eq(lv2, rv2, s)
        elif kind == "lt":
            out = _cpu_lt(lv2, rv2, s)
        elif kind == "gt":
            out = _cpu_lt(rv2, lv2, s)
        elif kind == "le":
            out = ~_cpu_lt(rv2, lv2, s)
        else:
            out = ~_cpu_lt(lv2, rv2, s)
        return out, lm & rm
    return run


def _cpu_eqns(expr, lv, lm, rv, rm):
    s = expr.left.dtype.is_string
    lv2, rv2 = _cmp_vals(expr, lv, rv)
    eq = _cpu_eq(lv2, rv2, s)
    return (lm & rm & eq) | (~lm & ~rm), np.ones(len(lm), bool)


def _cpu_and(expr, lv, lm, rv, rm):
    lt = lm & lv.astype(bool)
    rt = rm & rv.astype(bool)
    fl = lm & ~lv.astype(bool)
    fr = rm & ~rv.astype(bool)
    return lt & rt, (lm & rm) | fl | fr


def _cpu_or(expr, lv, lm, rv, rm):
    lt = lm & lv.astype(bool)
    rt = rm & rv.astype(bool)
    return lt | rt, (lm & rm) | lt | rt


_BINOPS = {
    "Add": _arith(lambda a, b: a + b),
    "Subtract": _arith(lambda a, b: a - b),
    "Multiply": _arith(lambda a, b: a * b),
    "Divide": _cpu_divide,
    "IntegralDivide": _cpu_intdiv,
    "Remainder": _cpu_rem,
    "Pmod": _cpu_pmod,
    "EqualTo": _comparison("eq"),
    "LessThan": _comparison("lt"),
    "GreaterThan": _comparison("gt"),
    "LessThanOrEqual": _comparison("le"),
    "GreaterThanOrEqual": _comparison("ge"),
    "EqualNullSafe": _cpu_eqns,
    "And": _cpu_and,
    "Or": _cpu_or,
    "BitwiseAnd": _arith(lambda a, b: a & b),
    "BitwiseOr": _arith(lambda a, b: a | b),
    "BitwiseXor": _arith(lambda a, b: a ^ b),
    "ShiftLeft": lambda e, lv, lm, rv, rm: (
        lv << (rv.astype(lv.dtype) % (lv.dtype.itemsize * 8)), lm & rm),
    "ShiftRight": lambda e, lv, lm, rv, rm: (
        lv >> (rv.astype(lv.dtype) % (lv.dtype.itemsize * 8)), lm & rm),
    "ShiftRightUnsigned": lambda e, lv, lm, rv, rm: (
        _srun(lv, rv), lm & rm),
}


def _srun(lv, rv):
    bits = lv.dtype.itemsize * 8
    u = lv.astype(np.uint64 if bits == 64 else np.uint32)
    return (u >> (rv % bits).astype(u.dtype)).astype(lv.dtype)


_MATH_UNARY = {
    "Sqrt": np.sqrt, "Cbrt": np.cbrt, "Exp": np.exp, "Expm1": np.expm1,
    "Sin": np.sin, "Cos": np.cos, "Tan": np.tan, "Asin": np.arcsin,
    "Acos": np.arccos, "Atan": np.arctan, "Sinh": np.sinh, "Cosh": np.cosh,
    "Tanh": np.tanh, "Asinh": np.arcsinh, "Acosh": np.arccosh,
    "Atanh": np.arctanh, "ToDegrees": np.degrees, "ToRadians": np.radians,
    "Signum": np.sign, "Rint": np.round,
    "Log": np.log, "Log2": np.log2, "Log10": np.log10, "Log1p": np.log1p,
}


# ---- cast -----------------------------------------------------------------

def _cpu_cast(col: CpuCol, src: DataType, dst: DataType, n: int) -> CpuCol:
    v, m = col
    if src is dst:
        return col
    if dst.is_string:
        out = np.empty(n, dtype=object)
        for i in range(n):
            if not m[i]:
                out[i] = None
            elif src is BooleanType:
                out[i] = "true" if v[i] else "false"
            elif src is DateType:
                out[i] = str(datetime.date(1970, 1, 1) +
                             datetime.timedelta(days=int(v[i])))
            elif src is TimestampType:
                dt = (datetime.datetime(1970, 1, 1) +
                      datetime.timedelta(microseconds=int(v[i])))
                out[i] = dt.strftime("%Y-%m-%d %H:%M:%S")
            else:
                out[i] = str(v[i])
        return out, m.copy()
    if src.is_string:
        vals = np.zeros(n, dtype=dst.np_dtype if dst.np_dtype is not None
                        else np.int64)
        valid = np.zeros(n, bool)
        for i in range(n):
            if not m[i] or v[i] is None:
                continue
            s = v[i].strip()
            try:
                if dst is BooleanType:
                    sl = s.lower()
                    if sl in ("true", "t", "yes", "y", "1"):
                        vals[i], valid[i] = True, True
                    elif sl in ("false", "f", "no", "n", "0"):
                        vals[i], valid[i] = False, True
                elif dst.is_integral:
                    x = int(s)
                    lo, hi = _INT_RANGE[dst.name]
                    if lo <= x <= hi:
                        vals[i], valid[i] = x, True
                elif dst.is_floating:
                    vals[i], valid[i] = float(s), True
                elif dst is DateType:
                    d = datetime.date.fromisoformat(s)
                    vals[i] = (d - datetime.date(1970, 1, 1)).days
                    valid[i] = True
                elif dst is TimestampType:
                    if " " in s:
                        dt = datetime.datetime.strptime(s,
                                                        "%Y-%m-%d %H:%M:%S")
                    else:
                        dt = datetime.datetime.combine(
                            datetime.date.fromisoformat(s),
                            datetime.time())
                    vals[i] = int((dt - datetime.datetime(1970, 1, 1))
                                  .total_seconds() * 1_000_000)
                    valid[i] = True
            except (ValueError, OverflowError):
                pass  # tpulint: disable=TPU006 cast fallthrough: unparseable strings yield null by Spark semantics
        return vals, valid
    if dst is BooleanType:
        return v != 0, m
    if src is BooleanType:
        return v.astype(dst.np_dtype), m
    if src is DateType and dst is TimestampType:
        return v.astype(np.int64) * 86_400_000_000, m
    if src is TimestampType and dst is DateType:
        return (v.astype(np.int64) // 86_400_000_000).astype(np.int32), m
    if src is TimestampType and dst.is_numeric:
        if dst.is_floating:
            return v.astype(np.float64) / 1e6, m
        return (v // 1_000_000).astype(dst.np_dtype), m
    if dst is TimestampType:
        if src.is_floating:
            return (v.astype(np.float64) * 1e6).astype(np.int64), m
        return v.astype(np.int64) * 1_000_000, m
    if dst.is_floating:
        return v.astype(dst.np_dtype), m
    if src.is_floating:
        lo, hi = _INT_RANGE[dst.name]
        x = np.nan_to_num(v.astype(np.float64), nan=0.0)
        x = np.trunc(x)
        out = np.clip(x, float(lo), float(hi))
        res = np.zeros(n, dtype=np.int64)
        inb = (out > lo) & (out < hi)
        res[inb] = out[inb].astype(np.int64)
        res[out >= hi] = hi
        res[out <= lo] = lo
        return res.astype(dst.np_dtype), m
    return v.astype(dst.np_dtype), m


# ---- strings --------------------------------------------------------------

def _str_lit(e):
    return S._literal_bytes(e).decode("utf-8")


def _cpu_string(expr, rec, n: int) -> CpuCol:
    t = type(expr).__name__
    if t in ("Upper", "Lower", "StringTrim", "StringTrimLeft",
             "StringTrimRight", "Length"):
        v, m = rec(expr.child)
        if t == "Length":
            out = np.array([len(x) if x is not None else 0 for x in v],
                           dtype=np.int32)
            return out, m
        fn = {"Upper": lambda s: s.upper(), "Lower": lambda s: s.lower(),
              "StringTrim": lambda s: s.strip(),
              "StringTrimLeft": lambda s: s.lstrip(),
              "StringTrimRight": lambda s: s.rstrip()}[t]
        out = np.array([fn(x) if x is not None else None for x in v],
                       dtype=object)
        return out, m
    if t == "Substring":
        v, m = rec(expr.child)
        p, pm = rec(expr.pos)
        ln, lm = rec(expr.length)
        out = np.empty(n, dtype=object)
        for i in range(n):
            s = v[i]
            if s is None:
                out[i] = None
                continue
            pos = int(p[i])
            length = max(int(ln[i]), 0)
            start = pos - 1 if pos > 0 else (len(s) + pos if pos < 0 else 0)
            start = max(start, 0)
            out[i] = s[start:start + length]
        return out, m & pm & lm
    if t == "Concat":
        parts = [rec(c) for c in expr.children]
        out = np.empty(n, dtype=object)
        valid = np.ones(n, bool)
        for pv, pm in parts:
            valid &= pm
        for i in range(n):
            if valid[i]:
                out[i] = "".join(pv[i] for pv, _ in parts)
        return out, valid
    if t in ("StartsWith", "EndsWith", "Contains"):
        v, m = rec(expr.child)
        pat = _str_lit(expr.pattern)
        fn = {"StartsWith": str.startswith, "EndsWith": str.endswith,
              "Contains": str.__contains__}[t]
        out = np.array([fn(x, pat) if x is not None else False for x in v],
                       dtype=bool)
        return out, m
    if t == "Like":
        import re
        v, m = rec(expr.child)
        pat = _str_lit(expr.pattern)
        rx = _like_to_regex(pat, expr.escape)
        out = np.array([bool(rx.fullmatch(x)) if x is not None else False
                        for x in v], dtype=bool)
        return out, m
    if t == "StringLocate":
        v, m = rec(expr.child)
        sub = _str_lit(expr.substr)
        st, sm = rec(expr.start)
        out = np.zeros(n, dtype=np.int32)
        for i in range(n):
            if v[i] is None:
                continue
            start = max(int(st[i]) - 1, 0) if int(st[i]) > 0 else None
            if int(st[i]) <= 0:
                out[i] = 0
                continue
            idx = v[i].find(sub, start)
            out[i] = idx + 1 if idx >= 0 else 0
        return out, m & sm
    if t == "StringReplace":
        v, m = rec(expr.child)
        search = _str_lit(expr.search)
        repl = _str_lit(expr.replace)
        out = np.array([x.replace(search, repl) if x is not None else None
                        for x in v], dtype=object)
        return out, m
    if t == "InitCap":
        v, m = rec(expr.child)

        def icap(s):
            out = []
            prev_space = True
            for ch in s:
                out.append(ch.upper() if prev_space else ch.lower())
                prev_space = ch == " "
            return "".join(out)
        out = np.array([icap(x) if x is not None else None for x in v],
                       dtype=object)
        return out, m
    if t == "Reverse":
        v, m = rec(expr.child)
        out = np.array([x[::-1] if x is not None else None for x in v],
                       dtype=object)
        return out, m
    if t == "Ascii":
        v, m = rec(expr.child)
        out = np.array([(ord(x[0]) if x else 0) if x is not None else 0
                        for x in v], dtype=np.int32)
        return out, m
    if t in ("StringLPad", "StringRPad"):
        # args evaluated per row: the CPU executor is the fallback for the
        # non-literal shapes the device tags away, so it cannot require
        # literals itself
        v, m = rec(expr.child)
        wv, wm = rec(expr.length)
        pv, pm = rec(expr.pad)

        def dopad(s, want, pad):
            want = max(int(want), 0)
            if len(s) >= want:
                return s[:want]
            if not pad:
                return s
            fill = (pad * (want // len(pad) + 1))[:want - len(s)]
            return fill + s if t == "StringLPad" else s + fill
        out = np.array(
            [dopad(x, w, p) if x is not None and p is not None else None
             for x, w, p in zip(v, wv, pv)], dtype=object)
        return out, m & wm & pm
    if t == "StringRepeat":
        v, m = rec(expr.child)
        kv, km = rec(expr.times)
        out = np.array(
            [x * max(int(k), 0) if x is not None else None
             for x, k in zip(v, kv)], dtype=object)
        return out, m & km
    if t == "SubstringIndex":
        v, m = rec(expr.child)
        dv, dm = rec(expr.delim)
        cv, cm = rec(expr.count)

        def ssi(s, delim, count):
            if count == 0 or not delim:
                return ""
            if count > 0:
                # count'th non-overlapping occurrence from the left
                idx, seen = 0, 0
                while seen < count:
                    found = s.find(delim, idx)
                    if found < 0:
                        return s
                    seen += 1
                    if seen == count:
                        return s[:found]
                    idx = found + len(delim)
                return s
            # count < 0: |count|'th occurrence from the end of the
            # left-to-right non-overlapping scan (device parity)
            starts = []
            idx = 0
            while True:
                found = s.find(delim, idx)
                if found < 0:
                    break
                starts.append(found)
                idx = found + len(delim)
            if len(starts) < -count:
                return s
            return s[starts[len(starts) + count] + len(delim):]
        out = np.array(
            [ssi(x, d, int(c))
             if x is not None and d is not None else None
             for x, d, c in zip(v, dv, cv)], dtype=object)
        return out, m & dm & cm
    if t == "RegExpReplace":
        import re
        v, m = rec(expr.child)
        pv, pm = rec(expr.pattern)
        rv, rm = rec(expr.replacement)
        cache = {}

        def sub(s, pat, repl):
            rx = cache.get(pat)
            if rx is None:
                rx = cache[pat] = re.compile(pat)
            return rx.sub(repl, s)
        out = np.array(
            [sub(x, p, r)
             if x is not None and p is not None and r is not None else None
             for x, p, r in zip(v, pv, rv)], dtype=object)
        return out, m & pm & rm
    raise NotImplementedError(f"cpu string {t}")


def _like_to_regex(pat: str, escape: str):
    import re
    out = []
    i = 0
    while i < len(pat):
        ch = pat[i]
        if escape and ch == escape and i + 1 < len(pat):
            out.append(re.escape(pat[i + 1]))
            i += 2
            continue
        if ch == "%":
            out.append(".*")
        elif ch == "_":
            out.append(".")
        else:
            out.append(re.escape(ch))
        i += 1
    return re.compile("".join(out), re.DOTALL)


# ---- datetime -------------------------------------------------------------

def _cpu_datetime(expr, rec, n: int) -> CpuCol:
    t = type(expr).__name__
    if isinstance(expr, D._DatePart):
        v, m = rec(expr.child)
        if expr.child.dtype is TimestampType:
            days = v.astype(np.int64) // 86_400_000_000
            micros = v
        else:
            days = v.astype(np.int64)
            micros = days * 86_400_000_000
        out = np.zeros(n, dtype=np.int32)
        for i in range(n):
            d = datetime.date(1970, 1, 1) + datetime.timedelta(
                days=int(days[i]))
            if t == "Year":
                out[i] = d.year
            elif t == "Month":
                out[i] = d.month
            elif t == "DayOfMonth":
                out[i] = d.day
            elif t == "DayOfWeek":
                out[i] = d.isoweekday() % 7 + 1
            elif t == "WeekDay":
                out[i] = d.weekday()
            elif t == "DayOfYear":
                out[i] = d.timetuple().tm_yday
            elif t == "Quarter":
                out[i] = (d.month - 1) // 3 + 1
            elif t == "LastDay":
                nxt = (d.replace(day=28) + datetime.timedelta(days=4))
                last = nxt - datetime.timedelta(days=nxt.day)
                out[i] = (last - datetime.date(1970, 1, 1)).days
            elif t in ("Hour", "Minute", "Second"):
                tod = int(micros[i]) % 86_400_000_000
                sec = tod // 1_000_000
                out[i] = {"Hour": sec // 3600, "Minute": (sec % 3600) // 60,
                          "Second": sec % 60}[t]
        return out, m
    if t in ("DateAdd", "DateSub"):
        lv, lm = rec(expr.left)
        rv, rm = rec(expr.right)
        sign = 1 if t == "DateAdd" else -1
        return (lv.astype(np.int32) + sign * rv.astype(np.int32)), lm & rm
    if t == "DateDiff":
        lv, lm = rec(expr.left)
        rv, rm = rec(expr.right)
        l = lv.astype(np.int64) if expr.left.dtype is DateType \
            else lv // 86_400_000_000
        r = rv.astype(np.int64) if expr.right.dtype is DateType \
            else rv // 86_400_000_000
        return (l - r).astype(np.int32), lm & rm
    if isinstance(expr, D.UnixTimestamp):
        v, m = rec(expr.child)
        src = expr.child.dtype
        if src is TimestampType:
            return v // 1_000_000, m
        if src is DateType:
            return v.astype(np.int64) * 86_400, m
        # string
        col = _cpu_cast((v, m), StringType, TimestampType, n)
        return col[0] // 1_000_000, col[1]
    if t == "FromUnixTime":
        v, m = rec(expr.child)
        out = np.empty(n, dtype=object)
        for i in range(n):
            dt = datetime.datetime(1970, 1, 1) + datetime.timedelta(
                seconds=int(v[i]))
            out[i] = dt.strftime("%Y-%m-%d %H:%M:%S")
        return out, m
    if t in ("TimeAdd", "TimeSub"):
        lv, lm = rec(expr.child)
        rv, rm = rec(expr.interval)
        sign = 1 if t == "TimeAdd" else -1
        return lv + sign * rv.astype(np.int64), lm & rm
    if t == "AddMonths":
        lv, lm = rec(expr.left)
        rv, rm = rec(expr.right)
        out = np.zeros(n, dtype=np.int32)
        epoch = datetime.date(1970, 1, 1)
        for i in range(n):
            d = epoch + datetime.timedelta(days=int(lv[i]))
            total = d.year * 12 + (d.month - 1) + int(rv[i])
            y, mo = total // 12, total % 12 + 1
            last = _last_dom(y, mo)
            out[i] = (datetime.date(y, mo, min(d.day, last)) - epoch).days
        return out, lm & rm
    if t == "MonthsBetween":
        lv, lm = rec(expr.left)
        rv, rm = rec(expr.right)
        d1 = lv.astype(np.int64) if expr.left.dtype is DateType \
            else lv // 86_400_000_000
        d2 = rv.astype(np.int64) if expr.right.dtype is DateType \
            else rv // 86_400_000_000
        out = np.zeros(n, dtype=np.float64)
        epoch = datetime.date(1970, 1, 1)
        for i in range(n):
            a = epoch + datetime.timedelta(days=int(d1[i]))
            b = epoch + datetime.timedelta(days=int(d2[i]))
            months = (a.year - b.year) * 12 + (a.month - b.month)
            la, lb = _last_dom(a.year, a.month), _last_dom(b.year, b.month)
            if a.day == b.day or (a.day == la and b.day == lb):
                out[i] = float(months)
            else:
                out[i] = months + (a.day - b.day) / 31.0
        from .expressions import Literal as _L
        if isinstance(expr.round_off, _L) and bool(expr.round_off.value):
            out = np.round(out * 1e8) / 1e8
        return out, lm & rm
    if t == "TruncDate":
        lv, lm = rec(expr.child)
        fv, fm = rec(expr.fmt)

        def _lvl(fmt):
            if fmt is None:
                return None
            fmt = fmt.lower()
            if fmt in ("year", "yyyy", "yy"):
                return "year"
            if fmt == "quarter":
                return "quarter"
            if fmt in ("month", "mon", "mm"):
                return "month"
            return "week" if fmt == "week" else None
        out = np.zeros(n, dtype=np.int32)
        valid = lm & fm
        epoch = datetime.date(1970, 1, 1)
        for i in range(n):
            level = _lvl(fv[i])
            if level is None:
                valid[i] = False
                continue
            d = epoch + datetime.timedelta(days=int(lv[i]))
            if level == "year":
                d = d.replace(month=1, day=1)
            elif level == "quarter":
                d = d.replace(month=(d.month - 1) // 3 * 3 + 1, day=1)
            elif level == "month":
                d = d.replace(day=1)
            else:  # week -> previous/same Monday
                d = d - datetime.timedelta(days=d.weekday())
            out[i] = (d - epoch).days
        return out, valid
    if t == "NextDay":
        lv, lm = rec(expr.child)
        dv, dm = rec(expr.day)
        out = np.zeros(n, dtype=np.int32)
        valid = lm & dm
        epoch = datetime.date(1970, 1, 1)
        for i in range(n):
            target = D._DAY_NAMES.get((dv[i] or "").strip().upper())
            if target is None:
                valid[i] = False
                continue
            d = epoch + datetime.timedelta(days=int(lv[i]))
            delta = (target - d.weekday() + 7) % 7 or 7
            out[i] = (d + datetime.timedelta(days=delta) - epoch).days
        return out, valid
    raise NotImplementedError(f"cpu datetime {t}")


def _last_dom(y: int, m: int) -> int:
    import calendar
    return calendar.monthrange(y, m)[1]


# ---- murmur3 (numpy mirror of the public MurmurHash3_x86_32 spec) ---------

def _np_u32(x):
    return x.astype(np.uint32)


def _np_rotl32(x, r):
    return _np_u32((x << np.uint32(r)) | (x >> np.uint32(32 - r)))


def _np_mix_k(k):
    k = _np_u32(k * np.uint32(0xcc9e2d51))
    k = _np_rotl32(k, 15)
    return _np_u32(k * np.uint32(0x1b873593))


def _np_mix_h(h, k):
    h = _np_u32(h ^ _np_mix_k(k))
    h = _np_rotl32(h, 13)
    return _np_u32(h * np.uint32(5) + np.uint32(0xe6546b64))


def _np_fmix(h, length):
    h = _np_u32(h ^ np.uint32(length))
    h ^= h >> np.uint32(16)
    h = _np_u32(h * np.uint32(0x85ebca6b))
    h ^= h >> np.uint32(13)
    h = _np_u32(h * np.uint32(0xc2b2ae35))
    h ^= h >> np.uint32(16)
    return h


def _np_hash_int(x_u32, seed_u32):
    return _np_fmix(_np_mix_h(seed_u32, x_u32), 4)


def _np_hash_long(x_i64, seed_u32):
    u = x_i64.astype(np.uint64)
    lo = _np_u32(u & np.uint64(0xFFFFFFFF))
    hi = _np_u32(u >> np.uint64(32))
    return _np_fmix(_np_mix_h(_np_mix_h(seed_u32, lo), hi), 8)


def _np_hash_bytes(bs: bytes, seed: int) -> int:
    h = np.uint32(seed)
    nb = len(bs) // 4
    for i in range(nb):
        w = np.uint32(int.from_bytes(bs[4 * i:4 * i + 4], "little"))
        h = _np_mix_h(h, w)
    for i in range(nb * 4, len(bs)):
        b = bs[i]
        signed = b - 256 if b >= 128 else b
        h = _np_mix_h(h, np.uint32(signed % 2**32))
    return int(_np_fmix(h, len(bs)))


def _np_spark_hash(v, m, dtype, seed_i32):
    """One column folded into the running per-row seed (int32 array)."""
    from ..types import (BooleanType, DateType, DoubleType, FloatType,
                         IntegerType, LongType, TimestampType)
    seed_u = seed_i32.astype(np.uint32)
    with np.errstate(all="ignore"):
        if dtype.is_string:
            out = np.empty(len(v), dtype=np.int32)
            for i, s in enumerate(v):
                if not m[i] or s is None:
                    out[i] = seed_i32[i]
                else:
                    out[i] = np.int32(np.uint32(_np_hash_bytes(
                        s.encode("utf-8"), int(seed_u[i]))))
            return out
        if dtype in (LongType, TimestampType):
            h = _np_hash_long(v.astype(np.int64), seed_u)
        elif dtype is DoubleType:
            d = v.astype(np.float64)
            d = np.where(d == 0.0, 0.0, d)
            # Java doubleToLongBits canonicalizes every NaN
            d = np.where(np.isnan(d), np.float64(np.nan), d)
            h = _np_hash_long(d.view(np.int64), seed_u)
        elif dtype is FloatType:
            f = v.astype(np.float32)
            f = np.where(f == 0.0, np.float32(0.0), f)
            f = np.where(np.isnan(f), np.float32(np.nan), f)
            h = _np_hash_int(f.view(np.uint32), seed_u)
        elif dtype is BooleanType:
            h = _np_hash_int(v.astype(np.uint32), seed_u)
        else:  # byte/short/int/date
            h = _np_hash_int(v.astype(np.int32).astype(np.uint32), seed_u)
    res = h.astype(np.int32)
    return np.where(m, res, seed_i32)
