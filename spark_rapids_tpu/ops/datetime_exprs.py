"""Date/time expression library.

Reference: org/.../rapids/datetimeExpressions.scala (+DateUtils.scala) —
year/month/day/hour/minute/second extraction, date add/sub/diff,
unix_timestamp family.  All pure integer arithmetic on days/micros via
datetime_utils, UTC only (the reference likewise requires UTC sessions).
"""
from __future__ import annotations

import jax.numpy as jnp

from ..columnar import Column
from ..types import (DateType, IntegerType, LongType, StringType,
                     TimestampType)
from . import datetime_utils as dtu
from .expressions import Expression, Literal, UnaryExpression


class _DatePart(Expression):
    """Extract an int field from a date or timestamp column."""

    out_dtype = IntegerType

    def __init__(self, child):
        self.child = child
        self.children = (child,)

    @property
    def dtype(self):
        return self.out_dtype

    def _days(self, c: Column):
        if self.child.dtype is TimestampType:
            return dtu.micros_to_days(c.data)
        return c.data

    def eval(self, batch):
        c = self.child.eval(batch)
        return Column(self.compute(c), c.valid, self.out_dtype)


class Year(_DatePart):
    def compute(self, c):
        y, _, _ = dtu.civil_from_days(self._days(c))
        return y


class Month(_DatePart):
    def compute(self, c):
        _, m, _ = dtu.civil_from_days(self._days(c))
        return m


class DayOfMonth(_DatePart):
    def compute(self, c):
        _, _, d = dtu.civil_from_days(self._days(c))
        return d


class DayOfWeek(_DatePart):
    """Spark: 1 = Sunday ... 7 = Saturday."""

    def compute(self, c):
        days = self._days(c).astype(jnp.int64)
        # 1970-01-01 was a Thursday (=> dayofweek 5)
        return ((days + 4) % 7 + 1).astype(jnp.int32)


class DayOfYear(_DatePart):
    def compute(self, c):
        days = self._days(c)
        y, _, _ = dtu.civil_from_days(days)
        jan1 = dtu.days_from_civil(y, jnp.ones_like(y), jnp.ones_like(y))
        return (days - jan1 + 1).astype(jnp.int32)


class Quarter(_DatePart):
    def compute(self, c):
        _, m, _ = dtu.civil_from_days(self._days(c))
        return ((m - 1) // 3 + 1).astype(jnp.int32)


class LastDay(_DatePart):
    out_dtype = DateType

    def compute(self, c):
        days = self._days(c)
        y, m, _ = dtu.civil_from_days(days)
        return dtu.days_from_civil(y, m, dtu.last_day_of_month(y, m))


class Hour(_DatePart):
    def compute(self, c):
        h, _, _, _ = dtu.micros_time_of_day(c.data)
        return h


class Minute(_DatePart):
    def compute(self, c):
        _, m, _, _ = dtu.micros_time_of_day(c.data)
        return m


class Second(_DatePart):
    def compute(self, c):
        _, _, s, _ = dtu.micros_time_of_day(c.data)
        return s


class WeekDay(_DatePart):
    """Spark weekday: 0 = Monday ... 6 = Sunday."""

    def compute(self, c):
        days = self._days(c).astype(jnp.int64)
        return ((days + 3) % 7).astype(jnp.int32)


class _DateArith(Expression):
    def __init__(self, left, right):
        self.left, self.right = left, right
        self.children = (left, right)

    @property
    def dtype(self):
        return DateType


class DateAdd(_DateArith):
    def eval(self, batch):
        l = self.left.eval(batch)
        r = self.right.eval(batch)
        data = (l.data.astype(jnp.int32) + r.data.astype(jnp.int32))
        return Column(data, l.valid & r.valid, DateType).mask_invalid()


class DateSub(_DateArith):
    def eval(self, batch):
        l = self.left.eval(batch)
        r = self.right.eval(batch)
        data = (l.data.astype(jnp.int32) - r.data.astype(jnp.int32))
        return Column(data, l.valid & r.valid, DateType).mask_invalid()


class DateDiff(_DateArith):
    @property
    def dtype(self):
        return IntegerType

    def eval(self, batch):
        end = self.left.eval(batch)
        start = self.right.eval(batch)
        e = end.data if self.left.dtype is DateType \
            else dtu.micros_to_days(end.data)
        s = start.data if self.right.dtype is DateType \
            else dtu.micros_to_days(start.data)
        return Column((e - s).astype(jnp.int32), end.valid & start.valid,
                      IntegerType).mask_invalid()


class UnixTimestamp(Expression):
    """unix_timestamp(ts|date|string[, fmt]) -> long seconds.  String input
    supports the default 'yyyy-MM-dd HH:mm:ss' format (conf-gated parse)."""

    def __init__(self, child, fmt: Expression = None):
        self.child = child
        self.fmt = fmt
        self.children = (child,)

    @property
    def dtype(self):
        return LongType

    def eval(self, batch):
        from .cast import Cast
        src = self.child.dtype
        if src is TimestampType:
            c = self.child.eval(batch)
            return Column(c.data // dtu.MICROS_PER_SECOND, c.valid, LongType)
        if src is DateType:
            c = self.child.eval(batch)
            return Column(c.data.astype(jnp.int64) * dtu.SECONDS_PER_DAY,
                          c.valid, LongType)
        if src is StringType:
            ts = Cast(self.child, TimestampType).eval(batch)
            return Column(ts.data // dtu.MICROS_PER_SECOND, ts.valid,
                          LongType).mask_invalid()
        raise NotImplementedError(f"unix_timestamp({src.name})")


class ToUnixTimestamp(UnixTimestamp):
    pass


class FromUnixTime(Expression):
    """from_unixtime(long) -> 'yyyy-MM-dd HH:mm:ss' string (default format)."""

    def __init__(self, child, fmt: Expression = None):
        self.child = child
        self.fmt = fmt
        self.children = (child,)

    @property
    def dtype(self):
        return StringType

    def eval(self, batch):
        from .cast import _format_timestamp
        c = self.child.eval(batch)
        micros = Column(c.data.astype(jnp.int64) * dtu.MICROS_PER_SECOND,
                        c.valid, TimestampType)
        return _format_timestamp(micros, StringType)


class TimeAdd(Expression):
    """timestamp + interval literal (micros)."""

    def __init__(self, child, interval_micros: Expression):
        self.child = child
        self.interval = interval_micros
        self.children = (child, interval_micros)

    @property
    def dtype(self):
        return TimestampType

    def eval(self, batch):
        c = self.child.eval(batch)
        i = self.interval.eval(batch)
        return Column(c.data + i.data.astype(jnp.int64), c.valid & i.valid,
                      TimestampType).mask_invalid()


class TimeSub(Expression):
    """timestamp - interval literal (micros) (Spark TimeSub; reference
    GpuTimeSub in datetimeExpressions.scala)."""

    def __init__(self, child, interval_micros: Expression):
        self.child = child
        self.interval = interval_micros
        self.children = (child, interval_micros)

    @property
    def dtype(self):
        return TimestampType

    def eval(self, batch):
        c = self.child.eval(batch)
        i = self.interval.eval(batch)
        return Column(c.data - i.data.astype(jnp.int64), c.valid & i.valid,
                      TimestampType).mask_invalid()


class AddMonths(Expression):
    """add_months(date, n): civil month arithmetic, day-of-month clamped to
    the target month's last day (Spark/DateTimeUtils semantics)."""

    def __init__(self, left, right):
        self.left, self.right = left, right
        self.children = (left, right)

    @property
    def dtype(self):
        return DateType

    def eval(self, batch):
        d = self.left.eval(batch)
        n = self.right.eval(batch)
        days = d.data.astype(jnp.int64)
        y, m, dom = dtu.civil_from_days(days)
        total = (y.astype(jnp.int64) * 12 + (m.astype(jnp.int64) - 1)
                 + n.data.astype(jnp.int64))
        ny = dtu.floordiv(total, 12).astype(jnp.int32)
        nm = (total - ny * 12 + 1).astype(jnp.int32)
        nd = jnp.minimum(dom, dtu.last_day_of_month(ny, nm))
        out = dtu.days_from_civil(ny, nm, nd)
        valid = d.valid & n.valid
        return Column(out.astype(jnp.int32), valid, DateType).mask_invalid()


class MonthsBetween(Expression):
    """months_between(d1, d2): whole months when the days-of-month match or
    both are month ends, else fractional with /31 (Spark DateTimeUtils;
    date inputs only — timestamps truncate to date first)."""

    def __init__(self, left, right, round_off=None):
        self.left, self.right = left, right
        self.round_off = round_off if round_off is not None \
            else Literal(True)
        self.children = (left, right, self.round_off)

    @property
    def dtype(self):
        from ..types import DoubleType
        return DoubleType

    def eval(self, batch):
        from ..types import DoubleType
        a = self.left.eval(batch)
        b = self.right.eval(batch)
        d1 = a.data.astype(jnp.int64) if self.left.dtype is DateType \
            else dtu.micros_to_days(a.data)
        d2 = b.data.astype(jnp.int64) if self.right.dtype is DateType \
            else dtu.micros_to_days(b.data)
        y1, m1, dom1 = dtu.civil_from_days(d1)
        y2, m2, dom2 = dtu.civil_from_days(d2)
        months = ((y1 - y2) * 12 + (m1 - m2)).astype(jnp.float64)
        last1 = dtu.last_day_of_month(y1, m1)
        last2 = dtu.last_day_of_month(y2, m2)
        whole = (dom1 == dom2) | ((dom1 == last1) & (dom2 == last2))
        frac = (dom1 - dom2).astype(jnp.float64) / 31.0
        out = months + jnp.where(whole, 0.0, frac)
        rnd = isinstance(self.round_off, Literal) and \
            bool(self.round_off.value)
        if rnd:
            out = jnp.round(out * 1e8) / 1e8
        valid = a.valid & b.valid
        return Column(out, valid, DoubleType).mask_invalid()


class TruncDate(Expression):
    """trunc(date, fmt) with LITERAL fmt: year|yyyy|yy, quarter, month|mon|mm,
    week (Monday start).  Unknown formats -> null (Spark behavior)."""

    def __init__(self, child, fmt):
        self.child, self.fmt = child, fmt
        self.children = (child, fmt)

    @property
    def dtype(self):
        return DateType

    def _level(self):
        if not (isinstance(self.fmt, Literal)
                and isinstance(self.fmt.value, str)):
            raise ValueError("trunc format must be a string literal")
        f = self.fmt.value.lower()
        if f in ("year", "yyyy", "yy"):
            return "year"
        if f == "quarter":
            return "quarter"
        if f in ("month", "mon", "mm"):
            return "month"
        if f == "week":
            return "week"
        return None

    def device_supported(self) -> bool:
        try:
            self._level()
            return True
        except ValueError:
            return False

    def eval(self, batch):
        c = self.child.eval(batch)
        days = c.data.astype(jnp.int64)
        level = self._level()
        if level is None:
            return Column(jnp.zeros_like(c.data), jnp.zeros_like(c.valid),
                          DateType)
        y, m, _ = dtu.civil_from_days(days)
        one = jnp.ones_like(m)
        if level == "year":
            out = dtu.days_from_civil(y, one, one)
        elif level == "quarter":
            qm = ((m - 1) // 3) * 3 + 1
            out = dtu.days_from_civil(y, qm, one)
        elif level == "month":
            out = dtu.days_from_civil(y, m, one)
        else:  # week: previous (or same) Monday
            out = days - (days + 3) % 7
        return Column(out.astype(jnp.int32), c.valid, DateType)


_DAY_NAMES = {"MO": 0, "MON": 0, "MONDAY": 0, "TU": 1, "TUE": 1,
              "TUESDAY": 1, "WE": 2, "WED": 2, "WEDNESDAY": 2, "TH": 3,
              "THU": 3, "THURSDAY": 3, "FR": 4, "FRI": 4, "FRIDAY": 4,
              "SA": 5, "SAT": 5, "SATURDAY": 5, "SU": 6, "SUN": 6,
              "SUNDAY": 6}


class NextDay(Expression):
    """next_day(date, dayOfWeek) with LITERAL day name: the first date LATER
    than `date` falling on that weekday; unknown names -> null (Spark)."""

    def __init__(self, child, day):
        self.child, self.day = child, day
        self.children = (child, day)

    @property
    def dtype(self):
        return DateType

    def _target(self):
        if not (isinstance(self.day, Literal)
                and isinstance(self.day.value, str)):
            raise ValueError("next_day weekday must be a string literal")
        return _DAY_NAMES.get(self.day.value.strip().upper())

    def device_supported(self) -> bool:
        try:
            self._target()
            return True
        except ValueError:
            return False

    def eval(self, batch):
        c = self.child.eval(batch)
        t = self._target()
        if t is None:
            return Column(jnp.zeros_like(c.data), jnp.zeros_like(c.valid),
                          DateType)
        days = c.data.astype(jnp.int64)
        wd = (days + 3) % 7  # 0 = Monday
        delta = (t - wd + 7) % 7
        delta = jnp.where(delta == 0, 7, delta)
        return Column((days + delta).astype(jnp.int32), c.valid, DateType)
