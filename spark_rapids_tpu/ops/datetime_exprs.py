"""Date/time expression library.

Reference: org/.../rapids/datetimeExpressions.scala (+DateUtils.scala) —
year/month/day/hour/minute/second extraction, date add/sub/diff,
unix_timestamp family.  All pure integer arithmetic on days/micros via
datetime_utils, UTC only (the reference likewise requires UTC sessions).
"""
from __future__ import annotations

import jax.numpy as jnp

from ..columnar import Column
from ..types import (DateType, IntegerType, LongType, StringType,
                     TimestampType)
from . import datetime_utils as dtu
from .expressions import Expression, Literal, UnaryExpression


class _DatePart(Expression):
    """Extract an int field from a date or timestamp column."""

    out_dtype = IntegerType

    def __init__(self, child):
        self.child = child
        self.children = (child,)

    @property
    def dtype(self):
        return self.out_dtype

    def _days(self, c: Column):
        if self.child.dtype is TimestampType:
            return dtu.micros_to_days(c.data)
        return c.data

    def eval(self, batch):
        c = self.child.eval(batch)
        return Column(self.compute(c), c.valid, self.out_dtype)


class Year(_DatePart):
    def compute(self, c):
        y, _, _ = dtu.civil_from_days(self._days(c))
        return y


class Month(_DatePart):
    def compute(self, c):
        _, m, _ = dtu.civil_from_days(self._days(c))
        return m


class DayOfMonth(_DatePart):
    def compute(self, c):
        _, _, d = dtu.civil_from_days(self._days(c))
        return d


class DayOfWeek(_DatePart):
    """Spark: 1 = Sunday ... 7 = Saturday."""

    def compute(self, c):
        days = self._days(c).astype(jnp.int64)
        # 1970-01-01 was a Thursday (=> dayofweek 5)
        return ((days + 4) % 7 + 1).astype(jnp.int32)


class DayOfYear(_DatePart):
    def compute(self, c):
        days = self._days(c)
        y, _, _ = dtu.civil_from_days(days)
        jan1 = dtu.days_from_civil(y, jnp.ones_like(y), jnp.ones_like(y))
        return (days - jan1 + 1).astype(jnp.int32)


class Quarter(_DatePart):
    def compute(self, c):
        _, m, _ = dtu.civil_from_days(self._days(c))
        return ((m - 1) // 3 + 1).astype(jnp.int32)


class LastDay(_DatePart):
    out_dtype = DateType

    def compute(self, c):
        days = self._days(c)
        y, m, _ = dtu.civil_from_days(days)
        return dtu.days_from_civil(y, m, dtu.last_day_of_month(y, m))


class Hour(_DatePart):
    def compute(self, c):
        h, _, _, _ = dtu.micros_time_of_day(c.data)
        return h


class Minute(_DatePart):
    def compute(self, c):
        _, m, _, _ = dtu.micros_time_of_day(c.data)
        return m


class Second(_DatePart):
    def compute(self, c):
        _, _, s, _ = dtu.micros_time_of_day(c.data)
        return s


class WeekDay(_DatePart):
    """Spark weekday: 0 = Monday ... 6 = Sunday."""

    def compute(self, c):
        days = self._days(c).astype(jnp.int64)
        return ((days + 3) % 7).astype(jnp.int32)


class _DateArith(Expression):
    def __init__(self, left, right):
        self.left, self.right = left, right
        self.children = (left, right)

    @property
    def dtype(self):
        return DateType


class DateAdd(_DateArith):
    def eval(self, batch):
        l = self.left.eval(batch)
        r = self.right.eval(batch)
        data = (l.data.astype(jnp.int32) + r.data.astype(jnp.int32))
        return Column(data, l.valid & r.valid, DateType).mask_invalid()


class DateSub(_DateArith):
    def eval(self, batch):
        l = self.left.eval(batch)
        r = self.right.eval(batch)
        data = (l.data.astype(jnp.int32) - r.data.astype(jnp.int32))
        return Column(data, l.valid & r.valid, DateType).mask_invalid()


class DateDiff(_DateArith):
    @property
    def dtype(self):
        return IntegerType

    def eval(self, batch):
        end = self.left.eval(batch)
        start = self.right.eval(batch)
        e = end.data if self.left.dtype is DateType \
            else dtu.micros_to_days(end.data)
        s = start.data if self.right.dtype is DateType \
            else dtu.micros_to_days(start.data)
        return Column((e - s).astype(jnp.int32), end.valid & start.valid,
                      IntegerType).mask_invalid()


class UnixTimestamp(Expression):
    """unix_timestamp(ts|date|string[, fmt]) -> long seconds.  String input
    supports the default 'yyyy-MM-dd HH:mm:ss' format (conf-gated parse)."""

    def __init__(self, child, fmt: Expression = None):
        self.child = child
        self.fmt = fmt
        self.children = (child,)

    @property
    def dtype(self):
        return LongType

    def eval(self, batch):
        from .cast import Cast
        src = self.child.dtype
        if src is TimestampType:
            c = self.child.eval(batch)
            return Column(c.data // dtu.MICROS_PER_SECOND, c.valid, LongType)
        if src is DateType:
            c = self.child.eval(batch)
            return Column(c.data.astype(jnp.int64) * dtu.SECONDS_PER_DAY,
                          c.valid, LongType)
        if src is StringType:
            ts = Cast(self.child, TimestampType).eval(batch)
            return Column(ts.data // dtu.MICROS_PER_SECOND, ts.valid,
                          LongType).mask_invalid()
        raise NotImplementedError(f"unix_timestamp({src.name})")


class ToUnixTimestamp(UnixTimestamp):
    pass


class FromUnixTime(Expression):
    """from_unixtime(long) -> 'yyyy-MM-dd HH:mm:ss' string (default format)."""

    def __init__(self, child, fmt: Expression = None):
        self.child = child
        self.fmt = fmt
        self.children = (child,)

    @property
    def dtype(self):
        return StringType

    def eval(self, batch):
        from .cast import _format_timestamp
        c = self.child.eval(batch)
        micros = Column(c.data.astype(jnp.int64) * dtu.MICROS_PER_SECOND,
                        c.valid, TimestampType)
        return _format_timestamp(micros, StringType)


class TimeAdd(Expression):
    """timestamp + interval literal (micros)."""

    def __init__(self, child, interval_micros: Expression):
        self.child = child
        self.interval = interval_micros
        self.children = (child, interval_micros)

    @property
    def dtype(self):
        return TimestampType

    def eval(self, batch):
        c = self.child.eval(batch)
        i = self.interval.eval(batch)
        return Column(c.data + i.data.astype(jnp.int64), c.valid & i.valid,
                      TimestampType).mask_invalid()
