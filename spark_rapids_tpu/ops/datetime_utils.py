"""Vectorized civil-calendar conversions (days since epoch <-> y/m/d and
micros since epoch <-> time-of-day), used by cast and datetime expressions.

Pure jnp integer arithmetic (Howard Hinnant's civil_from_days / days_from_civil
algorithms), so they trace into the same XLA program as the rest of a pipeline.
"""
from __future__ import annotations

import jax.numpy as jnp

MICROS_PER_SECOND = 1_000_000
SECONDS_PER_DAY = 86_400
MICROS_PER_DAY = MICROS_PER_SECOND * SECONDS_PER_DAY


def civil_from_days(days):
    """int32/64 days since 1970-01-01 -> (year, month, day) int32 arrays."""
    z = days.astype(jnp.int64) + 719468
    era = jnp.where(z >= 0, z, z - 146096) // 146097
    doe = z - era * 146097                                   # [0, 146096]
    yoe = (doe - doe // 1460 + doe // 36524 - doe // 146096) // 365
    y = yoe + era * 400
    doy = doe - (365 * yoe + yoe // 4 - yoe // 100)          # [0, 365]
    mp = (5 * doy + 2) // 153                                # [0, 11]
    d = doy - (153 * mp + 2) // 5 + 1                        # [1, 31]
    m = jnp.where(mp < 10, mp + 3, mp - 9)                   # [1, 12]
    y = jnp.where(m <= 2, y + 1, y)
    return y.astype(jnp.int32), m.astype(jnp.int32), d.astype(jnp.int32)


def days_from_civil(y, m, d):
    """(year, month, day) -> int32 days since 1970-01-01."""
    y = y.astype(jnp.int64)
    m = m.astype(jnp.int64)
    d = d.astype(jnp.int64)
    y = jnp.where(m <= 2, y - 1, y)
    era = jnp.where(y >= 0, y, y - 399) // 400
    yoe = y - era * 400                                       # [0, 399]
    mp = jnp.where(m > 2, m - 3, m + 9)
    doy = (153 * mp + 2) // 5 + d - 1                         # [0, 365]
    doe = yoe * 365 + yoe // 4 - yoe // 100 + doy             # [0, 146096]
    return (era * 146097 + doe - 719468).astype(jnp.int32)


def floordiv(a, b):
    """Floor division toward -inf on int64 (jnp // already floors)."""
    return a // b


def micros_to_days(micros):
    return (micros.astype(jnp.int64) // MICROS_PER_DAY).astype(jnp.int32)


def micros_time_of_day(micros):
    """-> (hour, minute, second, microsecond) int32 arrays."""
    tod = micros.astype(jnp.int64) % MICROS_PER_DAY
    sec = tod // MICROS_PER_SECOND
    us = tod % MICROS_PER_SECOND
    h = sec // 3600
    mi = (sec % 3600) // 60
    s = sec % 60
    return (h.astype(jnp.int32), mi.astype(jnp.int32), s.astype(jnp.int32),
            us.astype(jnp.int32))


def is_leap_year(y):
    return ((y % 4 == 0) & (y % 100 != 0)) | (y % 400 == 0)


def last_day_of_month(y, m):
    base = jnp.asarray([31, 28, 31, 30, 31, 30, 31, 31, 30, 31, 30, 31],
                       dtype=jnp.int32)
    d = base[m - 1]
    return jnp.where((m == 2) & is_leap_year(y), 29, d)
