"""Expression trees evaluated whole-column on device.

The TPU analogue of GpuExpression.columnarEval (reference: sql-plugin/.../
rapids/GpuExpressions.scala:74-370) — but where the reference dispatches one
cuDF kernel per operator, these eval() methods emit jnp ops that are traced
TOGETHER into a single XLA program per operator pipeline, so XLA fuses the
whole expression tree into a few VPU loops over the batch.

Null semantics follow Spark SQL: result is null if any input is null, except
where noted (Kleene and/or, null predicates, conditionals, coalesce).
Expression class names match Spark's expression class names so the planner's
rule table and the auto-derived `spark.rapids.sql.expr.<Name>` kill-switch
confs line up with the reference (reference: GpuOverrides.scala:453-1453).
"""
from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional, Sequence, Type

import jax.numpy as jnp
import numpy as np

from ..columnar import Column, ColumnarBatch
from ..types import (BooleanType, ByteType, DataType, DateType, DoubleType,
                     FloatType, IntegerType, LongType, NullType, ShortType,
                     StringType, TimestampType, promote)

EXPR_REGISTRY: Dict[str, Type["Expression"]] = {}


class Expression:
    """Bound expression node; eval(batch) -> Column of batch.capacity rows."""

    # subclasses override
    children: Sequence["Expression"] = ()

    def __init_subclass__(cls, **kw):
        super().__init_subclass__(**kw)
        EXPR_REGISTRY[cls.__name__] = cls

    @property
    def dtype(self) -> DataType:
        raise NotImplementedError

    @property
    def name(self) -> str:
        return type(self).__name__

    def eval(self, batch: ColumnarBatch) -> Column:
        raise NotImplementedError

    def __repr__(self):
        inner = ", ".join(repr(c) for c in self.children)
        return f"{self.name}({inner})"


def _broadcast_valid(*cols: Column):
    v = cols[0].valid
    for c in cols[1:]:
        v = jnp.logical_and(v, c.valid)
    return v


class BoundReference(Expression):
    """reference: GpuBoundAttribute.scala — resolved column index."""

    def __init__(self, index: int, dtype: DataType, column_name: str = ""):
        self.index = index
        self._dtype = dtype
        self.column_name = column_name

    @property
    def dtype(self):
        return self._dtype

    def eval(self, batch):
        return batch.columns[self.index]

    def __repr__(self):
        return f"input[{self.index} {self.column_name}:{self._dtype.name}]"


class Literal(Expression):
    """reference: rapids/literals.scala."""

    def __init__(self, value: Any, dtype: Optional[DataType] = None):
        if dtype is None:
            dtype = _infer_literal_type(value)
        self.value = value
        self._dtype = dtype

    @property
    def dtype(self):
        return self._dtype

    def eval(self, batch):
        cap = batch.capacity
        if self.value is None:
            return Column.all_null(
                self._dtype if self._dtype is not NullType else LongType, cap)
        if self._dtype.is_string:
            return Column.from_strings([self.value] * cap)
        data = jnp.full((cap,), self.value, dtype=self._dtype.jnp_dtype)
        return Column(data, jnp.ones(cap, dtype=jnp.bool_), self._dtype)

    def __repr__(self):
        return f"lit({self.value!r})"


def _infer_literal_type(v) -> DataType:
    if v is None:
        return NullType
    if isinstance(v, bool):
        return BooleanType
    if isinstance(v, (int, np.integer)):
        return IntegerType if -2**31 <= int(v) < 2**31 else LongType
    if isinstance(v, (float, np.floating)):
        return DoubleType
    if isinstance(v, str):
        return StringType
    raise TypeError(f"cannot infer literal type of {v!r}")


def lit(v, dtype=None) -> Literal:
    return v if isinstance(v, Expression) else Literal(v, dtype)


# --------------------------------------------------------------------------
# plan-cache parameters (serve/plan_cache.py)
# --------------------------------------------------------------------------
# A Parameter is a literal the serving tier's plan cache lifted out of a
# query so literal-variant re-submissions share one normalized plan — and,
# on the threaded dispatch paths (RowLocalExec / whole-stage / aggregate
# absorption / exchange bucketing), ONE compiled XLA program: the value
# rides into the program as a runtime argument instead of a baked trace
# constant.  The binding is a thread-local installed INSIDE the traced
# function (so Parameter.eval sees tracers at trace time and the compiled
# executable takes the values as real inputs); outside any binding the
# Parameter evaluates exactly like the Literal it replaced (CPU twins,
# un-threaded kernel paths — which key their caches on the value, so a
# baked constant can never be replayed for a different binding).

# built eagerly: the old lazy `global` init could race under concurrent
# serving — two first-touch threads built two locals and one thread's
# parameter bindings landed on the loser, vanishing mid-dispatch (TPU009)
_PARAM_BINDING = threading.local()


def _param_tls():
    return _PARAM_BINDING


def current_param(slot: int):
    """Traced value bound for `slot`, or None when no binding is active."""
    vals = getattr(_param_tls(), "values", None)
    if vals is None:
        return None
    return vals.get(slot)


class _BoundParams:
    """Context manager installing a slot->array binding for this thread.
    Plain class (not @contextmanager) so re-entry under jax tracing has
    no generator machinery in the traced call stack."""

    __slots__ = ("values", "_prev")

    def __init__(self, values):
        self.values = values

    def __enter__(self):
        tls = _param_tls()
        self._prev = getattr(tls, "values", None)
        tls.values = self.values
        return self

    def __exit__(self, *a):
        _param_tls().values = self._prev


def bound_params(values) -> _BoundParams:
    return _BoundParams(values)


class Parameter(Literal):
    """A lifted literal with a plan-cache slot (see module comment above)."""

    def __init__(self, slot: int, value: Any,
                 dtype: Optional[DataType] = None):
        super().__init__(value, dtype)
        self.slot = slot

    def eval(self, batch):
        arr = current_param(self.slot)
        if arr is None:
            return super().eval(batch)  # baked path: behaves as a Literal
        cap = batch.capacity
        data = jnp.broadcast_to(
            jnp.asarray(arr, dtype=self._dtype.jnp_dtype), (cap,))
        return Column(data, jnp.ones(cap, dtype=jnp.bool_), self._dtype)

    def __repr__(self):
        return f"param({self.slot}:{self._dtype.name}={self.value!r})"


def collect_parameters(exprs) -> list:
    """Unique Parameters in the given expression trees, ordered by slot —
    the argument order of a parameter-threaded compiled program."""
    found = {}

    def walk(e):
        if isinstance(e, Parameter):
            found.setdefault(e.slot, e)
        for c in getattr(e, "children", ()):
            walk(c)

    for e in exprs:
        walk(e)
    return [found[s] for s in sorted(found)]


def parameter_values(params) -> tuple:
    """Device-scalar argument tuple for `params` (collect_parameters
    order).  Committed jnp arrays, not Python scalars, so jit's argument
    signature is (dtype, shape ()) — stable across values: a re-bound
    literal re-dispatches the already-compiled program."""
    return tuple(jnp.asarray(p.value, dtype=p._dtype.jnp_dtype)
                 for p in params)


def parameter_signature(params) -> tuple:
    """Value-free cache-key component for a threaded program's params."""
    return tuple((p.slot, p._dtype.name) for p in params)


# --------------------------------------------------------------------------
# scaffolding: unary / binary with standard null propagation
# --------------------------------------------------------------------------

class UnaryExpression(Expression):
    def __init__(self, child: Expression):
        self.child = child
        self.children = (child,)

    @property
    def dtype(self):
        return self.child.dtype

    def eval(self, batch):
        c = self.child.eval(batch)
        data = self.do_op(c.data)
        return Column(data, c.valid, self.dtype)

    def do_op(self, x):
        raise NotImplementedError


class BinaryExpression(Expression):
    """Numeric binary op with promotion + null propagation."""

    promote_children = True

    def __init__(self, left: Expression, right: Expression):
        self.left = left
        self.right = right
        self.children = (left, right)

    @property
    def promoted_type(self) -> DataType:
        return promote(self.left.dtype, self.right.dtype)

    @property
    def dtype(self):
        if self.promote_children:
            return self.promoted_type
        return self.left.dtype

    def eval(self, batch):
        l = self.left.eval(batch)
        r = self.right.eval(batch)
        ld, rd = l.data, r.data
        if self.promote_children:
            t = self.promoted_type.jnp_dtype
            ld = ld.astype(t)
            rd = rd.astype(t)
        valid = _broadcast_valid(l, r)
        data, valid = self.do_op(ld, rd, valid)
        col = Column(data, valid, self.out_type())
        return col.mask_invalid()

    def out_type(self) -> DataType:
        return self.dtype

    def do_op(self, l, r, valid):
        raise NotImplementedError


# --------------------------------------------------------------------------
# arithmetic (reference: org/.../rapids/arithmetic.scala)
# --------------------------------------------------------------------------

class Add(BinaryExpression):
    def do_op(self, l, r, valid):
        return l + r, valid


class Subtract(BinaryExpression):
    def do_op(self, l, r, valid):
        return l - r, valid


class Multiply(BinaryExpression):
    def do_op(self, l, r, valid):
        return l * r, valid


class Divide(BinaryExpression):
    """Spark `/`: always double, x/0 -> null."""

    @property
    def dtype(self):
        return DoubleType

    def do_op(self, l, r, valid):
        l = l.astype(jnp.float64)
        r = r.astype(jnp.float64)
        nz = r != 0.0
        return jnp.where(nz, l, 1.0) / jnp.where(nz, r, 1.0), \
            jnp.logical_and(valid, nz)


class IntegralDivide(BinaryExpression):
    """Spark `div`: long division, x div 0 -> null."""

    @property
    def dtype(self):
        return LongType

    def do_op(self, l, r, valid):
        l = l.astype(jnp.int64)
        r = r.astype(jnp.int64)
        nz = r != 0
        safe_r = jnp.where(nz, r, 1)
        q = jnp.sign(l) * jnp.sign(safe_r) * (jnp.abs(l) // jnp.abs(safe_r))
        return q, jnp.logical_and(valid, nz)


def _trunc_mod(l, r):
    """JVM % semantics: result has sign of dividend (jnp % follows divisor)."""
    return l - r * (jnp.sign(l) * jnp.sign(r) * (jnp.abs(l) // jnp.abs(r)))


class Remainder(BinaryExpression):
    def do_op(self, l, r, valid):
        if jnp.issubdtype(l.dtype, jnp.floating):
            nz = r != 0.0
            safe = jnp.where(nz, r, 1.0)
            return jnp.fmod(l, safe), jnp.logical_and(valid, nz)
        nz = r != 0
        safe = jnp.where(nz, r, 1)
        return _trunc_mod(l, safe), jnp.logical_and(valid, nz)


class Pmod(BinaryExpression):
    def do_op(self, l, r, valid):
        if jnp.issubdtype(l.dtype, jnp.floating):
            nz = r != 0.0
            safe = jnp.where(nz, r, 1.0)
            m = jnp.fmod(l, safe)
            m = jnp.where(m < 0, jnp.fmod(m + safe, safe), m)
            return m, jnp.logical_and(valid, nz)
        nz = r != 0
        safe = jnp.where(nz, r, 1)
        m = _trunc_mod(l, safe)
        m = jnp.where(m < 0, _trunc_mod(m + safe, safe), m)
        return m, jnp.logical_and(valid, nz)


class UnaryMinus(UnaryExpression):
    def do_op(self, x):
        return -x


class UnaryPositive(UnaryExpression):
    def do_op(self, x):
        return x


class Abs(UnaryExpression):
    def do_op(self, x):
        return jnp.abs(x)


# --------------------------------------------------------------------------
# comparisons (reference: org/.../rapids/predicates.scala)
# Spark semantics: -0.0 == 0.0; NaN == NaN and NaN is greatest for ordering.
# --------------------------------------------------------------------------

def _cmp_prep(l, r):
    if jnp.issubdtype(l.dtype, jnp.floating):
        # normalize -0.0 to 0.0
        l = l + jnp.zeros((), l.dtype)
        r = r + jnp.zeros((), r.dtype)
    return l, r


def _string_pair(l: Column, r: Column):
    ml = max(l.max_len, r.max_len)
    return l.pad_strings_to(ml), r.pad_strings_to(ml)


def string_eq(l: Column, r: Column):
    a, b = _string_pair(l, r)
    return jnp.all(a.data == b.data, axis=1) & (a.lengths == b.lengths)


def string_lt(l: Column, r: Column):
    """Lexicographic byte order (zero padding sorts prefixes first)."""
    a, b = _string_pair(l, r)
    neq = a.data != b.data
    has_diff = jnp.any(neq, axis=1)
    idx = jnp.argmax(neq, axis=1)[:, None]
    av = jnp.take_along_axis(a.data, idx, axis=1)[:, 0]
    bv = jnp.take_along_axis(b.data, idx, axis=1)[:, 0]
    return jnp.where(has_diff, av < bv, a.lengths < b.lengths)


class _Comparison(BinaryExpression):
    @property
    def dtype(self):
        return BooleanType

    @property
    def promoted_type(self):
        lt, rt = self.left.dtype, self.right.dtype
        if lt is rt:
            return lt
        if lt.is_string and rt.is_string:
            return lt
        return promote(lt, rt)

    def out_type(self):
        return BooleanType

    def eval(self, batch):
        if self.left.dtype.is_string and self.right.dtype.is_string:
            l = self.left.eval(batch)
            r = self.right.eval(batch)
            valid = _broadcast_valid(l, r)
            kind = type(self).__name__
            if kind == "EqualTo":
                out = string_eq(l, r)
            elif kind == "LessThan":
                out = string_lt(l, r)
            elif kind == "GreaterThan":
                out = string_lt(r, l)
            elif kind == "LessThanOrEqual":
                out = jnp.logical_not(string_lt(r, l))
            elif kind == "GreaterThanOrEqual":
                out = jnp.logical_not(string_lt(l, r))
            else:
                raise NotImplementedError(kind)
            return Column(out, valid, BooleanType)
        return super().eval(batch)


class EqualTo(_Comparison):
    def do_op(self, l, r, valid):
        l, r = _cmp_prep(l, r)
        eq = l == r
        if jnp.issubdtype(l.dtype, jnp.floating):
            eq = jnp.logical_or(eq, jnp.logical_and(jnp.isnan(l),
                                                    jnp.isnan(r)))
        return eq, valid


class LessThan(_Comparison):
    def do_op(self, l, r, valid):
        l, r = _cmp_prep(l, r)
        lt = l < r
        if jnp.issubdtype(l.dtype, jnp.floating):
            # NaN is greatest: l<r iff (r is NaN and l isn't) or plain l<r
            lt = jnp.where(jnp.isnan(l), False,
                           jnp.where(jnp.isnan(r), True, lt))
        return lt, valid


class GreaterThan(_Comparison):
    def do_op(self, l, r, valid):
        return LessThan(self.right, self.left).do_op(r, l, valid)


class LessThanOrEqual(_Comparison):
    def do_op(self, l, r, valid):
        gt, v = GreaterThan(self.left, self.right).do_op(l, r, valid)
        return jnp.logical_not(gt), v


class GreaterThanOrEqual(_Comparison):
    def do_op(self, l, r, valid):
        lt, v = LessThan(self.left, self.right).do_op(l, r, valid)
        return jnp.logical_not(lt), v


class EqualNullSafe(_Comparison):
    """<=> : never null."""

    def eval(self, batch):
        l = self.left.eval(batch)
        r = self.right.eval(batch)
        if self.left.dtype.is_string:
            eq = string_eq(l, r)
        else:
            t = self.promoted_type.jnp_dtype
            eq, _ = EqualTo(self.left, self.right).do_op(
                l.data.astype(t), r.data.astype(t), None)
        both_null = jnp.logical_and(~l.valid, ~r.valid)
        both_valid = jnp.logical_and(l.valid, r.valid)
        out = jnp.logical_or(jnp.logical_and(both_valid, eq), both_null)
        return Column(out, jnp.ones_like(out), BooleanType)


# --------------------------------------------------------------------------
# boolean logic — Kleene (reference: predicates.scala GpuAnd/GpuOr/GpuNot)
# --------------------------------------------------------------------------

class And(Expression):
    def __init__(self, left, right):
        self.left, self.right = left, right
        self.children = (left, right)

    @property
    def dtype(self):
        return BooleanType

    def eval(self, batch):
        l = self.left.eval(batch)
        r = self.right.eval(batch)
        lv = jnp.logical_and(l.valid, l.data)
        rv = jnp.logical_and(r.valid, r.data)
        data = jnp.logical_and(lv, rv)
        # null unless one side is definitively False
        false_l = jnp.logical_and(l.valid, ~l.data)
        false_r = jnp.logical_and(r.valid, ~r.data)
        valid = jnp.logical_or(jnp.logical_and(l.valid, r.valid),
                               jnp.logical_or(false_l, false_r))
        return Column(data, valid, BooleanType)


class Or(Expression):
    def __init__(self, left, right):
        self.left, self.right = left, right
        self.children = (left, right)

    @property
    def dtype(self):
        return BooleanType

    def eval(self, batch):
        l = self.left.eval(batch)
        r = self.right.eval(batch)
        true_l = jnp.logical_and(l.valid, l.data)
        true_r = jnp.logical_and(r.valid, r.data)
        data = jnp.logical_or(true_l, true_r)
        valid = jnp.logical_or(jnp.logical_and(l.valid, r.valid),
                               jnp.logical_or(true_l, true_r))
        return Column(data, valid, BooleanType)


class Not(UnaryExpression):
    @property
    def dtype(self):
        return BooleanType

    def do_op(self, x):
        return jnp.logical_not(x)


# --------------------------------------------------------------------------
# null predicates / handling (reference: rapids/nullExpressions.scala)
# --------------------------------------------------------------------------

class IsNull(Expression):
    def __init__(self, child):
        self.child = child
        self.children = (child,)

    @property
    def dtype(self):
        return BooleanType

    def eval(self, batch):
        c = self.child.eval(batch)
        return Column(jnp.logical_not(c.valid),
                      jnp.ones(batch.capacity, dtype=jnp.bool_), BooleanType)


class IsNotNull(Expression):
    def __init__(self, child):
        self.child = child
        self.children = (child,)

    @property
    def dtype(self):
        return BooleanType

    def eval(self, batch):
        c = self.child.eval(batch)
        return Column(c.valid, jnp.ones(batch.capacity, dtype=jnp.bool_),
                      BooleanType)


class IsNaN(Expression):
    def __init__(self, child):
        self.child = child
        self.children = (child,)

    @property
    def dtype(self):
        return BooleanType

    def eval(self, batch):
        c = self.child.eval(batch)
        nan = jnp.logical_and(c.valid, jnp.isnan(c.data))
        return Column(nan, jnp.ones(batch.capacity, dtype=jnp.bool_),
                      BooleanType)


def _common_type(dtypes) -> DataType:
    """Least common type across conditional branches (Spark's coercion)."""
    out = None
    for dt in dtypes:
        if dt is NullType:
            continue
        if out is None or out is dt:
            out = dt
        else:
            out = promote(out, dt)
    return out if out is not None else NullType


class Coalesce(Expression):
    def __init__(self, *children):
        self.children = tuple(children)

    @property
    def dtype(self):
        return _common_type(c.dtype for c in self.children)

    def eval(self, batch):
        dt = self.dtype
        cols = [c.eval(batch) for c in self.children]
        out = cols[0]
        if not dt.is_string:
            tt = dt.jnp_dtype
            cols = [Column(c.data.astype(tt), c.valid, dt, c.lengths)
                    for c in cols]
            out = cols[0]
        for nxt in cols[1:]:
            if dt.is_string:
                ml = max(out.max_len, nxt.max_len)
                o, n = out.pad_strings_to(ml), nxt.pad_strings_to(ml)
                data = jnp.where(o.valid[:, None], o.data, n.data)
                lens = jnp.where(o.valid, o.lengths, n.lengths)
                out = Column(data, jnp.logical_or(o.valid, n.valid),
                             dt, lens)
            else:
                data = jnp.where(out.valid, out.data, nxt.data)
                out = Column(data, jnp.logical_or(out.valid, nxt.valid), dt)
        return out


class NaNvl(BinaryExpression):
    def eval(self, batch):
        l = self.left.eval(batch)
        r = self.right.eval(batch)
        use_r = jnp.isnan(l.data)
        data = jnp.where(use_r, r.data.astype(l.data.dtype), l.data)
        valid = jnp.where(use_r, r.valid, l.valid)
        return Column(data, valid, self.left.dtype).mask_invalid()


# --------------------------------------------------------------------------
# conditionals (reference: rapids/conditionalExpressions.scala)
# --------------------------------------------------------------------------

class If(Expression):
    def __init__(self, pred, then, other):
        self.pred, self.then, self.other = pred, then, other
        self.children = (pred, then, other)

    @property
    def dtype(self):
        return _common_type((self.then.dtype, self.other.dtype))

    def eval(self, batch):
        p = self.pred.eval(batch)
        t = self.then.eval(batch)
        o = self.other.eval(batch)
        cond = jnp.logical_and(p.valid, p.data)
        if self.dtype.is_string:
            ml = max(t.max_len, o.max_len)
            t, o = t.pad_strings_to(ml), o.pad_strings_to(ml)
            data = jnp.where(cond[:, None], t.data, o.data)
            lens = jnp.where(cond, t.lengths, o.lengths)
            valid = jnp.where(cond, t.valid, o.valid)
            return Column(data, valid, self.dtype, lens)
        tt = self.dtype.jnp_dtype
        data = jnp.where(cond, t.data.astype(tt), o.data.astype(tt))
        valid = jnp.where(cond, t.valid, o.valid)
        return Column(data, valid, self.dtype)


class CaseWhen(Expression):
    """branches: [(pred, value), ...], else_value optional."""

    def __init__(self, branches, else_value: Optional[Expression] = None):
        self.branches = list(branches)
        self.else_value = else_value
        ch = []
        for p, v in self.branches:
            ch += [p, v]
        if else_value is not None:
            ch.append(else_value)
        self.children = tuple(ch)

    @property
    def dtype(self):
        dts = [v.dtype for _, v in self.branches]
        if self.else_value is not None:
            dts.append(self.else_value.dtype)
        return _common_type(dts)

    def eval(self, batch):
        expr: Expression = (self.else_value if self.else_value is not None
                            else Literal(None, self.dtype))
        for p, v in reversed(self.branches):
            expr = If(p, v, expr)
        return expr.eval(batch)


# --------------------------------------------------------------------------
# IN (reference: rapids/GpuInSet.scala, predicates In)
# --------------------------------------------------------------------------

class In(Expression):
    def __init__(self, value: Expression, items: List[Any]):
        self.value = value
        self.items = items
        self.children = (value,)

    @property
    def dtype(self):
        return BooleanType

    def eval(self, batch):
        v = self.value.eval(batch)
        non_null = [i for i in self.items if i is not None]
        has_null_item = len(non_null) != len(self.items)
        if v.dtype.is_string:
            hit = jnp.zeros(batch.capacity, dtype=jnp.bool_)
            for item in non_null:
                litc = Literal(item, StringType).eval(batch)
                ml = max(v.max_len, litc.max_len)
                a, b = v.pad_strings_to(ml), litc.pad_strings_to(ml)
                eq = jnp.logical_and(
                    jnp.all(a.data == b.data, axis=1),
                    a.lengths == b.lengths)
                hit = jnp.logical_or(hit, eq)
        else:
            arr = jnp.asarray(np.array(non_null, dtype=v.dtype.np_dtype))
            hit = jnp.any(v.data[:, None] == arr[None, :], axis=1) \
                if len(non_null) else jnp.zeros(batch.capacity, jnp.bool_)
        # Spark: if no match and the list has a null -> null result
        valid = v.valid if not has_null_item \
            else jnp.logical_and(v.valid, hit)
        return Column(hit, valid, BooleanType)


InSet = In  # same device implementation


# --------------------------------------------------------------------------
# bitwise (reference: org/.../rapids/bitwise.scala)
# --------------------------------------------------------------------------

class BitwiseAnd(BinaryExpression):
    def do_op(self, l, r, valid):
        return l & r, valid


class BitwiseOr(BinaryExpression):
    def do_op(self, l, r, valid):
        return l | r, valid


class BitwiseXor(BinaryExpression):
    def do_op(self, l, r, valid):
        return l ^ r, valid


class BitwiseNot(UnaryExpression):
    def do_op(self, x):
        return ~x


class ShiftLeft(BinaryExpression):
    promote_children = False

    def do_op(self, l, r, valid):
        bits = l.dtype.itemsize * 8
        return l << (r.astype(l.dtype) % bits), valid


class ShiftRight(BinaryExpression):
    promote_children = False

    def do_op(self, l, r, valid):
        bits = l.dtype.itemsize * 8
        return l >> (r.astype(l.dtype) % bits), valid


class ShiftRightUnsigned(BinaryExpression):
    promote_children = False

    def do_op(self, l, r, valid):
        bits = l.dtype.itemsize * 8
        shift = (r % bits).astype(jnp.uint64 if bits == 64 else jnp.uint32)
        u = l.astype(jnp.uint64 if bits == 64 else jnp.uint32)
        return (u >> shift).astype(l.dtype), valid


# --------------------------------------------------------------------------
# misc (reference: GpuSparkPartitionID / GpuMonotonicallyIncreasingID / rand)
# --------------------------------------------------------------------------

# Row-offset plumbing: stateful expressions (monotonically_increasing_id,
# rand) need the count of rows in earlier batches of the partition.  The
# executing operator sets a traced offset scalar around expression eval (a
# trace-time context, so it compiles into the jitted per-batch program as an
# ordinary argument).
# thread-local, not a module slot: the serving tier evaluates N queries
# on N worker threads at once, and a shared slot would hand one query's
# partition offset to another query's trace (TPU009)
_ROW_OFFSET = threading.local()


def eval_with_row_offset(fn, batch, offset):
    _ROW_OFFSET.value = offset
    try:
        return fn(batch)
    finally:
        _ROW_OFFSET.value = None


def current_row_offset():
    off = getattr(_ROW_OFFSET, "value", None)
    return jnp.int64(0) if off is None else off


def tree_needs_row_offset(expr: "Expression") -> bool:
    if isinstance(expr, (MonotonicallyIncreasingID, Rand)):
        return True
    return any(tree_needs_row_offset(c) for c in expr.children)


# Per-task input-file provenance (reference: GpuInputFileName /
# GpuInputFileBlockStart/Length read Spark's InputFileBlockHolder,
# org/.../rapids/GpuInputFileBlock.scala).  File scan execs publish the
# (name, block start, block length) of the file each batch came from; the
# expressions bake it into the per-batch program as a constant (the
# executing operator keys its kernel cache on the current holder value, so
# a new file compiles a new constant program — see RowLocalExec.execute).
# Like Spark, the value is only meaningful directly above a file scan;
# elsewhere it is ("", -1, -1).
# thread-local for the same reason as _ROW_OFFSET: concurrent scans on
# scheduler worker threads publish different files at the same time
_INPUT_FILE = threading.local()
_NO_FILE = ("", -1, -1)


def set_input_file(name: str, start: int, length: int) -> None:
    _INPUT_FILE.value = (name, start, length)


def publish_input_file(path: str) -> None:
    """Publish provenance for one whole-file split: start=0, length=file
    size (-1 when unstattable).  The single place the block-semantics rule
    lives; every reader calls this."""
    import os
    try:
        set_input_file(path, 0, os.path.getsize(path))
    except OSError:
        set_input_file(path, 0, -1)


def clear_input_file() -> None:
    _INPUT_FILE.value = _NO_FILE


def current_input_file():
    return getattr(_INPUT_FILE, "value", _NO_FILE)


def tree_needs_input_file(expr: "Expression") -> bool:
    if isinstance(expr, (InputFileName, InputFileBlockStart,
                         InputFileBlockLength)):
        return True
    return any(tree_needs_input_file(c) for c in expr.children)


class InputFileName(Expression):
    """input_file_name(): the file the current batch was read from."""

    @property
    def dtype(self):
        return StringType

    def eval(self, batch):
        return Column.from_strings([current_input_file()[0]]
                                   * batch.capacity)

    def __repr__(self):
        return "input_file_name()"


class _InputFileLong(Expression):
    _slot = 1

    @property
    def dtype(self):
        return LongType

    def eval(self, batch):
        cap = batch.capacity
        v = current_input_file()[self._slot]
        return Column(jnp.full((cap,), v, dtype=jnp.int64),
                      jnp.ones(cap, dtype=jnp.bool_), LongType)


class InputFileBlockStart(_InputFileLong):
    _slot = 1


class InputFileBlockLength(_InputFileLong):
    _slot = 2


class AtLeastNNonNulls(Expression):
    """True when at least n of the children are non-null (and non-NaN for
    float children) — the predicate behind df.na.drop (Spark
    AtLeastNNonNulls semantics)."""

    def __init__(self, n: int, children: Sequence["Expression"]):
        self.n = int(n)
        self.children = tuple(children)

    @property
    def dtype(self):
        return BooleanType

    def eval(self, batch):
        cap = batch.capacity
        count = jnp.zeros(cap, dtype=jnp.int32)
        for ch in self.children:
            c = ch.eval(batch)
            ok = c.valid
            if c.dtype.is_floating:
                ok = ok & ~jnp.isnan(c.data)
            count = count + ok.astype(jnp.int32)
        return Column(count >= self.n, jnp.ones(cap, dtype=jnp.bool_),
                      BooleanType)

    def __repr__(self):
        return f"AtLeastNNonNulls({self.n}, {list(self.children)!r})"


class NormalizeNaNAndZero(UnaryExpression):
    """Canonicalize float values for grouping/join keys: every NaN becomes
    THE NaN, -0.0 becomes 0.0 (Spark NormalizeFloatingNumbers.scala
    semantics; the reference implements it as GpuNormalizeNaNAndZero with
    cuDF normalize_nans_and_zeros)."""

    @property
    def dtype(self):
        return self.child.dtype

    def eval(self, batch):
        c = self.child.eval(batch)
        if not c.dtype.is_floating:
            return c
        x = c.data
        nan = jnp.array(float("nan"), dtype=x.dtype)
        data = jnp.where(jnp.isnan(x), nan,
                         jnp.where(x == 0, jnp.zeros((), x.dtype), x))
        return Column(data, c.valid, c.dtype)


class KnownFloatingPointNormalized(UnaryExpression):
    """Analyzer marker that its input is already normalized — a pure
    passthrough on device, kept so plans containing it stay on TPU."""

    @property
    def dtype(self):
        return self.child.dtype

    def eval(self, batch):
        return self.child.eval(batch)


class SparkPartitionID(Expression):
    def __init__(self, partition_id: int = 0):
        self.partition_id = partition_id

    @property
    def dtype(self):
        return IntegerType

    def eval(self, batch):
        cap = batch.capacity
        return Column(jnp.full((cap,), self.partition_id, dtype=jnp.int32),
                      jnp.ones(cap, dtype=jnp.bool_), IntegerType)


class MonotonicallyIncreasingID(Expression):
    def __init__(self, partition_id: int = 0):
        self.partition_id = partition_id

    @property
    def dtype(self):
        return LongType

    def eval(self, batch):
        cap = batch.capacity
        base = jnp.int64(self.partition_id) << 33
        # position among live rows, offset by rows in earlier batches
        pos = jnp.cumsum(batch.sel.astype(jnp.int64)) - 1 \
            + current_row_offset()
        return Column(base + pos, jnp.ones(cap, dtype=jnp.bool_), LongType)


class Rand(Expression):
    """Philox-style per-row random via jax PRNG keyed on (seed, partition)."""

    def __init__(self, seed: int = 0, partition_id: int = 0):
        self.seed = seed
        self.partition_id = partition_id

    @property
    def dtype(self):
        return DoubleType

    def eval(self, batch):
        import jax
        key = jax.random.PRNGKey(self.seed + self.partition_id * 65537)
        # fold the batch's row offset in so each batch draws fresh values
        key = jax.random.fold_in(key,
                                 current_row_offset().astype(jnp.uint32))
        vals = jax.random.uniform(key, (batch.capacity,), dtype=jnp.float64)
        return Column(vals, jnp.ones(batch.capacity, dtype=jnp.bool_),
                      DoubleType)


class Alias(Expression):
    def __init__(self, child: Expression, alias: str):
        self.child = child
        self.alias = alias
        self.children = (child,)

    @property
    def dtype(self):
        return self.child.dtype

    def eval(self, batch):
        return self.child.eval(batch)

    def __repr__(self):
        return f"{self.child!r} AS {self.alias}"


class _ExtremeN(Expression):
    """least/greatest(e1..en): null-skipping n-ary extreme with Spark float
    semantics (NaN is greater than any non-NaN; result is null only when
    every argument is null)."""

    def __init__(self, *children):
        assert len(children) >= 2, "least/greatest needs >= 2 arguments"
        self.children = tuple(children)

    @property
    def dtype(self):
        return _common_type([c.dtype for c in self.children])

    def eval(self, batch):
        dt = self.dtype
        t = dt.jnp_dtype
        cols = [c.eval(batch) for c in self.children]
        acc_v = cols[0].data.astype(t)
        acc_m = cols[0].valid
        for c in cols[1:]:
            v = c.data.astype(t)
            m = c.valid
            better = self._better(v, acc_v)
            take = m & (~acc_m | better)
            acc_v = jnp.where(take, v, acc_v)
            acc_m = acc_m | m
        return Column(acc_v, acc_m, dt).mask_invalid()

    def _cmp_key(self, x):
        """NaN sorts greatest (Spark ordering)."""
        if jnp.issubdtype(x.dtype, jnp.floating):
            return jnp.where(jnp.isnan(x), jnp.inf, x), jnp.isnan(x)
        return x, None


class Least(_ExtremeN):
    def _better(self, v, acc):
        vk, vn = self._cmp_key(v)
        ak, an = self._cmp_key(acc)
        lt = vk < ak
        if vn is not None:
            # NaN < nothing except... NaN equals NaN; prefer keeping acc
            lt = lt | (~vn & an)
        return lt


class Greatest(_ExtremeN):
    def _better(self, v, acc):
        vk, vn = self._cmp_key(v)
        ak, an = self._cmp_key(acc)
        gt = vk > ak
        if vn is not None:
            gt = gt | (vn & ~an)
        return gt
