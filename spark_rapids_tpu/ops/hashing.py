"""Device hashing kernels.

Two uses, mirroring the reference:
  * murmur3_32 with Spark's seed 42 for hash partitioning parity
    (reference: GpuHashPartitioning.scala — cudf murmur3 matches Spark)
  * 64-bit mix hashes for sort-based grouping/joins (the TPU-first stand-in
    for cuDF's hash tables: we SORT by two independent 64-bit hashes and verify
    equality against the previous row, so a wrong group needs a 128-bit
    double collision *and* adjacency interleave)

All pure jnp integer ops; they trace into the surrounding pipeline program.
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..columnar import Column
from .expressions import Expression as _Expr

def _u(x):
    return x.astype(jnp.uint64)


def mix64(x):
    """splitmix64-style finalizer (uint64 in/out)."""
    x = _u(x)
    x = x ^ (x >> 33)
    x = x * jnp.uint64(0xff51afd7ed558ccd)
    x = x ^ (x >> 33)
    x = x * jnp.uint64(0xc4ceb9fe1a85ec53)
    x = x ^ (x >> 33)
    return x


def f64_bits(d):
    """Injective int64 encoding of a float64 array's values.

    On CPU this is the exact IEEE bit pattern.  On the TPU (axon) backend,
    f64<->int bitcasts are unimplemented (f64 itself is emulated as an
    f32-pair), so the encoding is (bits(hi_f32) << 32) | bits(lo_f32) where
    hi = round-to-f32(d), lo = d - hi — exactly the pair the emulation
    stores, hence injective on every value the device can represent."""
    import jax
    d = d.astype(jnp.float64)
    if jax.default_backend() == "cpu":
        return jax.lax.bitcast_convert_type(d, jnp.int64)
    hi = d.astype(jnp.float32)
    lo = (d - hi.astype(jnp.float64)).astype(jnp.float32)
    hb = jax_bitcast_i32(hi).astype(jnp.int64)
    lb = jax_bitcast_i32(lo).astype(jnp.int64)
    return (hb << jnp.int64(32)) | (lb & jnp.int64(0xFFFFFFFF))


def _normalize_bits(col: Column):
    """Value bits with Spark key semantics: -0.0 == 0.0, all NaN equal."""
    data = col.data
    if col.dtype.is_floating:
        d = data.astype(jnp.float64)
        # -0.0 -> 0.0 via select, NOT `d + 0.0`: XLA's algebraic
        # simplifier folds x+0 away under jit, skipping the normalization
        d = jnp.where(d == 0.0, jnp.float64(0.0), d)
        canonical_nan = jnp.float64(np.nan)
        d = jnp.where(jnp.isnan(d), canonical_nan, d)
        return f64_bits(d)
    if col.dtype.is_string:
        raise AssertionError("use string path")
    if data.dtype == jnp.bool_:
        return data.astype(jnp.int64)
    return data.astype(jnp.int64)


def hash_column64(col: Column, seed: int):
    """uint64 per-row hash of one column (nulls get a fixed tag)."""
    if col.dtype.is_string:
        h = _hash_bytes(col, seed)
    else:
        bits = _normalize_bits(col)
        h = mix64(_u(bits) ^ jnp.uint64(seed * 0x9e3779b97f4a7c15 % 2**64))
    null_h = mix64(jnp.uint64((seed + 0x51ed2701) % 2**64))
    return jnp.where(col.valid, h, null_h)


def _hash_bytes(col: Column, seed: int):
    """Polynomial rolling hash over the byte matrix, mixed; vectorized over
    rows, lax.scan over the (static) max_len positions."""
    import jax
    data = col.data
    cap, L = data.shape
    pos_mask = jnp.arange(L, dtype=jnp.int32)[None, :] < col.lengths[:, None]
    b = jnp.where(pos_mask, data, 0).astype(jnp.uint64)

    def step(carry, cols):
        byte, m = cols
        carry = jnp.where(m, carry * jnp.uint64(1099511628211) ^ byte, carry)
        return carry, None

    # derive the init from a (possibly shard_map-varying) input so the scan
    # carry has the same varying-axes type as xs: a constant init fails
    # vma typing when this runs inside shard_map (distributed string keys)
    vzero = (col.lengths ^ col.lengths).astype(jnp.uint64)
    init = vzero + jnp.uint64((14695981039346656037 + seed * 31) % 2**64)
    h, _ = jax.lax.scan(step, init, (b.T, pos_mask.T))
    return mix64(h ^ _u(col.lengths.astype(jnp.int64)))


def hash_columns_double(cols, live):
    """(h1, h2) independent uint64 hashes over multiple key columns.
    Dead rows get uint64 max so a stable sort pushes them last."""
    h1 = jnp.zeros(live.shape, dtype=jnp.uint64)
    h2 = jnp.zeros(live.shape, dtype=jnp.uint64)
    for i, c in enumerate(cols):
        h1 = mix64(h1 ^ hash_column64(c, 2 * i + 1))
        h2 = mix64(h2 ^ hash_column64(c, 7919 * (i + 1)))
    maxu = jnp.uint64(0xFFFFFFFFFFFFFFFF)
    h1 = jnp.where(live, h1, maxu)
    h2 = jnp.where(live, h2, maxu)
    return h1, h2


# ---- murmur3 32-bit, Spark-compatible (seed 42) ---------------------------

def _rotl32(x, r):
    return (x << jnp.uint32(r)) | (x >> jnp.uint32(32 - r))


def _mmh3_mix_k(k):
    k = k * jnp.uint32(0xcc9e2d51)
    k = _rotl32(k, 15)
    return k * jnp.uint32(0x1b873593)


def _mmh3_mix_h(h, k):
    h = h ^ _mmh3_mix_k(k)
    h = _rotl32(h, 13)
    return h * jnp.uint32(5) + jnp.uint32(0xe6546b64)


def _mmh3_final(h, length):
    h = h ^ jnp.uint32(length)
    h = h ^ (h >> jnp.uint32(16))
    h = h * jnp.uint32(0x85ebca6b)
    h = h ^ (h >> jnp.uint32(13))
    h = h * jnp.uint32(0xc2b2ae35)
    h = h ^ (h >> jnp.uint32(16))
    return h


def _seed_u32(seed, shape):
    if isinstance(seed, (int, np.integer)):
        return jnp.full(shape, np.uint32(seed % 2**32), dtype=jnp.uint32)
    return seed.astype(jnp.uint32)


def murmur3_int(x_i32, seed):
    """Spark hashInt: one 4-byte block."""
    h = _mmh3_mix_h(_seed_u32(seed, x_i32.shape), x_i32.astype(jnp.uint32))
    return _mmh3_final(h, 4).astype(jnp.int32)


def murmur3_long(x_i64, seed):
    """Spark hashLong: low word then high word."""
    u = x_i64.astype(jnp.uint64)
    lo = (u & jnp.uint64(0xFFFFFFFF)).astype(jnp.uint32)
    hi = (u >> jnp.uint64(32)).astype(jnp.uint32)
    h = _mmh3_mix_h(_seed_u32(seed, x_i64.shape), lo)
    h = _mmh3_mix_h(h, hi)
    return _mmh3_final(h, 8).astype(jnp.int32)


def spark_hash_column(col: Column, seed):
    """Spark Murmur3Hash semantics per type (null -> seed passthrough).

    reference: GpuHashPartitioning uses cudf murmur3 which matches Spark's
    Murmur3Hash expression for these types."""
    dt = col.dtype
    if dt.is_string:
        return _spark_hash_string(col, seed)
    if dt.name in ("int", "short", "byte", "date"):
        h = murmur3_int(col.data.astype(jnp.int32), seed)
    elif dt.name in ("long", "timestamp"):
        h = murmur3_long(col.data.astype(jnp.int64), seed)
    elif dt.name == "boolean":
        h = murmur3_int(col.data.astype(jnp.int32), seed)
    elif dt.name == "float":
        # normalize -0.0 and NaN in the INTEGER domain: float compares
        # flush subnormals to zero on XLA (FTZ), which would alias 5e-45
        # with 0.0 while the Spark oracle hashes the true bits
        bits = jax_bitcast_i32(col.data.astype(jnp.float32))
        bits = jnp.where(bits == jnp.int32(-2**31), jnp.int32(0), bits)
        exp = bits & jnp.int32(0x7F800000)
        mant = bits & jnp.int32(0x007FFFFF)
        is_nan = (exp == jnp.int32(0x7F800000)) & (mant != 0)
        bits = jnp.where(is_nan, jnp.int32(0x7FC00000), bits)
        h = murmur3_int(bits, seed)
    elif dt.name == "double":
        # exact Spark bit parity on CPU; injective pair encoding on TPU
        # (documented incompat: emulated f64 has no true IEEE bits)
        bits = f64_bits(col.data.astype(jnp.float64))
        bits = jnp.where(bits == jnp.int64(-2**63), jnp.int64(0), bits)
        exp = bits & jnp.int64(0x7FF0000000000000)
        mant = bits & jnp.int64(0x000FFFFFFFFFFFFF)
        is_nan = (exp == jnp.int64(0x7FF0000000000000)) & (mant != 0)
        bits = jnp.where(is_nan, jnp.int64(0x7FF8000000000000), bits)
        h = murmur3_long(bits, seed)
    else:
        raise NotImplementedError(f"spark hash of {dt.name}")
    if isinstance(seed, (int, np.integer)):
        seed_arr = jnp.full(h.shape, seed, dtype=jnp.int32)
    else:
        seed_arr = seed
    return jnp.where(col.valid, h, seed_arr)


def jax_bitcast_i32(x):
    import jax
    return jax.lax.bitcast_convert_type(x, jnp.int32)


def _spark_hash_string(col: Column, seed):
    """Murmur3 over UTF-8 bytes, 4-byte little-endian blocks + tail, exactly
    Spark's UTF8String hashing."""
    import jax
    data = col.data
    cap, L = data.shape
    nblocks_max = L // 4
    h0 = _seed_u32(seed, (cap,))
    lens = col.lengths
    nblocks = lens // 4

    if nblocks_max > 0:
        blocks = data[:, :nblocks_max * 4].reshape(cap, nblocks_max, 4)
        words = (blocks[:, :, 0].astype(jnp.uint32)
                 | (blocks[:, :, 1].astype(jnp.uint32) << 8)
                 | (blocks[:, :, 2].astype(jnp.uint32) << 16)
                 | (blocks[:, :, 3].astype(jnp.uint32) << 24))

        def step(carry, cols):
            w, active = cols
            nh = _mmh3_mix_h(carry, w)
            return jnp.where(active, nh, carry), None

        active = (jnp.arange(nblocks_max, dtype=jnp.int32)[None, :]
                  < nblocks[:, None])
        h, _ = jax.lax.scan(step, h0, (words.T, active.T))
    else:
        h = h0
    # tail: Spark's hashUnsafeBytes mixes each remaining byte individually
    # as a sign-extended int
    tail_start = nblocks * 4
    for t in range(3):
        idx = jnp.clip(tail_start + t, 0, L - 1)
        byte = jnp.take_along_axis(data, idx[:, None], axis=1)[:, 0]
        sb = byte.astype(jnp.int8).astype(jnp.int32)  # sign-extended
        active = (tail_start + t) < lens
        nh = _mmh3_mix_h(h, sb.astype(jnp.uint32))
        h = jnp.where(active, nh, h)
    # finalizer with per-row byte length
    hh = h ^ lens.astype(jnp.uint32)
    hh = hh ^ (hh >> jnp.uint32(16))
    hh = hh * jnp.uint32(0x85ebca6b)
    hh = hh ^ (hh >> jnp.uint32(13))
    hh = hh * jnp.uint32(0xc2b2ae35)
    hh = hh ^ (hh >> jnp.uint32(16))
    res = hh.astype(jnp.int32)
    seed_arr = _seed_u32(seed, res.shape).astype(jnp.int32)
    return jnp.where(col.valid, res, seed_arr)


def spark_hash_columns(cols, seed: int = 42):
    """Spark's Murmur3Hash(cols): fold, each column re-seeding with the
    previous hash."""
    h = None
    for c in cols:
        h = spark_hash_column(c, seed if h is None else h)
    return h


class Murmur3Hash(_Expr):
    """Spark `hash(...)` expression: murmur3_32 folded across the argument
    columns with seed 42, nulls passing the running seed through unchanged
    (reference: Murmur3Hash in HashExpression; GpuMurmur3Hash delegates to
    the same cudf kernel the partitioner uses)."""

    def __init__(self, *children, seed: int = 42):
        self.children = tuple(children)
        self.seed = int(seed)

    @property
    def dtype(self):
        from ..types import IntegerType
        return IntegerType

    def eval(self, batch):
        from ..types import IntegerType
        h = self.seed
        for ch in self.children:
            h = spark_hash_column(ch.eval(batch), h)
        cap = batch.capacity
        if isinstance(h, int):  # no children: constant seed
            h = jnp.full(cap, h, dtype=jnp.int32)
        valid = jnp.ones(cap, dtype=jnp.bool_)
        return Column(h.astype(jnp.int32), valid, IntegerType)
