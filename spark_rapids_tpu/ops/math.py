"""Math expression library (reference: org/.../rapids/mathExpressions.scala).

Spark semantics: math functions take/return DoubleType (the analyzer casts
inputs); Log-family returns NULL for non-positive inputs (unlike cuDF's -inf,
which the reference flags as incompat)."""
from __future__ import annotations

import jax.numpy as jnp

from ..columnar import Column
from ..types import DoubleType, LongType, IntegerType
from .expressions import (BinaryExpression, Expression, UnaryExpression)


class _DoubleUnary(UnaryExpression):
    @property
    def dtype(self):
        return DoubleType

    def eval(self, batch):
        c = self.child.eval(batch)
        x = c.data.astype(jnp.float64)
        data = self.do_op(x)
        return Column(data, c.valid, DoubleType)


class Sqrt(_DoubleUnary):
    def do_op(self, x):
        return jnp.sqrt(x)


class Cbrt(_DoubleUnary):
    def do_op(self, x):
        return jnp.cbrt(x)


class Exp(_DoubleUnary):
    def do_op(self, x):
        return jnp.exp(x)


class Expm1(_DoubleUnary):
    def do_op(self, x):
        return jnp.expm1(x)


class _LogBase(_DoubleUnary):
    """null for x <= lower bound, matching Spark's nullSafeEval."""

    lower = 0.0

    def eval(self, batch):
        c = self.child.eval(batch)
        x = c.data.astype(jnp.float64)
        ok = x > self.lower
        data = self.do_op(jnp.where(ok, x, 1.0))
        return Column(data, jnp.logical_and(c.valid, ok), DoubleType)


class Log(_LogBase):
    def do_op(self, x):
        return jnp.log(x)


class Log2(_LogBase):
    def do_op(self, x):
        return jnp.log2(x)


class Log10(_LogBase):
    def do_op(self, x):
        return jnp.log10(x)


class Log1p(_LogBase):
    lower = -1.0

    def do_op(self, x):
        return jnp.log1p(x)


class Sin(_DoubleUnary):
    def do_op(self, x):
        return jnp.sin(x)


class Cos(_DoubleUnary):
    def do_op(self, x):
        return jnp.cos(x)


class Tan(_DoubleUnary):
    def do_op(self, x):
        return jnp.tan(x)


class Asin(_DoubleUnary):
    def do_op(self, x):
        return jnp.arcsin(x)


class Acos(_DoubleUnary):
    def do_op(self, x):
        return jnp.arccos(x)


class Atan(_DoubleUnary):
    def do_op(self, x):
        return jnp.arctan(x)


class Sinh(_DoubleUnary):
    def do_op(self, x):
        return jnp.sinh(x)


class Cosh(_DoubleUnary):
    def do_op(self, x):
        return jnp.cosh(x)


class Tanh(_DoubleUnary):
    def do_op(self, x):
        return jnp.tanh(x)


class Asinh(_DoubleUnary):
    def do_op(self, x):
        return jnp.arcsinh(x)


class Acosh(_DoubleUnary):
    def do_op(self, x):
        # x < 1 -> NaN, matching StrictMath.log(x + sqrt(x*x - 1)) domain
        return jnp.arccosh(x)


class Atanh(_DoubleUnary):
    def do_op(self, x):
        return jnp.arctanh(x)


class ToDegrees(_DoubleUnary):
    def do_op(self, x):
        return jnp.degrees(x)


class ToRadians(_DoubleUnary):
    def do_op(self, x):
        return jnp.radians(x)


class Signum(_DoubleUnary):
    def do_op(self, x):
        return jnp.sign(x)


class Floor(UnaryExpression):
    @property
    def dtype(self):
        return LongType if self.child.dtype.is_floating else self.child.dtype

    def eval(self, batch):
        c = self.child.eval(batch)
        if not self.child.dtype.is_floating:
            return c
        return Column(jnp.floor(c.data).astype(jnp.int64), c.valid, LongType)


class Ceil(UnaryExpression):
    @property
    def dtype(self):
        return LongType if self.child.dtype.is_floating else self.child.dtype

    def eval(self, batch):
        c = self.child.eval(batch)
        if not self.child.dtype.is_floating:
            return c
        return Column(jnp.ceil(c.data).astype(jnp.int64), c.valid, LongType)


class Rint(_DoubleUnary):
    def do_op(self, x):
        return jnp.round(x)  # banker's rounding, matches Math.rint


class Pow(BinaryExpression):
    @property
    def dtype(self):
        return DoubleType

    def do_op(self, l, r, valid):
        return jnp.power(l.astype(jnp.float64), r.astype(jnp.float64)), valid


class Atan2(BinaryExpression):
    @property
    def dtype(self):
        return DoubleType

    def do_op(self, l, r, valid):
        return jnp.arctan2(l.astype(jnp.float64), r.astype(jnp.float64)), valid


class Cot(_DoubleUnary):
    def do_op(self, x):
        return 1.0 / jnp.tan(x)


class Hypot(BinaryExpression):
    @property
    def dtype(self):
        return DoubleType

    def do_op(self, l, r, valid):
        return jnp.hypot(l.astype(jnp.float64), r.astype(jnp.float64)), valid


class Logarithm(BinaryExpression):
    """log(base, x): null when x <= 0 or base <= 0 (Spark nullSafeEval)."""

    @property
    def dtype(self):
        return DoubleType

    def do_op(self, base, x, valid):
        b = base.astype(jnp.float64)
        v = x.astype(jnp.float64)
        ok = (v > 0.0) & (b > 0.0)
        out = jnp.log(jnp.where(v > 0, v, 1.0)) \
            / jnp.log(jnp.where(b > 0, b, 2.0))
        return out, valid & ok


class _RoundBase(Expression):
    """round/bround(child, scale) with literal scale.  Spark semantics:
    HALF_UP (round) / HALF_EVEN (bround) at decimal `scale`; integral
    inputs with scale >= 0 are unchanged."""

    def __init__(self, child, scale=None):
        from .expressions import Literal
        self.child = child
        self.scale = scale if scale is not None else Literal(0)
        self.children = (child, self.scale)

    @property
    def dtype(self):
        return self.child.dtype

    def _scale(self) -> int:
        from .expressions import Literal
        if isinstance(self.scale, Literal) and \
                isinstance(self.scale.value, int):
            return int(self.scale.value)
        raise ValueError("round scale must be an integer literal")

    def device_supported(self) -> bool:
        try:
            self._scale()
            return True
        except ValueError:
            return False

    def eval(self, batch):
        c = self.child.eval(batch)
        s = self._scale()
        if c.dtype.is_integral:
            if s >= 0:
                return c
            import numpy as _np
            if 10 ** (-s) > int(_np.iinfo(c.data.dtype).max):
                # every digit rounded away: Spark's BigDecimal yields 0
                return Column(jnp.zeros_like(c.data), c.valid, c.dtype)
            p = jnp.asarray(10 ** (-s), dtype=c.data.dtype)
            half = p // 2
            x = c.data
            q = x // p
            rem = x - q * p
            if self.half_even:
                up = (rem > half) | ((rem == half) & (q % 2 != 0))
            else:
                # HALF_UP on the absolute value
                up = jnp.where(x >= 0, rem >= half, rem > half)
            return Column((q + up.astype(c.data.dtype)) * p, c.valid,
                          c.dtype)
        x = c.data.astype(jnp.float64)
        p = jnp.float64(10.0 ** s)
        scaled = x * p
        if self.half_even:
            r = jnp.rint(scaled)
        else:
            r = jnp.trunc(scaled + jnp.where(scaled >= 0, 0.5, -0.5))
        out = r / p
        out = jnp.where(jnp.isfinite(x), out, x)
        return Column(out.astype(c.dtype.jnp_dtype), c.valid, c.dtype)


class Round(_RoundBase):
    half_even = False


class BRound(_RoundBase):
    half_even = True
