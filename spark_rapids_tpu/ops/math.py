"""Math expression library (reference: org/.../rapids/mathExpressions.scala).

Spark semantics: math functions take/return DoubleType (the analyzer casts
inputs); Log-family returns NULL for non-positive inputs (unlike cuDF's -inf,
which the reference flags as incompat)."""
from __future__ import annotations

import jax.numpy as jnp

from ..columnar import Column
from ..types import DoubleType, LongType, IntegerType
from .expressions import (BinaryExpression, Expression, UnaryExpression)


class _DoubleUnary(UnaryExpression):
    @property
    def dtype(self):
        return DoubleType

    def eval(self, batch):
        c = self.child.eval(batch)
        x = c.data.astype(jnp.float64)
        data = self.do_op(x)
        return Column(data, c.valid, DoubleType)


class Sqrt(_DoubleUnary):
    def do_op(self, x):
        return jnp.sqrt(x)


class Cbrt(_DoubleUnary):
    def do_op(self, x):
        return jnp.cbrt(x)


class Exp(_DoubleUnary):
    def do_op(self, x):
        return jnp.exp(x)


class Expm1(_DoubleUnary):
    def do_op(self, x):
        return jnp.expm1(x)


class _LogBase(_DoubleUnary):
    """null for x <= lower bound, matching Spark's nullSafeEval."""

    lower = 0.0

    def eval(self, batch):
        c = self.child.eval(batch)
        x = c.data.astype(jnp.float64)
        ok = x > self.lower
        data = self.do_op(jnp.where(ok, x, 1.0))
        return Column(data, jnp.logical_and(c.valid, ok), DoubleType)


class Log(_LogBase):
    def do_op(self, x):
        return jnp.log(x)


class Log2(_LogBase):
    def do_op(self, x):
        return jnp.log2(x)


class Log10(_LogBase):
    def do_op(self, x):
        return jnp.log10(x)


class Log1p(_LogBase):
    lower = -1.0

    def do_op(self, x):
        return jnp.log1p(x)


class Sin(_DoubleUnary):
    def do_op(self, x):
        return jnp.sin(x)


class Cos(_DoubleUnary):
    def do_op(self, x):
        return jnp.cos(x)


class Tan(_DoubleUnary):
    def do_op(self, x):
        return jnp.tan(x)


class Asin(_DoubleUnary):
    def do_op(self, x):
        return jnp.arcsin(x)


class Acos(_DoubleUnary):
    def do_op(self, x):
        return jnp.arccos(x)


class Atan(_DoubleUnary):
    def do_op(self, x):
        return jnp.arctan(x)


class Sinh(_DoubleUnary):
    def do_op(self, x):
        return jnp.sinh(x)


class Cosh(_DoubleUnary):
    def do_op(self, x):
        return jnp.cosh(x)


class Tanh(_DoubleUnary):
    def do_op(self, x):
        return jnp.tanh(x)


class ToDegrees(_DoubleUnary):
    def do_op(self, x):
        return jnp.degrees(x)


class ToRadians(_DoubleUnary):
    def do_op(self, x):
        return jnp.radians(x)


class Signum(_DoubleUnary):
    def do_op(self, x):
        return jnp.sign(x)


class Floor(UnaryExpression):
    @property
    def dtype(self):
        return LongType if self.child.dtype.is_floating else self.child.dtype

    def eval(self, batch):
        c = self.child.eval(batch)
        if not self.child.dtype.is_floating:
            return c
        return Column(jnp.floor(c.data).astype(jnp.int64), c.valid, LongType)


class Ceil(UnaryExpression):
    @property
    def dtype(self):
        return LongType if self.child.dtype.is_floating else self.child.dtype

    def eval(self, batch):
        c = self.child.eval(batch)
        if not self.child.dtype.is_floating:
            return c
        return Column(jnp.ceil(c.data).astype(jnp.int64), c.valid, LongType)


class Rint(_DoubleUnary):
    def do_op(self, x):
        return jnp.round(x)  # banker's rounding, matches Math.rint


class Pow(BinaryExpression):
    @property
    def dtype(self):
        return DoubleType

    def do_op(self, l, r, valid):
        return jnp.power(l.astype(jnp.float64), r.astype(jnp.float64)), valid


class Atan2(BinaryExpression):
    @property
    def dtype(self):
        return DoubleType

    def do_op(self, l, r, valid):
        return jnp.arctan2(l.astype(jnp.float64), r.astype(jnp.float64)), valid
