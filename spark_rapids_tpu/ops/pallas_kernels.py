"""Pallas TPU kernels for hot inner loops.

`cumsum_1d` is the prefix-sum that the segmented aggregation path turns
scatter-adds into (exec/aggregate.py _seg_sum): one sequential-grid pass
where each (8, 128) tile computes its local prefix sum on the VPU and a
scalar carry in SMEM threads the running total across tiles — the TPU
grid executes in order, which is exactly what a carry needs (pallas guide:
grids are sequential on TPU).  XLA's own cumsum is a log-depth scan of
full-array passes; the fused single pass halves HBM traffic for long
columns.

Gated by `spark.rapids.sql.tpu.pallas.enabled` (default off) and used
opportunistically: any pallas failure (unsupported dtype — 64-bit types
are emulated on current chips — or an interpret-less CPU backend) falls
back to `jnp.cumsum` at the call site.  Tests exercise the kernel in
interpret mode on the CPU backend (tests/test_pallas.py).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

_LANES = 128
_SUBLANES = 8
_BLOCK = _LANES * _SUBLANES


def _cumsum_kernel(x_ref, o_ref, carry_ref):
    """One (8, 128) tile: row-major local prefix sum + carry-in."""
    from jax.experimental import pallas as pl

    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        carry_ref[0] = jnp.zeros((), carry_ref.dtype)

    blk = x_ref[:]                                  # (8, 128)
    within = jnp.cumsum(blk, axis=1)                # per-row prefix
    row_tot = within[:, -1:]                        # (8, 1)
    row_off = jnp.cumsum(row_tot, axis=0) - row_tot  # exclusive row offset
    carry = carry_ref[0]
    o_ref[:] = within + row_off + carry
    carry_ref[0] = carry + row_off[-1, 0] + row_tot[-1, 0]


def cumsum_1d(v, interpret: bool = False):
    """Inclusive prefix sum of a 1-D array whose length is a multiple of
    1024 (the engine's capacity buckets guarantee this)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n = v.shape[0]
    if n % _BLOCK:
        raise ValueError(f"length {n} not a multiple of {_BLOCK}")
    x = v.reshape(n // _LANES, _LANES)
    grid = (n // _BLOCK,)
    out = pl.pallas_call(
        _cumsum_kernel,
        out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
        grid=grid,
        in_specs=[pl.BlockSpec((_SUBLANES, _LANES),
                               lambda i: (i, 0))],
        out_specs=pl.BlockSpec((_SUBLANES, _LANES), lambda i: (i, 0)),
        scratch_shapes=[pltpu.SMEM((1,), x.dtype)],
        interpret=interpret,
    )(x)
    return out.reshape(n)
