"""Pallas TPU kernels for hot inner loops.

`cumsum_1d` is the prefix-sum that the segmented aggregation path turns
scatter-adds into (exec/aggregate.py _seg_sum): one sequential-grid pass
where each (8, 128) tile computes its local prefix sum on the VPU and a
scalar carry in SMEM threads the running total across tiles — the TPU
grid executes in order, which is exactly what a carry needs (pallas guide:
grids are sequential on TPU).  XLA's own cumsum is a log-depth scan of
full-array passes; the fused single pass halves HBM traffic for long
columns.

`seg_agg_1d` generalizes the same carry pattern into a fused SEGMENTED
scan: for sorted group ids it computes, in ONE pass over the rows, the
running sum/min/max (restarting at every segment boundary) of ANY number
of value columns at once — all requested aggregates of a group-by read
gid and each value column exactly once, where the XLA formulation pays
one full scatter/prefix pass per aggregate.  The per-segment results are
the running values at each segment's last row (exec/aggregate.py gathers
them with one shared searchsorted pair).

`bitonic_sort_u64` is the tiled bitonic network behind the packed-key
sort (utils/packed_sort): blocks sort locally in VMEM, cross-block merge
substages are elementwise min/max between paired blocks (at distances >=
a block the bitonic pairing lines up element offsets), sub-block tails
run in-VMEM — O(log^2) passes but each one streams HBM linearly instead
of the sort HLO's comparator loop.

Gated by `spark.rapids.sql.tpu.pallas.enabled` (default off) and used
opportunistically: any pallas failure (unsupported dtype — 64-bit types
are emulated on current chips — or an interpret-less CPU backend) falls
back to the XLA lowering at the call site.  Tests exercise every kernel
in interpret mode on the CPU backend (tests/test_pallas.py).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

_LANES = 128
_SUBLANES = 8
_BLOCK = _LANES * _SUBLANES


def _cumsum_kernel(x_ref, o_ref, carry_ref):
    """One (8, 128) tile: row-major local prefix sum + carry-in."""
    from jax.experimental import pallas as pl

    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        carry_ref[0] = jnp.zeros((), carry_ref.dtype)

    blk = x_ref[:]                                  # (8, 128)
    within = jnp.cumsum(blk, axis=1)                # per-row prefix
    row_tot = within[:, -1:]                        # (8, 1)
    row_off = jnp.cumsum(row_tot, axis=0) - row_tot  # exclusive row offset
    carry = carry_ref[0]
    o_ref[:] = within + row_off + carry
    carry_ref[0] = carry + row_off[-1, 0] + row_tot[-1, 0]


def cumsum_1d(v, interpret: bool = False):
    """Inclusive prefix sum of a 1-D array whose length is a multiple of
    1024 (the engine's capacity buckets guarantee this)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n = v.shape[0]
    if n % _BLOCK:
        raise ValueError(f"length {n} not a multiple of {_BLOCK}")
    x = v.reshape(n // _LANES, _LANES)
    grid = (n // _BLOCK,)
    out = pl.pallas_call(
        _cumsum_kernel,
        out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
        grid=grid,
        in_specs=[pl.BlockSpec((_SUBLANES, _LANES),
                               lambda i: (i, 0))],
        out_specs=pl.BlockSpec((_SUBLANES, _LANES), lambda i: (i, 0)),
        scratch_shapes=[pltpu.SMEM((1,), x.dtype)],
        interpret=interpret,
    )(x)
    return out.reshape(n)


# --------------------------------------------------------------------------
# fused segmented scan (single-pass multi-aggregate group-by reducer)
# --------------------------------------------------------------------------

_COMBINE = {"sum": lambda a, b: a + b,
            "min": jnp.minimum,
            "max": jnp.maximum}


def _make_seg_agg_kernel(ops):
    """Kernel over one (8, 128) tile: segmented inclusive scan of every
    value ref (restarting where gid changes), with a (last_gid, running
    value per op) carry in SMEM threading segments that span tiles."""
    from jax.experimental import pallas as pl

    k = len(ops)

    def kernel(*refs):
        g_ref = refs[0]
        v_refs = refs[1:1 + k]
        o_refs = refs[1 + k:1 + 2 * k]
        cg_ref = refs[1 + 2 * k]
        cv_refs = refs[2 + 2 * k:]
        i = pl.program_id(0)

        @pl.when(i == 0)
        def _init():
            cg_ref[0] = jnp.int32(-1)  # gid >= 0: never matches
            for cv in cv_refs:
                cv[0] = jnp.zeros((), cv.dtype)

        g = g_ref[:]                                      # (8, 128)
        lane = jax.lax.broadcasted_iota(jnp.int32, g.shape, 1)
        row = jax.lax.broadcasted_iota(jnp.int32, g.shape, 0)
        gl = g[:, -1:]                                    # (8, 1) row-end gid
        # row-level segmented-scan masks are shared by every value col
        row1 = jax.lax.broadcasted_iota(jnp.int32, gl.shape, 0)
        carry_g_tile = cg_ref[0]
        for vi, op in enumerate(ops):
            comb = _COMBINE[op]
            v = v_refs[vi][:]
            # 1) within-row segmented Hillis-Steele scan (log2(128) steps)
            for d in (1, 2, 4, 8, 16, 32, 64):
                ok = (lane >= d) & (jnp.roll(g, d, axis=1) == g)
                v = jnp.where(ok, comb(v, jnp.roll(v, d, axis=1)), v)
            # 2) row carries: segmented scan over the 8 row summaries
            vl = v[:, -1:]
            for d in (1, 2, 4):
                ok = (row1 >= d) & (jnp.roll(gl, d, axis=0) == gl)
                vl = jnp.where(ok, comb(vl, jnp.roll(vl, d, axis=0)), vl)
            carry_g_rows = jnp.roll(gl, 1, axis=0)        # row r <- row r-1
            carry_v_rows = jnp.roll(vl, 1, axis=0)
            v = jnp.where((row >= 1) & (g == carry_g_rows),
                          comb(v, carry_v_rows), v)
            # 3) cross-tile carry from SMEM (the leading run of this tile
            # continues the previous tile's trailing segment)
            v = jnp.where(g == carry_g_tile,
                          comb(v, cv_refs[vi][0]), v)
            o_refs[vi][:] = v
            cv_refs[vi][0] = v[-1, -1]
        cg_ref[0] = g[-1, -1]
    return kernel


def seg_agg_1d(gid, vals, ops, interpret: bool = False):
    """Fused segmented running aggregates.

    `gid`: int32 [n], sorted ascending (n a multiple of 1024 — the
    engine's capacity buckets guarantee it).  `vals`: sequence of [n]
    value arrays (pre-masked: non-contributing rows already hold the
    op's identity).  `ops`: matching 'sum'|'min'|'max' names.

    Returns one [n] array per value: the INCLUSIVE running aggregate of
    the segment containing each row, restarting at every boundary — so
    the value at a segment's last row is that segment's full reduction
    (exec/aggregate.py gathers those with one shared searchsorted pair).
    All values stream through ONE kernel pass: gid and each column are
    read exactly once."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n = gid.shape[0]
    if n % _BLOCK:
        raise ValueError(f"length {n} not a multiple of {_BLOCK}")
    if not vals or len(vals) != len(ops):
        raise ValueError("vals/ops mismatch")
    for op in ops:
        if op not in _COMBINE:
            raise ValueError(f"unknown op {op!r}")
    rows = n // _LANES
    g2 = gid.astype(jnp.int32).reshape(rows, _LANES)
    vs2 = [v.reshape(rows, _LANES) for v in vals]
    spec = pl.BlockSpec((_SUBLANES, _LANES), lambda i: (i, 0))
    outs = pl.pallas_call(
        _make_seg_agg_kernel(tuple(ops)),
        out_shape=[jax.ShapeDtypeStruct(v.shape, v.dtype) for v in vs2],
        grid=(n // _BLOCK,),
        in_specs=[spec] * (1 + len(vs2)),
        out_specs=[spec] * len(vs2),
        scratch_shapes=([pltpu.SMEM((1,), jnp.int32)]
                        + [pltpu.SMEM((1,), v.dtype) for v in vs2]),
        interpret=interpret,
    )(g2, *vs2)
    if not isinstance(outs, (list, tuple)):
        outs = [outs]
    return [o.reshape(n) for o in outs]


# --------------------------------------------------------------------------
# tiled bitonic sort (packed-key sort backend)
# --------------------------------------------------------------------------

def _xor_permute(v, d):
    """v with positions XOR-shuffled by distance d inside one (8, 128)
    tile (row-major index i -> i ^ d).  Built from reshape + flip only —
    pallas kernels may not capture index-array constants, and an XOR
    shuffle by a power of two is exactly a pairwise swap of d-wide
    groups: reshape to (..., 2, d) and reverse the pair axis."""
    r, c = v.shape
    if d < _LANES:
        x = v.reshape(r, c // (2 * d), 2, d)
        return jnp.flip(x, axis=2).reshape(r, c)
    dr = d // _LANES
    x = v.reshape(r // (2 * dr), 2, dr, c)
    return jnp.flip(x, axis=1).reshape(r, c)


def _make_bitonic_local_kernel(k_lo, k_hi):
    """Per-block kernel running stages k_lo..k_hi's sub-block substages
    (d < 1024) in VMEM.  For the initial local sort (k_lo=1) directions
    vary WITHIN the block; for a global stage's tail they are constant
    per block — both fall out of the global-index direction bit."""
    from jax.experimental import pallas as pl

    def kernel(x_ref, o_ref):
        bi = pl.program_id(0)
        v = x_ref[:]
        lane = jax.lax.broadcasted_iota(jnp.int32, v.shape, 1)
        row = jax.lax.broadcasted_iota(jnp.int32, v.shape, 0)
        local = row * _LANES + lane
        gidx = bi * _BLOCK + local                        # global index
        for k in range(k_lo, k_hi + 1):
            d0 = min(1 << (k - 1), _BLOCK // 2)
            d = d0
            while d >= 1:
                pv = _xor_permute(v, d)
                lower = (gidx & d) == 0
                asc = ((gidx >> k) & 1) == 0
                take_min = lower == asc
                v = jnp.where(take_min, jnp.minimum(v, pv),
                              jnp.maximum(v, pv))
                d //= 2
        o_ref[:] = v
    return kernel


def _make_bitonic_merge_kernel(k, d):
    """Cross-block substage: output block bi = elementwise min/max of
    blocks bi and bi ^ (d/1024); at distances >= a block the bitonic
    pairing lines up element offsets, so no shuffle is needed."""
    from jax.experimental import pallas as pl
    bd = d // _BLOCK

    def kernel(a_ref, b_ref, o_ref):
        bi = pl.program_id(0)
        a = a_ref[:]
        b = b_ref[:]
        lower = (bi & bd) == 0
        asc = (((bi * _BLOCK) >> k) & 1) == 0
        take_min = lower == asc
        o_ref[:] = jnp.where(take_min, jnp.minimum(a, b),
                             jnp.maximum(a, b))
    return kernel


def bitonic_sort_u64(keys, interpret: bool = False):
    """Ascending sort of a uint64 array whose length is a power of two
    and a multiple of 1024 (utils/packed_sort feeds packed words at the
    engine's capacity buckets).  Tiled bitonic network: one local-sort
    pass, then per global stage its cross-block substages (elementwise
    paired-block min/max) and one sub-block tail pass."""
    from jax.experimental import pallas as pl

    n = keys.shape[0]
    if n % _BLOCK or n & (n - 1):
        raise ValueError(f"length {n} not a power-of-two multiple "
                         f"of {_BLOCK}")
    rows = n // _LANES
    x = keys.reshape(rows, _LANES)
    nblocks = n // _BLOCK
    block_log2 = _BLOCK.bit_length() - 1  # 10
    spec = pl.BlockSpec((_SUBLANES, _LANES), lambda i: (i, 0))
    shape = jax.ShapeDtypeStruct(x.shape, x.dtype)

    # initial local sort: stages 1..10 entirely inside each block
    x = pl.pallas_call(
        _make_bitonic_local_kernel(1, min(block_log2, n.bit_length() - 1)),
        out_shape=shape, grid=(nblocks,), in_specs=[spec],
        out_specs=spec, interpret=interpret)(x)
    # global stages: cross-block substages then the sub-block tail
    for k in range(block_log2 + 1, n.bit_length()):
        d = 1 << (k - 1)
        while d >= _BLOCK:
            bd = d // _BLOCK
            x = pl.pallas_call(
                _make_bitonic_merge_kernel(k, d),
                out_shape=shape, grid=(nblocks,),
                in_specs=[spec,
                          pl.BlockSpec((_SUBLANES, _LANES),
                                       lambda i, _bd=bd: (i ^ _bd, 0))],
                out_specs=spec, interpret=interpret)(x, x)
            d //= 2
        x = pl.pallas_call(
            _make_bitonic_local_kernel(k, k),
            out_shape=shape, grid=(nblocks,), in_specs=[spec],
            out_specs=spec, interpret=interpret)(x)
    return x.reshape(n)
