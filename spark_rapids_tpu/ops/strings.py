"""String expression library over byte-matrix columns.

Reference: org/.../rapids/stringFunctions.scala (upper/lower/substring/
locate/replace/trim/startsWith/endsWith/concat/contains/Like/Length).

Device representation is uint8[rows, max_len] + int32 lengths (see
columnar/column.py).  Everything here is plain vectorized VPU arithmetic —
no scatter, no per-row loops — so XLA fuses string predicates into the same
program as the rest of the pipeline.  Multi-byte UTF-8: Length counts
characters; case-mapping and substring positions are ASCII-exact (documented
incompat, like the reference's unicode carve-outs).

Pattern-matching ops (StartsWith/EndsWith/Contains/Like/Locate/Replace)
require a LITERAL pattern, as in the reference (tagged otherwise).
"""
from __future__ import annotations

from typing import Optional

import jax.numpy as jnp
import numpy as np

from ..columnar import Column, bucket_strlen
from ..types import (BooleanType, IntegerType, StringType)
from .expressions import Expression, Literal


def _literal_bytes(e: Expression) -> bytes:
    if isinstance(e, Literal) and isinstance(e.value, str):
        return e.value.encode("utf-8")
    raise ValueError("pattern must be a string literal")


def _is_cont(b):
    """UTF-8 continuation byte?"""
    return (b & 0xC0) == 0x80


class _StringUnary(Expression):
    def __init__(self, child: Expression):
        self.child = child
        self.children = (child,)

    @property
    def dtype(self):
        return StringType


class Upper(_StringUnary):
    def eval(self, batch):
        c = self.child.eval(batch)
        lower = (c.data >= ord("a")) & (c.data <= ord("z"))
        return Column(jnp.where(lower, c.data - 32, c.data), c.valid,
                      StringType, c.lengths)


class Lower(_StringUnary):
    def eval(self, batch):
        c = self.child.eval(batch)
        upper = (c.data >= ord("A")) & (c.data <= ord("Z"))
        return Column(jnp.where(upper, c.data + 32, c.data), c.valid,
                      StringType, c.lengths)


class Length(_StringUnary):
    """Character count (UTF-8 aware: skip continuation bytes)."""

    @property
    def dtype(self):
        return IntegerType

    def eval(self, batch):
        c = self.child.eval(batch)
        pos = jnp.arange(c.max_len, dtype=jnp.int32)[None, :]
        in_range = pos < c.lengths[:, None]
        starts = in_range & ~_is_cont(c.data)
        return Column(jnp.sum(starts, axis=1).astype(jnp.int32), c.valid,
                      IntegerType)


class StringTrim(_StringUnary):
    def eval(self, batch):
        from .cast import _trim_ws
        return _trim_ws(self.child.eval(batch))


class StringTrimLeft(_StringUnary):
    def eval(self, batch):
        c = self.child.eval(batch)
        data, lens = c.data, c.lengths
        L = c.max_len
        pos = jnp.arange(L, dtype=jnp.int32)[None, :]
        in_range = pos < lens[:, None]
        nonws = (data > 0x20) & in_range
        start = jnp.min(jnp.where(nonws, pos, L), axis=1)
        new_lens = jnp.maximum(lens - start, 0).astype(jnp.int32)
        idx = jnp.clip(pos + start[:, None], 0, L - 1)
        shifted = jnp.take_along_axis(data, idx, axis=1)
        shifted = jnp.where(pos < new_lens[:, None], shifted, 0)
        return Column(shifted, c.valid, StringType, new_lens)


class StringTrimRight(_StringUnary):
    def eval(self, batch):
        c = self.child.eval(batch)
        pos = jnp.arange(c.max_len, dtype=jnp.int32)[None, :]
        in_range = pos < c.lengths[:, None]
        nonws = (c.data > 0x20) & in_range
        end = jnp.max(jnp.where(nonws, pos + 1, 0), axis=1).astype(jnp.int32)
        data = jnp.where(pos < end[:, None], c.data, 0)
        return Column(data, c.valid, StringType, end)


class Substring(Expression):
    """Spark substring(str, pos, len): 1-based, negative pos counts from the
    end, pos=0 treated as 1.  Byte-positioned (ASCII-exact)."""

    def __init__(self, child, pos, length):
        self.child, self.pos, self.length = child, pos, length
        self.children = (child, pos, length)

    @property
    def dtype(self):
        return StringType

    def eval(self, batch):
        c = self.child.eval(batch)
        p = self.pos.eval(batch).data.astype(jnp.int32)
        n = self.length.eval(batch).data.astype(jnp.int32)
        L = c.max_len
        lens = c.lengths
        # resolve 1-based/negative start to 0-based
        start = jnp.where(p > 0, p - 1, jnp.where(p < 0, lens + p, 0))
        start = jnp.clip(start, 0, lens)
        stop = jnp.clip(start + jnp.maximum(n, 0), start, lens)
        new_lens = (stop - start).astype(jnp.int32)
        pos_m = jnp.arange(L, dtype=jnp.int32)[None, :]
        idx = jnp.clip(pos_m + start[:, None], 0, L - 1)
        shifted = jnp.take_along_axis(c.data, idx, axis=1)
        shifted = jnp.where(pos_m < new_lens[:, None], shifted, 0)
        return Column(shifted, c.valid, StringType, new_lens)


class Concat(Expression):
    def __init__(self, *children):
        self.children = tuple(children)

    @property
    def dtype(self):
        return StringType

    def eval(self, batch):
        cols = [ch.eval(batch) for ch in self.children]
        out = cols[0]
        for nxt in cols[1:]:
            out = _concat2(out, nxt)
        return out


def _concat2(a: Column, b: Column) -> Column:
    L = bucket_strlen(a.max_len + b.max_len)
    a = a.pad_strings_to(L)
    b = b.pad_strings_to(L)
    pos = jnp.arange(L, dtype=jnp.int32)[None, :]
    bidx = jnp.clip(pos - a.lengths[:, None], 0, L - 1)
    b_shifted = jnp.take_along_axis(b.data, bidx, axis=1)
    data = jnp.where(pos < a.lengths[:, None], a.data, b_shifted)
    lens = a.lengths + b.lengths
    data = jnp.where(pos < lens[:, None], data, 0)
    valid = a.valid & b.valid
    return Column(data, valid, StringType, lens.astype(jnp.int32))


class _PatternPredicate(Expression):
    def __init__(self, child, pattern):
        self.child, self.pattern = child, pattern
        self.children = (child, pattern)

    @property
    def dtype(self):
        return BooleanType

    def _pat(self) -> bytes:
        return _literal_bytes(self.pattern)


class StartsWith(_PatternPredicate):
    def eval(self, batch):
        c = self.child.eval(batch)
        pat = np.frombuffer(self._pat(), dtype=np.uint8)
        m = len(pat)
        if m == 0:
            return Column(jnp.ones(c.capacity, jnp.bool_), c.valid,
                          BooleanType)
        if m > c.max_len:
            return Column(jnp.zeros(c.capacity, jnp.bool_), c.valid,
                          BooleanType)
        hit = jnp.all(c.data[:, :m] == jnp.asarray(pat)[None, :], axis=1) \
            & (c.lengths >= m)
        return Column(hit, c.valid, BooleanType)


class EndsWith(_PatternPredicate):
    def eval(self, batch):
        c = self.child.eval(batch)
        pat = np.frombuffer(self._pat(), dtype=np.uint8)
        m = len(pat)
        if m == 0:
            return Column(jnp.ones(c.capacity, jnp.bool_), c.valid,
                          BooleanType)
        if m > c.max_len:
            return Column(jnp.zeros(c.capacity, jnp.bool_), c.valid,
                          BooleanType)
        L = c.max_len
        pos = jnp.arange(L, dtype=jnp.int32)[None, :]
        start = c.lengths[:, None] - m
        idx = jnp.clip(pos + start, 0, L - 1)
        tail = jnp.take_along_axis(c.data, idx, axis=1)[:, :m]
        hit = jnp.all(tail == jnp.asarray(pat)[None, :], axis=1) \
            & (c.lengths >= m)
        return Column(hit, c.valid, BooleanType)


def _contains_at(c: Column, pat: np.ndarray):
    """bool[rows, L]: does pat occur starting at each position?"""
    L = c.max_len
    m = len(pat)
    acc = jnp.ones((c.capacity, L), dtype=jnp.bool_)
    for j in range(m):
        shifted = jnp.roll(c.data, -j, axis=1)
        # positions beyond L-j invalid; rely on length check below
        acc = acc & (shifted == int(pat[j]))
    pos = jnp.arange(L, dtype=jnp.int32)[None, :]
    acc = acc & (pos + m <= c.lengths[:, None])
    return acc


def _nonoverlap_starts(occ, m: int):
    """Greedy left-to-right suppression of overlapping matches: a match at
    position p hides matches at p+1..p+m-1 (Spark's indexOf-then-advance
    scan semantics)."""
    import jax

    def step(carry, col_occ):
        active = col_occ & (carry == 0)
        new_carry = jnp.where(active, m - 1, jnp.maximum(carry - 1, 0))
        return new_carry, active

    carry0 = jnp.zeros(occ.shape[0], dtype=jnp.int32)
    _, starts = jax.lax.scan(step, carry0, occ.T)
    return starts.T


class Contains(_PatternPredicate):
    def eval(self, batch):
        c = self.child.eval(batch)
        pat = np.frombuffer(self._pat(), dtype=np.uint8)
        if len(pat) == 0:
            return Column(jnp.ones(c.capacity, jnp.bool_), c.valid,
                          BooleanType)
        if len(pat) > c.max_len:
            return Column(jnp.zeros(c.capacity, jnp.bool_), c.valid,
                          BooleanType)
        hit = jnp.any(_contains_at(c, pat), axis=1)
        return Column(hit, c.valid, BooleanType)


class StringLocate(Expression):
    """locate(substr, str, start): 1-based position of first occurrence at or
    after `start`; 0 if absent."""

    def __init__(self, substr, child, start=None):
        self.substr, self.child = substr, child
        self.start = start if start is not None else Literal(1)
        self.children = (substr, child, self.start)

    @property
    def dtype(self):
        return IntegerType

    def eval(self, batch):
        c = self.child.eval(batch)
        pat = np.frombuffer(_literal_bytes(self.substr), dtype=np.uint8)
        st = self.start.eval(batch).data.astype(jnp.int32)
        L = c.max_len
        if len(pat) == 0:
            res = jnp.where(st <= 1, 1, jnp.where(st - 1 <= c.lengths, st, 0))
            return Column(res.astype(jnp.int32), c.valid, IntegerType)
        if len(pat) > L:
            return Column(jnp.zeros(c.capacity, jnp.int32), c.valid,
                          IntegerType)
        occ = _contains_at(c, pat)
        pos = jnp.arange(L, dtype=jnp.int32)[None, :]
        occ = occ & (pos >= st[:, None] - 1)
        found = jnp.any(occ, axis=1)
        first = jnp.argmax(occ, axis=1).astype(jnp.int32) + 1
        return Column(jnp.where(found, first, 0), c.valid, IntegerType)


class Like(_PatternPredicate):
    r"""SQL LIKE: % any run, _ any char, \ escapes.  Compiled into a
    position-set DP unrolled over the (literal) pattern — each pattern token
    is one vector op over the batch, no regex engine on device."""

    def __init__(self, child, pattern, escape: str = "\\"):
        super().__init__(child, pattern)
        self.escape = escape

    def eval(self, batch):
        c = self.child.eval(batch)
        pat = self._pat()
        esc = self.escape.encode()[0] if self.escape else None
        # tokenize
        tokens = []  # ("char", b) | ("any1",) | ("many",)
        i = 0
        while i < len(pat):
            b = pat[i]
            if esc is not None and b == esc and i + 1 < len(pat):
                tokens.append(("char", pat[i + 1]))
                i += 2
                continue
            if b == ord("%"):
                tokens.append(("many",))
            elif b == ord("_"):
                tokens.append(("any1",))
            else:
                tokens.append(("char", b))
            i += 1
        L = c.max_len
        cap = c.capacity
        pos = jnp.arange(L + 1, dtype=jnp.int32)[None, :]
        # reach[i, p] : pattern prefix consumed matches string prefix length p
        reach = pos == 0
        reach = jnp.broadcast_to(reach, (cap, L + 1))
        in_str = (pos[:, 1:] <= c.lengths[:, None]) if L else None
        for tok in tokens:
            if tok[0] == "many":
                reach = jnp.cumsum(reach, axis=1) > 0
            elif tok[0] == "any1":
                nxt = jnp.zeros_like(reach)
                nxt = nxt.at[:, 1:].set(reach[:, :-1] & in_str)
                reach = nxt
            else:
                hit = (c.data == tok[1]) & (
                    jnp.arange(L, dtype=jnp.int32)[None, :]
                    < c.lengths[:, None])
                nxt = jnp.zeros_like(reach)
                nxt = nxt.at[:, 1:].set(reach[:, :-1] & hit)
                reach = nxt
        final = jnp.take_along_axis(reach, c.lengths[:, None].astype(jnp.int32),
                                    axis=1)[:, 0]
        return Column(final, c.valid, BooleanType)


class StringReplace(Expression):
    """replace(str, search, replace) with literal search/replace.

    General replace changes row lengths arbitrarily; the device kernel
    supports same-length search/replace (the common fixed-width cleanup
    case); other shapes are planner-tagged to the CPU executor."""

    def __init__(self, child, search, replace):
        self.child, self.search, self.replace = child, search, replace
        self.children = (child, search, replace)

    @property
    def dtype(self):
        return StringType

    def device_supported(self) -> bool:
        try:
            s = _literal_bytes(self.search)
            r = _literal_bytes(self.replace)
            return len(s) == len(r) and len(s) > 0
        except ValueError:
            return False

    def eval(self, batch):
        c = self.child.eval(batch)
        s = np.frombuffer(_literal_bytes(self.search), dtype=np.uint8)
        r = np.frombuffer(_literal_bytes(self.replace), dtype=np.uint8)
        if len(s) != len(r) or len(s) == 0:
            raise NotImplementedError(
                "device StringReplace requires equal-length literals")
        if len(s) > c.max_len:
            return c
        occ = _contains_at(c, s)
        m = len(s)
        starts = _nonoverlap_starts(occ, m)  # [rows, L]
        data = c.data
        for j in range(m):
            mask = jnp.roll(starts, j, axis=1)
            if j > 0:
                mask = mask.at[:, :j].set(False)
            data = jnp.where(mask, int(r[j]), data)
        return Column(data, c.valid, StringType, c.lengths)


class InitCap(_StringUnary):
    """initcap: first character of each space-delimited word uppercased,
    the rest lowercased (ASCII-exact, like the module's other case ops;
    reference: stringFunctions.scala GpuInitCap, delimiter = space)."""

    def eval(self, batch):
        c = self.child.eval(batch)
        data = c.data
        # word start = position 0 or previous byte is a space
        prev = jnp.concatenate(
            [jnp.full((c.capacity, 1), ord(" "), dtype=data.dtype),
             data[:, :-1]], axis=1)
        first = prev == ord(" ")
        lower = (data >= ord("a")) & (data <= ord("z"))
        upper = (data >= ord("A")) & (data <= ord("Z"))
        out = jnp.where(first & lower, data - 32,
                        jnp.where(~first & upper, data + 32, data))
        return Column(out, c.valid, StringType, c.lengths)


class Reverse(_StringUnary):
    """Byte-wise reverse within each row's length (ASCII-exact)."""

    def eval(self, batch):
        c = self.child.eval(batch)
        L = c.max_len
        pos = jnp.arange(L, dtype=jnp.int32)[None, :]
        idx = jnp.clip(c.lengths[:, None] - 1 - pos, 0, max(L - 1, 0))
        rev = jnp.take_along_axis(c.data, idx, axis=1)
        rev = jnp.where(pos < c.lengths[:, None], rev, 0)
        return Column(rev, c.valid, StringType, c.lengths)


class Ascii(_StringUnary):
    """ascii(str): code point of the first character (ASCII-exact: first
    byte); 0 for the empty string."""

    @property
    def dtype(self):
        return IntegerType

    def eval(self, batch):
        c = self.child.eval(batch)
        first = c.data[:, 0].astype(jnp.int32) if c.max_len else \
            jnp.zeros(c.capacity, jnp.int32)
        return Column(jnp.where(c.lengths > 0, first, 0), c.valid,
                      IntegerType)


def _literal_int(e: Expression) -> int:
    if isinstance(e, Literal) and isinstance(e.value, int):
        return int(e.value)
    raise ValueError("argument must be an integer literal")


class _PadBase(Expression):
    """lpad/rpad(str, len, pad) with LITERAL len/pad (static output width;
    the reference requires literal pad arguments the same way)."""

    def __init__(self, child, length, pad):
        self.child, self.length, self.pad = child, length, pad
        self.children = (child, length, pad)

    @property
    def dtype(self):
        return StringType

    def device_supported(self) -> bool:
        try:
            _literal_int(self.length)
            _literal_bytes(self.pad)
            return True
        except ValueError:
            return False

    def _args(self):
        want = max(_literal_int(self.length), 0)
        pad = np.frombuffer(_literal_bytes(self.pad), dtype=np.uint8)
        return want, pad


class StringLPad(_PadBase):
    def eval(self, batch):
        c = self.child.eval(batch)
        want, pad = self._args()
        L = bucket_strlen(max(want, 1))
        c = c.pad_strings_to(max(L, c.max_len))
        Lc = c.max_len
        pos = jnp.arange(Lc, dtype=jnp.int32)[None, :]
        # empty pad: nothing can be prepended, only truncation applies
        npad = jnp.maximum(want - c.lengths, 0)[:, None] if len(pad) \
            else jnp.zeros((c.capacity, 1), dtype=jnp.int32)
        # output[j] = pad[j % len(pad)] for j < npad else str[j - npad]
        sidx = jnp.clip(pos - npad, 0, Lc - 1)
        from_str = jnp.take_along_axis(c.data, sidx, axis=1)
        if len(pad):
            pad_row = jnp.asarray(pad)[
                jnp.arange(Lc, dtype=jnp.int32) % len(pad)]
            pv = jnp.broadcast_to(pad_row[None, :], from_str.shape)
        else:
            pv = jnp.zeros_like(from_str)
        out = jnp.where(pos < npad, pv, from_str)
        if len(pad):
            new_len = jnp.full_like(c.lengths, want)
        else:  # nothing to pad with: truncate only
            new_len = jnp.minimum(c.lengths, want)
        new_len = new_len.astype(jnp.int32)
        out = jnp.where(pos < new_len[:, None], out, 0)
        return Column(out, c.valid, StringType, new_len)


class StringRPad(_PadBase):
    def eval(self, batch):
        c = self.child.eval(batch)
        want, pad = self._args()
        L = bucket_strlen(max(want, 1))
        c = c.pad_strings_to(max(L, c.max_len))
        Lc = c.max_len
        pos = jnp.arange(Lc, dtype=jnp.int32)[None, :]
        if len(pad):
            # pad cycle restarts at the end of the source string
            off = jnp.clip(pos - c.lengths[:, None], 0, Lc - 1)
            pv = jnp.asarray(pad)[off % len(pad)]
            new_len = jnp.full_like(c.lengths, want)
        else:
            pv = jnp.zeros_like(c.data)
            new_len = jnp.minimum(c.lengths, want)
        out = jnp.where(pos < c.lengths[:, None], c.data, pv)
        new_len = jnp.where(c.lengths >= want, want, new_len).astype(
            jnp.int32)
        out = jnp.where(pos < new_len[:, None], out, 0)
        return Column(out, c.valid, StringType, new_len)


class StringRepeat(Expression):
    """repeat(str, n) with LITERAL n (static output width)."""

    def __init__(self, child, times):
        self.child, self.times = child, times
        self.children = (child, times)

    @property
    def dtype(self):
        return StringType

    def device_supported(self) -> bool:
        try:
            return _literal_int(self.times) >= 0
        except ValueError:
            return False

    def eval(self, batch):
        c = self.child.eval(batch)
        k = max(_literal_int(self.times), 0)
        if k == 0 or c.max_len == 0:
            z = jnp.zeros((c.capacity, 1), dtype=jnp.uint8)
            return Column(z, c.valid, StringType,
                          jnp.zeros(c.capacity, jnp.int32))
        L = bucket_strlen(c.max_len * k)
        pos = jnp.arange(L, dtype=jnp.int32)[None, :]
        lens = jnp.maximum(c.lengths, 1)[:, None]   # avoid mod-by-zero
        src = jnp.clip(pos % lens, 0, c.max_len - 1)
        out = jnp.take_along_axis(
            jnp.pad(c.data, ((0, 0), (0, L - c.max_len))), src, axis=1)
        new_len = (c.lengths * k).astype(jnp.int32)
        out = jnp.where(pos < new_len[:, None], out, 0)
        return Column(out, c.valid, StringType, new_len)


class SubstringIndex(Expression):
    """substring_index(str, delim, count) with LITERAL delim/count:
    count>0 -> prefix before the count'th delimiter, count<0 -> suffix
    after the count'th-from-the-end delimiter, 0 -> empty."""

    def __init__(self, child, delim, count):
        self.child, self.delim, self.count = child, delim, count
        self.children = (child, delim, count)

    @property
    def dtype(self):
        return StringType

    def device_supported(self) -> bool:
        try:
            _literal_bytes(self.delim)
            _literal_int(self.count)
            return True
        except ValueError:
            return False

    def eval(self, batch):
        c = self.child.eval(batch)
        delim = np.frombuffer(_literal_bytes(self.delim), dtype=np.uint8)
        count = _literal_int(self.count)
        cap, L = c.capacity, c.max_len
        if count == 0 or len(delim) == 0 or L == 0:
            z = jnp.zeros((cap, max(L, 1)), dtype=jnp.uint8)
            return Column(z, c.valid, StringType,
                          jnp.zeros(cap, jnp.int32))
        m = len(delim)
        # non-overlapping occurrences, like Spark's indexOf-advance scan
        occ = _nonoverlap_starts(_contains_at(c, delim), m)   # [cap, L]
        pos = jnp.arange(L, dtype=jnp.int32)[None, :]
        if count > 0:
            # end = start of the count'th occurrence (whole string if fewer)
            rank = jnp.cumsum(occ.astype(jnp.int32), axis=1)
            hit = occ & (rank == count)
            found = jnp.any(hit, axis=1)
            cut = jnp.argmax(hit, axis=1).astype(jnp.int32)
            new_len = jnp.where(found, cut, c.lengths).astype(jnp.int32)
            out = jnp.where(pos < new_len[:, None], c.data, 0)
            return Column(out, c.valid, StringType, new_len)
        # count < 0: start after the |count|'th occurrence from the end
        total = jnp.sum(occ.astype(jnp.int32), axis=1)
        rank = jnp.cumsum(occ.astype(jnp.int32), axis=1)
        want = total + count  # index (1-based) from the left of the cut
        hit = occ & (rank == (want + 1)[:, None])
        found = jnp.any(hit, axis=1) & (want >= 0)
        start = jnp.where(found,
                          jnp.argmax(hit, axis=1).astype(jnp.int32) + m, 0)
        new_len = (c.lengths - start).astype(jnp.int32)
        idx = jnp.clip(pos + start[:, None], 0, L - 1)
        out = jnp.take_along_axis(c.data, idx, axis=1)
        out = jnp.where(pos < new_len[:, None], out, 0)
        return Column(out, c.valid, StringType, new_len)


_REGEX_META = set(b".^$*+?{}[]|()\\")


class RegExpReplace(Expression):
    """regexp_replace with a LITERAL pattern.  The device kernel supports
    metacharacter-free patterns with equal-length replacement (delegating to
    the StringReplace kernel); everything else is planner-tagged to the CPU
    executor.  The reference similarly ships literal-only regexp support in
    this era (stringFunctions.scala GpuRegExpReplace via cudf replace)."""

    def __init__(self, child, pattern, replacement):
        self.child, self.pattern, self.replacement = (child, pattern,
                                                      replacement)
        self.children = (child, pattern, replacement)

    @property
    def dtype(self):
        return StringType

    def device_supported(self) -> bool:
        try:
            pat = _literal_bytes(self.pattern)
            rep = _literal_bytes(self.replacement)
        except ValueError:
            return False
        if any(b in _REGEX_META for b in pat):
            return False
        return len(pat) == len(rep) and len(pat) > 0

    def eval(self, batch):
        if not self.device_supported():
            raise NotImplementedError(
                "device RegExpReplace requires a literal, metacharacter-"
                "free pattern with equal-length replacement")
        return StringReplace(self.child, self.pattern,
                             self.replacement).eval(batch)
