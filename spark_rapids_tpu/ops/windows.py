"""Window functions: resolution + device kernels.

TPU-native analogue of GpuWindowExpression / GpuWindowExec
(rapids/GpuWindowExpression.scala:87-233 — window specs mapped to device
rolling aggregations, row-based frames, row_number; GpuWindowExec.scala:92+).
Where cuDF evaluates each window spec with a rolling-window kernel, the TPU
implementation sorts ONCE by (partition keys, order keys) and computes every
function with segmented scans / prefix sums over the sorted batch — one XLA
program, no per-row loops:

  * segment boundaries      = neighbour inequality on partition keys
  * row_number/rank/dense   = iota arithmetic on segment/peer starts
  * sum/count/avg any frame = prefix sums + clamped frame-bound gathers
  * min/max unbounded side  = segmented associative scans
  * min/max bounded frames  = static stack of shifted gathers (width-capped)
  * lag/lead                = shifted gathers fenced at segment bounds
  * default frame w/ order  = RANGE UNBOUNDED PRECEDING..CURRENT ROW, i.e.
    the frame end is the last PEER row (Spark default-frame tie semantics)
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..columnar import Column, ColumnarBatch
from ..types import (DataType, DoubleType, IntegerType, LongType, Schema,
                     StructField)
from . import expressions as E

UNBOUNDED = 1 << 62
MAX_BOUNDED_MINMAX_WIDTH = 256

RANKING_FUNCS = ("RowNumber", "Rank", "DenseRank")
OFFSET_FUNCS = ("Lag", "Lead")
AGG_WINDOW_FUNCS = ("Sum", "Min", "Max", "Count", "Average", "First", "Last")


@dataclass
class WindowFunc:
    """One resolved window function over a shared (partition, order) spec."""
    kind: str
    child: Optional[E.Expression]      # value expression (aggs, lag/lead)
    frame: Tuple                       # ("rows", start, end) |
                                       # ("range_to_current",) | ("whole",)
    name: str
    dtype: DataType
    offset: int = 1                    # lag/lead
    default: object = None             # lag/lead


class WindowUnsupported(Exception):
    pass


def _result_dtype(kind: str, child: Optional[E.Expression]) -> DataType:
    if kind in RANKING_FUNCS:
        return IntegerType
    if kind == "Count":
        return LongType
    if kind == "Average":
        return DoubleType
    if kind == "Sum":
        assert child is not None
        return LongType if child.dtype.is_integral else DoubleType
    assert child is not None
    return child.dtype


def resolve_window_func(func_ce, spec, schema: Schema, resolve,
                        device: bool = True) -> WindowFunc:
    """ColumnExpr window function + WindowSpec -> WindowFunc.

    Semantic violations always raise WindowUnsupported; device-capability
    limits (frame widths the TPU kernels cap) raise only when `device` is
    True, mirroring the reference's tagging-vs-capability split
    (GpuWindowExpression.scala tag checks)."""
    op = func_ce.op
    name = func_ce.output_name
    has_order = bool(spec.orders)

    if spec.frame is not None:
        _kind, start, end = spec.frame
        start = -UNBOUNDED if start <= -UNBOUNDED else start
        end = UNBOUNDED if end >= UNBOUNDED else end
        if start > end:
            raise WindowUnsupported(f"empty frame [{start}, {end}]")
        frame = ("rows", start, end)
    elif has_order:
        frame = ("range_to_current",)
    else:
        frame = ("whole",)

    if op in RANKING_FUNCS:
        if not has_order:
            raise WindowUnsupported(f"{op} requires an ORDER BY")
        return WindowFunc(op, None, frame, name, IntegerType)

    if op in OFFSET_FUNCS:
        child_ce, offset, default = func_ce.args
        if not has_order:
            raise WindowUnsupported(f"{op} requires an ORDER BY")
        child = resolve(child_ce, schema)
        return WindowFunc(op, child, frame, name, child.dtype,
                          offset=int(offset), default=default)

    from .aggregates import AGG_FUNCS
    if op in AGG_FUNCS:
        if op == "Percentile":
            raise WindowUnsupported("percentile window aggregates")
        child_ce, distinct = func_ce.args
        if distinct:
            raise WindowUnsupported("DISTINCT window aggregates")
        if op == "Count" and (child_ce.op == "lit"
                              and child_ce.args[0] in (1, "*")):
            child = None
        else:
            child = resolve(child_ce, schema)
        if op in ("Sum", "Average") and child is not None \
                and not child.dtype.is_numeric:
            raise WindowUnsupported(f"{op} over {child.dtype.name}")
        if device and op in ("Min", "Max") and frame[0] == "rows":
            start, end = frame[1], frame[2]
            bounded = start > -UNBOUNDED and end < UNBOUNDED
            if bounded and end - start + 1 > MAX_BOUNDED_MINMAX_WIDTH:
                raise WindowUnsupported(
                    f"bounded {op} frame wider than "
                    f"{MAX_BOUNDED_MINMAX_WIDTH} rows")
            if start > -UNBOUNDED and child is not None \
                    and child.dtype.is_string:
                # the string kernel is a forward segmented scan: it needs
                # the frame to start at the partition start
                raise WindowUnsupported(
                    f"{op} over strings with a bounded frame start")
        if child is not None and child.dtype.is_string \
                and op not in ("Min", "Max", "First", "Last", "Count"):
            raise WindowUnsupported(f"{op} over strings")
        return WindowFunc(op, child, frame, name,
                          _result_dtype(op, child))

    raise WindowUnsupported(f"{op} is not a window function")


# --------------------------------------------------------------------------
# device kernels (all operate on the SORTED batch; segments contiguous)
# --------------------------------------------------------------------------

def _shift_prev(x):
    return jnp.concatenate([x[:1], x[:-1]])


def _neq_prev(c: Column) -> jnp.ndarray:
    """True where a row's value differs from the previous row's (null-safe:
    null == null)."""
    pv = _shift_prev(c.valid)
    if c.dtype.is_string:
        data_eq = jnp.all(c.data == _shift_prev(c.data), axis=1)
        data_eq = data_eq & (c.lengths == _shift_prev(c.lengths))
    else:
        d = c.data
        if c.dtype.is_floating:
            # NaN == NaN and -0.0 == 0.0 for grouping/ordering purposes;
            # value compare stays in float (no f64 bitcast on axon)
            f = d.astype(jnp.float64)
            nan = jnp.isnan(f)
            v = jnp.where(nan | (f == 0.0), jnp.float64(0.0), f)
            data_eq = (v == _shift_prev(v)) & (nan == _shift_prev(nan))
        else:
            data_eq = d == _shift_prev(d)
    eq = jnp.where(c.valid & pv, data_eq, c.valid == pv)
    return ~eq


def segment_flags(sorted_batch: ColumnarBatch,
                  part_exprs: Sequence[E.Expression],
                  order_exprs: Sequence[E.Expression]):
    """(seg_start, new_peer) boolean flags on the sorted batch."""
    cap = sorted_batch.capacity
    first = jnp.arange(cap, dtype=jnp.int32) == 0
    live = sorted_batch.sel
    seg_start = first | (live != _shift_prev(live))
    for e in part_exprs:
        seg_start = seg_start | _neq_prev(e.eval(sorted_batch))
    new_peer = seg_start
    for e in order_exprs:
        new_peer = new_peer | _neq_prev(e.eval(sorted_batch))
    return seg_start, new_peer


def segment_indices(seg_start, new_peer):
    """Per-row segment-first / segment-last / peer-first / peer-last row
    indices (all int32)."""
    cap = seg_start.shape[0]
    iota = jnp.arange(cap, dtype=jnp.int32)
    seg_first = jax.lax.cummax(jnp.where(seg_start, iota, 0))
    peer_first = jax.lax.cummax(jnp.where(new_peer, iota, 0))
    seg_end_flag = jnp.concatenate([seg_start[1:],
                                    jnp.ones(1, dtype=jnp.bool_)])
    peer_end_flag = jnp.concatenate([new_peer[1:],
                                     jnp.ones(1, dtype=jnp.bool_)])
    seg_last = jnp.flip(jax.lax.cummin(
        jnp.flip(jnp.where(seg_end_flag, iota, cap))))
    peer_last = jnp.flip(jax.lax.cummin(
        jnp.flip(jnp.where(peer_end_flag, iota, cap))))
    return seg_first, seg_last.astype(jnp.int32), peer_first, \
        peer_last.astype(jnp.int32)


def _segmented_scan(vals, reset, op, reverse=False):
    """Associative segmented scan: within a segment, running `op`; resets at
    `reset` flags (forward: segment starts; reverse: segment ends)."""
    def combine(a, b):
        va, fa = a
        vb, fb = b
        return jnp.where(fb, vb, op(va, vb)), fa | fb
    v, _ = jax.lax.associative_scan(combine, (vals, reset), reverse=reverse)
    return v


def _frame_bounds(func: WindowFunc, iota, seg_first, seg_last, peer_last):
    """Per-row inclusive [a, b] frame row-index bounds."""
    if func.frame[0] == "whole":
        return seg_first, seg_last
    if func.frame[0] == "range_to_current":
        return seg_first, peer_last
    _r, start, end = func.frame
    a = seg_first if start <= -UNBOUNDED else \
        jnp.maximum(seg_first, iota + jnp.int32(start))
    b = seg_last if end >= UNBOUNDED else \
        jnp.minimum(seg_last, iota + jnp.int32(end))
    return a, b


def _prefix_sum_frame(vals_f, a, b, seg_start=None):
    """Sum over rows [a, b] via prefix sums; empty frame -> 0.

    With `seg_start` the prefix sum is SEGMENTED (resets at every segment
    start).  Frames never cross segment boundaries, and a global float
    cumsum would let one segment's values poison every later frame's
    subtraction — catastrophically (a 1e300 value absorbs everything
    below ~1e284) or absorbingly (inf - inf = NaN).  Integer counts are
    exact under wraparound, so callers may omit seg_start for them."""
    if seg_start is None:
        p = jnp.cumsum(vals_f)
    else:
        def comb(x, y):
            vx, rx = x
            vy, ry = y
            return (jnp.where(ry, vy, vx + vy), rx | ry)
        p, _ = jax.lax.associative_scan(comb, (vals_f, seg_start))
    p = jnp.concatenate([jnp.zeros(1, dtype=p.dtype), p])
    take = lambda idx: jnp.take(p, jnp.clip(idx, 0, p.shape[0] - 1))
    if seg_start is None:
        lower = take(a)
    else:
        # frames start no earlier than their own segment (a >= seg_first);
        # when a IS the segment start the lower term is 0 — take(a) would
        # be the PREVIOUS segment's tail, which the reset already excluded
        # from take(b + 1)
        a_c = jnp.clip(a, 0, seg_start.shape[0] - 1)
        lower = jnp.where(jnp.take(seg_start, a_c),
                          jnp.zeros((), p.dtype), take(a))
    return jnp.where(b >= a, take(b + 1) - lower, jnp.zeros((), p.dtype))


def eval_window_func(func: WindowFunc, sorted_batch: ColumnarBatch,
                     seg_start, new_peer) -> Column:
    """Evaluate one window function on the sorted batch."""
    cap = sorted_batch.capacity
    iota = jnp.arange(cap, dtype=jnp.int32)
    seg_first, seg_last, peer_first, peer_last = \
        segment_indices(seg_start, new_peer)

    if func.kind == "RowNumber":
        out = (iota - seg_first + 1).astype(jnp.int32)
        return Column(out, jnp.ones(cap, dtype=jnp.bool_), IntegerType)
    if func.kind == "Rank":
        out = (peer_first - seg_first + 1).astype(jnp.int32)
        return Column(out, jnp.ones(cap, dtype=jnp.bool_), IntegerType)
    if func.kind == "DenseRank":
        changes = (new_peer & ~seg_start).astype(jnp.int32)
        c = jnp.cumsum(changes)
        out = (c - jnp.take(c, seg_first) + 1).astype(jnp.int32)
        return Column(out, jnp.ones(cap, dtype=jnp.bool_), IntegerType)

    if func.kind in OFFSET_FUNCS:
        c = func.child.eval(sorted_batch)
        k = func.offset if func.kind == "Lag" else -func.offset
        src = iota - jnp.int32(k)
        ok = (src >= seg_first) & (src <= seg_last)
        src_c = jnp.clip(src, 0, cap - 1)
        g = c.take(src_c)
        if func.default is not None:
            dflt = E.lit(func.default, func.dtype).eval(sorted_batch)
            if func.dtype.is_string and dflt.max_len != g.max_len:
                # bucketed byte-matrix widths must agree before the select
                width = max(dflt.max_len, g.max_len)
                dflt = dflt.pad_strings_to(width)
                g = g.pad_strings_to(width)
            data = jnp.where(_bmask(ok, g.data), g.data, dflt.data)
            valid = jnp.where(ok, g.valid, dflt.valid)
            if func.dtype.is_string:
                lens = jnp.where(ok, g.lengths, dflt.lengths)
                return Column(data, valid, func.dtype, lens)
            return Column(data, valid, func.dtype)
        valid = ok & g.valid
        return Column(g.data, valid, func.dtype, g.lengths)

    # aggregates over frames
    a, b = _frame_bounds(func, iota, seg_first, seg_last, peer_last)

    if func.kind == "Count":
        if func.child is None:
            ones = jnp.ones(cap, dtype=jnp.int64)
        else:
            ones = func.child.eval(sorted_batch).valid.astype(jnp.int64)
        out = _prefix_sum_frame(ones, a, b)
        return Column(out, jnp.ones(cap, dtype=jnp.bool_), LongType)

    c = func.child.eval(sorted_batch).mask_invalid()

    if func.kind in ("First", "Last"):
        idx = jnp.clip(a if func.kind == "First" else b, 0, cap - 1)
        g = c.take(idx)
        valid = (b >= a) & g.valid
        return Column(g.data, valid, func.dtype, g.lengths)

    if func.kind in ("Sum", "Average"):
        acc_dtype = jnp.int64 if (func.kind == "Sum"
                                  and c.dtype.is_integral) else jnp.float64
        vals = jnp.where(c.valid, c.data.astype(acc_dtype),
                         jnp.zeros((), acc_dtype))
        n = _prefix_sum_frame(c.valid.astype(jnp.int64), a, b)
        if acc_dtype == jnp.float64:
            # float sums are SEGMENTED (cross-segment cancellation: one
            # huge value would absorb every later segment's values in a
            # global cumsum) and split finite/non-finite: an inf/NaN
            # inside the segment but OUTSIDE a bounded frame must not
            # leak in via the prefix subtraction, so the IEEE result is
            # rebuilt from per-frame counts of nan/+inf/-inf
            finite = jnp.isfinite(vals)
            s = _prefix_sum_frame(jnp.where(finite, vals, 0.0), a, b,
                                  seg_start)
            n_nan = _prefix_sum_frame(
                jnp.isnan(vals).astype(jnp.int64), a, b)
            n_pinf = _prefix_sum_frame(
                (vals == jnp.inf).astype(jnp.int64), a, b)
            n_ninf = _prefix_sum_frame(
                (vals == -jnp.inf).astype(jnp.int64), a, b)
            s = jnp.where(
                (n_nan > 0) | ((n_pinf > 0) & (n_ninf > 0)), jnp.nan,
                jnp.where(n_pinf > 0, jnp.inf,
                          jnp.where(n_ninf > 0, -jnp.inf, s)))
        else:
            s = _prefix_sum_frame(vals, a, b)
        if func.kind == "Sum":
            return Column(s.astype(func.dtype.jnp_dtype), n > 0, func.dtype)
        avg = s.astype(jnp.float64) / jnp.maximum(n, 1).astype(jnp.float64)
        return Column(avg, n > 0, DoubleType)

    assert func.kind in ("Min", "Max"), func.kind
    if c.dtype.is_floating:
        return _min_max_float(func, c, a, b, iota, seg_start)
    return _min_max(func, c, a, b, iota, seg_start, seg_first, seg_last)


def _bmask(ok, data):
    return ok[:, None] if data.ndim == 2 else ok


def _min_max_float(func: WindowFunc, c: Column, a, b, iota,
                   seg_start) -> Column:
    """Floats: (nan_flag, value) pair scans — NaN greatest (Spark), nulls
    never win, NO f64<->int bitcast (unimplemented on the axon backend)."""
    cap = iota.shape[0]
    is_min = func.kind == "Min"
    d = c.data.astype(jnp.float64)
    nan = jnp.isnan(d)
    v = jnp.where(nan | (d == 0.0), jnp.float64(0.0), d)
    inf = jnp.float64(np.inf)
    # sentinel pair for nulls: always loses
    flag = jnp.where(c.valid, nan.astype(jnp.int32),
                     jnp.int32(2 if is_min else -1))
    v = jnp.where(c.valid, v, inf if is_min else -inf)

    def better(x, y):
        fx, vx = x
        fy, vy = y
        if is_min:
            keep_x = (fx < fy) | ((fx == fy) & (vx <= vy))
        else:
            keep_x = (fx > fy) | ((fx == fy) & (vx >= vy))
        return (jnp.where(keep_x, fx, fy), jnp.where(keep_x, vx, vy))

    def seg_scan(pair, reset, reverse=False):
        def comb(p, q):
            (fp, vp, rp), (fq, vq, rq) = p, q
            nf, nv = better((fp, vp), (fq, vq))
            return (jnp.where(rq, fq, nf), jnp.where(rq, vq, nv), rp | rq)
        f, val, _ = jax.lax.associative_scan(
            comb, (pair[0], pair[1], reset), reverse=reverse)
        return f, val

    n_valid = _prefix_sum_frame(c.valid.astype(jnp.int64), a, b)
    frame = func.frame
    if frame[0] in ("whole", "range_to_current") or \
            (frame[0] == "rows" and frame[1] <= -UNBOUNDED):
        ff, fv = seg_scan((flag, v), seg_start)
        bf = jnp.take(ff, jnp.clip(b, 0, cap - 1))
        bv = jnp.take(fv, jnp.clip(b, 0, cap - 1))
    elif frame[0] == "rows" and frame[2] >= UNBOUNDED:
        seg_end_flag = jnp.concatenate([seg_start[1:],
                                        jnp.ones(1, dtype=jnp.bool_)])
        rf, rv = seg_scan((flag, v), seg_end_flag, reverse=True)
        bf = jnp.take(rf, jnp.clip(a, 0, cap - 1))
        bv = jnp.take(rv, jnp.clip(a, 0, cap - 1))
    else:
        _r, start, end = frame
        bf = jnp.full(cap, 2 if is_min else -1, dtype=jnp.int32)
        bv = jnp.full(cap, inf if is_min else -inf, dtype=jnp.float64)
        for off in range(start, end + 1):
            src = jnp.clip(iota + jnp.int32(off), 0, cap - 1)
            in_f = (iota + off >= a) & (iota + off <= b)
            cf = jnp.where(in_f, jnp.take(flag, src),
                           jnp.int32(2 if is_min else -1))
            cv = jnp.where(in_f, jnp.take(v, src), inf if is_min else -inf)
            bf, bv = better((bf, bv), (cf, cv))
    out = jnp.where(bf == 1, jnp.float64(np.nan), bv)
    return Column(out.astype(func.dtype.jnp_dtype), n_valid > 0, func.dtype)


def _min_max(func: WindowFunc, c: Column, a, b, iota, seg_start,
             seg_first, seg_last) -> Column:
    cap = iota.shape[0]
    is_min = func.kind == "Min"
    if c.dtype.is_string:
        return _min_max_string(func, c, a, b, iota, seg_first, seg_last)
    from ..exec.sort import column_sort_keys
    # encode to order-preserving int64 keys so one scan handles floats with
    # Spark NaN/-0.0 semantics too
    keys = column_sort_keys(c, ascending=True)
    assert len(keys) == 1
    k = keys[0]
    # int64 extremes: NaN's sort key (0x7FF8...) exceeds 2^62, so anything
    # smaller would let nulls beat valid NaNs in a Min
    big = jnp.int64(2 ** 63 - 1) if is_min else jnp.int64(-(2 ** 63))
    k = jnp.where(c.valid, k, big)  # nulls never win
    op = jnp.minimum if is_min else jnp.maximum
    frame = func.frame
    n_valid = _prefix_sum_frame(c.valid.astype(jnp.int64), a, b)
    if frame[0] in ("whole", "range_to_current") or \
            (frame[0] == "rows" and frame[1] <= -UNBOUNDED):
        fwd = _segmented_scan(k, seg_start, op)
        best_k = jnp.take(fwd, jnp.clip(b, 0, cap - 1))
    elif frame[0] == "rows" and frame[2] >= UNBOUNDED:
        seg_end_flag = jnp.concatenate([seg_start[1:],
                                        jnp.ones(1, dtype=jnp.bool_)])
        rev = _segmented_scan(k, seg_end_flag, op, reverse=True)
        best_k = jnp.take(rev, jnp.clip(a, 0, cap - 1))
    else:  # bounded both sides: static stack of shifted gathers
        _r, start, end = frame
        best_k = big
        for off in range(start, end + 1):
            src = jnp.clip(iota + jnp.int32(off), 0, cap - 1)
            in_seg = (iota + off >= a) & (iota + off <= b)
            kk = jnp.where(in_seg, jnp.take(k, src), big)
            best_k = op(best_k, kk)
    # decode: find the row holding best_k is wasteful; instead recompute the
    # value by inverting the key encoding per dtype
    out = _decode_sort_key(best_k, c.dtype)
    return Column(out, n_valid > 0, func.dtype)


def _decode_sort_key(k, dtype: DataType):
    """Invert exec.sort.column_sort_keys for single-key integer dtypes
    (floats take the pair-scan path in _min_max_float)."""
    assert not dtype.is_floating
    if dtype.name == "boolean":
        return k.astype(jnp.uint8)
    return k.astype(dtype.jnp_dtype)


def _min_max_string(func, c: Column, a, b, iota, seg_first, seg_last):
    """Strings: frame gathers with lexicographic reduce via stacked shifted
    compare is costly; support unbounded frames with a segmented scan over
    (row index of current best), comparing byte rows."""
    cap = iota.shape[0]
    is_min = func.kind == "Min"
    from .expressions import string_lt

    def better(i_idx, j_idx):
        ci, cj = c.take(i_idx), c.take(j_idx)
        lt = string_lt(ci, cj)
        i_wins = jnp.where(is_min, lt, ~lt & ~_string_eq_rows(ci, cj))
        # nulls never win
        i_wins = jnp.where(ci.valid & ~cj.valid, True, i_wins)
        i_wins = jnp.where(~ci.valid, False, i_wins)
        return jnp.where(i_wins, i_idx, j_idx)

    if func.frame[0] == "rows" and func.frame[1] > -UNBOUNDED:
        raise WindowUnsupported(
            "min/max over strings with a bounded frame start")
    fwd = _segmented_scan(iota, _seg_start_from_first(seg_first, iota),
                          better)
    best_idx = jnp.take(fwd, jnp.clip(b, 0, cap - 1))
    g = c.take(best_idx)
    n_valid = _prefix_sum_frame(c.valid.astype(jnp.int64), a, b)
    return Column(g.data, n_valid > 0, c.dtype, g.lengths)


def _string_eq_rows(x: Column, y: Column):
    return jnp.all(x.data == y.data, axis=1) & (x.lengths == y.lengths)


def _seg_start_from_first(seg_first, iota):
    return seg_first == iota
