"""Distributed query steps: SPMD operators over a device mesh.

The TPU-native replacement for the reference's accelerated shuffle path
(reference: rapids/shuffle/RapidsShuffleClient.scala, RapidsShuffleServer.scala,
shuffle-plugin/.../ucx/): where the reference moves device buffers peer-to-peer
over UCX/RDMA with a flatbuffers control plane and bounce-buffer pools, here a
repartition-by-key is ONE XLA collective (`all_to_all` over ICI) inside a
`shard_map`-traced program — no control plane, no staging copies, and the
compiler overlaps it with compute.

Key trick that makes this static-shape friendly: batches carry a selection
mask, so "send rows with bucket==d to device d" does not compact anything —
every device sends its full (identical) column data tiled n ways with n
different selection masks.  Sel-mask shuffles trade bandwidth for zero
dynamic shapes; the coalesce pass compacts after the exchange.
"""
from __future__ import annotations

from functools import partial
from typing import Sequence

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

try:
    from jax import shard_map  # jax>=0.8
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map

from ..columnar import Column, ColumnarBatch
from ..ops.hashing import hash_columns_double
from .mesh import DATA_AXIS


def _all_to_all(x, axis: str):
    """Tiled all-to-all on the leading (row) axis: the array is split into
    `n` equal row blocks, block d goes to device d, received blocks are
    re-concatenated in peer order."""
    return jax.lax.all_to_all(x, axis, split_axis=0, concat_axis=0,
                              tiled=True)


def exchange_by_bucket(batch: ColumnarBatch, bucket, axis: str = DATA_AXIS
                       ) -> ColumnarBatch:
    """Inside shard_map: route each live row to device `bucket[row] % n`.

    Returns a batch of capacity n*cap whose selection mask keeps exactly the
    rows this device owns.  Since every destination receives the SAME column
    data (only the selection mask differs per destination), the data movement
    is an all_gather; only the mask needs a true all_to_all.
    """
    n = jax.lax.psum(1, axis)
    cap = batch.capacity
    dest = jnp.arange(n, dtype=jnp.int32)[:, None]            # [n, 1]
    sel_nd = batch.sel[None, :] & (bucket[None, :] == dest)    # [n, cap]
    recv_sel = _all_to_all(sel_nd.reshape(n * cap), axis)

    def gather(x):
        return jax.lax.all_gather(x, axis, tiled=True)

    def exchange_col(c: Column) -> Column:
        if c.dtype.is_string:
            return Column(gather(c.data), gather(c.valid), c.dtype,
                          gather(c.lengths))
        return Column(gather(c.data), gather(c.valid), c.dtype)

    cols = [exchange_col(c) for c in batch.columns]
    return ColumnarBatch(cols, recv_sel, batch.schema)


def key_buckets(key_cols: Sequence[Column], live, n: int):
    """Owner device of each row: h1(keys) % n (dead rows -> garbage, masked
    by sel downstream)."""
    if not key_cols:
        return jnp.zeros(live.shape, dtype=jnp.int32)
    h1, _ = hash_columns_double(key_cols, live)
    return (h1 % jnp.uint64(n)).astype(jnp.int32)


def distributed_aggregate_step(agg, mesh: Mesh, axis: str = DATA_AXIS,
                               pre=None):
    """Build the full SPMD aggregation step over a mesh.

    Per device: [optional fused filter/project `pre`] -> update-aggregate
    local rows -> all_to_all partial states by key hash -> merge-aggregate
    owned groups -> finalize.  This is the TPU equivalent of the reference's
    partial-agg -> shuffle -> final-agg stage pair (reference:
    rapids/aggregate.scala Partial/Final modes + GpuShuffleExchangeExec), as
    one compiled XLA program.

    `agg` is a TpuHashAggregateExec (provides the three kernels).
    Returns a function: globally row-sharded batch -> row-sharded result
    batch whose live rows are each device's owned groups.
    """
    n = mesh.shape[axis]
    nkeys = len(agg.grouping)

    def step(local: ColumnarBatch) -> ColumnarBatch:
        if pre is not None:
            local = pre(local)
        state = agg._update_kernel(local)
        bucket = key_buckets(list(state.columns[:nkeys]), state.sel, n)
        gathered = exchange_by_bucket(state, bucket, axis)
        merged = agg._merge_kernel(gathered)
        return agg._finalize_kernel(merged)

    return shard_map(step, mesh=mesh, in_specs=(P(axis),),
                     out_specs=P(axis))
