"""Distributed query steps: SPMD operators over a device mesh.

The TPU-native replacement for the reference's accelerated shuffle path
(reference: rapids/shuffle/RapidsShuffleClient.scala, RapidsShuffleServer.scala,
shuffle-plugin/.../ucx/): where the reference moves device buffers peer-to-peer
over UCX/RDMA with a flatbuffers control plane and bounce-buffer pools, here a
repartition-by-key is ONE XLA collective (`all_to_all` over ICI) inside a
`shard_map`-traced program — no control plane, no staging copies, and the
compiler overlaps it with compute.

Two exchange strategies, both static-shape:

  * `exchange_compact` (default): each device compacts its live rows into a
    fixed per-destination quota block [n, q] and ONE tiled `all_to_all`
    moves exactly the owned rows — per-device traffic and received capacity
    are O(cap), independent of mesh size.  Quota overflow is *detected*
    (returned as a scalar) and the host driver retries with a doubled
    quota — the bounded-capacity + overflow-retry pattern this framework
    uses everywhere XLA's static shapes meet data-dependent sizes.
  * `exchange_by_bucket` (fallback knob): sel-mask all_gather — every device
    receives all n*cap rows with n different selection masks.  Zero overflow
    risk, linear-in-n cost; kept for tiny meshes and as the safety net.
"""
from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

try:
    from jax import shard_map  # jax>=0.8
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map

from ..columnar import Column, ColumnarBatch
from ..ops.hashing import hash_columns_double
from ..utils import pow2_bucket
from .mesh import DATA_AXIS


def _all_to_all(x, axis: str):
    """Tiled all-to-all on the leading (row) axis: the array is split into
    `n` equal row blocks, block d goes to device d, received blocks are
    re-concatenated in peer order."""
    return jax.lax.all_to_all(x, axis, split_axis=0, concat_axis=0,
                              tiled=True)


def default_quota(local_cap: int, n: int, factor: int = 2,
                  minimum: int = 8) -> int:
    """Per-destination row quota for exchange_compact: a power-of-two bucket
    of factor*cap/n, clamped to cap.  `factor` absorbs hash imbalance so the
    overflow-retry path stays cold."""
    want = max(minimum, factor * local_cap // max(n, 1))
    return min(pow2_bucket(want, minimum), local_cap)


def exchange_compact(batch: ColumnarBatch, bucket, quota: int,
                     axis: str = DATA_AXIS):
    """Inside shard_map: route each live row to device `bucket[row]` with a
    fixed quota of `quota` rows per destination.

    Returns (out_batch, overflow):
      * out_batch has capacity n*quota — quota rows received from each peer,
        live rows flagged by its selection mask;
      * overflow = total rows (across all devices) that exceeded their
        destination quota and were DROPPED.  overflow == 0 means lossless;
        a driver must treat overflow > 0 as a retry signal, not a result.

    Reference contract analogue: RapidsShuffleTransport.scala:38-500 moves
    partitions through bounded bounce-buffer pools with throttled receives;
    here the bound is the static quota block and the "throttle" is the
    compiled all_to_all schedule.
    """
    n = jax.lax.psum(1, axis)  # concrete: mesh size
    cap = batch.capacity
    live = batch.sel
    dest = jnp.where(live, bucket.astype(jnp.int32), n)
    # group rows by destination (stable: preserves row order within a
    # dest).  Packed single-operand sort when the capacity allows it:
    # jnp.argsort is a VARIADIC sort HLO (operand + iota) costing ~6x a
    # single-operand sort on the CPU/TPU sort path (utils/packed_sort,
    # PR-11 measurement), and this sort runs inside EVERY quota-block
    # exchange dispatch — the permutation is bit-identical either way
    from ..utils import packed_sort as PS
    if PS.packed_enabled() and cap & (cap - 1) == 0:
        order = PS.packed_argsort(
            [(dest.astype(jnp.uint64), max(1, int(n).bit_length() + 1))],
            cap)
    else:
        order = jnp.argsort(dest, stable=True).astype(jnp.int32)
    dsorted = jnp.take(dest, order)
    start_of = jnp.searchsorted(dsorted, jnp.arange(n, dtype=jnp.int32)
                                ).astype(jnp.int32)
    pos = jnp.arange(cap, dtype=jnp.int32)
    rank = pos - jnp.take(start_of, jnp.clip(dsorted, 0, n - 1))
    fits = (dsorted < n) & (rank < quota)
    slot = jnp.where(fits, dsorted * quota + rank, n * quota)
    send_idx = jnp.full((n * quota,), cap, jnp.int32).at[slot].set(
        order, mode="drop")
    send_ok = jnp.zeros((n * quota,), jnp.bool_).at[slot].set(
        True, mode="drop")
    overflow = jnp.sum(((dsorted < n) & (rank >= quota)).astype(jnp.int32))

    def exchange_col(c: Column) -> Column:
        t = c.take(send_idx)
        if c.dtype.is_string:
            return Column(_all_to_all(t.data, axis),
                          _all_to_all(t.valid, axis), c.dtype,
                          _all_to_all(t.lengths, axis))
        return Column(_all_to_all(t.data, axis), _all_to_all(t.valid, axis),
                      c.dtype)

    cols = [exchange_col(c) for c in batch.columns]
    recv_sel = _all_to_all(send_ok, axis)
    out = ColumnarBatch(cols, recv_sel, batch.schema)
    return out, jax.lax.psum(overflow, axis)


def exchange_by_bucket(batch: ColumnarBatch, bucket, axis: str = DATA_AXIS
                       ) -> ColumnarBatch:
    """Sel-mask fallback: route each live row to device `bucket[row] % n`.

    Returns a batch of capacity n*cap whose selection mask keeps exactly the
    rows this device owns.  Since every destination receives the SAME column
    data (only the selection mask differs per destination), the data movement
    is an all_gather; only the mask needs a true all_to_all.  O(n*cap)
    received capacity — fine for small meshes, disqualifying at pod scale.
    """
    n = jax.lax.psum(1, axis)
    cap = batch.capacity
    dest = jnp.arange(n, dtype=jnp.int32)[:, None]            # [n, 1]
    sel_nd = batch.sel[None, :] & (bucket[None, :] == dest)    # [n, cap]
    recv_sel = _all_to_all(sel_nd.reshape(n * cap), axis)

    def gather(x):
        return jax.lax.all_gather(x, axis, tiled=True)

    def exchange_col(c: Column) -> Column:
        if c.dtype.is_string:
            return Column(gather(c.data), gather(c.valid), c.dtype,
                          gather(c.lengths))
        return Column(gather(c.data), gather(c.valid), c.dtype)

    cols = [exchange_col(c) for c in batch.columns]
    return ColumnarBatch(cols, recv_sel, batch.schema)


def key_buckets(key_cols: Sequence[Column], live, n: int):
    """Owner device of each row: h1(keys) % n (dead rows -> garbage, masked
    by sel downstream)."""
    if not key_cols:
        return jnp.zeros(live.shape, dtype=jnp.int32)
    h1, _ = hash_columns_double(key_cols, live)
    return (h1 % jnp.uint64(n)).astype(jnp.int32)


# ---------------------------------------------------------------------------
# generic exchange (shuffle/mesh_exchange.py drives this)
# ---------------------------------------------------------------------------

def append_pid_column(batch: ColumnarBatch, pids) -> ColumnarBatch:
    """Carry per-row partition ids through an exchange as a trailing
    int32 column (the receiving side needs them to serve per-partition
    reads; the exchange collectives move COLUMNS, so the ids ride as
    one)."""
    from ..types import IntegerType, Schema, StructField
    pid_col = Column(pids.astype(jnp.int32),
                     jnp.ones(batch.capacity, dtype=jnp.bool_),
                     IntegerType)
    schema = Schema(list(batch.schema) +
                    [StructField("__ici_pid__", IntegerType)])
    return ColumnarBatch(list(batch.columns) + [pid_col], batch.sel,
                         schema)


def exchange_partition_step(mesh: Mesh, num_partitions: int, pid_fn,
                            quota: int, pre=None, param_slots=None,
                            axis: str = DATA_AXIS,
                            use_allgather: bool = False):
    """The GENERIC-exchange collective (TpuShuffleExchangeExec's mesh
    lowering, shuffle/mesh_exchange.py): per device, [optional fused
    row-local chain `pre`] -> `pid_fn(local, global_start)` per-row
    partition ids over `num_partitions` -> global per-partition live-row
    counts (the AQE map statistics, computed DEVICE-side) -> ids carried
    as a trailing column through ONE tiled all-to-all routed by owner
    device `(pid * n) // num_partitions`.  Chain, partition-id compute
    and collective land in one compiled program; the data never leaves
    device memory.

    Returns fn: (row-sharded batch, start[, param values]) ->
    (exchanged batch + trailing ``__ici_pid__`` column, overflow scalar,
    per-partition global live counts).  `start` is the map task's
    round-robin offset (traced, so every map task shares one program);
    `param_slots` threads plan-cache parameter values as a trailing
    traced argument (exec/basic.bound_param_builder rationale).
    overflow > 0 means the compact quota dropped rows — the driver must
    retry with a doubled quota, exactly like every other quota-block
    exchange in this module."""
    n = mesh.shape[axis]

    def step(local: ColumnarBatch, start):
        if pre is not None:
            local = pre(local)
        base = jax.lax.axis_index(axis).astype(jnp.int32) \
            * jnp.int32(local.capacity)
        pids = pid_fn(local, start + base).astype(jnp.int32)
        counts = jnp.bincount(
            jnp.where(local.sel, pids, jnp.int32(num_partitions)),
            length=num_partitions + 1)[:num_partitions]
        counts = jax.lax.psum(counts, axis)
        owner = (pids * jnp.int32(n)) // jnp.int32(num_partitions)
        carried = append_pid_column(local, pids)
        if use_allgather:
            ex = exchange_by_bucket(carried, owner, axis)
            return ex, jnp.int32(0), counts
        ex, overflow = exchange_compact(carried, owner, quota, axis)
        return ex, overflow, counts

    if param_slots is None:
        return shard_map(step, mesh=mesh, in_specs=(P(axis), P()),
                         out_specs=(P(axis), P(), P()))
    from ..ops import expressions as PE

    def step_p(local: ColumnarBatch, start, pvals):
        with PE.bound_params(dict(zip(param_slots, pvals))):
            return step(local, start)

    return shard_map(step_p, mesh=mesh, in_specs=(P(axis), P(), P()),
                     out_specs=(P(axis), P(), P()))


# ---------------------------------------------------------------------------
# aggregate
# ---------------------------------------------------------------------------

def distributed_aggregate_step(agg, mesh: Mesh, axis: str = DATA_AXIS,
                               pre=None, quota=None,
                               use_allgather: bool = False):
    """Build the full SPMD aggregation step over a mesh.

    Per device: [optional fused filter/project `pre`] -> update-aggregate
    local rows -> all_to_all partial states by key hash -> merge-aggregate
    owned groups -> finalize.  This is the TPU equivalent of the reference's
    partial-agg -> shuffle -> final-agg stage pair (reference:
    rapids/aggregate.scala Partial/Final modes + GpuShuffleExchangeExec), as
    one compiled XLA program.

    Returns a function: globally row-sharded batch -> (row-sharded result
    batch whose live rows are each device's owned groups, overflow scalar).
    overflow > 0 means the exchange quota was exceeded: the result is
    incomplete and the caller must retry with a larger quota (see
    run_distributed_aggregate).  The sel-mask path never overflows.
    """
    n = mesh.shape[axis]
    nkeys = len(agg.grouping)

    def step(local: ColumnarBatch):
        if pre is not None:
            local = pre(local)
        state = agg._update_kernel(local)
        bucket = key_buckets(list(state.columns[:nkeys]), state.sel, n)
        if use_allgather:
            gathered = exchange_by_bucket(state, bucket, axis)
            overflow = jnp.int32(0)
        else:
            q = quota if quota is not None \
                else default_quota(state.capacity, n)
            gathered, overflow = exchange_compact(state, bucket, q, axis)
        merged = agg._merge_kernel(gathered)
        return agg._finalize_kernel(merged), overflow

    return shard_map(step, mesh=mesh, in_specs=(P(axis),),
                     out_specs=(P(axis), P()))


def _jit_step(builder, cache_key):
    """jit a distributed step, optionally through the process-wide kernel
    cache (planner-integrated execs pass a structural key so repeated
    queries reuse the compiled SPMD program instead of retracing)."""
    if cache_key is None:
        return jax.jit(builder())
    from ..utils.kernel_cache import cached_kernel
    return cached_kernel(cache_key, builder)


def run_distributed_aggregate(agg, mesh: Mesh, batch: ColumnarBatch,
                              pre=None, axis: str = DATA_AXIS,
                              use_allgather: bool = False,
                              cache_key=None) -> ColumnarBatch:
    """Host driver: run the SPMD aggregate with overflow-retry.

    Doubles the exchange quota (recompiling) until the exchange is lossless;
    terminates because quota caps at the local capacity, where every row
    fits by construction."""
    n = mesh.shape[axis]
    local_cap = batch.capacity // n
    quota = None if use_allgather else default_quota(local_cap, n)
    while True:
        ck = None if cache_key is None else \
            cache_key + (n, local_cap, quota, use_allgather)
        step = _jit_step(
            lambda: distributed_aggregate_step(
                agg, mesh, axis=axis, pre=pre, quota=quota,
                use_allgather=use_allgather), ck)
        with mesh:
            out, overflow = step(batch)
        if use_allgather or int(overflow) == 0:
            return out
        if quota >= local_cap:  # pragma: no cover - cannot overflow at cap
            raise AssertionError("overflow with quota == local capacity")
        quota = min(local_cap, quota * 2)


# ---------------------------------------------------------------------------
# streaming aggregate (VERDICT r3 item 4: no whole-input host concat)
# ---------------------------------------------------------------------------

def _concat_local(a: ColumnarBatch, b: ColumnarBatch,
                  schema) -> ColumnarBatch:
    """Trace-safe per-device concat of two state batches (live rows stay
    wherever their sel marks them; the merge kernel keys off sel, not
    position).  Unlike columnar.concat_batches this never syncs row counts
    to the host, so it can run inside a shard_map program."""
    cols = []
    for ca, cb, f in zip(a.columns, b.columns, schema):
        if f.dtype.is_string:
            ml = max(ca.max_len, cb.max_len)
            pa_, pb = ca.pad_strings_to(ml), cb.pad_strings_to(ml)
            cols.append(Column(
                jnp.concatenate([pa_.data, pb.data], axis=0),
                jnp.concatenate([pa_.valid, pb.valid]), f.dtype,
                jnp.concatenate([pa_.lengths, pb.lengths])))
        else:
            cols.append(Column(
                jnp.concatenate([ca.data, cb.data]),
                jnp.concatenate([ca.valid, cb.valid]), f.dtype))
    sel = jnp.concatenate([a.sel, b.sel])
    return ColumnarBatch(cols, sel, schema)


def distributed_aggregate_partial_step(agg, mesh: Mesh,
                                       axis: str = DATA_AXIS, pre=None,
                                       quota=None,
                                       use_allgather: bool = False):
    """The streaming chunk step: update -> all_to_all by key hash -> merge,
    WITHOUT finalize.  Because the exchange routes every state row by key
    hash, a given group's partials land on the same device in every chunk —
    so cross-chunk merging is purely device-local (no further collective).

    Returns fn: sharded chunk -> (sharded state, overflow, max_groups)
    where max_groups is the largest per-device live-group count (for the
    host's state-compaction decision)."""
    n = mesh.shape[axis]
    nkeys = len(agg.grouping)

    def step(local: ColumnarBatch):
        if pre is not None:
            local = pre(local)
        state = agg._update_kernel(local)
        bucket = key_buckets(list(state.columns[:nkeys]), state.sel, n)
        if use_allgather:
            gathered = exchange_by_bucket(state, bucket, axis)
            overflow = jnp.int32(0)
        else:
            q = quota if quota is not None \
                else default_quota(state.capacity, n)
            gathered, overflow = exchange_compact(state, bucket, q, axis)
        merged = agg._merge_kernel(gathered)
        ng = jax.lax.pmax(jnp.sum(merged.sel.astype(jnp.int32)), axis)
        return merged, overflow, ng

    return shard_map(step, mesh=mesh, in_specs=(P(axis),),
                     out_specs=(P(axis), P(), P()))


def distributed_aggregate_combine_step(agg, mesh: Mesh,
                                       axis: str = DATA_AXIS):
    """Cross-chunk state merge, device-local: concat the running state with
    a chunk's partial state and re-merge.  Returns fn:
    (state, partial) -> (merged state at concat capacity, max_groups)."""
    def step(a: ColumnarBatch, b: ColumnarBatch):
        merged = agg._merge_kernel(_concat_local(a, b, agg._state_schema))
        ng = jax.lax.pmax(jnp.sum(merged.sel.astype(jnp.int32)), axis)
        return merged, ng

    return shard_map(step, mesh=mesh, in_specs=(P(axis), P(axis)),
                     out_specs=(P(axis), P()))


def distributed_shrink_step(mesh: Mesh, new_local_cap: int,
                            axis: str = DATA_AXIS):
    """Compact a state batch down to `new_local_cap` rows per device (live
    groups are front-compacted by the merge kernel, so a prefix slice is
    lossless once new_local_cap >= every device's live count)."""
    def step(state: ColumnarBatch):
        idx = jnp.arange(new_local_cap, dtype=jnp.int32)
        cols = [c.take(idx) for c in state.columns]
        return ColumnarBatch(cols, jnp.take(state.sel, idx), state.schema)

    return shard_map(step, mesh=mesh, in_specs=(P(axis),),
                     out_specs=P(axis))


def distributed_finalize_step(agg, mesh: Mesh, axis: str = DATA_AXIS):
    def step(state: ColumnarBatch):
        return agg._finalize_kernel(state)
    return shard_map(step, mesh=mesh, in_specs=(P(axis),),
                     out_specs=P(axis))


def run_distributed_aggregate_streaming(agg, mesh: Mesh, chunks,
                                        pre=None, axis: str = DATA_AXIS,
                                        use_allgather: bool = False,
                                        cache_key=None):
    """Host driver: stream sharded input chunks through the mesh.

    Per chunk: partial step (update/exchange/merge) with quota
    overflow-retry; then a device-local combine with the running state;
    then, when the running state's capacity is far above its live-group
    count, a prefix-slice compaction (one host sync per chunk reads the
    max group count).  Peak device memory is one chunk + the compacted
    state — never the whole input (reference: partial/final agg pair
    streams batches through the shuffle the same way).  Returns the
    finalized sharded result, or None for empty input."""
    from ..columnar.batch import bucket_rows
    n = mesh.shape[axis]
    state = None
    state_ng = 0
    for chunk in chunks:
        local_cap = chunk.capacity // n
        quota = None if use_allgather else default_quota(local_cap, n)
        while True:
            ck = None if cache_key is None else \
                cache_key + ("spartial", n, local_cap, quota, use_allgather)
            pstep = _jit_step(
                lambda: distributed_aggregate_partial_step(
                    agg, mesh, axis=axis, pre=pre, quota=quota,
                    use_allgather=use_allgather), ck)
            with mesh:
                partial, overflow, ng = pstep(chunk)
            if use_allgather or int(overflow) == 0:
                break
            quota = min(local_cap, quota * 2)
        if state is None:
            state, state_ng = partial, int(ng)
        else:
            a_cap = state.capacity // n
            b_cap = partial.capacity // n
            ck = None if cache_key is None else \
                cache_key + ("scombine", n, a_cap, b_cap)
            cstep = _jit_step(
                lambda: distributed_aggregate_combine_step(agg, mesh, axis),
                ck)
            with mesh:
                state, ng = cstep(state, partial)
            state_ng = int(ng)
        # compact: keep the state near its live size so capacity doesn't
        # grow with chunk COUNT when the group count is small
        state_local = state.capacity // n
        target = bucket_rows(max(state_ng, 1))
        if target < state_local:
            ck = None if cache_key is None else \
                cache_key + ("sshrink", n, state_local, target)
            sstep = _jit_step(
                lambda: distributed_shrink_step(mesh, target, axis), ck)
            with mesh:
                state = sstep(state)
    if state is None:
        return None
    ck = None if cache_key is None else \
        cache_key + ("sfinal", n, state.capacity // n)
    fstep = _jit_step(lambda: distributed_finalize_step(agg, mesh, axis),
                      ck)
    with mesh:
        return fstep(state)


# ---------------------------------------------------------------------------
# join
# ---------------------------------------------------------------------------

def distributed_join_step(join, mesh: Mesh, max_dup: int, out_cap: int,
                          quota_left: int, quota_right: int,
                          axis: str = DATA_AXIS,
                          use_allgather: bool = False):
    """SPMD hash join: hash-partition both sides by join key, local
    sort+searchsorted join per device (the reference pairs
    GpuShuffleExchangeExec with GpuShuffledHashJoinExec the same way;
    GpuShuffledHashJoinExec.scala:83-87).

    Static knobs (bounded-capacity + overflow-retry, see module docstring):
      * quota_left/right — exchange quotas per side;
      * max_dup  — widest candidate hash window the probe loop scans;
      * out_cap  — output slot count per device (inner/left only).

    Returns fn: (left_sharded, right_sharded) ->
        (out_batch, left_overflow, right_overflow, dup_overflow,
         cap_overflow)
    where the four scalars flag which knob was too small (0 = fine).
    """
    n = mesh.shape[axis]

    def step(lleft: ColumnarBatch, lright: ColumnarBatch):
        lkey_cols = [e.eval(lleft) for e in join.left_keys]
        rkey_cols = [e.eval(lright) for e in join.right_keys]
        lbucket = key_buckets(lkey_cols, lleft.sel, n)
        rbucket = key_buckets(rkey_cols, lright.sel, n)
        if use_allgather:
            lex = exchange_by_bucket(lleft, lbucket, axis)
            rex = exchange_by_bucket(lright, rbucket, axis)
            lovf = rovf = jnp.int32(0)
        else:
            lex, lovf = exchange_compact(lleft, lbucket, quota_left, axis)
            rex, rovf = exchange_compact(lright, rbucket, quota_right, axis)

        build, bkeys, h1s = join._build_kernel(rex)
        lo, hi, max_dup_t = join._window_kernel(lex, h1s)
        dup_overflow = jnp.maximum(max_dup_t.astype(jnp.int32) - max_dup, 0)
        counts, starts, total = join._count_kernel(
            max_dup, lex, build, bkeys, lo, hi, vary_axes=(axis,))
        if join.join_type in ("left_semi", "left_anti"):
            out = join._semi_kernel(lex, counts)
            out = ColumnarBatch(out.columns, out.sel, join._schema)
            cap_overflow = jnp.int32(0)
        else:
            out = join._gather_kernel(max_dup, out_cap, lex, build, bkeys,
                                      lo, hi, counts, starts, total,
                                      vary_axes=(axis,))
            cap_overflow = jnp.maximum(total.astype(jnp.int32) - out_cap, 0)
        return (out, jax.lax.psum(lovf, axis), jax.lax.psum(rovf, axis),
                jax.lax.psum(dup_overflow, axis),
                jax.lax.psum(cap_overflow, axis))

    return shard_map(step, mesh=mesh, in_specs=(P(axis), P(axis)),
                     out_specs=(P(axis), P(), P(), P(), P()))


def run_distributed_join(join, mesh: Mesh, left: ColumnarBatch,
                         right: ColumnarBatch, axis: str = DATA_AXIS,
                         max_dup: int = 8, out_cap=None,
                         use_allgather: bool = False,
                         cache_key=None) -> ColumnarBatch:
    """Host driver for the SPMD join with overflow-retry on all three knobs."""
    n = mesh.shape[axis]
    lcap, rcap = left.capacity // n, right.capacity // n
    quota_l = default_quota(lcap, n)
    quota_r = default_quota(rcap, n)
    # received capacities are n*quota; out_cap defaults assume modest fanout
    if out_cap is None:
        out_cap = max(n * quota_l, 1024)
    while True:
        ck = None if cache_key is None else \
            cache_key + (n, lcap, rcap, max_dup, out_cap, quota_l, quota_r,
                         use_allgather)
        step = _jit_step(
            lambda: distributed_join_step(
                join, mesh, max_dup, out_cap, quota_l, quota_r, axis=axis,
                use_allgather=use_allgather), ck)
        with mesh:
            out, l_ovf, r_ovf, dup_ovf, cap_ovf = step(left, right)
        retry = False
        if not use_allgather and int(l_ovf) > 0:
            if quota_l >= lcap:  # pragma: no cover - cap always fits
                raise AssertionError("left exchange overflow at full quota")
            quota_l = min(lcap, quota_l * 2)
            retry = True
        if not use_allgather and int(r_ovf) > 0:
            if quota_r >= rcap:  # pragma: no cover - cap always fits
                raise AssertionError("right exchange overflow at full quota")
            quota_r = min(rcap, quota_r * 2)
            retry = True
        if int(dup_ovf) > 0:
            # power-of-two bucket: bounded kernel-cache keys
            max_dup = pow2_bucket(max_dup + int(dup_ovf))
            retry = True
        if int(cap_ovf) > 0:
            out_cap = out_cap * 2
            retry = True
        if not retry:
            return out


def distributed_join_build_exchange_step(join, mesh: Mesh, quota_right: int,
                                         axis: str = DATA_AXIS,
                                         use_allgather: bool = False):
    """Exchange the BUILD side by join-key hash once; the exchanged batch
    stays mesh-resident for every probe chunk (the reference keeps the
    built hash table across stream batches the same way,
    GpuShuffledHashJoinExec.scala:83-87)."""
    n = mesh.shape[axis]

    def step(lright: ColumnarBatch):
        rkey_cols = [e.eval(lright) for e in join.right_keys]
        rbucket = key_buckets(rkey_cols, lright.sel, n)
        if use_allgather:
            rex = exchange_by_bucket(lright, rbucket, axis)
            rovf = jnp.int32(0)
        else:
            rex, rovf = exchange_compact(lright, rbucket, quota_right, axis)
        return rex, jax.lax.psum(rovf, axis)

    return shard_map(step, mesh=mesh, in_specs=(P(axis),),
                     out_specs=(P(axis), P()))


def distributed_join_probe_step(join, mesh: Mesh, max_dup: int,
                                out_cap: int, quota_left: int,
                                axis: str = DATA_AXIS,
                                use_allgather: bool = False):
    """Per-chunk probe: exchange one STREAM-side chunk by key hash and join
    it against the resident exchanged build side.  Correct per chunk for
    inner/left/left_semi/left_anti because each left row's result depends
    only on the build side."""
    n = mesh.shape[axis]

    def step(lleft: ColumnarBatch, rex: ColumnarBatch):
        lkey_cols = [e.eval(lleft) for e in join.left_keys]
        lbucket = key_buckets(lkey_cols, lleft.sel, n)
        if use_allgather:
            lex = exchange_by_bucket(lleft, lbucket, axis)
            lovf = jnp.int32(0)
        else:
            lex, lovf = exchange_compact(lleft, lbucket, quota_left, axis)
        build, bkeys, h1s = join._build_kernel(rex)
        lo, hi, max_dup_t = join._window_kernel(lex, h1s)
        dup_overflow = jnp.maximum(max_dup_t.astype(jnp.int32) - max_dup, 0)
        counts, starts, total = join._count_kernel(
            max_dup, lex, build, bkeys, lo, hi, vary_axes=(axis,))
        if join.join_type in ("left_semi", "left_anti"):
            out = join._semi_kernel(lex, counts)
            out = ColumnarBatch(out.columns, out.sel, join._schema)
            cap_overflow = jnp.int32(0)
        else:
            out = join._gather_kernel(max_dup, out_cap, lex, build, bkeys,
                                      lo, hi, counts, starts, total,
                                      vary_axes=(axis,))
            cap_overflow = jnp.maximum(total.astype(jnp.int32) - out_cap, 0)
        return (out, jax.lax.psum(lovf, axis),
                jax.lax.psum(dup_overflow, axis),
                jax.lax.psum(cap_overflow, axis))

    return shard_map(step, mesh=mesh, in_specs=(P(axis), P(axis)),
                     out_specs=(P(axis), P(), P(), P()))


def run_distributed_join_streaming(join, mesh: Mesh, left_chunks,
                                   right: ColumnarBatch,
                                   axis: str = DATA_AXIS, max_dup: int = 8,
                                   out_cap=None,
                                   use_allgather: bool = False,
                                   cache_key=None):
    """Host driver: exchange the build side once (quota overflow-retry),
    then stream probe chunks through the mesh, yielding one sharded output
    batch per chunk.  Retry knobs (left quota / dup window / out capacity)
    warm up across chunks, so steady state is one dispatch per chunk."""
    n = mesh.shape[axis]
    rcap = right.capacity // n
    quota_r = default_quota(rcap, n)
    while True:
        ck = None if cache_key is None else \
            cache_key + ("jbuild", n, rcap, quota_r, use_allgather)
        bstep = _jit_step(
            lambda: distributed_join_build_exchange_step(
                join, mesh, quota_r, axis=axis,
                use_allgather=use_allgather), ck)
        with mesh:
            rex, rovf = bstep(right)
        if use_allgather or int(rovf) == 0:
            break
        if quota_r >= rcap:  # pragma: no cover - cap always fits
            raise AssertionError("right exchange overflow at full quota")
        quota_r = min(rcap, quota_r * 2)

    quota_l = None
    for chunk in left_chunks:
        lcap = chunk.capacity // n
        if quota_l is None or quota_l > lcap:
            quota_l = default_quota(lcap, n)
        if out_cap is None:
            out_cap = max(n * quota_l, 1024)
        while True:
            ck = None if cache_key is None else \
                cache_key + ("jprobe", n, lcap, rcap, max_dup, out_cap,
                             quota_l, quota_r, use_allgather)
            pstep = _jit_step(
                lambda: distributed_join_probe_step(
                    join, mesh, max_dup, out_cap, quota_l, axis=axis,
                    use_allgather=use_allgather), ck)
            with mesh:
                out, l_ovf, dup_ovf, cap_ovf = pstep(chunk, rex)
            retry = False
            if not use_allgather and int(l_ovf) > 0:
                if quota_l >= lcap:  # pragma: no cover - cap always fits
                    raise AssertionError(
                        "left exchange overflow at full quota")
                quota_l = min(lcap, quota_l * 2)
                retry = True
            if int(dup_ovf) > 0:
                max_dup = pow2_bucket(max_dup + int(dup_ovf))
                retry = True
            if int(cap_ovf) > 0:
                out_cap = out_cap * 2
                retry = True
            if not retry:
                break
        yield out


# ---------------------------------------------------------------------------
# sort
# ---------------------------------------------------------------------------

def _range_scalar_key(col: Column, ascending: bool, nulls_first: bool):
    """A monotone float64 COARSENING of one sort column's order, used only
    for range bucketing: rows that compare equal under the coarse key are
    guaranteed to land on the same device, so local full-precision sorting
    plus device order yields a correct global order.

    (f64 precision loss over int64/strings only *merges* adjacent key values
    — a coarsening — never reorders them.  Sentinels are ±inf, which MERGES
    NaN with +inf data values and nulls with ±inf extremes rather than
    inventing an order between them — merged rows colocate and the local
    full-precision sort places them.)"""
    if col.dtype.is_string:
        cap, L = col.data.shape
        w = col.data[:, :8].astype(jnp.uint64) if L >= 8 else jnp.pad(
            col.data, ((0, 0), (0, 8 - L))).astype(jnp.uint64)
        shifts = jnp.arange(56, -8, -8, dtype=jnp.uint64)
        key = jnp.sum(w << shifts, axis=1, dtype=jnp.uint64).astype(
            jnp.float64)
    elif col.dtype.is_floating:
        d = col.data.astype(jnp.float64)
        # NaN is greatest under Spark sort semantics: merge it with +inf
        key = jnp.where(jnp.isnan(d), jnp.float64(np.inf), d)
    else:
        key = col.data.astype(jnp.float64)
    if not ascending:
        key = -key
    null_key = jnp.float64(-np.inf if nulls_first else np.inf)
    return jnp.where(col.valid, key, null_key)


def distributed_sort_step(sort_exprs, ascending, nulls_first, mesh: Mesh,
                          quota: int, n_samples: int = 64,
                          axis: str = DATA_AXIS,
                          use_allgather: bool = False):
    """SPMD global sort: sample range bounds -> range-partition exchange ->
    local lexsort.  The reference realizes global sort as
    GpuRangePartitioner (host-side reservoir sampling) + per-partition
    GpuSortExec (GpuRangePartitioner.scala:42-216, GpuSortExec.scala); here
    the sampling, exchange and sort are one compiled SPMD program.

    Returns fn: sharded batch -> (sharded sorted batch, overflow).  Device
    d's live rows are all <= device d+1's under the sort order, and locally
    sorted — so shard order IS global order.
    """
    from ..exec.sort import sort_order
    n = mesh.shape[axis]
    first = sort_exprs[0]

    def step(local: ColumnarBatch):
        cap = local.capacity
        c0 = first.eval(local)
        coarse = _range_scalar_key(c0, ascending[0], nulls_first[0])
        live = local.sel
        m = jnp.sum(live.astype(jnp.int32))
        # sample n_samples evenly spaced live coarse keys (sorted, dead last)
        ckey = jnp.where(live, coarse, jnp.float64(np.inf))
        csorted = jnp.sort(ckey)
        sample_pos = (jnp.arange(n_samples, dtype=jnp.int32)
                      * jnp.maximum(m, 1)) // n_samples
        samples = jnp.take(csorted, jnp.clip(sample_pos, 0, cap - 1))
        samples = jnp.where(m > 0, samples, jnp.float64(np.inf))
        all_samples = jnp.sort(
            jax.lax.all_gather(samples, axis, tiled=True))     # [n*n_samples]
        bounds = jnp.take(all_samples,
                          jnp.arange(1, n, dtype=jnp.int32) * n_samples)
        bucket = jnp.searchsorted(bounds, coarse, side="left").astype(
            jnp.int32)
        if use_allgather:
            ex = exchange_by_bucket(local, bucket, axis)
            overflow = jnp.int32(0)
        else:
            ex, overflow = exchange_compact(local, bucket, quota, axis)
        order = sort_order(ex, sort_exprs, ascending, nulls_first)
        out = ex.take(order)
        k = jnp.arange(out.capacity, dtype=jnp.int32)
        out = out.with_sel(k < jnp.sum(ex.sel.astype(jnp.int32)))
        return out, jax.lax.psum(overflow, axis)

    return shard_map(step, mesh=mesh, in_specs=(P(axis),),
                     out_specs=(P(axis), P()))


def run_distributed_sort(sort_exprs, ascending, nulls_first, mesh: Mesh,
                         batch: ColumnarBatch, axis: str = DATA_AXIS,
                         use_allgather: bool = False,
                         cache_key=None) -> ColumnarBatch:
    """Host driver for the SPMD sort with quota overflow-retry."""
    n = mesh.shape[axis]
    local_cap = batch.capacity // n
    # range partitions are less uniform than hash: start with a wider quota
    quota = default_quota(local_cap, n, factor=4)
    while True:
        ck = None if cache_key is None else \
            cache_key + (n, local_cap, quota, use_allgather)
        step = _jit_step(
            lambda: distributed_sort_step(
                sort_exprs, ascending, nulls_first, mesh, quota, axis=axis,
                use_allgather=use_allgather), ck)
        with mesh:
            out, overflow = step(batch)
        if use_allgather or int(overflow) == 0:
            return out
        if quota >= local_cap:  # pragma: no cover - cannot overflow at cap
            raise AssertionError("overflow with quota == local capacity")
        quota = min(local_cap, quota * 2)
