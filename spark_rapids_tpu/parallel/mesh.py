"""Device mesh management for multi-chip execution.

The reference scales by "one GPU per Spark executor" plus UCX peer-to-peer
shuffle (reference: rapids/GpuDeviceManager.scala:98-112, shuffle-plugin/).
The TPU-native model is different and better matched to the hardware: all
chips of a slice form one `jax.sharding.Mesh`, columnar batches are sharded
over the row axis, and repartitioning rides ICI as an XLA all-to-all instead
of an RDMA transport (SURVEY.md §2.9, §5).

Axis convention:
  * "data"  — row-sharded batch parallelism (the SQL engine's only
    first-class axis; rows are this domain's "big dimension").
"""
from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"


def make_mesh(n_devices: Optional[int] = None,
              axis: str = DATA_AXIS,
              devices: Optional[Sequence] = None) -> Mesh:
    """A 1-D mesh over the first `n_devices` local devices."""
    devs = list(devices) if devices is not None else jax.devices()
    n = n_devices if n_devices is not None else len(devs)
    if n > len(devs):
        raise ValueError(f"asked for {n} devices, have {len(devs)}")
    return Mesh(np.array(devs[:n]), (axis,))


def row_sharding(mesh: Mesh, axis: str = DATA_AXIS) -> NamedSharding:
    """Shard the leading (row) axis of every leaf of a ColumnarBatch."""
    return NamedSharding(mesh, P(axis))


def shard_batch(batch, mesh: Mesh, axis: str = DATA_AXIS):
    """Place a host/single-device ColumnarBatch row-sharded onto the mesh.

    The batch capacity must divide evenly by the mesh size (callers pick
    power-of-two capacities via bucket_rows, so any power-of-two mesh fits).
    """
    n = mesh.shape[axis]
    if batch.capacity % n != 0:
        raise ValueError(
            f"batch capacity {batch.capacity} not divisible by mesh size {n}")
    return jax.device_put(batch, row_sharding(mesh, axis))
