"""Device mesh management for multi-chip execution.

The reference scales by "one GPU per Spark executor" plus UCX peer-to-peer
shuffle (reference: rapids/GpuDeviceManager.scala:98-112, shuffle-plugin/).
The TPU-native model is different and better matched to the hardware: all
chips of a slice form one `jax.sharding.Mesh`, columnar batches are sharded
over the row axis, and repartitioning rides ICI as an XLA all-to-all instead
of an RDMA transport (SURVEY.md §2.9, §5).

Axis convention:
  * "data"  — row-sharded batch parallelism (the SQL engine's only
    first-class axis; rows are this domain's "big dimension").
"""
from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"


def init_distributed(conf=None) -> bool:
    """Multi-host bring-up: join the jax.distributed coordination service so
    `jax.devices()` enumerates EVERY host's chips and one Mesh spans the
    pod (collectives ride ICI within a slice, DCN across slices — XLA
    routes by device topology; the reference's analogue is the UCX
    management-port handshake that exchanges worker addresses,
    shuffle-plugin UCX.scala:193-247).

    Controlled by spark.rapids.sql.tpu.mesh.coordinator (host:port);
    process count/id come from the companion confs or the standard
    JAX_NUM_PROCESSES/JAX_PROCESS_ID environment.  Returns True when
    distributed mode was initialized (idempotent; False = single-host)."""
    import os

    from .. import config as C
    coordinator = ""
    n_proc = proc_id = None
    if conf is not None:
        coordinator = str(conf.get(C.MESH_COORDINATOR) or "")
        n_proc = conf.get(C.MESH_NUM_PROCESSES)
        proc_id = conf.get(C.MESH_PROCESS_ID)
    coordinator = coordinator or os.environ.get("JAX_COORDINATOR", "")
    if not coordinator:
        return False
    kwargs = {"coordinator_address": coordinator}
    if n_proc:  # conf provided the topology: conf's process id goes with it
        kwargs["num_processes"] = int(n_proc)
        kwargs["process_id"] = int(proc_id or 0)
    elif int(os.environ.get("JAX_NUM_PROCESSES", 0) or 0):
        kwargs["num_processes"] = int(os.environ["JAX_NUM_PROCESSES"])
        kwargs["process_id"] = int(os.environ.get("JAX_PROCESS_ID", 0))
    done = getattr(init_distributed, "_done", None)
    if done == coordinator:
        return True  # idempotent per coordinator
    if done is not None:
        # jax.distributed.initialize would raise an opaque RuntimeError;
        # name the actual misconfiguration instead
        raise RuntimeError(
            f"jax.distributed already initialized with coordinator "
            f"{done!r}; cannot re-initialize with {coordinator!r} in the "
            f"same process")
    jax.distributed.initialize(**kwargs)
    init_distributed._done = coordinator
    return True


def make_mesh(n_devices: Optional[int] = None,
              axis: str = DATA_AXIS,
              devices: Optional[Sequence] = None) -> Mesh:
    """A 1-D mesh over the first `n_devices` devices.  After
    `init_distributed`, jax.devices() is the GLOBAL pod device list, so the
    same call shapes a multi-host mesh."""
    devs = list(devices) if devices is not None else jax.devices()
    n = n_devices if n_devices is not None else len(devs)
    if n > len(devs):
        raise ValueError(f"asked for {n} devices, have {len(devs)}")
    return Mesh(np.array(devs[:n]), (axis,))


def row_sharding(mesh: Mesh, axis: str = DATA_AXIS) -> NamedSharding:
    """Shard the leading (row) axis of every leaf of a ColumnarBatch."""
    return NamedSharding(mesh, P(axis))


def shard_batch(batch, mesh: Mesh, axis: str = DATA_AXIS):
    """Place a host/single-device ColumnarBatch row-sharded onto the mesh.

    The batch capacity must divide evenly by the mesh size (callers pick
    power-of-two capacities via bucket_rows, so any power-of-two mesh fits).
    """
    n = mesh.shape[axis]
    if batch.capacity % n != 0:
        raise ValueError(
            f"batch capacity {batch.capacity} not divisible by mesh size {n}")
    return jax.device_put(batch, row_sharding(mesh, axis))
