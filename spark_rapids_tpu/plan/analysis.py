"""Analysis: resolve the unresolved DSL against child schemas.

Produces typed, bound Expression trees (ops/expressions.py) and computes
output schemas for every logical node.  Inserts Casts for type coercion the
way Spark's analyzer would (string literal vs date column -> cast literal,
numeric promotion, etc.), so the device expression engine only ever sees
well-typed trees.
"""
from __future__ import annotations

from typing import List, Optional, Tuple

from ..ops import expressions as E
from ..ops import math as M
from ..ops.aggregates import AGG_FUNCS, AggregateExpression
from ..ops.cast import Cast, supported_cast
from ..types import (BooleanType, DataType, DateType, DoubleType, IntegerType,
                     LongType, NullType, Schema, StringType, StructField,
                     TimestampType, promote)
from .logical import ColumnExpr, SortOrder, WhenBuilder

# ops resolved via simple constructor lookup: ColumnExpr op name -> class
_SIMPLE = {}
for _n in ("Add Subtract Multiply Divide IntegralDivide Remainder Pmod "
           "UnaryMinus Abs EqualTo LessThan GreaterThan LessThanOrEqual "
           "GreaterThanOrEqual EqualNullSafe And Or Not IsNull IsNotNull "
           "IsNaN Coalesce NaNvl BitwiseAnd BitwiseOr BitwiseXor BitwiseNot "
           "ShiftLeft ShiftRight ShiftRightUnsigned").split():
    _SIMPLE[_n] = getattr(E, _n)
for _n in ("Sqrt Cbrt Exp Expm1 Log Log2 Log10 Log1p Sin Cos Tan Asin Acos "
           "Atan Sinh Cosh Tanh Asinh Acosh Atanh ToDegrees ToRadians "
           "Signum Floor Ceil Rint Pow Atan2").split():
    _SIMPLE[_n] = getattr(M, _n)
for _n in ("NormalizeNaNAndZero", "KnownFloatingPointNormalized",
           "InputFileName", "InputFileBlockStart", "InputFileBlockLength"):
    _SIMPLE[_n] = getattr(E, _n)

_COMPARISONS = {"EqualTo", "LessThan", "GreaterThan", "LessThanOrEqual",
                "GreaterThanOrEqual", "EqualNullSafe"}
_ARITH = {"Add", "Subtract", "Multiply", "Divide", "IntegralDivide",
          "Remainder", "Pmod"}


class AnalysisError(Exception):
    pass


def coerce_pair(l: E.Expression, r: E.Expression, op: str
                ) -> Tuple[E.Expression, E.Expression]:
    """Insert casts so a binary op sees compatible types."""
    lt, rt = l.dtype, r.dtype
    if lt is rt:
        return l, r
    if lt is NullType:
        return E.Literal(None, rt), r
    if rt is NullType:
        return l, E.Literal(None, lt)
    if lt.is_numeric and rt.is_numeric:
        return l, r  # BinaryExpression promotes internally
    # string vs date/timestamp/numeric: cast the string side (Spark coerces
    # string literals to the other operand's type)
    if lt.is_string and supported_cast(lt, rt):
        return Cast(l, rt), r
    if rt.is_string and supported_cast(rt, lt):
        return l, Cast(r, lt)
    # date vs timestamp: widen date
    if lt is DateType and rt is TimestampType:
        return Cast(l, TimestampType), r
    if lt is TimestampType and rt is DateType:
        return l, Cast(r, TimestampType)
    if op in _COMPARISONS and lt.name == rt.name:
        return l, r
    raise AnalysisError(f"cannot apply {op} to {lt.name} and {rt.name}")


def resolve(ce, schema: Schema, partition_id: int = 0) -> E.Expression:
    """ColumnExpr -> typed bound Expression."""
    if not isinstance(ce, ColumnExpr):
        return E.lit(ce)
    op = ce.op
    if op == "col":
        name = ce.args[0]
        try:
            idx = schema.index_of(name)
        except KeyError:
            raise AnalysisError(
                f"column {name!r} not found in {schema.names}")
        return E.BoundReference(idx, schema[idx].dtype, name)
    if op == "lit":
        return E.Literal(ce.args[0])
    if op == "param":
        # plan-cache parameter (serve/plan_cache.py): a lifted literal
        # carrying (slot, dtype, current value) inline — resolves to a
        # Parameter whose value re-binds per submission
        slot, dtype, value = ce.args
        return E.Parameter(slot, value, dtype)
    if op == "Cast":
        child = resolve(ce.args[0], schema, partition_id)
        to = ce.args[1]
        if child.dtype is NullType:
            return E.Literal(None, to)
        if not supported_cast(child.dtype, to):
            raise AnalysisError(f"cast {child.dtype.name}->{to.name} "
                                "not supported")
        return Cast(child, to)
    if op == "In":
        child = resolve(ce.args[0], schema, partition_id)
        return E.In(child, list(ce.args[1]))
    if op == "CaseWhen":
        branches, otherwise = ce.args
        rb = [(resolve(p, schema, partition_id),
               resolve(v, schema, partition_id)) for p, v in branches]
        ro = resolve(otherwise, schema, partition_id) \
            if otherwise is not None else None
        return E.CaseWhen(rb, ro)
    if op in AGG_FUNCS:
        if op == "Percentile":
            child_ce, distinct, pct = ce.args
            if distinct:
                raise AnalysisError("percentile(DISTINCT) is not supported")
            if not (0.0 <= float(pct) <= 1.0):
                raise AnalysisError(f"percentile p={pct} outside [0, 1]")
            child = resolve(child_ce, schema, partition_id)
            if not child.dtype.is_numeric:
                raise AnalysisError(
                    f"percentile over {child.dtype.name}")
            return AggregateExpression(op, child, False,
                                       output_name=ce.output_name,
                                       param=float(pct))
        child_ce, distinct = ce.args
        child = None
        if not (child_ce.op == "lit" and child_ce.args[0] in (1, "*")):
            child = resolve(child_ce, schema, partition_id)
        return AggregateExpression(op, child, distinct,
                                   output_name=ce.output_name)
    if op == "Rand":
        return E.Rand(ce.args[0], partition_id)
    if op == "SparkPartitionID":
        return E.SparkPartitionID(partition_id)
    if op == "MonotonicallyIncreasingID":
        return E.MonotonicallyIncreasingID(partition_id)
    # string/date ops resolved lazily to keep import cycles away
    from ..ops import strings as S
    from ..ops import datetime_exprs as D
    _STRING = {"Upper": S.Upper, "Lower": S.Lower, "Length": S.Length,
               "Substring": S.Substring, "Concat": S.Concat,
               "StartsWith": S.StartsWith, "EndsWith": S.EndsWith,
               "Contains": S.Contains, "Like": S.Like, "Trim": S.StringTrim,
               "LTrim": S.StringTrimLeft, "RTrim": S.StringTrimRight,
               "StringReplace": S.StringReplace, "Locate": S.StringLocate,
               "InitCap": S.InitCap, "Reverse": S.Reverse,
               "Ascii": S.Ascii, "StringLPad": S.StringLPad,
               "StringRPad": S.StringRPad, "StringRepeat": S.StringRepeat,
               "SubstringIndex": S.SubstringIndex,
               "RegExpReplace": S.RegExpReplace}
    _DATE = {"Year": D.Year, "Month": D.Month, "DayOfMonth": D.DayOfMonth,
             "Hour": D.Hour, "Minute": D.Minute, "Second": D.Second,
             "DayOfWeek": D.DayOfWeek, "DayOfYear": D.DayOfYear,
             "Quarter": D.Quarter, "LastDay": D.LastDay,
             "DateAdd": D.DateAdd, "DateSub": D.DateSub,
             "DateDiff": D.DateDiff, "UnixTimestamp": D.UnixTimestamp,
             "FromUnixTime": D.FromUnixTime, "AddMonths": D.AddMonths,
             "MonthsBetween": D.MonthsBetween, "TruncDate": D.TruncDate,
             "NextDay": D.NextDay}
    if op == "AtLeastNNonNulls":
        n, child_ces = ce.args
        return E.AtLeastNNonNulls(
            n, [resolve(a, schema, partition_id) for a in child_ces])
    if op in ("TimeAdd", "TimeSub"):
        from ..ops import datetime_exprs as D2
        cls = D2.TimeAdd if op == "TimeAdd" else D2.TimeSub
        return cls(resolve(ce.args[0], schema, partition_id),
                   resolve(ce.args[1], schema, partition_id))
    if op in ("Round", "BRound", "Hypot", "Cot", "Logarithm",
              "Least", "Greatest", "Murmur3Hash"):
        from ..ops import math as M
        from ..ops.hashing import Murmur3Hash
        args = [resolve(a, schema, partition_id) for a in ce.args]
        _extra = {"Round": M.Round, "BRound": M.BRound, "Hypot": M.Hypot,
                  "Cot": M.Cot, "Logarithm": M.Logarithm,
                  "Least": E.Least, "Greatest": E.Greatest,
                  "Murmur3Hash": Murmur3Hash}
        return _extra[op](*args)
    if op in _STRING:
        args = [resolve(a, schema, partition_id) for a in ce.args]
        return _STRING[op](*args)
    if op in _DATE:
        args = [resolve(a, schema, partition_id) for a in ce.args]
        return _DATE[op](*args)
    if op in _SIMPLE:
        args = [resolve(a, schema, partition_id) for a in ce.args]
        if len(args) == 2 and (op in _COMPARISONS or op in _ARITH):
            args = list(coerce_pair(args[0], args[1], op))
        return _SIMPLE[op](*args)
    raise AnalysisError(f"unknown expression op {op!r}")


def output_field(ce: ColumnExpr, expr: E.Expression) -> StructField:
    return StructField(ce.output_name, expr.dtype)


def _infer_value_dtype(values) -> Optional[DataType]:
    """Common type of an array literal's elements (numeric promotion; None
    when elements are mixed beyond promotion)."""
    from ..ops.expressions import _infer_literal_type
    dt: Optional[DataType] = None
    for v in values:
        if v is None:
            continue
        t = _infer_literal_type(v)
        if dt is None or dt is t:
            dt = t
        elif dt.is_numeric and t.is_numeric:
            dt = promote(dt, t)
        else:
            return None
    return dt
