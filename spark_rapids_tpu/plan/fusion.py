"""Whole-stage fusion pass.

Runs LAST in plan/transitions.finalize: walks the physical tree and
greedily groups maximal chains of row-local device operators
(exec/basic.RowLocalExec — project/filter/expand, including legacy
FusedPipelineExec chains, over scan-decode output) into
`TpuWholeStageExec` nodes, then numbers every stage for Spark-style
`*(N)` EXPLAIN rendering.  Chains longer than
`spark.rapids.sql.tpu.fusion.maxOpsPerStage` split into consecutive
stages so no single XLA program grows unboundedly.

Fusion BOUNDARIES are simply the non-row-local operators: exchange, join
build, sort, full aggregation, coalesce, limit — a stage always produces
exactly one materialized ColumnarBatch where one of those consumes it.
Two further fusions happen at the boundary itself, outside this pass:
`TpuHashAggregateExec` absorbs a whole-stage child into its own
update/merge/finalize program (exec/aggregate._try_whole_stage), and
`TpuShuffleExchangeExec` fuses its child stage's chain with the
hash-partition bucketing compute into one program per map batch
(exec/exchange._write_phase).

With `spark.rapids.sql.tpu.fusion.enabled=false` the pass degrades to
the legacy `fuse_row_local` behavior (FusedPipelineExec chain fusion, no
stage-level retry, no *(N) numbering).  The kill switch disables the
ENTIRE compiled-stage family — including the aggregate's whole-stage
absorption and the exchange bucketing fusion — so `false` is strictly
per-operator dispatch; use `wholeStage.enabled` to toggle the aggregate
absorption alone while fusion stays on.

The pass is idempotent on already-fused trees: a lone TpuWholeStageExec
chain is returned unchanged (identity preserved, so QueryExecution node
ids survive), which lets adaptive execution re-run it over re-planned
reduce sides (adaptive/executor.py) and fuse only the nodes the rules
introduced.
"""
from __future__ import annotations

from typing import List

from .. import config as C
from ..config import TpuConf
from ..exec import basic as B
from ..exec.base import ExecNode
from ..exec.whole_stage import TpuWholeStageExec


def fuse_stages(node: ExecNode, conf: TpuConf) -> ExecNode:
    """Entry point: whole-stage fusion + stage numbering (or the legacy
    chain fusion when disabled)."""
    from .transitions import fuse_row_local
    if not conf.get(C.FUSION_ENABLED):
        return fuse_row_local(node)
    max_ops = max(1, int(conf.get(C.FUSION_MAX_OPS)))
    node = _fuse(node, max_ops)
    number_stages(node)
    return node


def _fuse(node: ExecNode, max_ops: int) -> ExecNode:
    node.children = [_fuse(c, max_ops) for c in node.children]
    if not isinstance(node, B.RowLocalExec):
        return node
    # collect the maximal chain, outermost first, flattening through
    # already-fused nodes (FusedPipelineExec and TpuWholeStageExec both
    # expose .stages)
    chain: List[B.RowLocalExec] = []
    cur: ExecNode = node
    while isinstance(cur, B.RowLocalExec):
        chain.append(cur)
        cur = cur.children[0]
    if all(isinstance(n, TpuWholeStageExec) and len(n.stages) <= max_ops
           for n in chain):
        # already fused (incl. chains CHUNKED by maxOpsPerStage into
        # stacked stages): keep identity, so node metrics/ids and *(N)
        # numbering survive AQE re-runs of the pass
        return node
    stages: List[B.RowLocalExec] = []  # execution order
    for n in reversed(chain):
        if isinstance(n, B.FusedPipelineExec):
            stages.extend(n.stages)
        else:
            stages.append(n)
    out = cur  # the source under the chain
    for i in range(0, len(stages), max_ops):
        ws = TpuWholeStageExec(stages[i:i + max_ops], out)
        # last-consumer analysis for buffer donation: this stage is the
        # only consumer of its source's batches exactly when the source
        # yields fresh per-call device arrays (see source_donatable);
        # chunked chains compose — stage i+1's source is stage i, whose
        # outputs are fresh program outputs
        ws.donate_inputs = source_donatable(out)
        out = ws
    return out


def source_donatable(source: ExecNode) -> bool:
    """True when `source.execute()` yields batches this plan's consumer
    is the LAST owner of: fresh device arrays built per call and
    referenced nowhere else.  Scan decode (memory/file), host->device
    adoption, coalesce (fresh concat/compact) and upstream whole stages
    qualify; shuffle readers (fetched batches live in the received-buffer
    catalog), joins/broadcasts (build batches are reused across probe
    calls) and everything unknown do NOT.  Runtime pins (mem/donation.py)
    still veto individual batches — the scan cache re-serves scan
    batches, so a whitelisted source does not by itself prove donation
    safe; this is the static half of the proof only."""
    from ..io.scan import TpuFileScanExec
    return isinstance(source, (B.TpuScanMemoryExec, B.HostToDeviceExec,
                               B.TpuCoalesceBatchesExec, TpuWholeStageExec,
                               TpuFileScanExec))


def number_stages(node: ExecNode, start: int = 1) -> int:
    """Assign Spark-style `*(N)` stage ids preorder over UNNUMBERED
    stages (stage_id 0); already-numbered stages keep their id, so
    re-running after adaptive re-planning numbers only the fresh ones.
    Returns the next unassigned id."""
    counter = [start]

    def walk(n: ExecNode) -> None:
        if isinstance(n, TpuWholeStageExec) and n.stage_id == 0:
            n.stage_id = counter[0]
            counter[0] += 1
        for c in n.children:
            walk(c)

    walk(node)
    return counter[0]


def max_stage_id(node: ExecNode) -> int:
    """Highest stage id already assigned in a tree (0 when none)."""
    best = 0
    stack = [node]
    while stack:
        n = stack.pop()
        if isinstance(n, TpuWholeStageExec):
            best = max(best, n.stage_id)
        stack.extend(n.children)
    return best
