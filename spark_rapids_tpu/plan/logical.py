"""Logical plan and the unresolved column DSL (the framework's frontend).

The reference plugs into Spark's Catalyst plans; this standalone framework
provides its own DataFrame-style frontend that produces the same *shape* of
physical-planning problem: a logical tree that the overrides pass (see
overrides.py) tags, converts to device operators where supported, and leaves
on the CPU executor where not.
"""
from __future__ import annotations

import dataclasses
from typing import Any, List, Optional, Sequence, Tuple

from ..types import DataType, Schema


# --------------------------------------------------------------------------
# unresolved expression DSL:  col("a") + 1, f.sum(...), etc.
# --------------------------------------------------------------------------

class ColumnExpr:
    """Unresolved expression; analysis resolves it against a child schema."""

    def __init__(self, op: str, args: Tuple = (), alias: Optional[str] = None):
        self.op = op
        self.args = args
        self._alias = alias

    # -- operators ----------------------------------------------------------
    def _bin(self, op, other, flip=False):
        other = _wrap(other)
        return ColumnExpr(op, (other, self) if flip else (self, other))

    def __add__(self, o):
        return self._bin("Add", o)

    def __radd__(self, o):
        return self._bin("Add", o, flip=True)

    def __sub__(self, o):
        return self._bin("Subtract", o)

    def __rsub__(self, o):
        return self._bin("Subtract", o, flip=True)

    def __mul__(self, o):
        return self._bin("Multiply", o)

    def __rmul__(self, o):
        return self._bin("Multiply", o, flip=True)

    def __truediv__(self, o):
        return self._bin("Divide", o)

    def __rtruediv__(self, o):
        return self._bin("Divide", o, flip=True)

    def __mod__(self, o):
        return self._bin("Remainder", o)

    def __neg__(self):
        return ColumnExpr("UnaryMinus", (self,))

    def __eq__(self, o):  # type: ignore[override]
        return self._bin("EqualTo", o)

    def __ne__(self, o):  # type: ignore[override]
        return ColumnExpr("Not", (self._bin("EqualTo", o),))

    def __lt__(self, o):
        return self._bin("LessThan", o)

    def __le__(self, o):
        return self._bin("LessThanOrEqual", o)

    def __gt__(self, o):
        return self._bin("GreaterThan", o)

    def __ge__(self, o):
        return self._bin("GreaterThanOrEqual", o)

    def __and__(self, o):
        return self._bin("And", o)

    def __or__(self, o):
        return self._bin("Or", o)

    def __invert__(self):
        return ColumnExpr("Not", (self,))

    def __hash__(self):
        return id(self)

    # -- methods ------------------------------------------------------------
    def alias(self, name: str) -> "ColumnExpr":
        return ColumnExpr(self.op, self.args, alias=name)

    def cast(self, to) -> "ColumnExpr":
        if isinstance(to, str):  # Spark accepts type names: .cast("BIGINT")
            from ..types import _TYPES_BY_NAME
            name = to.strip().lower()
            name = {"bigint": "long", "integer": "int",
                    "smallint": "short", "tinyint": "byte"}.get(name, name)
            if name not in _TYPES_BY_NAME:
                raise ValueError(
                    f"cast target type {to!r} is not supported "
                    f"(supported: {sorted(_TYPES_BY_NAME)})")
            to = _TYPES_BY_NAME[name]
        return ColumnExpr("Cast", (self, to))

    def isin(self, *items) -> "ColumnExpr":
        vals = items[0] if len(items) == 1 and isinstance(items[0],
                                                          (list, tuple)) \
            else items
        return ColumnExpr("In", (self, list(vals)))

    def is_null(self) -> "ColumnExpr":
        return ColumnExpr("IsNull", (self,))

    def is_not_null(self) -> "ColumnExpr":
        return ColumnExpr("IsNotNull", (self,))

    def between(self, lo, hi) -> "ColumnExpr":
        return (self >= lo) & (self <= hi)

    def asc(self) -> "SortOrder":
        return SortOrder(self, ascending=True)

    def desc(self) -> "SortOrder":
        return SortOrder(self, ascending=False)

    def over(self, spec: "WindowSpec") -> "ColumnExpr":
        """Turn an aggregate/ranking expression into a window expression
        (pyspark's Column.over)."""
        return ColumnExpr("WindowExpr", (self, spec), alias=self._alias)

    def substr(self, pos, length) -> "ColumnExpr":
        return ColumnExpr("Substring", (self, _wrap(pos), _wrap(length)))

    def startswith(self, s) -> "ColumnExpr":
        return ColumnExpr("StartsWith", (self, _wrap(s)))

    def endswith(self, s) -> "ColumnExpr":
        return ColumnExpr("EndsWith", (self, _wrap(s)))

    def contains(self, s) -> "ColumnExpr":
        return ColumnExpr("Contains", (self, _wrap(s)))

    def like(self, pattern: str) -> "ColumnExpr":
        return ColumnExpr("Like", (self, _wrap(pattern)))

    def rlike(self, pattern: str) -> "ColumnExpr":
        return ColumnExpr("RLike", (self, _wrap(pattern)))

    @property
    def output_name(self) -> str:
        if self._alias:
            return self._alias
        if self.op == "col":
            return self.args[0]
        return self.op.lower()

    def __repr__(self):
        if self.op == "col":
            return f"col({self.args[0]!r})"
        if self.op == "lit":
            return f"lit({self.args[0]!r})"
        if self.op == "param":
            slot, dtype, value = self.args
            return f"param({slot}:{dtype.name}={value!r})"
        return f"{self.op}({', '.join(map(repr, self.args))})"

    def __bool__(self):
        raise TypeError("Cannot convert ColumnExpr to bool; use & | ~")


def _wrap(v) -> ColumnExpr:
    if isinstance(v, ColumnExpr):
        return v
    return ColumnExpr("lit", (v,))


def col(name: str) -> ColumnExpr:
    return ColumnExpr("col", (name,))


def lit(v) -> ColumnExpr:
    return ColumnExpr("lit", (v,))


@dataclasses.dataclass
class SortOrder:
    child: ColumnExpr
    ascending: bool = True
    nulls_first: Optional[bool] = None  # default: first if asc, last if desc

    @property
    def effective_nulls_first(self) -> bool:
        return self.ascending if self.nulls_first is None else self.nulls_first


# functions namespace -------------------------------------------------------

class functions:
    """spark.sql.functions equivalent surface."""

    col = staticmethod(col)
    lit = staticmethod(lit)

    @staticmethod
    def _agg(op, e, distinct=False):
        return ColumnExpr(op, (_wrap(e), distinct))

    @staticmethod
    def sum(e):
        return functions._agg("Sum", e)

    @staticmethod
    def percentile(e, p: float):
        """Exact percentile with linear interpolation (Spark's
        `percentile`).  No device rule exists — the aggregate falls back
        to the CPU executors, exactly like the reference (which ships no
        GPU Percentile rule in this era)."""
        return ColumnExpr("Percentile", (_wrap(e), False, float(p)))

    @staticmethod
    def avg(e):
        return functions._agg("Average", e)

    mean = avg

    @staticmethod
    def min(e):
        return functions._agg("Min", e)

    @staticmethod
    def max(e):
        return functions._agg("Max", e)

    @staticmethod
    def count(e):
        return functions._agg("Count", e)

    @staticmethod
    def count_distinct(e):
        return functions._agg("Count", e, distinct=True)

    @staticmethod
    def first(e):
        return functions._agg("First", e)

    @staticmethod
    def last(e):
        return functions._agg("Last", e)

    @staticmethod
    def when(cond, value):
        return WhenBuilder([(cond, _wrap(value))])

    @staticmethod
    def input_file_name():
        return ColumnExpr("InputFileName", ())

    @staticmethod
    def input_file_block_start():
        return ColumnExpr("InputFileBlockStart", ())

    @staticmethod
    def input_file_block_length():
        return ColumnExpr("InputFileBlockLength", ())

    @staticmethod
    def asinh(e):
        return ColumnExpr("Asinh", (_wrap(e),))

    @staticmethod
    def acosh(e):
        return ColumnExpr("Acosh", (_wrap(e),))

    @staticmethod
    def atanh(e):
        return ColumnExpr("Atanh", (_wrap(e),))

    @staticmethod
    def coalesce(*exprs):
        return ColumnExpr("Coalesce", tuple(_wrap(e) for e in exprs))

    @staticmethod
    def abs(e):
        return ColumnExpr("Abs", (_wrap(e),))

    @staticmethod
    def sqrt(e):
        return ColumnExpr("Sqrt", (_wrap(e),))

    @staticmethod
    def exp(e):
        return ColumnExpr("Exp", (_wrap(e),))

    @staticmethod
    def log(e):
        return ColumnExpr("Log", (_wrap(e),))

    @staticmethod
    def pow(a, b):
        return ColumnExpr("Pow", (_wrap(a), _wrap(b)))

    @staticmethod
    def floor(e):
        return ColumnExpr("Floor", (_wrap(e),))

    @staticmethod
    def ceil(e):
        return ColumnExpr("Ceil", (_wrap(e),))

    @staticmethod
    def upper(e):
        return ColumnExpr("Upper", (_wrap(e),))

    @staticmethod
    def lower(e):
        return ColumnExpr("Lower", (_wrap(e),))

    @staticmethod
    def length(e):
        return ColumnExpr("Length", (_wrap(e),))

    @staticmethod
    def substring(e, pos, length):
        return ColumnExpr("Substring", (_wrap(e), _wrap(pos), _wrap(length)))

    @staticmethod
    def concat(*exprs):
        return ColumnExpr("Concat", tuple(_wrap(e) for e in exprs))

    @staticmethod
    def year(e):
        return ColumnExpr("Year", (_wrap(e),))

    @staticmethod
    def month(e):
        return ColumnExpr("Month", (_wrap(e),))

    @staticmethod
    def dayofmonth(e):
        return ColumnExpr("DayOfMonth", (_wrap(e),))

    @staticmethod
    def hour(e):
        return ColumnExpr("Hour", (_wrap(e),))

    @staticmethod
    def minute(e):
        return ColumnExpr("Minute", (_wrap(e),))

    @staticmethod
    def second(e):
        return ColumnExpr("Second", (_wrap(e),))

    @staticmethod
    def to_date(e):
        return ColumnExpr("Cast", (_wrap(e), __import__(
            "spark_rapids_tpu.types", fromlist=["DateType"]).DateType))

    @staticmethod
    def date_add(e, days):
        return ColumnExpr("DateAdd", (_wrap(e), _wrap(days)))

    @staticmethod
    def date_sub(e, days):
        return ColumnExpr("DateSub", (_wrap(e), _wrap(days)))

    @staticmethod
    def datediff(end, start):
        return ColumnExpr("DateDiff", (_wrap(end), _wrap(start)))

    @staticmethod
    def isnan(e):
        return ColumnExpr("IsNaN", (_wrap(e),))

    @staticmethod
    def rand(seed=0):
        return ColumnExpr("Rand", (seed,))

    @staticmethod
    def spark_partition_id():
        return ColumnExpr("SparkPartitionID", ())

    @staticmethod
    def monotonically_increasing_id():
        return ColumnExpr("MonotonicallyIncreasingID", ())

    @staticmethod
    def row_number():
        return ColumnExpr("RowNumber", ())

    @staticmethod
    def rank():
        return ColumnExpr("Rank", ())

    @staticmethod
    def dense_rank():
        return ColumnExpr("DenseRank", ())

    @staticmethod
    def lag(e, offset: int = 1, default=None):
        return ColumnExpr("Lag", (_wrap(e), offset, default))

    @staticmethod
    def lead(e, offset: int = 1, default=None):
        return ColumnExpr("Lead", (_wrap(e), offset, default))

    @staticmethod
    def initcap(e):
        return ColumnExpr("InitCap", (_wrap(e),))

    @staticmethod
    def reverse(e):
        return ColumnExpr("Reverse", (_wrap(e),))

    @staticmethod
    def ascii(e):
        return ColumnExpr("Ascii", (_wrap(e),))

    @staticmethod
    def lpad(e, length, pad=" "):
        return ColumnExpr("StringLPad", (_wrap(e), _wrap(length),
                                         _wrap(pad)))

    @staticmethod
    def rpad(e, length, pad=" "):
        return ColumnExpr("StringRPad", (_wrap(e), _wrap(length),
                                         _wrap(pad)))

    @staticmethod
    def repeat(e, n):
        return ColumnExpr("StringRepeat", (_wrap(e), _wrap(n)))

    @staticmethod
    def substring_index(e, delim, count):
        return ColumnExpr("SubstringIndex", (_wrap(e), _wrap(delim),
                                             _wrap(count)))

    @staticmethod
    def regexp_replace(e, pattern, replacement):
        return ColumnExpr("RegExpReplace", (_wrap(e), _wrap(pattern),
                                            _wrap(replacement)))

    @staticmethod
    def round(e, scale=0):
        return ColumnExpr("Round", (_wrap(e), _wrap(scale)))

    @staticmethod
    def bround(e, scale=0):
        return ColumnExpr("BRound", (_wrap(e), _wrap(scale)))

    @staticmethod
    def hypot(a, b):
        return ColumnExpr("Hypot", (_wrap(a), _wrap(b)))

    @staticmethod
    def cot(e):
        return ColumnExpr("Cot", (_wrap(e),))

    @staticmethod
    def log_base(base, e):
        return ColumnExpr("Logarithm", (_wrap(base), _wrap(e)))

    @staticmethod
    def least(*exprs):
        return ColumnExpr("Least", tuple(_wrap(e) for e in exprs))

    @staticmethod
    def greatest(*exprs):
        return ColumnExpr("Greatest", tuple(_wrap(e) for e in exprs))

    @staticmethod
    def hash(*exprs):
        return ColumnExpr("Murmur3Hash", tuple(_wrap(e) for e in exprs))

    @staticmethod
    def add_months(e, n):
        return ColumnExpr("AddMonths", (_wrap(e), _wrap(n)))

    @staticmethod
    def months_between(a, b, round_off=True):
        return ColumnExpr("MonthsBetween", (_wrap(a), _wrap(b),
                                            _wrap(round_off)))

    @staticmethod
    def trunc(e, fmt):
        return ColumnExpr("TruncDate", (_wrap(e), _wrap(fmt)))

    @staticmethod
    def next_day(e, day_of_week):
        return ColumnExpr("NextDay", (_wrap(e), _wrap(day_of_week)))

    @staticmethod
    def explode(values):
        """Explode an array literal: one output row per element per input
        row (reference scope: GpuGenerateExec.scala:101+ supports
        explode/posexplode of array literals)."""
        return ColumnExpr("Explode", (list(values),))

    @staticmethod
    def posexplode(values):
        """Like explode, plus a 0-based position column."""
        return ColumnExpr("PosExplode", (list(values),))


class WindowSpec:
    """partition/order/frame spec (pyspark WindowSpec equivalent; reference:
    rapids/GpuWindowExpression.scala window spec mapping)."""

    def __init__(self, parts=(), orders=(), frame=None):
        self.parts = list(parts)        # partition-by ColumnExprs
        self.orders = list(orders)      # SortOrders
        # frame: None (Spark default) | ("rows", start, end)
        self.frame = frame

    def partition_by(self, *cols) -> "WindowSpec":
        return WindowSpec([c if isinstance(c, ColumnExpr) else col(c)
                           for c in cols], self.orders, self.frame)

    partitionBy = partition_by

    def order_by(self, *orders) -> "WindowSpec":
        os = []
        for o in orders:
            if isinstance(o, SortOrder):
                os.append(o)
            elif isinstance(o, str):
                os.append(SortOrder(col(o)))
            else:
                os.append(SortOrder(o))
        return WindowSpec(self.parts, os, self.frame)

    orderBy = order_by

    def rows_between(self, start: int, end: int) -> "WindowSpec":
        return WindowSpec(self.parts, self.orders,
                          ("rows", int(start), int(end)))

    rowsBetween = rows_between

    def _group_key(self):
        """Specs with the same partition/order can share one window node."""
        return (tuple(repr(c) for c in self.parts),
                tuple((repr(o.child), o.ascending, o.effective_nulls_first)
                      for o in self.orders))


class Window:
    """pyspark.sql.Window-compatible namespace."""

    unboundedPreceding = unbounded_preceding = -(1 << 62)
    unboundedFollowing = unbounded_following = (1 << 62)
    currentRow = current_row = 0

    @staticmethod
    def partition_by(*cols) -> WindowSpec:
        return WindowSpec().partition_by(*cols)

    partitionBy = partition_by

    @staticmethod
    def order_by(*orders) -> WindowSpec:
        return WindowSpec().order_by(*orders)

    orderBy = order_by


class WhenBuilder(ColumnExpr):
    def __init__(self, branches, otherwise=None):
        super().__init__("CaseWhen", (tuple(branches), otherwise))
        self.branches = branches
        self.otherwise_value = otherwise

    def when(self, cond, value):
        return WhenBuilder(self.branches + [(cond, _wrap(value))])

    def otherwise(self, value):
        return WhenBuilder(self.branches, _wrap(value))


# --------------------------------------------------------------------------
# logical plan nodes
# --------------------------------------------------------------------------

class LogicalPlan:
    children: Tuple["LogicalPlan", ...] = ()

    def __repr__(self):
        return type(self).__name__


class LogicalScan(LogicalPlan):
    """A data source: in-memory arrow table or a file scan."""

    def __init__(self, source, schema: Schema, fmt: str,
                 options: Optional[dict] = None):
        self.source = source      # pa.Table | list[str] paths
        self.schema = schema
        self.fmt = fmt            # "memory" | "parquet" | "csv" | "orc"
        self.options = options or {}


class LogicalProject(LogicalPlan):
    def __init__(self, exprs: Sequence[ColumnExpr], child: LogicalPlan):
        self.exprs = list(exprs)
        self.children = (child,)


class LogicalFilter(LogicalPlan):
    def __init__(self, condition: ColumnExpr, child: LogicalPlan):
        self.condition = condition
        self.children = (child,)


class LogicalAggregate(LogicalPlan):
    def __init__(self, grouping: Sequence[ColumnExpr],
                 aggregates: Sequence[ColumnExpr], child: LogicalPlan):
        self.grouping = list(grouping)
        self.aggregates = list(aggregates)
        self.children = (child,)


class LogicalJoin(LogicalPlan):
    def __init__(self, left: LogicalPlan, right: LogicalPlan,
                 join_type: str, condition: Optional[ColumnExpr] = None,
                 using: Optional[List[str]] = None):
        self.join_type = join_type  # inner|left|right|left_semi|left_anti|cross|full
        self.condition = condition
        self.using = using
        self.children = (left, right)


class LogicalSort(LogicalPlan):
    def __init__(self, orders: Sequence[SortOrder], child: LogicalPlan):
        self.orders = [o if isinstance(o, SortOrder) else SortOrder(o)
                       for o in orders]
        self.children = (child,)


class LogicalLimit(LogicalPlan):
    def __init__(self, n: int, child: LogicalPlan):
        self.n = n
        self.children = (child,)


class LogicalUnion(LogicalPlan):
    def __init__(self, children: Sequence[LogicalPlan]):
        self.children = tuple(children)


class LogicalDistinct(LogicalPlan):
    def __init__(self, child: LogicalPlan):
        self.children = (child,)


class LogicalRepartition(LogicalPlan):
    def __init__(self, num_partitions: int, keys: Sequence[ColumnExpr],
                 child: LogicalPlan, mode: str = "hash",
                 ascending: Optional[Sequence[bool]] = None,
                 nulls_first: Optional[Sequence[bool]] = None):
        self.num_partitions = num_partitions
        self.keys = list(keys)
        self.mode = mode  # hash | round_robin | range | single
        self.ascending = list(ascending) if ascending is not None \
            else [True] * len(self.keys)
        self.nulls_first = list(nulls_first) if nulls_first is not None \
            else list(self.ascending)
        self.children = (child,)


class LogicalExpand(LogicalPlan):
    """ROLLUP/CUBE fan-out: list of projection lists."""

    def __init__(self, projections: Sequence[Sequence[ColumnExpr]],
                 child: LogicalPlan):
        self.projections = [list(p) for p in projections]
        self.children = (child,)


class LogicalGenerate(LogicalPlan):
    """Generator (explode/posexplode of an array literal) appended to the
    child's columns (Spark GenerateExec shape; reference:
    rapids/GpuGenerateExec.scala)."""

    def __init__(self, generator: ColumnExpr, names, child: LogicalPlan):
        self.generator = generator          # Explode | PosExplode ColumnExpr
        self.names = list(names)            # output column names (1 or 2)
        self.children = (child,)


class LogicalWindow(LogicalPlan):
    def __init__(self, window_exprs, partition_by, order_by, child):
        self.window_exprs = list(window_exprs)
        self.partition_by = list(partition_by)
        self.order_by = list(order_by)
        self.children = (child,)


class LogicalWrite(LogicalPlan):
    def __init__(self, path: str, fmt: str, child: LogicalPlan,
                 options: Optional[dict] = None,
                 partition_by: Optional[List[str]] = None):
        self.path = path
        self.fmt = fmt
        self.options = options or {}
        self.partition_by = partition_by or []
        self.children = (child,)


class LogicalPlaceholder(LogicalPlan):
    """Stage-input marker for SHIPPED plan fragments.

    The multi-process cluster driver (cluster.py) serializes a reduce-side
    fragment with this node where the shuffle feed attaches; the executing
    worker (shuffle/worker.py) swaps in an in-memory scan over the
    partitions it fetched.  The analogue of the shuffle-read RDD boundary
    in a serialized Spark task binary."""

    def __init__(self, schema: "Schema"):
        self.schema = schema
