"""The overrides pass: tag -> explain -> convert.

Reference behavior being reproduced (structure, not code):
  * GpuOverrides rule tables keyed by operator class, each rule deriving a
    kill-switch conf `spark.rapids.sql.<kind>.<Name>`
    (reference: rapids/GpuOverrides.scala:66-258 rule framework,
     453-1705 rule tables)
  * RapidsMeta tagging tree: every plan/expression node gets a meta wrapper;
    tagging marks `willNotWorkOnTpu(reason)` bottom-up; `explain` prints the
    reasons; conversion swaps supported subtrees to device operators
    (reference: rapids/RapidsMeta.scala:173-196)
  * type gate (reference: GpuOverrides.isSupportedType:375-387)

The planner here goes logical plan -> physical ExecNode tree where each node
is either the Tpu* or Cpu* implementation; transitions.py then inserts
host<->device edges, coalesce nodes and fuses row-local chains.
"""
from __future__ import annotations

from typing import Callable, Dict, List, Optional, Sequence, Tuple

from .. import config as C
from ..config import TpuConf
from ..ops import expressions as E
from ..ops import math as M
from ..ops import strings as S
from ..ops import datetime_exprs as D
from ..ops.aggregates import AggregateExpression
from ..ops.cast import Cast, supported_cast
from ..types import (DataType, NullType, Schema, StructField, StringType,
                     SUPPORTED_TYPES, DoubleType, FloatType)
from . import logical as L
from .analysis import AnalysisError, resolve

# --------------------------------------------------------------------------
# expression rule table — class name -> optional extra tagger
# (the device implementation is the Expression.eval itself)
# --------------------------------------------------------------------------

def _tag_cast(meta: "ExprMeta", conf: TpuConf):
    e: Cast = meta.expr
    src, dst = e.child.dtype, e.to
    if not supported_cast(src, dst):
        meta.will_not_work(f"cast {src.name} to {dst.name} is not supported "
                           "on TPU")
        return
    if src.is_string and dst.is_floating \
            and not conf.get(C.ENABLE_CAST_STRING_TO_FLOAT):
        meta.will_not_work(
            "string to float casts can produce results different from Spark "
            "in corner cases; set "
            f"{C.ENABLE_CAST_STRING_TO_FLOAT.key}=true to enable")
    if src.is_floating and dst.is_string \
            and not conf.get(C.ENABLE_CAST_FLOAT_TO_STRING):
        meta.will_not_work(
            "float to string casts are formatted differently than Spark; set "
            f"{C.ENABLE_CAST_FLOAT_TO_STRING.key}=true to enable")
    if src.is_string and dst.name == "timestamp" \
            and not conf.get(C.ENABLE_CAST_STRING_TO_TIMESTAMP):
        meta.will_not_work(
            "string to timestamp casts only support a subset of formats; set "
            f"{C.ENABLE_CAST_STRING_TO_TIMESTAMP.key}=true to enable")


def _tag_literal_pattern(meta: "ExprMeta", conf: TpuConf):
    e = meta.expr
    pat = getattr(e, "pattern", None) or getattr(e, "search", None)
    if not (isinstance(pat, E.Literal) and isinstance(pat.value, str)):
        meta.will_not_work("only literal patterns are supported on TPU")


def _tag_replace(meta: "ExprMeta", conf: TpuConf):
    e: S.StringReplace = meta.expr
    if not e.device_supported():
        meta.will_not_work("device StringReplace requires equal-length "
                           "literal search/replace strings")


def _tag_agg(meta: "ExprMeta", conf: TpuConf):
    e: AggregateExpression = meta.expr
    if not conf.is_op_enabled(expr_conf_key(e.func)):
        # per-function kill-switch, like the reference's expr rules for
        # Sum/Count/Min/Max/Average/First/Last (GpuOverrides.scala)
        meta.will_not_work(
            f"aggregate {e.func} has been disabled; set "
            f"{expr_conf_key(e.func)}=true to enable")
    if e.distinct and e.func in ("First", "Last"):
        # value depends on arrival order after dedup; Spark itself plans
        # these as non-distinct — reject defensively
        meta.will_not_work(f"distinct {e.func} is not supported on TPU")
    if e.func in ("Min", "Max") and e.child is not None \
            and e.child.dtype.is_string:
        meta.will_not_work("min/max over strings is not supported on TPU "
                           "yet (byte-matrix segment reduction pending)")
    if e.func in ("Sum", "Average") and e.child is not None \
            and e.child.dtype.is_floating \
            and not (conf.get(C.VARIABLE_FLOAT_AGG)
                     or conf.get(C.INCOMPATIBLE_OPS)):
        meta.will_not_work(
            "floating point aggregation reduces in a different order than "
            f"Spark; set {C.VARIABLE_FLOAT_AGG.key}=true to enable")


_EXPR_RULES: Dict[str, Optional[Callable]] = {}
for _n in ("BoundReference Literal Alias Add Subtract Multiply Divide "
           "IntegralDivide Remainder Pmod UnaryMinus UnaryPositive Abs "
           "EqualTo LessThan GreaterThan LessThanOrEqual GreaterThanOrEqual "
           "EqualNullSafe And Or Not IsNull IsNotNull IsNaN Coalesce NaNvl "
           "If CaseWhen In InSet BitwiseAnd BitwiseOr BitwiseXor BitwiseNot "
           "ShiftLeft ShiftRight ShiftRightUnsigned SparkPartitionID "
           "MonotonicallyIncreasingID Rand "
           "Sqrt Cbrt Exp Expm1 Log Log2 Log10 Log1p Sin Cos Tan Asin Acos "
           "Atan Sinh Cosh Tanh ToDegrees ToRadians Signum Floor Ceil Rint "
           "Pow Atan2 "
           "Upper Lower Length StringTrim StringTrimLeft StringTrimRight "
           "Substring Concat "
           "Year Month DayOfMonth DayOfWeek WeekDay DayOfYear Quarter "
           "LastDay Hour Minute Second DateAdd DateSub DateDiff "
           "UnixTimestamp ToUnixTimestamp FromUnixTime TimeAdd").split():
    _EXPR_RULES[_n] = None
# plan-cache parameter (serve/plan_cache.py): evaluates like the Literal
# it replaced (broadcast scalar), device-supported unconditionally
_EXPR_RULES["Parameter"] = None
_EXPR_RULES["Cast"] = _tag_cast
_EXPR_RULES["AnsiCast"] = _tag_cast
_EXPR_RULES["StartsWith"] = _tag_literal_pattern
_EXPR_RULES["EndsWith"] = _tag_literal_pattern
_EXPR_RULES["Contains"] = _tag_literal_pattern
_EXPR_RULES["Like"] = _tag_literal_pattern
_EXPR_RULES["StringLocate"] = None
_EXPR_RULES["StringReplace"] = _tag_replace
_EXPR_RULES["AggregateExpression"] = _tag_agg


def _tag_device_supported(meta: "ExprMeta", conf: TpuConf):
    """Ops whose device kernel needs literal arguments (static shapes /
    compiled patterns) expose device_supported(); tag the rest to CPU."""
    e = meta.expr
    if hasattr(e, "device_supported") and not e.device_supported():
        meta.will_not_work(
            f"{meta.name} arguments are not supported on TPU "
            "(literal arguments with device-supported shapes required)")


for _n in ("InitCap Reverse Ascii Cot Hypot Logarithm Least Greatest "
           "Murmur3Hash AddMonths MonthsBetween "
           "Asinh Acosh Atanh AtLeastNNonNulls TimeSub "
           "NormalizeNaNAndZero KnownFloatingPointNormalized "
           "InputFileName InputFileBlockStart InputFileBlockLength "
           "AttributeReference SortOrder").split():
    _EXPR_RULES[_n] = None
# aggregate functions are registered by name like the reference's expr
# rules for Sum/Count/... (GpuOverrides.scala agg entries); the kill-switch
# conf check runs in _tag_agg against the AggregateExpression's func name
for _n in ("Sum Count Min Max Average First Last").split():
    _EXPR_RULES[_n] = None
# window functions: resolved via ops/windows.resolve_window_func (not the
# Expression tree), but registered here so the per-op kill-switch conf
# surface matches the reference's window rule table (GpuOverrides window
# expressions; the conf check runs in plan/tagging._tag_window)
for _n in ("RowNumber Rank DenseRank Lag Lead WindowExpression "
           "WindowSpecDefinition SpecifiedWindowFrame").split():
    _EXPR_RULES[_n] = None
for _n in ("StringLPad StringRPad StringRepeat SubstringIndex "
           "RegExpReplace Round BRound TruncDate NextDay").split():
    _EXPR_RULES[_n] = _tag_device_supported


def expr_conf_key(name: str) -> str:
    return f"spark.rapids.sql.expr.{name}"


def exec_conf_key(name: str) -> str:
    return f"spark.rapids.sql.exec.{name}"


# --------------------------------------------------------------------------
# meta tree
# --------------------------------------------------------------------------

class MetaBase:
    def __init__(self):
        self._reasons: List[str] = []

    def will_not_work(self, reason: str):
        if reason not in self._reasons:
            self._reasons.append(reason)

    @property
    def can_this_run(self) -> bool:
        return not self._reasons

    @property
    def reasons(self):
        return list(self._reasons)


class ExprMeta(MetaBase):
    def __init__(self, expr: E.Expression, conf: TpuConf):
        super().__init__()
        self.expr = expr
        self.conf = conf
        self.children = [ExprMeta(c, conf) for c in expr.children]

    @property
    def name(self) -> str:
        return type(self.expr).__name__

    def tag(self):
        for c in self.children:
            c.tag()
        name = self.name
        rule = _EXPR_RULES.get(name, "missing")
        if rule == "missing":
            self.will_not_work(f"expression {name} is not supported on TPU")
        else:
            dt = self.expr.dtype
            if dt is not NullType and dt not in SUPPORTED_TYPES:
                self.will_not_work(f"expression {name} produces an "
                                   f"unsupported type {dt.name}")
            if not self.conf.is_op_enabled(expr_conf_key(name)):
                self.will_not_work(
                    f"expression {name} has been disabled; set "
                    f"{expr_conf_key(name)}=true to enable")
            if rule is not None:
                rule(self, self.conf)

    @property
    def can_run_deep(self) -> bool:
        return self.can_this_run and all(c.can_run_deep
                                         for c in self.children)

    def all_reasons(self) -> List[str]:
        out = list(self._reasons)
        for c in self.children:
            out.extend(c.all_reasons())
        return out


class PlanMeta(MetaBase):
    """Meta wrapper for one logical node."""

    def __init__(self, plan: L.LogicalPlan, conf: TpuConf,
                 session=None):
        super().__init__()
        self.plan = plan
        self.conf = conf
        self.session = session
        self.children = [PlanMeta(c, conf, session) for c in plan.children]
        self.expr_metas: List[ExprMeta] = []
        self.resolved = {}     # stashed resolved expressions for conversion
        self.on_tpu = False

    @property
    def name(self) -> str:
        return _exec_name(self.plan)

    def input_schema(self, i=0) -> Schema:
        return plan_schema(self.children[i].plan, self.conf)

    def tag_tree(self):
        for c in self.children:
            c.tag_tree()
        if not self.conf.sql_enabled:
            self.will_not_work("TPU acceleration is disabled "
                               f"({C.SQL_ENABLED.key}=false)")
        if not self.conf.is_op_enabled(exec_conf_key(self.name)):
            self.will_not_work(f"exec {self.name} has been disabled; set "
                               f"{exec_conf_key(self.name)}=true to enable")
        try:
            self._tag_self()
        except AnalysisError as ex:
            raise
        except NotImplementedError as ex:
            self.will_not_work(str(ex))
        for em in self.expr_metas:
            em.tag()
            if not em.can_run_deep:
                for r in em.all_reasons():
                    self.will_not_work(r)
        self.on_tpu = self.can_this_run

    # -- per-node tagging+resolution --------------------------------------
    def _tag_self(self):
        from . import tagging
        tagging.tag_node(self)

    def explain(self, verbose: bool = False, indent: int = 0) -> str:
        mark = "*" if self.on_tpu else "!"
        line = " " * indent + f"{mark}{self.name}"
        if not self.on_tpu:
            why = "; ".join(self._reasons) or "child not on TPU"
            line += f" cannot run on TPU because {why}"
        lines = [line]
        for c in self.children:
            lines.append(c.explain(verbose, indent + 2))
        return "\n".join(lines)


_DISPLAY_NAMES = {
    L.LogicalProject: "ProjectExec",
    L.LogicalFilter: "FilterExec",
    L.LogicalAggregate: "HashAggregateExec",
    L.LogicalSort: "SortExec",
    L.LogicalLimit: "CollectLimitExec",
    L.LogicalUnion: "UnionExec",
    L.LogicalExpand: "ExpandExec",
    L.LogicalWindow: "WindowExec",
    L.LogicalGenerate: "GenerateExec",
    L.LogicalRepartition: "ShuffleExchangeExec",
    L.LogicalWrite: "DataWritingCommandExec",
    L.LogicalDistinct: "HashAggregateExec",
    L.LogicalScan: "FileSourceScanExec",
    L.LogicalJoin: "SortMergeJoinExec",
    # shipped-fragment stage input (cluster.py); swapped for a scan before
    # planning, but tagging/explain must still name it if one leaks through
    L.LogicalPlaceholder: "ShuffleQueryStageExec",
}


def _exec_name(plan: L.LogicalPlan) -> str:
    """Logical node -> reference exec-rule name (so conf keys match the
    reference's per-exec kill-switches)."""
    mapping = _DISPLAY_NAMES
    if isinstance(plan, L.LogicalScan):
        return {"memory": "LocalTableScanExec",
                "parquet": "FileSourceScanExec",
                "csv": "BatchScanExec",
                "orc": "FileSourceScanExec"}.get(plan.fmt,
                                                 "FileSourceScanExec")
    if isinstance(plan, L.LogicalJoin):
        return "SortMergeJoinExec"  # pre-conversion name; see tagging
    return mapping.get(type(plan), type(plan).__name__)


# schema computation --------------------------------------------------------

def plan_schema(plan: L.LogicalPlan, conf: TpuConf) -> Schema:
    s = getattr(plan, "_cached_schema", None)
    if s is None:
        s = _compute_schema(plan, conf)
        plan._cached_schema = s
    return s


def _compute_schema(plan: L.LogicalPlan, conf: TpuConf) -> Schema:
    if isinstance(plan, (L.LogicalScan, L.LogicalPlaceholder)):
        return plan.schema
    if isinstance(plan, L.LogicalProject):
        child = plan_schema(plan.children[0], conf)
        fields = []
        for ce in plan.exprs:
            ex = resolve(ce, child)
            fields.append(StructField(ce.output_name, ex.dtype))
        return Schema(fields)
    if isinstance(plan, L.LogicalAggregate):
        child = plan_schema(plan.children[0], conf)
        fields = []
        for ce in plan.grouping:
            ex = resolve(ce, child)
            fields.append(StructField(ce.output_name, ex.dtype))
        for ce in plan.aggregates:
            ex = resolve(ce, child)
            fields.append(StructField(ce.output_name, ex.dtype))
        return Schema(fields)
    if isinstance(plan, L.LogicalJoin):
        ls = plan_schema(plan.children[0], conf)
        rs = plan_schema(plan.children[1], conf)
        if plan.join_type in ("left_semi", "left_anti"):
            return ls
        if plan.using:
            rfields = [f for f in rs if f.name not in plan.using]
            return Schema(list(ls.fields) + rfields)
        return Schema(list(ls.fields) + list(rs.fields))
    if isinstance(plan, (L.LogicalFilter, L.LogicalSort, L.LogicalLimit,
                         L.LogicalDistinct, L.LogicalRepartition,
                         L.LogicalWrite)):
        return plan_schema(plan.children[0], conf)
    if isinstance(plan, L.LogicalUnion):
        return plan_schema(plan.children[0], conf)
    if isinstance(plan, L.LogicalExpand):
        child = plan_schema(plan.children[0], conf)
        fields = []
        for ce in plan.projections[0]:
            ex = resolve(ce, child)
            fields.append(StructField(ce.output_name, ex.dtype))
        return Schema(fields)
    if isinstance(plan, L.LogicalGenerate):
        from ..types import IntegerType
        from .analysis import _infer_value_dtype
        child = plan_schema(plan.children[0], conf)
        fields = list(child.fields)
        dtype = _infer_value_dtype(plan.generator.args[0]) or StringType
        if plan.generator.op == "PosExplode":
            fields.append(StructField(plan.names[0], IntegerType))
        fields.append(StructField(plan.names[-1], dtype))
        return Schema(fields)
    if isinstance(plan, L.LogicalWindow):
        from ..ops.windows import resolve_window_func
        child = plan_schema(plan.children[0], conf)
        fields = list(child.fields)
        for ce in plan.window_exprs:
            func_ce, spec = ce.args
            wf = resolve_window_func(func_ce, spec, child, resolve,
                                     device=False)
            fields.append(StructField(ce.output_name, wf.dtype))
        return Schema(fields)
    raise NotImplementedError(f"schema of {type(plan).__name__}")


# --------------------------------------------------------------------------
# generated supported-ops documentation
# --------------------------------------------------------------------------

_EXEC_DOC_ROWS = [
    ("ProjectExec", "expression projection; row-local stages fuse into one "
     "compiled kernel"),
    ("FilterExec", "predicates AND into the selection mask (no gather "
     "until a shape-changing op needs one)"),
    ("HashAggregateExec", "sort-based segmented reduction; ROLLUP/CUBE via "
     "ExpandExec; single-distinct; whole-stage vmapped path"),
    ("SortMergeJoinExec", "replaced by the device hash join: "
     "inner/left/right/full outer/left semi/left anti (right runs "
     "side-swapped under a column reorder); conditional joins for "
     "inner/semi/anti (residual evaluated pair-wise in the candidate "
     "walk); broadcast and partitioned (EnsureRequirements) variants; "
     "USING full joins fall back for Spark's coalesced-key "
     "contract"),
    ("SortExec", "order-preserving integer key encoding, one lexsort; "
     "external (partitioned) sort above the in-memory threshold"),
    ("WindowExec", "sort-once segmented-scan windows; external window"),
    ("ExpandExec", "grouping-set projections"),
    ("GenerateExec", "explode/posexplode"),
    ("UnionExec", "batch interleave"),
    ("CollectLimitExec", "device head-N"),
    ("ShuffleExchangeExec", "hash (murmur3 Spark-parity)/range/round-robin/"
     "single partitioners; device-resident shuffle"),
    ("DataWritingCommandExec", "parquet and ORC encode ON DEVICE "
     "(snappy/uncompressed parquet); CSV and dynamic partitions via the "
     "host arrow writer (the reference's GPU write formats are parquet/"
     "ORC only; CSV is read-only there too)"),
    ("FileSourceScanExec", "parquet/ORC device decode (see formats "
     "below); pushdown + schema evolution"),
    ("BatchScanExec", "CSV device parse (native quote-aware tokenizer + "
     "device gather/Horner kernels)"),
    ("LocalTableScanExec", "arrow/pydict ingestion"),
    ("BroadcastExchangeExec", "device broadcast for hash joins under the "
     "size threshold/hint"),
]


def supported_ops_doc() -> str:
    """docs/supported-ops.md content: execs, expression rules, formats —
    generated from the live rule registry (the reference generates its
    docs/supported_ops.md from GpuOverrides the same way)."""
    from ..types import SUPPORTED_TYPES
    lines = [
        "# Supported operators and expressions",
        "",
        "Generated from the rule registry "
        "(`python -m spark_rapids_tpu.plan.overrides`); do not edit.",
        "Counterpart: the reference's generated docs/supported_ops.md.",
        "",
        "## Types",
        "",
        "On-device columns: "
        + ", ".join(sorted(t.name for t in SUPPORTED_TYPES)) + ".",
        "Decimal/binary/calendar-interval/nested types keep the plan on "
        "the CPU executor (the reference's isSupportedType gate).",
        "",
        "## Execs",
        "",
        "Every exec has a kill-switch conf "
        "`spark.rapids.sql.exec.<name>`.",
        "",
        "| Exec | Device support |",
        "|---|---|",
    ]
    for name, note in _EXEC_DOC_ROWS:
        lines.append(f"| {name} | {note} |")
    lines += [
        "",
        "## Expressions",
        "",
        f"{len(_EXPR_RULES)} expression rules.  Every expression has a "
        "kill-switch conf `spark.rapids.sql.expr.<name>`.  Rules marked "
        "*conditional* run on device only for supported argument shapes "
        "(literal patterns, in-range pad widths, ...) and tag the plan "
        "back to CPU otherwise, with the reason shown by explain().",
        "",
        "| Expression | Device support |",
        "|---|---|",
    ]
    for name in sorted(_EXPR_RULES):
        tagger = _EXPR_RULES[name]
        if tagger is None:
            note = "supported"
        else:
            doc = (tagger.__doc__ or "").strip().split("\n")[0]
            note = f"conditional — {doc}" if doc else "conditional"
        lines.append(f"| {name} | {note} |")
    lines += [
        "",
        "## File formats",
        "",
        "| Format | Read | Write |",
        "|---|---|---|",
        "| Parquet | device decode: PLAIN, RLE/PLAIN_DICTIONARY (incl. "
        "strings), DELTA_BINARY_PACKED, DELTA_LENGTH_BYTE_ARRAY, "
        "BYTE_STREAM_SPLIT, PLAIN BYTE_ARRAY strings; page v1/v2; "
        "row-group pruning | device encode (snappy/uncompressed) |",
        "| ORC | device decode: full RLEv2 (SHORT_REPEAT/DIRECT/DELTA/"
        "PATCHED_BASE on device), strings (DIRECT_V2 + DICTIONARY_V2), "
        "timestamps, booleans; stripe pruning from footer statistics | "
        "device encode (uncompressed, RLEv1/DIRECT) |",
        "| CSV | device parse (native tokenizer incl. quoted fields and "
        "CRLF; device gather + Horner numeric kernels) | host arrow "
        "writer (reference parity: GPU CSV is read-only there) |",
        "",
    ]
    return "\n".join(lines)


def write_supported_ops_docs(path: str = None) -> str:
    import os
    if path is None:
        path = os.path.join(os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))), "docs", "supported-ops.md")
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "w") as f:
        f.write(supported_ops_doc())
    return path


if __name__ == "__main__":  # python -m spark_rapids_tpu.plan.overrides
    print(write_supported_ops_docs())
