"""Conversion: tagged meta tree -> physical ExecNode tree.

The reference's convertIfNeeded (RapidsMeta.scala) swaps supported subtrees
to Gpu* operators; here each node independently becomes Tpu* (if tagged ok)
or Cpu* (fallback), and transitions.py stitches the boundaries.
"""
from __future__ import annotations

from ..exec import basic as B
from ..exec import cpu_relational as CR
from ..exec.base import ExecNode
from . import logical as L
from .overrides import PlanMeta, plan_schema


def convert(meta: PlanMeta) -> ExecNode:
    children = [convert(c) for c in meta.children]
    plan = meta.plan
    on_tpu = meta.on_tpu
    r = meta.resolved

    if isinstance(plan, L.LogicalScan):
        return _convert_scan(meta, on_tpu)
    if isinstance(plan, L.LogicalProject):
        cls = B.TpuProjectExec if on_tpu else B.CpuProjectExec
        return cls(r["exprs"], r["names"], children[0])
    if isinstance(plan, L.LogicalFilter):
        cls = B.TpuFilterExec if on_tpu else B.CpuFilterExec
        return cls(r["condition"], children[0])
    if isinstance(plan, L.LogicalAggregate):
        if on_tpu:
            from ..exec.aggregate import TpuHashAggregateExec
            return TpuHashAggregateExec(r["grouping"], r["group_names"],
                                        r["aggregates"], children[0])
        return CR.CpuHashAggregateExec(r["grouping"], r["group_names"],
                                       r["aggregates"], children[0])
    if isinstance(plan, L.LogicalJoin):
        out_schema = plan_schema(plan, meta.conf)
        using_drop = []
        if plan.using:
            lw = len(plan_schema(plan.children[0], meta.conf))
            rs = plan_schema(plan.children[1], meta.conf)
            for name in plan.using:
                using_drop.append(lw + rs.index_of(name))
        if on_tpu:
            from ..exec.join import TpuHashJoinExec
            jt = plan.join_type
            lc, rc = children[0], children[1]
            lkeys, rkeys = r["left_keys"], r["right_keys"]
            cond = r["condition"]
            build_plan = plan.children[1]
            join_schema = out_schema
            reorder = None
            build_bytes = None   # precomputed estimate, threaded below
            if jt in ("right", "right_outer"):
                # right outer == left outer with the sides swapped BEFORE
                # the variant dispatch (so broadcast/partitioned apply),
                # columns reordered back afterwards (the reference has no
                # right-outer device join, GpuHashJoin.scala:31-32;
                # tagging admits only the residual-free case).  USING key
                # columns surface the RIGHT side's values (Spark's
                # coalesced-key contract for a right-preserving join).
                jt = "left"
                lc, rc = rc, lc
                lkeys, rkeys = rkeys, lkeys
                cond = None
                build_plan, join_schema, using_drop, reorder = _swap_sides(
                    plan, meta.conf, key_from_right=True)
            elif jt == "inner" and cond is None \
                    and "broadcast" not in getattr(plan.children[1],
                                                   "_hints", ()):
                # build-side selection (Spark's planner picks the smaller
                # side to build; the kernels here always build the RIGHT
                # child): when the left side is clearly smaller — or the
                # user hinted broadcast on it — swap the children and
                # reorder columns back afterwards.  Without this,
                # dim.join(fact) builds the FACT side: at SF1 that pushed
                # q19 through a 2.88M-row partitioned exchange instead of
                # a small broadcast build.  An explicit broadcast hint on
                # the RIGHT child suppresses the swap (the user chose the
                # build side).
                lhint = "broadcast" in getattr(plan.children[0],
                                               "_hints", ())
                lb = _estimate_plan_bytes(plan.children[0], meta.conf)
                rb = _estimate_plan_bytes(plan.children[1], meta.conf)
                if lhint or (lb is not None and rb is not None
                             and lb * 2 < rb):
                    lc, rc = rc, lc
                    lkeys, rkeys = rkeys, lkeys
                    build_plan, join_schema, using_drop, reorder = \
                        _swap_sides(plan, meta.conf, key_from_right=False)
                    build_bytes = lb
                else:
                    build_bytes = rb

            def wrap(node):
                if reorder is None:
                    return node
                from ..exec.join import TpuReorderColumnsExec
                return TpuReorderColumnsExec(node, reorder, out_schema)

            if (_should_broadcast_build(plan, meta.conf, build_plan,
                                        build_bytes)
                    and jt != "full"):
                # full outer never broadcasts: the never-matched-build
                # tail is emitted once per probe STREAM, so a replicated
                # build would duplicate it under any parallel probe
                from ..exec.broadcast import (TpuBroadcastExchangeExec,
                                              TpuBroadcastHashJoinExec)
                return wrap(TpuBroadcastHashJoinExec(
                    lc, TpuBroadcastExchangeExec(rc), jt, lkeys, rkeys,
                    cond, join_schema, using_drop))
            if _should_partition_join(plan, meta.conf, build_plan,
                                      build_bytes):
                # EnsureRequirements analogue: hash-partition BOTH sides on
                # the join keys so the single-build-batch requirement holds
                # per partition (reference GpuShuffledHashJoinExec.scala:83-87)
                from .. import config as C
                from ..exec.exchange import TpuShuffleExchangeExec
                from ..exec.join import TpuShuffledHashJoinExec
                n = meta.conf.get(C.SHUFFLE_PARTITIONS)
                lex = TpuShuffleExchangeExec("hash", lkeys, n, lc)
                rex = TpuShuffleExchangeExec("hash", rkeys, n, rc)
                return wrap(TpuShuffledHashJoinExec(
                    lex, rex, jt, lkeys, rkeys, cond, join_schema,
                    using_drop))
            return wrap(TpuHashJoinExec(lc, rc, jt, lkeys, rkeys, cond,
                                        join_schema, using_drop))
        return CR.CpuJoinExec(children[0], children[1], plan.join_type,
                              r["left_keys"], r["right_keys"],
                              r["condition"], out_schema, using_drop)
    if isinstance(plan, L.LogicalGenerate):
        from ..exec.generate import make_generate_exec
        return make_generate_exec(meta, children[0], on_tpu)
    if isinstance(plan, L.LogicalSort):
        if on_tpu:
            from ..exec.sort import TpuSortExec
            return TpuSortExec(r["sort_exprs"], r["ascending"],
                               r["nulls_first"], children[0])
        return CR.CpuSortExec(r["sort_exprs"], r["ascending"],
                              r["nulls_first"], children[0])
    if isinstance(plan, L.LogicalLimit):
        cls = B.TpuGlobalLimitExec if on_tpu else B.CpuLimitExec
        return cls(plan.n, children[0])
    if isinstance(plan, L.LogicalUnion):
        all_tpu = on_tpu
        cls = B.TpuUnionExec if all_tpu else B.CpuUnionExec
        return cls(children)
    if isinstance(plan, L.LogicalDistinct):
        if on_tpu:
            from ..exec.aggregate import TpuHashAggregateExec
            child_schema = plan_schema(plan.children[0], meta.conf)
            return TpuHashAggregateExec(r["grouping"], child_schema.names,
                                        [], children[0])
        return CR.CpuDistinctExec(children[0])
    if isinstance(plan, L.LogicalExpand):
        cls = B.TpuExpandExec if on_tpu else B.CpuExpandExec
        return cls(r["projections"], r["names"], children[0])
    if isinstance(plan, L.LogicalRepartition):
        if on_tpu:
            from ..exec.exchange import make_repartition_exec
            return make_repartition_exec(plan, r.get("keys", []), children[0],
                                         on_tpu)
        return CR.CpuRepartitionExec(plan.num_partitions, children[0])
    if isinstance(plan, L.LogicalWrite):
        from ..io.writer import make_write_exec
        return make_write_exec(plan, children[0], on_tpu)
    if isinstance(plan, L.LogicalWindow):
        from ..exec.window import make_window_exec
        return make_window_exec(meta, children[0], on_tpu)
    raise NotImplementedError(f"convert {type(plan).__name__}")


def _convert_scan(meta: PlanMeta, on_tpu: bool) -> ExecNode:
    plan: L.LogicalScan = meta.plan
    if plan.fmt == "memory":
        cls = B.TpuScanMemoryExec if on_tpu else B.CpuScanMemoryExec
        return cls(plan.source, plan.schema)
    from ..io.scan import make_scan_exec
    return make_scan_exec(plan, on_tpu, meta.conf)


def _schema_row_bytes(schema) -> int:
    """Estimated bytes per row of a schema (strings at a fixed guess —
    Spark's defaultSizeInBytes per type, simplified)."""
    total = 0
    for f in schema:
        if f.dtype.np_dtype is not None:
            total += f.dtype.np_dtype.itemsize
        else:
            total += 32  # string/unknown
    return max(total, 1)


def _estimate_plan_rows(plan: L.LogicalPlan, conf):
    """Rough output row-count estimate (Spark's stats rowCount,
    simplified; VERDICT r3: estimates must survive aggregates/joins so a
    pre-aggregated dimension can still broadcast).  Upper-bound-ish:
    over-estimating keeps a huge build side off the broadcast path, which
    is the safe direction.  None = unknown."""
    import os
    if isinstance(plan, L.LogicalScan):
        if plan.fmt == "memory":
            rows = getattr(plan.source, "num_rows", None)
            return int(rows) if rows is not None else None
        try:
            nbytes = sum(os.path.getsize(f) for f in plan.source)
        except (OSError, TypeError):
            return None
        return nbytes // _schema_row_bytes(plan.schema)
    if isinstance(plan, (L.LogicalProject, L.LogicalFilter, L.LogicalSort,
                         L.LogicalRepartition, L.LogicalWindow)):
        # no-CBO Spark keeps the child estimate through row-local nodes
        # (filters keep it too: selectivity guessing under-estimates, the
        # dangerous direction for broadcast).  Generate (explode) is NOT
        # row-preserving — its fan-out is unbounded, so it stays unknown.
        return _estimate_plan_rows(plan.children[0], conf)
    if isinstance(plan, L.LogicalLimit):
        child = _estimate_plan_rows(plan.children[0], conf)
        return plan.n if child is None else min(plan.n, child)
    if isinstance(plan, L.LogicalAggregate):
        if not plan.grouping:
            return 1
        return _estimate_plan_rows(plan.children[0], conf)  # upper bound
    if isinstance(plan, L.LogicalDistinct):
        return _estimate_plan_rows(plan.children[0], conf)
    if isinstance(plan, L.LogicalUnion):
        parts = [_estimate_plan_rows(c, conf) for c in plan.children]
        return None if any(p is None for p in parts) else sum(parts)
    if isinstance(plan, L.LogicalExpand):
        child = _estimate_plan_rows(plan.children[0], conf)
        return None if child is None else child * len(plan.projections)
    if isinstance(plan, L.LogicalJoin):
        left = _estimate_plan_rows(plan.children[0], conf)
        right = _estimate_plan_rows(plan.children[1], conf)
        if left is None or right is None:
            return None
        if plan.join_type in ("left_semi", "left_anti"):
            return left
        # star-join heuristic: fact side dominates an equi-join's output;
        # dim x dim stays small.  (True worst case is the product — using
        # it would disable broadcast everywhere.)
        return max(left, right)
    return None


def _estimate_plan_bytes(plan: L.LogicalPlan, conf):
    """Rough byte-size estimate of a subtree's output: estimated rows x
    OUTPUT schema width (so a projection that drops wide columns shrinks
    the estimate, unlike passing raw file size through).  None =
    unknown."""
    import os
    if isinstance(plan, L.LogicalScan):
        # raw source size: better than rows x width for compressed files
        if plan.fmt == "memory":
            nbytes = getattr(plan.source, "nbytes", None)
            return int(nbytes) if nbytes is not None else None
        try:
            return sum(os.path.getsize(f) for f in plan.source)
        except (OSError, TypeError):
            return None
    rows = _estimate_plan_rows(plan, conf)
    if rows is None:
        return None
    try:
        schema = plan_schema(plan, conf)
    except Exception:
        return None
    return rows * _schema_row_bytes(schema)


def _should_partition_join(plan: "L.LogicalJoin", conf, build_plan=None,
                           build_bytes=None) -> bool:
    """Partition a non-broadcast join when the build side is too big for
    (or of unknown size relative to) one bounded build batch.
    `build_plan` overrides the default right child (side-swapped joins —
    right outer, small-left inner — build the original LEFT);
    `build_bytes` passes an estimate the caller already computed."""
    from .. import config as C
    if not conf.get(C.PARTITIONED_JOIN_ENABLED):
        return False
    est = build_bytes if build_bytes is not None else _estimate_plan_bytes(
        build_plan if build_plan is not None else plan.children[1], conf)
    threshold = conf.get(C.PARTITIONED_JOIN_THRESHOLD)
    return est is None or est > int(threshold)


def _should_broadcast_build(plan: "L.LogicalJoin", conf, build_plan=None,
                            build_bytes=None) -> bool:
    """Broadcast the build side when hinted or when its estimated size is
    under spark.sql.autoBroadcastJoinThreshold (Spark planning behavior;
    reference: GpuBroadcastHashJoinExec replaces Spark's
    BroadcastHashJoinExec when Spark already chose broadcast).
    `build_plan` overrides the default right child (side-swapped joins —
    right outer, small-left inner — build the original LEFT);
    `build_bytes` passes an estimate the caller already computed."""
    from .. import config as C
    build = build_plan if build_plan is not None else plan.children[1]
    if "broadcast" in getattr(build, "_hints", ()):
        return True
    threshold = conf.get(C.AUTO_BROADCAST_JOIN_THRESHOLD)
    if threshold is None or int(threshold) < 0:
        return False
    est = build_bytes if build_bytes is not None \
        else _estimate_plan_bytes(build, conf)
    return est is not None and est <= int(threshold)


def _swap_sides(plan, conf, key_from_right: bool):
    """Column bookkeeping for running a join with its children swapped
    (the kernels always build the RIGHT child): the swapped exec emits
    [R..., L...]; the returned `reorder` selects the logical
    [L..., R-minus-USING] output.  `key_from_right` picks which block a
    USING key column surfaces from: right-preserving outer joins must
    surface the right side's values (Spark's coalesced-key contract);
    inner joins take the left block (values equal across sides, null
    keys never match).  Returns
    (build_plan, join_schema, using_drop, reorder)."""
    ls_f = plan_schema(plan.children[0], conf)
    rs_f = plan_schema(plan.children[1], conf)
    n_l, n_r = len(ls_f), len(rs_f)
    join_schema = _swapped_join_schema(plan, conf)
    if plan.using:
        # the exec itself drops nothing; the reorder both selects and
        # drops the duplicated USING columns
        if key_from_right:
            reorder = [rs_f.index_of(f.name) if f.name in plan.using
                       else n_r + i for i, f in enumerate(ls_f)]
        else:
            reorder = [n_r + i for i in range(n_l)]
        reorder += [i for i, f in enumerate(rs_f)
                    if f.name not in plan.using]
    else:
        reorder = list(range(n_r, n_r + n_l)) + list(range(n_r))
    return plan.children[0], join_schema, [], reorder


def _swapped_join_schema(plan, conf):
    """Output schema of a side-swapped join (right outer, small-left
    inner): the original RIGHT fields first, original LEFT fields renamed
    on collision — the same rename rule the join kernels apply, from the
    swapped perspective."""
    from ..exec.join import TpuHashJoinExec
    from ..types import Schema
    ls = plan_schema(plan.children[0], conf)
    rs = plan_schema(plan.children[1], conf)
    lf, rf = TpuHashJoinExec._joined_fields(rs, ls)
    return Schema(lf + rf)
