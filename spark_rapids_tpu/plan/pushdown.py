"""Scan pushdown: column pruning + predicate row-group skipping.

Reference behavior (structure, not code): GpuParquetScan clips the columns
read to the requested schema and rebuilds the pushed-down filters against
the file footer so whole row groups can be skipped
(GpuParquetScan.scala:106-147); FileSourceScanExec arrives already pruned by
Spark's optimizer.  This engine has no Catalyst in front of it, so the
equivalent optimizer pass lives here: a functional rewrite over the logical
plan that

  * computes the set of column names each scan must actually produce and
    narrows the scan's schema to it (the exec then passes `columns=` to the
    reader — no bytes decoded, no H2D for pruned columns), and
  * collects conjunctive `col <op> literal` predicates sitting directly
    above a scan (through other filters) into the scan options, where the
    parquet reader tests them against row-group min/max statistics.

The Filter node stays in the plan — row-group skipping is advisory; exact
filtering still happens on device.
"""
from __future__ import annotations

from typing import List, Optional, Set, Tuple

from ..types import Schema
from .logical import (ColumnExpr, LogicalAggregate, LogicalDistinct,
                      LogicalExpand, LogicalFilter, LogicalGenerate,
                      LogicalJoin, LogicalLimit, LogicalPlan, LogicalProject,
                      LogicalRepartition, LogicalScan, LogicalSort,
                      LogicalUnion, LogicalWindow, LogicalWrite, SortOrder)

# conjuncts with these ops and a (col, literal) shape can prune row groups
_PUSHABLE = {"EqualTo", "LessThan", "GreaterThan", "LessThanOrEqual",
             "GreaterThanOrEqual"}
_FLIP = {"LessThan": "GreaterThan", "GreaterThan": "LessThan",
         "LessThanOrEqual": "GreaterThanOrEqual",
         "GreaterThanOrEqual": "LessThanOrEqual", "EqualTo": "EqualTo"}


def col_refs(e, out: Set[str]) -> None:
    """Collect column names referenced by a ColumnExpr tree (descends
    arbitrarily nested arg containers — CaseWhen holds (cond, value) pairs,
    window specs hold order lists, etc.)."""
    if isinstance(e, SortOrder):
        col_refs(e.child, out)
        return
    if isinstance(e, (list, tuple)):
        for x in e:
            col_refs(x, out)
        return
    if not isinstance(e, ColumnExpr):
        return
    if e.op == "col":
        out.add(e.args[0])
        return
    for a in e.args:
        col_refs(a, out)


def _literal_of(a):
    """Python literal value of an argument, or (None, False) if not one."""
    if isinstance(a, ColumnExpr):
        if a.op == "lit":
            return a.args[0], True
        if a.op == "param":
            # plan-cache parameter (serve/plan_cache.py): the CURRENT
            # bound value rides inline as args[2], so footer-statistic
            # row-group pruning still sees a concrete bound per
            # submission — the pushed predicate is re-derived at plan
            # time from the re-bound tree, never cached
            return a.args[2], True
        return None, False
    if isinstance(a, SortOrder):
        return None, False
    return a, True


def _conjuncts(e, out: List[ColumnExpr]) -> None:
    if isinstance(e, ColumnExpr) and e.op == "And":
        _conjuncts(e.args[0], out)
        _conjuncts(e.args[1], out)
    else:
        out.append(e)


def extract_predicates(condition) -> List[Tuple[str, str, object]]:
    """(col_name, op, literal) conjuncts usable against footer statistics."""
    preds: List[Tuple[str, str, object]] = []
    parts: List[ColumnExpr] = []
    _conjuncts(condition, parts)
    for p in parts:
        if not (isinstance(p, ColumnExpr) and p.op in _PUSHABLE
                and len(p.args) == 2):
            continue
        a, b = p.args
        if isinstance(a, ColumnExpr) and a.op == "col":
            v, ok = _literal_of(b)
            if ok and v is not None:
                preds.append((a.args[0], p.op, v))
        elif isinstance(b, ColumnExpr) and b.op == "col":
            v, ok = _literal_of(a)
            if ok and v is not None:
                preds.append((b.args[0], _FLIP[p.op], v))
    return preds


def optimize_scans(plan: LogicalPlan, conf=None) -> LogicalPlan:
    """Functional rewrite: returns a plan whose scans are column-pruned and
    carry pushdown predicates.  Never mutates the input tree (DataFrames
    share logical nodes)."""
    return _Rewriter(conf).rewrite(plan, required=None, preds=[])


def _rebuild(node: LogicalPlan, children: List[LogicalPlan]) -> LogicalPlan:
    """Shallow-copy a node with new children (logical nodes are simple
    attribute bags; children is always a tuple attribute)."""
    if all(c is old for c, old in zip(children, node.children)) \
            and len(children) == len(node.children):
        return node
    import copy
    new = copy.copy(node)
    new.children = tuple(children)
    new.__dict__.pop("_cached_schema", None)  # schema may have narrowed
    return new


class _Rewriter:
    def __init__(self, conf):
        self.conf = conf

    def _child_names(self, plan: LogicalPlan) -> Set[str]:
        from .overrides import plan_schema
        from ..config import TpuConf
        conf = self.conf if self.conf is not None else TpuConf()
        return set(plan_schema(plan, conf).names)

    def rewrite(self, node: LogicalPlan, required: Optional[Set[str]],
                preds: List[Tuple[str, str, object]]) -> LogicalPlan:
        """`required` = column names the parent needs (None = all);
        `preds` = filter conjuncts that hold on every row this node produces
        (only ever non-empty immediately below Filter chains)."""
        _rewrite = self.rewrite
        if isinstance(node, LogicalScan):
            return _rewrite_scan(node, required, preds)

        if isinstance(node, LogicalFilter):
            child_req = None
            if required is not None:
                child_req = set(required)
                col_refs(node.condition, child_req)
            child_preds = preds + extract_predicates(node.condition)
            child = _rewrite(node.children[0], child_req, child_preds)
            return _rebuild(node, [child])

        if isinstance(node, LogicalProject):
            child_req: Set[str] = set()
            for e in node.exprs:
                col_refs(e, child_req)
            child = _rewrite(node.children[0], child_req, [])
            return _rebuild(node, [child])

        if isinstance(node, LogicalAggregate):
            child_req = set()
            for e in list(node.grouping) + list(node.aggregates):
                col_refs(e, child_req)
            child = _rewrite(node.children[0], child_req, [])
            return _rebuild(node, [child])

        if isinstance(node, LogicalJoin):
            refs: Set[str] = set() if required is None else set(required)
            if node.condition is not None:
                col_refs(node.condition, refs)
            if node.using:
                refs.update(node.using)
            children = []
            for c in node.children:
                if required is None:
                    children.append(_rewrite(c, None, []))
                else:
                    children.append(
                        _rewrite(c, refs & self._child_names(c), []))
            return _rebuild(node, children)

        if isinstance(node, (LogicalSort, LogicalRepartition)):
            child_req = None
            if required is not None:
                child_req = set(required)
                keys = node.orders if isinstance(node, LogicalSort) \
                    else node.keys
                for o in keys:
                    col_refs(o, child_req)
            child = _rewrite(node.children[0], child_req, [])
            return _rebuild(node, [child])

        if isinstance(node, LogicalWindow):
            child_req = None
            if required is not None:
                child_req = set(required)
                for e in (list(node.window_exprs) + list(node.partition_by)
                          + list(node.order_by)):
                    col_refs(e, child_req)
            child = _rewrite(node.children[0], child_req, [])
            return _rebuild(node, [child])

        if isinstance(node, LogicalGenerate):
            child_req = None
            if required is not None:
                child_req = set(required) - set(node.names)
                col_refs(node.generator, child_req)
            child = _rewrite(node.children[0], child_req, [])
            return _rebuild(node, [child])

        if isinstance(node, LogicalExpand):
            child_req = set()
            for proj in node.projections:
                for e in proj:
                    col_refs(e, child_req)
            child = _rewrite(node.children[0], child_req, [])
            return _rebuild(node, [child])

        if isinstance(node, LogicalUnion):
            # do NOT prune through a union: children concatenate
            # positionally, and only scan-backed children can narrow — a
            # Project/Aggregate child keeps its declared output, so passing
            # `required` down would mis-align the branches
            children = [_rewrite(c, None, []) for c in node.children]
            return _rebuild(node, children)

        if isinstance(node, LogicalLimit):
            # drop predicates: skipping row groups under a limit would
            # change WHICH rows the limit takes
            child = _rewrite(node.children[0], required, [])
            return _rebuild(node, [child])

        if isinstance(node, (LogicalDistinct, LogicalWrite)):
            # distinct dedups FULL rows; write persists every child column
            children = [_rewrite(c, None, []) for c in node.children]
            return _rebuild(node, children)

        # unknown node: be conservative — need everything, push nothing
        children = [_rewrite(c, None, []) for c in node.children]
        return _rebuild(node, children)


def _rewrite_scan(scan: LogicalScan, required: Optional[Set[str]],
                  preds: List[Tuple[str, str, object]]) -> LogicalScan:
    new_opts = dict(scan.options)
    schema = scan.schema
    # CSV parses positionally against the declared schema — pruning there
    # would misalign columns; parquet/orc/memory sources prune cleanly
    if required is not None and scan.fmt != "csv":
        keep = [f for f in schema.fields if f.name in required]
        if not keep:  # count(*)-style: keep one narrow column for row counts
            keep = [min(schema.fields,
                        key=lambda f: 99 if f.dtype.is_string else 1)]
        if len(keep) < len(schema.fields):
            schema = Schema(keep)
    file_preds = [(n, op, v) for (n, op, v) in preds
                  if n in schema.names]
    if scan.fmt in ("parquet", "orc") and file_preds:
        # parquet: row groups skipped by footer statistics before any read;
        # orc: pyarrow exposes no stripe statistics, so the reader decodes
        # the (narrow) predicate columns first and skips the remaining
        # columns of provably-dead stripes (io/scan.py _iter_orc; the
        # reference builds a hive sarg instead, OrcFilters.scala:1-194)
        new_opts["__predicates__"] = file_preds
    if schema is scan.schema and "__predicates__" not in new_opts:
        return scan
    return LogicalScan(scan.source, schema, scan.fmt, new_opts)
