"""Per-node tagging + expression resolution (called from PlanMeta.tag_tree).

Each handler resolves the node's expressions against child schemas (stashing
results on the meta for conversion) and applies node-specific constraints,
mirroring the reference's per-meta tagPlanForGpu methods (e.g.
GpuHashAggregateMeta.tagPlanForGpu aggregate.scala:64-111,
GpuHashJoin.tagJoin GpuHashJoin.scala:27-45, sort nulls-ordering checks in
GpuSortExec.scala).
"""
from __future__ import annotations

from .. import config as C
from ..ops.aggregates import AggregateExpression
from ..types import StringType
from . import logical as L
from .analysis import resolve
from .overrides import ExprMeta, PlanMeta, expr_conf_key, plan_schema

_TPU_JOIN_TYPES = {"inner", "left", "left_outer", "left_semi", "left_anti",
                   "full", "full_outer", "right", "right_outer"}




def _require_exec(meta: PlanMeta, module: str):
    """Feature-gate: tag off-TPU until the device exec module lands."""
    import importlib.util
    if importlib.util.find_spec(f"spark_rapids_tpu.exec.{module}") is None \
            and importlib.util.find_spec(
                f"spark_rapids_tpu.io.{module}") is None:
        meta.will_not_work(f"TPU {module} exec is not implemented yet")


def tag_node(meta: PlanMeta):
    plan = meta.plan
    conf = meta.conf

    if isinstance(plan, L.LogicalScan):
        _tag_scan(meta)
    elif isinstance(plan, L.LogicalProject):
        schema = meta.input_schema()
        exprs = [resolve(ce, schema) for ce in plan.exprs]
        meta.resolved["exprs"] = exprs
        meta.resolved["names"] = [ce.output_name for ce in plan.exprs]
        meta.expr_metas = [ExprMeta(e, conf) for e in exprs]
    elif isinstance(plan, L.LogicalFilter):
        schema = meta.input_schema()
        cond = resolve(plan.condition, schema)
        meta.resolved["condition"] = cond
        meta.expr_metas = [ExprMeta(cond, conf)]
    elif isinstance(plan, L.LogicalAggregate):
        _tag_aggregate(meta)
    elif isinstance(plan, L.LogicalJoin):
        _tag_join(meta)
    elif isinstance(plan, L.LogicalSort):
        _tag_sort(meta)
    elif isinstance(plan, L.LogicalLimit):
        pass
    elif isinstance(plan, L.LogicalUnion):
        pass
    elif isinstance(plan, L.LogicalDistinct):
        # device distinct = hash aggregate over all output columns with no
        # aggregate expressions (Spark plans Distinct the same way; the
        # reference then accelerates that HashAggregateExec)
        schema = meta.input_schema()
        grouping = [resolve(L.col(f.name), schema) for f in schema]
        meta.resolved["grouping"] = grouping
        meta.expr_metas = [ExprMeta(e, conf) for e in grouping]
    elif isinstance(plan, L.LogicalExpand):
        schema = meta.input_schema()
        projections = [[resolve(ce, schema) for ce in proj]
                       for proj in plan.projections]
        meta.resolved["projections"] = projections
        meta.resolved["names"] = [ce.output_name
                                  for ce in plan.projections[0]]
        meta.expr_metas = [ExprMeta(e, conf)
                           for proj in projections for e in proj]
    elif isinstance(plan, L.LogicalRepartition):
        _require_exec(meta, "exchange")
        schema = meta.input_schema()
        keys = [resolve(ce, schema) for ce in plan.keys]
        meta.resolved["keys"] = keys
        meta.expr_metas = [ExprMeta(e, conf) for e in keys]
    elif isinstance(plan, L.LogicalGenerate):
        _tag_generate(meta)
    elif isinstance(plan, L.LogicalWindow):
        _tag_window(meta)
    elif isinstance(plan, L.LogicalWrite):
        _require_exec(meta, "writer")
        if plan.fmt == "parquet" and not (
                conf.get(C.PARQUET_ENABLED)
                and conf.get(C.PARQUET_WRITE_ENABLED)):
            meta.will_not_work("parquet writes disabled by conf")
    else:
        meta.will_not_work(
            f"{type(plan).__name__} is not supported on TPU")


def _tag_scan(meta: PlanMeta):
    plan: L.LogicalScan = meta.plan
    conf = meta.conf
    if plan.fmt == "parquet":
        if not (conf.get(C.PARQUET_ENABLED)
                and conf.get(C.PARQUET_READ_ENABLED)):
            meta.will_not_work(
                f"parquet reads disabled; set {C.PARQUET_ENABLED.key}=true "
                f"and {C.PARQUET_READ_ENABLED.key}=true")
    elif plan.fmt == "csv":
        if not (conf.get(C.CSV_ENABLED) and conf.get(C.CSV_READ_ENABLED)):
            meta.will_not_work("csv reads disabled by conf")
    elif plan.fmt == "orc":
        if not (conf.get(C.ORC_ENABLED) and conf.get(C.ORC_READ_ENABLED)):
            meta.will_not_work("orc reads disabled by conf")
    for f in plan.schema:
        from ..types import SUPPORTED_TYPES
        if f.dtype not in SUPPORTED_TYPES:
            meta.will_not_work(f"scan column {f.name} has unsupported type "
                               f"{f.dtype.name}")


def _tag_aggregate(meta: PlanMeta):
    _require_exec(meta, "aggregate")
    plan: L.LogicalAggregate = meta.plan
    conf = meta.conf
    schema = meta.input_schema()
    grouping = [resolve(ce, schema) for ce in plan.grouping]
    aggs = []
    for ce in plan.aggregates:
        ex = resolve(ce, schema)
        if not isinstance(ex, AggregateExpression):
            raise NotImplementedError(
                "non-aggregate expression in agg list; wrap in first()")
        aggs.append(ex)
    meta.resolved["grouping"] = grouping
    meta.resolved["group_names"] = [ce.output_name for ce in plan.grouping]
    meta.resolved["aggregates"] = aggs
    meta.expr_metas = [ExprMeta(e, conf) for e in grouping]
    meta.expr_metas += [ExprMeta(e, conf) for e in aggs]
    # one distinct child is deduped inside the update kernel; several
    # distinct children would each need their own dedup ordering, which a
    # single sorted pass cannot provide (the reference likewise falls back
    # for multi-distinct, GpuHashAggregateMeta.tagPlanForGpu,
    # aggregate.scala:64-111)
    for a in aggs:
        if a.func == "Percentile":
            # exact percentile needs the group's full multiset (state is
            # unbounded/unmergeable); the reference ships no GPU
            # Percentile rule either — CPU fallback is parity
            meta.will_not_work(
                "percentile is not supported on TPU (falls back, like "
                "the reference)")
    distinct_children = {repr(a.child) for a in aggs if a.distinct}
    if len(distinct_children) > 1:
        meta.will_not_work(
            "multiple distinct aggregate children are not supported on TPU")
    if conf.get(C.HAS_NANS):
        # like the reference's hasNans gate on float agg keys
        for g in grouping:
            if g.dtype.is_floating and not conf.get(C.INCOMPATIBLE_OPS):
                # we implement Spark NaN-equal grouping; allowed
                pass


def _tag_join(meta: PlanMeta):
    _require_exec(meta, "join")
    plan: L.LogicalJoin = meta.plan
    if plan.join_type not in _TPU_JOIN_TYPES:
        meta.will_not_work(
            f"{plan.join_type} joins are not supported on TPU "
            "(Inner/Left/Right/Full/LeftSemi/LeftAnti; the reference "
            "stops at Inner/Left/LeftSemi/LeftAnti — device RIGHT and "
            "FULL OUTER go beyond it)")
    if plan.join_type in ("full", "full_outer") and plan.using:
        # USING full joins coalesce the key across BOTH preserved sides
        # per row; the device kernels carry one side's keys, so Spark's
        # coalesced-key contract needs the CPU path.  (Right USING joins
        # ARE supported: every output row preserves a right row, so the
        # key surfaces from the right block via the post-join reorder.)
        meta.will_not_work(f"{plan.join_type} USING joins (coalesced "
                           "keys) are not supported on TPU")
    ls = plan_schema(plan.children[0], meta.conf)
    rs = plan_schema(plan.children[1], meta.conf)
    lkeys, rkeys, cond = [], [], None
    if plan.using:
        for name in plan.using:
            lkeys.append(resolve(L.col(name), ls))
            rkeys.append(resolve(L.col(name), rs))
    elif plan.condition is not None:
        eqs, residual = _split_equi(plan.condition)
        for lc, rc in eqs:
            try:
                lk = resolve(lc, ls)
                rk = resolve(rc, rs)
            except Exception:
                lk = resolve(rc, ls)
                rk = resolve(lc, rs)
            lkeys.append(lk)
            rkeys.append(rk)
        if residual is not None:
            if plan.join_type not in ("inner", "left_semi", "left_anti"):
                # the device join applies the residual pair-wise inside
                # the candidate walk, which is exact for inner and for
                # semi/anti EXISTS semantics; outer joins would need
                # matched-row bookkeeping the kernels do not carry
                # (reference: GpuHashJoin tagJoin allows inner ONLY —
                # device semi/anti conditionals go beyond it)
                meta.will_not_work(
                    f"conditional {plan.join_type} joins are not supported "
                    "on TPU (inner/semi/anti only)")
            joined = _joined_schema(ls, rs)
            cond = resolve(residual, joined)
            meta.expr_metas.append(ExprMeta(cond, meta.conf))
    if not lkeys:
        meta.will_not_work("join without equi-join keys is not supported "
                           "on TPU (no cross/theta join)")
    # key TYPE coercion (Spark inserts the same implicit casts): an
    # int32-vs-int64 key pair compares equal by value but HASHES
    # differently (murmur3 hashInt vs hashLong), so an uncoerced pair
    # silently matches nothing in the hash join / hash partitioning.
    # coerce_pair handles null/string/date-vs-timestamp; numerics then
    # need the promotion MATERIALIZED as casts — join keys are evaluated
    # separately per side, so there is no BinaryExpression to promote
    # them internally.
    from ..ops.cast import Cast
    from ..types import promote
    from .analysis import AnalysisError, coerce_pair
    for i, (lk, rk) in enumerate(zip(lkeys, rkeys)):
        if lk.dtype is rk.dtype:
            continue
        try:
            lk, rk = coerce_pair(lk, rk, "EqualTo")
        except AnalysisError as e:
            meta.will_not_work(f"join key: {e}")
            continue
        if lk.dtype is not rk.dtype:
            if not (lk.dtype.is_numeric and rk.dtype.is_numeric):
                meta.will_not_work(
                    f"join key type mismatch {lk.dtype.name} vs "
                    f"{rk.dtype.name} has no implicit coercion")
                continue
            target = promote(lk.dtype, rk.dtype)
            if lk.dtype is not target:
                lk = Cast(lk, target)
            if rk.dtype is not target:
                rk = Cast(rk, target)
        lkeys[i], rkeys[i] = lk, rk
    meta.resolved["left_keys"] = lkeys
    meta.resolved["right_keys"] = rkeys
    meta.resolved["condition"] = cond
    meta.expr_metas += [ExprMeta(e, meta.conf) for e in lkeys + rkeys]


def _joined_schema(ls, rs):
    from ..types import Schema, StructField
    names = [f.name for f in ls]
    rfields = []
    for f in rs:
        nm = f.name if f.name not in names else f.name + "_r"
        rfields.append(StructField(nm, f.dtype))
    return Schema(list(ls.fields) + rfields)


def _split_equi(cond):
    """Split a join condition into equi key pairs + residual."""
    eqs = []
    residual = []

    def walk(ce):
        if ce.op == "And":
            walk(ce.args[0])
            walk(ce.args[1])
        elif ce.op == "EqualTo":
            eqs.append((ce.args[0], ce.args[1]))
        else:
            residual.append(ce)
    walk(cond)
    res = None
    for r in residual:
        res = r if res is None else (res & r)
    return eqs, res


def _tag_sort(meta: PlanMeta):
    _require_exec(meta, "sort")
    plan: L.LogicalSort = meta.plan
    schema = meta.input_schema()
    exprs = [resolve(o.child, schema) for o in plan.orders]
    meta.resolved["sort_exprs"] = exprs
    meta.resolved["ascending"] = [o.ascending for o in plan.orders]
    meta.resolved["nulls_first"] = [o.effective_nulls_first
                                    for o in plan.orders]
    meta.expr_metas = [ExprMeta(e, meta.conf) for e in exprs]
    # reference restriction: nulls ordering must match cudf defaults
    # (GpuSortExec.scala); our lexsort handles both, no restriction needed


def _tag_window(meta: PlanMeta):
    """Resolve a LogicalWindow: partition/order keys + every window function
    (reference: GpuWindowExpression tagging, GpuWindowExpression.scala:87-233).
    Device-capability limits fall back to the CPU window exec; semantic
    errors surface as analysis errors."""
    from ..ops.windows import WindowUnsupported, resolve_window_func
    plan: L.LogicalWindow = meta.plan
    schema = meta.input_schema()
    part_exprs = [resolve(ce, schema) for ce in plan.partition_by]
    order_exprs = [resolve(o.child, schema) for o in plan.order_by]
    meta.resolved["part_exprs"] = part_exprs
    meta.resolved["order_exprs"] = order_exprs
    meta.resolved["ascending"] = [o.ascending for o in plan.order_by]
    meta.resolved["nulls_first"] = [o.effective_nulls_first
                                    for o in plan.order_by]

    def _resolve_funcs(device: bool):
        funcs = []
        for ce in plan.window_exprs:
            func_ce, spec = ce.args
            wf = resolve_window_func(func_ce, spec, schema, resolve,
                                     device=device)
            wf.name = ce.output_name
            funcs.append(wf)
        return funcs

    try:
        meta.resolved["funcs"] = _resolve_funcs(device=True)
        # per-op kill-switch conf parity with the reference's window rules
        # (spark.rapids.sql.expr.RowNumber etc.; GpuOverrides window
        # expression table)
        for f in meta.resolved["funcs"]:
            if not meta.conf.is_op_enabled(expr_conf_key(f.kind)):
                meta.will_not_work(
                    f"window function {f.kind} has been disabled; set "
                    f"{expr_conf_key(f.kind)}=true to enable")
    except WindowUnsupported as e:
        meta.will_not_work(f"window: {e}")
        try:
            meta.resolved["funcs"] = _resolve_funcs(device=False)
        except WindowUnsupported as e2:
            # unsupported on BOTH engines (e.g. percentile windows):
            # surface a proper analysis error, not a planner-internal one
            from .analysis import AnalysisError
            raise AnalysisError(f"window: {e2}") from e2
    meta.expr_metas = [ExprMeta(e, meta.conf)
                       for e in part_exprs + order_exprs] + \
        [ExprMeta(f.child, meta.conf)
         for f in meta.resolved["funcs"] if f.child is not None]


def _tag_generate(meta: PlanMeta):
    """explode/posexplode of an array literal (the reference's supported
    generator surface, GpuGenerateExec.scala:101+)."""
    plan: L.LogicalGenerate = meta.plan
    values = list(plan.generator.args[0])
    if not values:
        meta.will_not_work("explode of an empty array literal")
        values = [None]
    from .analysis import _infer_value_dtype
    dtype = _infer_value_dtype(values)
    if dtype is None:
        meta.will_not_work("explode values must share one supported type")
        from ..types import StringType as _S
        dtype = _S
        values = [None if v is None else str(v) for v in values]
    meta.resolved["values"] = values
    meta.resolved["value_dtype"] = dtype
    meta.resolved["pos"] = plan.generator.op == "PosExplode"
    meta.resolved["names"] = list(plan.names)
