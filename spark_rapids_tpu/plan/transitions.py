"""Transition pass over the physical tree.

Reference: rapids/GpuTransitionOverrides.scala — inserts host<->device
transitions at CPU/TPU boundaries, inserts coalesce nodes per child goal,
optimizes adjacent transitions away, and in test mode asserts the whole plan
is on the device except an allowlist (assertIsOnTheGpu :211-254).

TPU-specific extra pass: maximal chains of row-local device ops are fused
into a single FusedPipelineExec so the per-batch work compiles to ONE XLA
program.
"""
from __future__ import annotations

from typing import List

from .. import config as C
from ..config import TpuConf
from ..exec import basic as B
from ..exec.base import CpuExec, ExecNode, TpuExec


class PlanOnCpuError(AssertionError):
    """Raised in test mode when something silently fell back to CPU."""


def insert_transitions(node: ExecNode) -> ExecNode:
    node.children = [insert_transitions(c) for c in node.children]
    new_children = []
    for child in node.children:
        if isinstance(node, TpuExec) and isinstance(child, CpuExec):
            new_children.append(B.HostToDeviceExec(child))
        elif isinstance(node, CpuExec) and isinstance(child, TpuExec):
            new_children.append(B.DeviceToHostExec(child))
        else:
            new_children.append(child)
    node.children = new_children
    return node


def optimize_transitions(node: ExecNode) -> ExecNode:
    """Remove D2H->H2D and H2D->D2H pairs (reference: optimizeGpuPlanTransitions)."""
    node.children = [optimize_transitions(c) for c in node.children]
    if isinstance(node, B.HostToDeviceExec) \
            and isinstance(node.children[0], B.DeviceToHostExec):
        return node.children[0].children[0]
    if isinstance(node, B.DeviceToHostExec) \
            and isinstance(node.children[0], B.HostToDeviceExec):
        return node.children[0].children[0]
    return node


def insert_coalesce(node: ExecNode, conf: TpuConf) -> ExecNode:
    """Insert TpuCoalesceBatchesExec under device ops that declare a child
    coalesce goal (reference: insertCoalesce per childrenCoalesceGoal)."""
    node.children = [insert_coalesce(c, conf) for c in node.children]
    goal = getattr(node, "child_coalesce_goal", None)
    if goal is not None and isinstance(node, TpuExec):
        node.children = [
            B.TpuCoalesceBatchesExec(c, goal="single"
                                     if goal == "single" else "target")
            if isinstance(c, TpuExec)
            and not isinstance(c, B.TpuCoalesceBatchesExec) else c
            for c in node.children]
    return node


def fuse_row_local(node: ExecNode) -> ExecNode:
    """Collapse maximal chains of RowLocalExec into one FusedPipelineExec
    (flattening through already-fused children so a 3+ op chain still
    compiles to a single program)."""
    node.children = [fuse_row_local(c) for c in node.children]
    if isinstance(node, B.RowLocalExec):
        chain: List[B.RowLocalExec] = []  # outermost first
        cur: ExecNode = node
        while isinstance(cur, B.RowLocalExec):
            chain.append(cur)
            cur = cur.children[0]
        if len(chain) > 1 or any(isinstance(c, B.FusedPipelineExec)
                                 for c in chain):
            stages: List[B.RowLocalExec] = []  # execution order
            for n in reversed(chain):
                if isinstance(n, B.FusedPipelineExec):
                    stages.extend(n.stages)
                else:
                    stages.append(n)
            if len(stages) == 1:
                return node
            return B.FusedPipelineExec(stages, cur)
    return node


def assert_on_tpu(node: ExecNode, conf: TpuConf):
    """Test-mode check (reference: GpuTransitionOverrides.assertIsOnTheGpu)."""
    allowed = {s.strip() for s in
               str(conf.get(C.TEST_ALLOWED_NONTPU)).split(",") if s.strip()}
    always_ok = {"DeviceToHostExec", "HostToDeviceExec"}

    def walk(n: ExecNode):
        if isinstance(n, CpuExec) and n.name not in allowed \
                and n.name not in always_ok:
            raise PlanOnCpuError(
                f"plan is not on the TPU: {n.describe()} "
                f"(allow with {C.TEST_ALLOWED_NONTPU.key})")
        for c in n.children:
            walk(c)
    walk(node)


def finalize(node: ExecNode, conf: TpuConf) -> ExecNode:
    node = insert_transitions(node)
    node = optimize_transitions(node)
    node = insert_coalesce(node, conf)
    node = fuse_row_local(node)
    if conf.is_test_enabled:
        assert_on_tpu(node, conf)
    return node
