"""Transition pass over the physical tree.

Reference: rapids/GpuTransitionOverrides.scala — inserts host<->device
transitions at CPU/TPU boundaries, inserts coalesce nodes per child goal,
optimizes adjacent transitions away, and in test mode asserts the whole plan
is on the device except an allowlist (assertIsOnTheGpu :211-254).

TPU-specific extra pass: maximal chains of row-local device ops are fused
into a single FusedPipelineExec so the per-batch work compiles to ONE XLA
program.
"""
from __future__ import annotations

from typing import List

from .. import config as C
from ..config import TpuConf
from ..exec import basic as B
from ..exec.base import CpuExec, ExecNode, TpuExec


class PlanOnCpuError(AssertionError):
    """Raised in test mode when something silently fell back to CPU."""


def insert_transitions(node: ExecNode) -> ExecNode:
    node.children = [insert_transitions(c) for c in node.children]
    new_children = []
    for child in node.children:
        if isinstance(node, TpuExec) and isinstance(child, CpuExec):
            new_children.append(B.HostToDeviceExec(child))
        elif isinstance(node, CpuExec) and isinstance(child, TpuExec):
            new_children.append(B.DeviceToHostExec(child))
        else:
            new_children.append(child)
    node.children = new_children
    return node


def optimize_transitions(node: ExecNode) -> ExecNode:
    """Remove D2H->H2D and H2D->D2H pairs (reference: optimizeGpuPlanTransitions)."""
    node.children = [optimize_transitions(c) for c in node.children]
    if isinstance(node, B.HostToDeviceExec) \
            and isinstance(node.children[0], B.DeviceToHostExec):
        return node.children[0].children[0]
    if isinstance(node, B.DeviceToHostExec) \
            and isinstance(node.children[0], B.HostToDeviceExec):
        return node.children[0].children[0]
    return node


def insert_coalesce(node: ExecNode, conf: TpuConf) -> ExecNode:
    """Insert TpuCoalesceBatchesExec under device ops that declare a child
    coalesce goal (reference: insertCoalesce per childrenCoalesceGoal)."""
    node.children = [insert_coalesce(c, conf) for c in node.children]
    goal = getattr(node, "child_coalesce_goal", None)
    if goal is not None and isinstance(node, TpuExec):
        node.children = [
            B.TpuCoalesceBatchesExec(c, goal="single"
                                     if goal == "single" else "target")
            if isinstance(c, TpuExec)
            and not isinstance(c, B.TpuCoalesceBatchesExec) else c
            for c in node.children]
    return node


def fuse_row_local(node: ExecNode) -> ExecNode:
    """Collapse maximal chains of RowLocalExec into one FusedPipelineExec
    (flattening through already-fused children so a 3+ op chain still
    compiles to a single program)."""
    node.children = [fuse_row_local(c) for c in node.children]
    if isinstance(node, B.RowLocalExec):
        chain: List[B.RowLocalExec] = []  # outermost first
        cur: ExecNode = node
        while isinstance(cur, B.RowLocalExec):
            chain.append(cur)
            cur = cur.children[0]
        if len(chain) > 1 or any(isinstance(c, B.FusedPipelineExec)
                                 for c in chain):
            stages: List[B.RowLocalExec] = []  # execution order
            for n in reversed(chain):
                if isinstance(n, B.FusedPipelineExec):
                    stages.extend(n.stages)
                else:
                    stages.append(n)
            if len(stages) == 1:
                return node
            return B.FusedPipelineExec(stages, cur)
    return node


def assert_on_tpu(node: ExecNode, conf: TpuConf):
    """Test-mode check (reference: GpuTransitionOverrides.assertIsOnTheGpu)."""
    allowed = {s.strip() for s in
               str(conf.get(C.TEST_ALLOWED_NONTPU)).split(",") if s.strip()}
    always_ok = {"DeviceToHostExec", "HostToDeviceExec"}

    def walk(n: ExecNode):
        if isinstance(n, CpuExec) and n.name not in allowed \
                and n.name not in always_ok:
            raise PlanOnCpuError(
                f"plan is not on the TPU: {n.describe()} "
                f"(allow with {C.TEST_ALLOWED_NONTPU.key})")
        for c in n.children:
            walk(c)
    walk(node)


def mark_ici_exchanges(node: ExecNode, mesh) -> ExecNode:
    """Stamp the ICI-lowering decision on every generic shuffle exchange
    of a mesh plan: an exchange carrying `ici_mesh` materializes its map
    phase as jitted collectives over that mesh instead of the host
    socket tier (shuffle/mesh_exchange.py), behind the
    spark.rapids.sql.tpu.shuffle.ici.enabled kill switch and the
    capability checks (no cluster, non-range partitioning).

    IDEMPOTENT by construction (re-stamping the same mesh is a no-op),
    so AQE's `_replan` re-runs it over re-planned trees — exchanges the
    rules introduce (a demoted broadcast's replacement repartition) get
    the same lowering decision as planner-built ones."""
    from ..exec.exchange import TpuShuffleExchangeExec

    def walk(n: ExecNode) -> None:
        if isinstance(n, TpuShuffleExchangeExec):
            n.ici_mesh = mesh
        for c in n.children:
            walk(c)

    walk(node)
    return node


def distribute(node: ExecNode, conf: TpuConf) -> ExecNode:
    """Swap shuffle-shaped subtrees for SPMD mesh operators when
    spark.rapids.sql.tpu.mesh.devices > 1 (the planner integration of
    parallel/distributed.py; reference analogue: the shuffle manager being
    the execution path of every exchange,
    rapids/GpuShuffleExchangeExec.scala:60-155)."""
    from ..exec.distributed import (TpuDistributedAggregateExec,
                                    TpuDistributedJoinExec,
                                    TpuDistributedSortExec, resolve_mesh)
    mesh = resolve_mesh(conf)
    if mesh is None:
        return node
    from ..exec.aggregate import TpuHashAggregateExec
    from ..exec.broadcast import TpuBroadcastHashJoinExec
    from ..exec.join import TpuHashJoinExec, TpuShuffledHashJoinExec
    from ..exec.sort import TpuSortExec
    allgather = conf.get(C.MESH_USE_ALLGATHER)

    def walk(n: ExecNode) -> ExecNode:
        if isinstance(n, TpuShuffledHashJoinExec) \
                and n.join_type != "full":
            # the mesh all-to-all IS the exchange: unwrap the planner-
            # inserted single-chip exchanges and join their inputs SPMD.
            # FULL joins stay single-chip: their never-matched-build tail
            # is emitted once per probe stream, which per-chunk
            # concatenation cannot compose.
            left = n.children[0].children[0]
            right = n.children[1].children[0]
            out = TpuDistributedJoinExec(
                walk(left), walk(right), n.join_type, n.left_keys,
                n.right_keys, n.condition, n.schema, n.using_drop, mesh,
                allgather)
            return out
        n.children = [walk(c) for c in n.children]
        if isinstance(n, TpuDistributedAggregateExec) \
                or isinstance(n, TpuDistributedSortExec) \
                or isinstance(n, TpuDistributedJoinExec) \
                or isinstance(n, TpuBroadcastHashJoinExec):
            return n
        if type(n) is TpuHashAggregateExec and n.grouping \
                and not n._needs_offset() \
                and not any(a.distinct for a in n.aggregates):
            # global (ungrouped) aggregates stay single-chip (their state
            # is one row, an all-to-all buys nothing); offset-dependent
            # aggregates (First/Last) keep the single-chip path so the
            # arrival-order tiebreak stays deterministic; distinct
            # aggregates dedup inside ONE update kernel (partial states are
            # not mergeable across shards), so they stay single-chip too
            return TpuDistributedAggregateExec(
                n.grouping, n.group_names, n.aggregates, n.children[0],
                mesh, allgather)
        if type(n) is TpuHashJoinExec and n.join_type != "full":
            return TpuDistributedJoinExec(
                n.children[0], n.children[1], n.join_type, n.left_keys,
                n.right_keys, n.condition, n.schema, n.using_drop, mesh,
                allgather)
        if type(n) is TpuSortExec:
            return TpuDistributedSortExec(
                n.sort_exprs, n.ascending, n.nulls_first, n.children[0],
                mesh, allgather)
        return n

    # generic exchanges the swap left behind (repartitions, full-join
    # exchange pairs) lower their OWN write phase into collectives over
    # the same mesh — the shuffle side of ROADMAP item 1
    return mark_ici_exchanges(walk(node), mesh)


def finalize(node: ExecNode, conf: TpuConf) -> ExecNode:
    from .fusion import fuse_stages
    node = distribute(node, conf)
    node = insert_transitions(node)
    node = optimize_transitions(node)
    node = insert_coalesce(node, conf)
    # whole-stage fusion (plan/fusion.py): maximal row-local chains ->
    # TpuWholeStageExec with *(N) ids; falls back to fuse_row_local when
    # spark.rapids.sql.tpu.fusion.enabled=false
    node = fuse_stages(node, conf)
    if conf.is_test_enabled:
        assert_on_tpu(node, conf)
    return node
