"""Plugin bootstrap + local multi-executor cluster.

Reference analogue: com/nvidia/spark/SQLPlugin.scala + rapids/Plugin.scala
(RapidsDriverPlugin.init validates confs and broadcasts them,
RapidsExecutorPlugin.init brings up the device, memory pools and shuffle
wiring per executor, both with shutdown hooks; Plugin.scala:208-247).

The TPU-native process model differs on purpose: mesh SPMD execution
replaces executor fan-out for on-chip scale-out, so "executors" here are
the HOST-MODE shuffle domains — each owns a runtime (pool, semaphore,
spill stores) and a ShuffleEnv registered on a shared transport wire.
`TpuCluster` runs N of them in one interpreter: map tasks of a shuffle
write to their executor's catalog, reduce tasks fetch local blocks and
pull the rest through the transport client/server path (bounce buffers,
throttle, metadata round trip) exactly as a multi-process deployment
would."""
from __future__ import annotations

from typing import List, Optional

from . import config as C
from .config import TpuConf


class TpuDriverPlugin:
    """Driver-side bootstrap: validate confs once, produce the dict every
    executor plugin initializes from (the reference broadcasts the same
    way; Plugin.scala RapidsDriverPlugin.init)."""

    def __init__(self, conf: Optional[TpuConf] = None):
        self.conf = conf or TpuConf()
        self._initialized = False

    def init(self) -> dict:
        # touching every registered entry validates types/values eagerly,
        # like the reference's conf validation at plugin init
        for entry in C.registered_entries():
            entry.get(self.conf)
        n = int(self.conf.get(C.CLUSTER_EXECUTORS))
        if n < 1:
            raise ValueError(f"{C.CLUSTER_EXECUTORS.key} must be >= 1")
        self._initialized = True
        return dict(self.conf._settings)

    def shutdown(self) -> None:
        self._initialized = False


class TpuExecutorPlugin:
    """Per-executor bring-up: runtime (HBM pool, semaphore, spill stores)
    + shuffle env on the shared wire; shutdown releases everything
    (reference: RapidsExecutorPlugin.init/shutdown)."""

    def __init__(self, executor_id: str, conf: TpuConf, transport=None,
                 pool_limit_bytes: Optional[int] = None):
        from .mem.runtime import TpuRuntime
        from .shuffle.manager import ShuffleEnv
        self.executor_id = executor_id
        self.conf = conf
        self.runtime = TpuRuntime(conf, pool_limit_bytes=pool_limit_bytes)
        self.env = ShuffleEnv(self.runtime, conf, executor_id, transport)

    def shutdown(self) -> None:
        # drop every shuffle the env still holds (idempotent per shuffle)
        for sid in list(self.env.catalog._by_shuffle):
            self.env.remove_shuffle(sid)


class TpuCluster:
    """N executor plugins over one loopback/ICI transport wire."""

    def __init__(self, conf: TpuConf, n_executors: Optional[int] = None):
        from .shuffle.ici import IciShuffleTransport
        self.conf = conf
        self.n = int(n_executors if n_executors is not None
                     else conf.get(C.CLUSTER_EXECUTORS))
        self.driver = TpuDriverPlugin(conf)
        self.driver.init()
        pinned = int(conf.get(C.PINNED_POOL_SIZE))
        self.transport = IciShuffleTransport(
            max_inflight_bytes=int(conf.get(C.SHUFFLE_MAX_RECV_INFLIGHT)),
            # same staging-pool rule as every other transport bring-up:
            # bounce confs are the source of truth, pinned pool overrides
            pool_size=pinned if pinned > 0
            else int(conf.get(C.SHUFFLE_BOUNCE_POOL_SIZE)),
            chunk_size=int(conf.get(C.SHUFFLE_BOUNCE_CHUNK_SIZE)))
        # adopt the session conf on the shared wire: checksum algorithm
        # and the negotiated compression codec (compress/) — without this
        # the cluster transport would silently keep the defaults
        self.transport.configure(conf)
        # N executors share ONE device WITH the driving session's compute
        # pool (engine.TpuSession.runtime, which halves itself in cluster
        # mode): the executors split one half of the session budget —
        # an explicit poolSizeBytes when set, else allocFraction of
        # detected HBM — so session + executors account for HBM once
        from .mem.runtime import configured_pool_bytes
        total_pool = configured_pool_bytes(conf) // 2
        per_executor = max(total_pool // self.n, 1)
        self.executors: List[TpuExecutorPlugin] = [
            TpuExecutorPlugin(f"exec-{i}", conf, self.transport,
                              pool_limit_bytes=per_executor)
            for i in range(self.n)]
        import threading
        self._sid = [0]
        self._sid_lock = threading.Lock()
        # when the process telemetry plane is live, expose the executor
        # pools' roll-up as one sampler source (label replacement in
        # metrics/ring.py keeps re-created clusters from stacking stale
        # closures)
        from .metrics import ring as R
        t = R.get_telemetry()
        if t is not None:
            t.sampler.add_source("cluster-pools", self.telemetry_gauges)

    def telemetry_gauges(self) -> dict:
        """Aggregate pool occupancy across the in-process executors, in
        the sampler's series vocabulary (names.TELEMETRY_GAUGES)."""
        dev = spill = 0.0
        for e in self.executors:
            stats = e.runtime.pool_stats()
            dev += float(stats.get("device_used", 0) or 0)
            spill += float((stats.get("host_used", 0) or 0)
                           + (stats.get("disk_used", 0) or 0))
        return {"cluster_device_used": dev, "cluster_spill_bytes": spill}

    def new_shuffle_id(self) -> int:
        with self._sid_lock:
            self._sid[0] += 1
            return self._sid[0]

    def env_for(self, task_id: int):
        return self.executors[task_id % self.n].env

    def peer_ids(self, excluding: str) -> List[str]:
        return [e.executor_id for e in self.executors
                if e.executor_id != excluding]

    @property
    def map_epoch(self) -> int:
        """Cluster lost-map-output epoch: any executor marking map output
        lost bumps its tracker epoch, and the sum invalidates every
        cached MapOutputStatistics view (exec/exchange._ShuffleHandle)."""
        return sum(e.env.map_stats.epoch for e in self.executors)

    def map_output_stats(self, sid: int, num_partitions: int):
        """Cluster-wide MapOutputStatistics for one shuffle: every
        executor's tracker snapshot merged (the MapOutputTrackerMaster
        aggregation; ProcCluster does the same over rpc_map_output_stats)."""
        from .adaptive.stats import merge_cluster_stats
        return merge_cluster_stats(
            sid, num_partitions,
            (e.env.map_stats.snapshot(sid) for e in self.executors))

    def remove_shuffle(self, sid: int) -> None:
        for e in self.executors:
            e.env.remove_shuffle(sid)

    def shutdown(self) -> None:
        for e in self.executors:
            e.shutdown()
        self.driver.shutdown()
