"""Data-movement policy engine: the decision tier over the measured
memory hierarchy (ROADMAP item 3).

PR 8's memory-pressure ledger and PR 13's roofline ledger made every
movement decision *measurable* — spill churn, victim re-touch quality,
headroom, per-node bottleneck resource — but the decisions themselves
stayed blind: victims were picked purely by (priority, id) order,
unspill was reactive, a slow reduce side could balloon host memory, and
the shuffle codec was fixed at plan time.  This package closes the
measure->act loop with four policies behind ONE master switch
(`spark.rapids.sql.tpu.policy.enabled`; the kill switch is byte-identical
to the pre-policy engine):

  * next-use spill victim selection (engine.py MovementPolicy): the
    stores' `synchronous_spill` ranks victims by a next-use score built
    from AQE map-output read order, shuffle-partition liveness (dead vs.
    about to be read) and the ledger's re-touch history;
  * proactive unspill (engine.py): a per-runtime policy thread unspills
    soon-needed spilled buffers while headroom exists, charged to the
    owning query's scope so it can never cause another query's OOM;
  * end-to-end flow control (flow.py FlowController): map-side serve and
    `fetch_partitions_async` admission ride a windowed in-flight-bytes
    budget driven by the reduce side's observed consumption rate;
  * roofline-driven codec re-selection (codec.py CodecAdvisor): an
    exchange proven wire-bound at runtime flips none->lz4/zstd through
    the PR 5 negotiation path for subsequent fetches.

Every decision is journaled (journal kind `policy`) and counted;
`python -m spark_rapids_tpu.metrics --memory` replays the decision
stream from journal shards alone (metrics/memledger.py).
"""
from .codec import CodecAdvisor
from .engine import MovementPolicy
from .flow import FlowController

__all__ = ["CodecAdvisor", "FlowController", "MovementPolicy"]
