"""Roofline-driven codec re-selection: wire-bound exchanges flip the
shuffle codec at runtime.

The static plan fixes the wire codec at session start
(spark.rapids.shuffle.compression.codec, default none).  The roofline
ledger (PR 13) can *prove* at runtime that an exchange was wire-bound —
its read phase moved bytes at a significant fraction of the platform's
wire peak — which is exactly the regime where paying codec CPU to shrink
wire bytes wins.  `CodecAdvisor` watches each exchange's observed read
throughput against `platform_peaks()["wire"]` and, once an exchange
crosses the wire-bound threshold at sufficient volume, advises the
configured candidate codec (none->lz4/zstd) for that shuffle id AND for
subsequent exchanges of the session (sticky, the same way AQE re-plans
on observed sizes).

The advice rides the existing PR 5 negotiation path end to end: the
reader names the advised codec in its MetadataRequest, the server
answers with what it will actually frame (raw when the library is
missing there — graceful fallback, counted), and fetches pull framed
compressed leaves through the same verify-before/after ladder.  The
override is attached per-client (`compression_override` on the
transport client), so only policy-advised fetches negotiate — a session
with compression explicitly configured is never second-guessed.
"""
from __future__ import annotations

import threading
from typing import Dict, Optional

from ..metrics import names as MN
from ..metrics.journal import journal_event


class CodecAdvisor:
    """Per-runtime wire-codec re-selection (see module doc)."""

    def __init__(self, conf, metrics=None):
        from .. import config as C
        self.candidate = str(conf.get(C.POLICY_CODEC)).lower()
        self.min_bytes = int(conf.get(C.POLICY_CODEC_MIN_BYTES))
        self.bound_fraction = float(conf.get(C.POLICY_CODEC_WIRE_BOUND))
        self.metrics = metrics
        self._conf = conf
        self._lock = threading.Lock()
        self._overrides: Dict[int, str] = {}
        self._sticky: Optional[str] = None
        self._reader_policy = None
        self._wire_peak: Optional[float] = None

    def _peak(self) -> float:
        if self._wire_peak is None:
            from ..metrics.roofline import platform_peaks
            peaks = platform_peaks(conf=self._conf)
            self._wire_peak = float(peaks.get("wire") or 0.0)  # tpulint: disable=TPU009 idempotent lazy cache: every racer computes the same conf-derived value, so the last write is indistinguishable from the first
        return self._wire_peak

    def observe_exchange(self, shuffle_id: int, wire_bytes: int,
                         seconds: float) -> bool:
        """Runtime evidence from one exchange's read phase; returns
        whether it (newly) triggered a re-selection for this shuffle."""
        if self.candidate in ("", "none") or seconds <= 0 \
                or wire_bytes < self.min_bytes:
            return False
        peak = self._peak()
        if peak <= 0:
            return False
        utilization = (wire_bytes / seconds) / peak
        if utilization < self.bound_fraction:
            return False
        from ..compress import is_codec_available
        if not is_codec_available(self.candidate):
            return False
        with self._lock:
            fresh = shuffle_id not in self._overrides
            self._overrides[shuffle_id] = self.candidate
            self._sticky = self.candidate
        if fresh:
            if self.metrics is not None:
                self.metrics.add(MN.NUM_CODEC_RESELECTIONS, 1)
            journal_event("policy", "codec", shuffle=shuffle_id,
                          codec=self.candidate,
                          wire_bytes=int(wire_bytes),
                          seconds=float(seconds),
                          utilization=float(utilization))
        return fresh

    def wire_codec(self, shuffle_id: int) -> Optional[str]:
        """The advised codec for a shuffle's fetches, or None.  Falls
        back to the session-sticky advice (a later exchange of a
        wire-bound session starts compressed from its first fetch)."""
        with self._lock:
            return self._overrides.get(shuffle_id) or self._sticky

    def shuffle_released(self, shuffle_id: int) -> None:
        with self._lock:
            self._overrides.pop(shuffle_id, None)

    def reader_policy(self):
        """The reader-side CompressionPolicy that rides advised fetches
        as the client's `compression_override` — built once, framed with
        the session's shuffle chunking parameters."""
        with self._lock:
            if self._reader_policy is None:
                from .. import config as C
                from ..compress.framed import CompressionPolicy
                self._reader_policy = CompressionPolicy(
                    self.candidate,
                    int(self._conf.get(C.SHUFFLE_COMPRESSION_CHUNK_SIZE)),
                    int(self._conf.get(C.SHUFFLE_COMPRESSION_MIN_SIZE)),
                    metrics=self.metrics)
            return self._reader_policy
