"""MovementPolicy: per-runtime next-use victim scoring + proactive unspill.

The engine rides the BufferCatalog like integrity/compression/ledger do
(`catalog.policy`, installed by TpuRuntime), so the stores' spill path
can consult it without plumbing.  Its knowledge base is cheap runtime
state the shuffle layer already produces:

  * `note_shuffle_buffer` — every device-resident shuffle partition
    write names its (shuffle, reduce partition) block;
  * `begin_shuffle_read` — the exchange read phase declares the reduce
    partition order it is about to consume (the AQE-planned specs);
  * `partition_consumed` — each partition handed to the consumer
    advances the read cursor and marks the partition's buffers DEAD;
  * the memory ledger's spill counts — the re-touch history.

Early release: a single-consumer local exchange read also declares how
many times the planned specs will consume each reduce partition (skew
slices and coalesced specs may read one partition more than once).
When the FINAL planned consumption of a partition lands, its map-side
buffers have next-use = never — the engine frees them outright
(`runtime.free_batch`), returning the bytes to the pool with no spill
write.  This is the decision that kills churn at the source: the
baseline keeps consumed partitions resident until the whole shuffle is
released, so under pressure it re-spills bytes that will never be read
again — and every such eviction of a previously-spilled partition
counts a re-spill.  Never applied with a cluster attached (a peer or a
speculative re-read may still fetch the block).

Victim scoring (`scores_for`, consumed by BufferStore._pick_victim):
lower score spills first.  Dead shuffle buffers score 0 (their bytes
will never be read again), unknown buffers score a neutral 1.0 (so with
no shuffle knowledge the ordering degrades to the exact deterministic
baseline, (spill_priority, id)), and buffers ahead of the read cursor
score 1 + 1/(1+distance): an imminent read approaches 2.0 (maximally
protected), a far-future one decays toward the neutral 1.0 — lookahead
knowledge must never protect a cold shuffle partition over the ACTIVE
working set it would displace.
Buffers the ledger has seen spill before gain a protection bonus
(retouchWeight per prior spill, capped), which is what kills churn: a
buffer that already paid a spill+unspill round trip becomes the LAST
candidate to evict again.

Proactive unspill: a lazy-started daemon thread (one per runtime,
holding only a weakref — a collected runtime ends it) wakes every
unspill.intervalMs, and while device headroom stays above
headroomFraction of the pool AND the pool has been spill-quiescent
since the previous tick (no OOM-spill counter movement — a contended
pool means the prefetch would race the query for the very bytes it
frees), re-materializes the one spilled buffer with the nearest next
use.  The unspill runs inside the owning query's
ledger scope with the serving-tier budget, so its reservation is
charged to (and budget-bounded by) the owner — it can never cause
another query's OOM; any RetryOOM is caught and the prefetch simply
skipped.  A prefetched buffer later read from device counts a hit
(numPrefetchHits); one evicted or released untouched counts wasted
(numPrefetchWasted).

Every decision journals under kind `policy` (victim/unspill/
backpressure/codec) — the stream `python -m spark_rapids_tpu.metrics
--memory` replays.
"""
from __future__ import annotations

import threading
import weakref
from typing import Dict, List, Optional, Set, Tuple

from ..metrics import names as MN
from ..metrics.journal import journal_event
from ..metrics.registry import count_swallowed

_RETOUCH_CAP = 4  # protection saturates: 4 round trips = maximally sticky


class MovementPolicy:
    """Per-runtime data-movement decision engine (see module doc)."""

    def __init__(self, conf, runtime=None):
        from .. import config as C
        self.conf = conf
        self.enabled = bool(conf.get(C.POLICY_ENABLED))
        self.early_release = bool(conf.get(C.POLICY_EARLY_RELEASE))
        self.retouch_weight = float(conf.get(C.POLICY_RETOUCH_WEIGHT))
        self.unspill_interval_s = \
            max(0, int(conf.get(C.POLICY_UNSPILL_INTERVAL))) / 1000.0
        self.unspill_headroom = float(conf.get(C.POLICY_UNSPILL_HEADROOM))
        self._serve_budget = int(conf.get(C.SERVE_QUERY_BUDGET))
        self._flow_min_window = int(conf.get(C.POLICY_FLOW_MIN_WINDOW))
        self._flow_horizon_s = \
            max(0, int(conf.get(C.POLICY_FLOW_HORIZON))) / 1000.0
        self._flow_max_stall_s = \
            max(0, int(conf.get(C.POLICY_FLOW_MAX_STALL))) / 1000.0
        self._rt = (weakref.ref(runtime) if runtime is not None
                    else (lambda: None))
        self.metrics = getattr(runtime, "metrics", None)
        from .codec import CodecAdvisor
        self.codec = CodecAdvisor(conf, metrics=self.metrics)
        self._lock = threading.Lock()
        # bid -> (shuffle_id, reduce_id) for device-resident shuffle writes
        self._buffer_block: Dict[int, Tuple[int, int]] = {}
        self._by_shuffle: Dict[int, Set[int]] = {}
        # shuffle_id -> {reduce_id: position} of the declared read order
        self._read_order: Dict[int, Dict[int, int]] = {}
        self._read_cursor: Dict[int, int] = {}
        self._consumed: Dict[int, Set[int]] = {}
        # sid -> {rid: planned consumptions left} — present only for
        # exclusive (single-consumer local) reads; drives early release
        self._remaining: Dict[int, Dict[int, int]] = {}
        # bid -> touched-since-proactive-unspill (False = pending hit)
        self._prefetched: Dict[int, bool] = {}
        self._buffer_bytes: Dict[int, int] = {}
        self._flow = None
        self._thread: Optional[threading.Thread] = None
        self._wake = threading.Event()
        self._closed = False
        # spill-activity signature at the last tick (quiescence gate)
        self._spill_sig = None

    # ---- shuffle-lifecycle feeds (shuffle/manager.py + exec/exchange.py) ----

    def note_shuffle_buffer(self, buffer_id: int, shuffle_id: int,
                            reduce_id: int, nbytes: int = 0) -> None:
        if not self.enabled:
            return
        with self._lock:
            self._buffer_block[buffer_id] = (shuffle_id, reduce_id)
            self._by_shuffle.setdefault(shuffle_id, set()).add(buffer_id)
            if nbytes:
                self._buffer_bytes[buffer_id] = int(nbytes)

    def begin_shuffle_read(self, shuffle_id: int, order: List[int],
                           counts: Optional[Dict[int, int]] = None,
                           exclusive: bool = False) -> None:
        """The exchange read phase declares the reduce-partition order
        it will consume — the plan-lookahead half of the score.  With
        `exclusive` (single local consumer, no cluster), `counts` gives
        how many times the planned specs consume each partition; the
        final consumption triggers early release (module doc)."""
        if not self.enabled:
            return
        with self._lock:
            self._read_order[shuffle_id] = \
                {rid: i for i, rid in enumerate(order)}
            self._read_cursor[shuffle_id] = 0
            self._consumed.setdefault(shuffle_id, set())
            if exclusive and counts and self.early_release:
                self._remaining[shuffle_id] = \
                    {int(r): int(c) for r, c in counts.items()}
        self._maybe_start()
        self._wake.set()

    def partition_consumed(self, shuffle_id: int, reduce_id: int) -> None:
        if not self.enabled:
            return
        to_free: List[Tuple[int, int]] = []
        with self._lock:
            self._consumed.setdefault(shuffle_id, set()).add(reduce_id)
            order = self._read_order.get(shuffle_id)
            if order is not None:
                pos = order.get(reduce_id)
                if pos is not None and \
                        pos >= self._read_cursor.get(shuffle_id, 0):
                    self._read_cursor[shuffle_id] = pos + 1
            rem = self._remaining.get(shuffle_id)
            if rem is not None and reduce_id in rem:
                rem[reduce_id] -= 1
                if rem[reduce_id] <= 0:
                    del rem[reduce_id]
                    live = self._by_shuffle.get(shuffle_id, set())
                    for bid in [b for b in live
                                if self._buffer_block.get(b)
                                == (shuffle_id, reduce_id)]:
                        to_free.append(
                            (bid, self._buffer_bytes.get(bid, 0)))
                        live.discard(bid)
                        self._buffer_block.pop(bid, None)
                        self._buffer_bytes.pop(bid, None)
                        self._prefetched.pop(bid, None)
        if not to_free:
            return
        # frees run OUTSIDE the policy lock (free_batch takes catalog +
        # store locks; policy._lock stays a strict leaf).  free_batch is
        # double-free tolerant, so the shuffle's own remove_shuffle
        # cleanup later is a no-op for these ids.
        rt = self._rt()
        freed = 0
        for bid, nbytes in to_free:
            if rt is not None:
                try:
                    rt.free_batch(bid)
                    freed += 1
                except Exception as e:  # noqa: BLE001 — a failed free
                    # must not kill the read; remove_shuffle retries it
                    count_swallowed("numPolicyTickErrors", __name__,
                                    "early release of %d failed (%r)",
                                    bid, e)
            journal_event("policy", "release", buffer=bid,
                          bytes=int(nbytes), shuffle=shuffle_id,
                          partition=reduce_id)
        if freed and self.metrics is not None:
            self.metrics.add(MN.NUM_POLICY_EARLY_RELEASES, freed)

    def shuffle_released(self, shuffle_id: int) -> None:
        if not self.enabled:
            return
        wasted = 0
        with self._lock:
            for bid in self._by_shuffle.pop(shuffle_id, ()):
                self._buffer_block.pop(bid, None)
                self._buffer_bytes.pop(bid, None)
                if self._prefetched.pop(bid, None) is False:
                    wasted += 1
            self._read_order.pop(shuffle_id, None)
            self._read_cursor.pop(shuffle_id, None)
            self._consumed.pop(shuffle_id, None)
            self._remaining.pop(shuffle_id, None)
        if wasted and self.metrics is not None:
            self.metrics.add(MN.NUM_PREFETCH_WASTED, wasted)
        self.codec.shuffle_released(shuffle_id)

    def note_access(self, buffer_id: int) -> None:
        """A buffer read through the runtime: a pending prefetch that
        gets read before eviction is a hit."""
        if not self.enabled or not self._prefetched:
            return
        hit = False
        with self._lock:
            if self._prefetched.get(buffer_id) is False:
                self._prefetched[buffer_id] = True
                hit = True
        if hit and self.metrics is not None:
            self.metrics.add(MN.NUM_PREFETCH_HITS, 1)

    # ---- victim scoring (mem/stores.py _pick_victim) ------------------------

    def wants_victim_scoring(self) -> bool:
        return self.enabled

    def scores_for(self, buffer_ids) -> Dict[int, float]:
        """Next-use scores, lower spills first (see module doc).  Called
        under the store lock: this takes only the ledger lock then the
        policy lock — both leaves of the store's lock order."""
        rt = self._rt()
        counts: Dict[int, int] = {}
        ledger = getattr(rt, "ledger", None) if rt is not None else None
        if ledger is not None:
            counts = ledger.spill_counts_for(buffer_ids)
        out: Dict[int, float] = {}
        with self._lock:
            for bid in buffer_ids:
                score = 1.0
                info = self._buffer_block.get(bid)
                if info is not None:
                    sid, rid = info
                    order = self._read_order.get(sid)
                    if rid in self._consumed.get(sid, ()):
                        score = 0.0  # dead: never read again, evict first
                    elif order is not None and rid in order:
                        d = max(0, order[rid]
                                - self._read_cursor.get(sid, 0))
                        score = 1.0 + 1.0 / (1.0 + d)
                if score > 0.0:
                    score += min(counts.get(bid, 0), _RETOUCH_CAP) \
                        * self.retouch_weight
                out[bid] = score
        return out

    def record_victim(self, tier, decision: dict) -> None:
        """Journal + count one victim decision (flushed by
        synchronous_spill OUTSIDE the store lock)."""
        bid = decision.get("buffer")
        wasted = False
        with self._lock:
            if self._prefetched.pop(bid, None) is False:
                wasted = True  # prefetched, evicted before any read
        if self.metrics is not None:
            self.metrics.add(MN.NUM_POLICY_VICTIM_PICKS, 1)
            if decision.get("overridden"):
                self.metrics.add(MN.NUM_POLICY_VICTIM_OVERRIDES, 1)
            if wasted:
                self.metrics.add(MN.NUM_PREFETCH_WASTED, 1)
        journal_event("policy", "victim", tier=tier.name, **decision)

    # ---- proactive unspill --------------------------------------------------

    def _maybe_start(self) -> None:
        if not self.enabled or self.unspill_interval_s <= 0 \
                or self._closed:
            return
        if self._thread is not None and self._thread.is_alive():
            return
        with self._lock:
            if self._thread is not None and self._thread.is_alive():
                return
            # the loop body installs the owner's ledger query_scope
            # around every unspill (the thread-context discipline TPU009
            # audits for)
            t = threading.Thread(target=self._run,
                                 name="movement-policy", daemon=True)
            self._thread = t
        t.start()

    def _run(self) -> None:
        while not self._closed:
            self._wake.wait(timeout=self.unspill_interval_s)
            self._wake.clear()
            if self._closed:
                return
            rt = self._rt()
            if rt is None:
                return  # runtime collected: this engine is dead too
            try:
                self.tick(rt)
            except Exception as e:  # noqa: BLE001 — a policy tick must
                # never take the engine down; the miss is counted
                count_swallowed("numPolicyTickErrors", __name__,
                                "proactive-unspill tick failed (%r)", e)
            del rt

    def tick(self, runtime=None) -> int:
        """One proactive-unspill pass (synchronous — tests drive this
        directly; the policy thread calls it on its interval).  Returns
        the number of buffers unspilled (at most one: the prefetch must
        trickle into headroom, never burst into it)."""
        rt = runtime if runtime is not None else self._rt()
        if rt is None or not self.enabled:
            return 0
        if not self._pool_quiescent(rt):
            return 0
        # an actively-streaming reduce pipeline owns prefetch: the async
        # fetch path is already materializing upcoming partitions, and a
        # concurrent thread unspill would race it for the same pool
        # bytes (measured as prefetch-then-respill churn).  The rate
        # span decays ~1s after the last consumption, re-arming the
        # thread for idle pools.
        flow = self._flow
        if flow is not None and flow.rate_bytes_per_s() > 0:
            return 0
        cand = self._next_unspill_candidate(rt)
        if cand is None or not self._unspill_one(rt, *cand):
            return 0
        return 1

    def _pool_quiescent(self, rt) -> bool:
        """True when no spill-pressure counter moved since the last
        tick.  A contended pool means any prefetch would race the query
        for the bytes it is actively evicting — the measured condition
        that turns proactive unspill into churn."""
        try:
            vals = rt.metrics.values
            sig = (vals.get(MN.OOM_SPILL_RETRIES, 0),
                   vals.get(MN.OOM_SPILL_BYTES, 0),
                   vals.get(MN.SPILL_TIME, 0.0))
        except Exception:  # noqa: BLE001 — no metrics: assume quiet
            return True
        quiet = self._spill_sig is None or sig == self._spill_sig
        self._spill_sig = sig  # tpulint: disable=TPU009 single-owner: only the policy thread (or a test driving tick() with the thread disabled) ever reads or writes the signature
        return quiet

    def _next_unspill_candidate(self, rt):
        """(buffer_id, size) of the spilled buffer with the nearest next
        use that fits in present headroom, or None.  Headroom is
        conservative: after the unspill, at least headroomFraction of
        the pool must remain free — the prefetch is opportunistic and
        must never push the pool toward an eviction."""
        headroom = rt.pool_limit - rt.device_store.current_size
        floor = int(rt.pool_limit * self.unspill_headroom)
        best = None
        with self._lock:
            items = list(self._buffer_block.items())
            cursors = dict(self._read_cursor)
            orders = self._read_order
            consumed = self._consumed
            for bid, (sid, rid) in items:
                order = orders.get(sid)
                if order is None or rid not in order:
                    continue
                if rid in consumed.get(sid, ()):
                    continue
                pos = order[rid]
                cur = cursors.get(sid, 0)
                if pos < cur:
                    continue
                nbytes = self._buffer_bytes.get(bid, 0)
                if nbytes <= 0 or headroom - nbytes < floor:
                    continue
                key = (pos - cur, bid)
                if best is None or key < best[0]:
                    best = (key, bid, nbytes)
        if best is None:
            return None
        _, bid, nbytes = best
        # only spilled buffers are worth a pass; a device-resident one
        # is already where it needs to be
        try:
            from ..mem.buffer import StorageTier
            if rt.catalog.lookup_tier(bid) == StorageTier.DEVICE:
                return None if len(self._buffer_block) <= 1 \
                    else self._next_other_candidate(rt, skip=bid)
        except KeyError:
            return None
        return bid, nbytes

    def _next_other_candidate(self, rt, skip: int):
        """Fallback scan when the nearest-next-use buffer is already on
        device: the first spilled, still-unconsumed, in-order buffer."""
        from ..mem.buffer import StorageTier
        headroom = rt.pool_limit - rt.device_store.current_size
        floor = int(rt.pool_limit * self.unspill_headroom)
        with self._lock:
            cands = []
            for bid, (sid, rid) in self._buffer_block.items():
                if bid == skip:
                    continue
                order = self._read_order.get(sid)
                if order is None or rid not in order \
                        or rid in self._consumed.get(sid, ()):
                    continue
                cur = self._read_cursor.get(sid, 0)
                if order[rid] < cur:
                    continue
                nbytes = self._buffer_bytes.get(bid, 0)
                if nbytes <= 0 or headroom - nbytes < floor:
                    continue
                cands.append((order[rid] - cur, bid, nbytes))
        for _, bid, nbytes in sorted(cands):
            try:
                if rt.catalog.lookup_tier(bid) != StorageTier.DEVICE:
                    return bid, nbytes
            except KeyError:  # tpulint: disable=TPU006 buffer freed between snapshot and lookup (early release / shuffle teardown race is benign: the candidate is simply gone)
                continue
        return None

    def _unspill_one(self, rt, bid: int, nbytes: int) -> bool:
        """Re-materialize one spilled buffer inside its owner's ledger
        scope (reservation charged to, and budget-bounded by, the
        owner); an OOM or a vanished buffer skips quietly."""
        owner = None
        try:
            buf = rt.catalog.acquire(bid)
        except KeyError:
            return False
        try:
            owner = buf.owner
            if owner is not None:
                with rt.ledger.query_scope(owner, self._serve_budget):
                    rt._materialize(buf)
            else:
                rt._materialize(buf)
        except MemoryError:
            return False
        finally:
            rt.catalog.release(buf)
        with self._lock:
            if bid in self._buffer_block and bid not in self._prefetched:
                self._prefetched[bid] = False
        if self.metrics is not None:
            self.metrics.add(MN.NUM_PROACTIVE_UNSPILLS, 1)
        journal_event("policy", "unspill", buffer=bid, bytes=int(nbytes),
                      owner=owner)
        return True

    # ---- flow control / codec handles ---------------------------------------

    def flow_controller(self):
        """The runtime's shared FlowController (lazy; None when the
        engine is disabled)."""
        if not self.enabled:
            return None
        if self._flow is None:
            from .flow import FlowController
            rt_ref = self._rt

            def headroom() -> int:
                rt = rt_ref()
                if rt is None:
                    return 1 << 62  # runtime collected: no clamp
                return rt.pool_limit - rt.device_store.current_size
            with self._lock:
                if self._flow is None:
                    self._flow = FlowController(
                        self._flow_min_window, self._flow_horizon_s,
                        self._flow_max_stall_s, metrics=self.metrics,
                        headroom=headroom)
        return self._flow

    def wire_codec(self, shuffle_id: int):
        if not self.enabled:
            return None
        return self.codec.wire_codec(shuffle_id)

    def observe_exchange(self, shuffle_id: int, wire_bytes: int,
                         seconds: float) -> None:
        if self.enabled:
            self.codec.observe_exchange(shuffle_id, wire_bytes, seconds)

    # ---- observability ------------------------------------------------------

    def gauges(self) -> Dict[str, float]:
        """Sampler-source snapshot (GaugeSampler 'policy' series)."""
        with self._lock:
            pending = sum(1 for v in self._prefetched.values()
                          if v is False)
            tracked = len(self._buffer_block)
        flow = self._flow
        return {
            "policy_tracked_buffers": float(tracked),
            "policy_prefetch_pending": float(pending),
            "policy_flow_window_bytes":
                float(flow.window_bytes()) if flow is not None else 0.0,
        }

    def close(self) -> None:
        self._closed = True
        self._wake.set()
