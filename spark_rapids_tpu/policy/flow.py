"""Reduce-driven flow control: windowed in-flight-bytes budgets.

One FlowController per runtime (shared by the shuffle env's fetch and
serve sides).  The reduce side reports every consumed batch via
`on_consumed`; the controller derives a consumption rate over a short
sliding span and turns it into an admission window

    window_bytes = max(minWindowBytes, rate * horizon)

so a producer may hold at most ~horizon's worth of un-consumed bytes in
flight.  Two admission points ride the window:

  * `AsyncFetchIterator._admit` (shuffle/fetch.py) caps its in-flight
    bytes at min(maxReceiveInflightBytes, fetch_window_bytes) — a
    stalled consumer shrinks the window to the floor and the producer
    waits (resumable: admission re-checks on every consumption notify,
    and the oversized-batch-alone rule is preserved, so a stalled
    reducer is back-pressured, never deadlocked).  The fetch window is
    additionally POOL-AWARE when a headroom provider is attached: it
    never exceeds current device headroom, so under memory pressure
    readahead collapses toward one-partition-at-a-time — each fetched
    partition is consumed (and early-released) before the next one
    materializes, instead of fetched-ahead partitions evicting each
    other (measured as respill churn);
  * `ShuffleServer._leaves` (map-side serve staging) takes a BOUNDED
    `serve_acquire` before staging bytes for a peer: when in-flight
    served bytes exceed the window the serve stalls up to
    maxServeStallMs and then proceeds anyway — soft backpressure, by
    construction deadlock-free.  `done_serving` (the reader's release,
    i.e. reduce-side consumption evidence crossing the wire) releases
    the bytes and feeds the rate.

Stalls are counted (numBackpressureStalls) and journaled (kind `policy`,
name `backpressure`) so BENCH_WIRE / the memory CLI can attribute them.
"""
from __future__ import annotations

import threading
import time
from collections import deque
from typing import Dict

from ..metrics import names as MN
from ..metrics.journal import journal_event


class FlowController:
    """Consumption-rate-windowed in-flight-bytes budget (see module doc)."""

    def __init__(self, min_window_bytes: int, horizon_s: float,
                 max_stall_s: float, metrics=None, headroom=None):
        self.min_window = max(1, int(min_window_bytes))
        self.horizon_s = max(0.0, float(horizon_s))
        self.max_stall_s = max(0.0, float(max_stall_s))
        self.metrics = metrics
        # optional device-headroom provider (callable -> free pool
        # bytes); clamps the FETCH window only — the serve side stages
        # host bytes and is not bounded by device headroom
        self._headroom = headroom
        self._cv = threading.Condition()
        # (monotonic, nbytes) consumption events inside the rate span
        self._events: deque = deque()
        self._serve_inflight = 0
        self._serve_sizes: Dict[int, int] = {}

    # ---- reduce-side signal -------------------------------------------------

    def on_consumed(self, nbytes: int) -> None:
        """One consumed batch: feeds the rate and wakes stalled admits."""
        now = time.monotonic()
        with self._cv:
            self._events.append((now, int(nbytes)))
            self._trim_locked(now)
            self._cv.notify_all()

    def _trim_locked(self, now: float) -> None:
        span = max(1.0, 5.0 * self.horizon_s)
        while self._events and now - self._events[0][0] > span:
            self._events.popleft()

    def rate_bytes_per_s(self) -> float:
        now = time.monotonic()
        with self._cv:
            self._trim_locked(now)
            if not self._events:
                return 0.0
            total = sum(nb for _, nb in self._events)
            return total / max(now - self._events[0][0], 1e-3)

    def window_bytes(self) -> int:
        return max(self.min_window,
                   int(self.rate_bytes_per_s() * self.horizon_s))

    def fetch_window_bytes(self) -> int:
        """The reduce-side fetch admission window: the rate window,
        clamped to present device headroom when a provider is attached
        (never below 1 — the oversized-batch-alone rule in _admit keeps
        a zero-headroom pool progressing serially)."""
        window = self.window_bytes()
        if self._headroom is None:
            return window
        try:
            free = int(self._headroom())
        except Exception:  # noqa: BLE001 — a dead provider never stalls
            return window
        return max(1, min(window, free))

    # ---- map-side serve window ----------------------------------------------

    def serve_acquire(self, buffer_id: int, nbytes: int) -> bool:
        """Admit `nbytes` of serve staging; bounded wait when in-flight
        served bytes exceed the window (proceeds after maxServeStallMs —
        soft backpressure, never a deadlock).  Returns whether it
        stalled."""
        deadline = time.monotonic() + self.max_stall_s
        stalled = False
        with self._cv:
            while self._serve_inflight > 0 and \
                    self._serve_inflight + nbytes > self.window_bytes():
                left = deadline - time.monotonic()
                if left <= 0:
                    break
                stalled = True
                self._cv.wait(timeout=min(left, 0.05))
            self._serve_inflight += int(nbytes)
            self._serve_sizes[buffer_id] = \
                self._serve_sizes.get(buffer_id, 0) + int(nbytes)
        if stalled:
            self.note_stall("serve")
        return stalled

    def serve_release(self, buffer_id: int) -> int:
        """Release a served buffer's staged bytes (the reader's
        done_serving ack); returns the bytes released (0 when the id was
        never acquired — every cache-removal path calls this, balanced
        by the per-id size ledger)."""
        with self._cv:
            nb = self._serve_sizes.pop(buffer_id, 0)
            if nb:
                self._serve_inflight = max(0, self._serve_inflight - nb)
                self._cv.notify_all()
        return nb

    def serve_inflight_bytes(self) -> int:
        with self._cv:
            return self._serve_inflight

    # ---- observability ------------------------------------------------------

    def note_stall(self, where: str) -> None:
        if self.metrics is not None:
            self.metrics.add(MN.NUM_BACKPRESSURE_STALLS, 1)
        journal_event("policy", "backpressure", where=where,
                      window=self.window_bytes())
