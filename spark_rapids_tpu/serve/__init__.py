"""Serving tier: concurrent query scheduling over one TpuRuntime.

ROADMAP item 2 ("Accelerating Presto with GPUs" is the production
exemplar): a session-multiplexing `QueryScheduler` with priority queues
and fair-share admission control layered on the device semaphore,
per-query memory budgets feeding the existing reserve()/RetryOOM spill
machinery, and a parameterized plan cache that lifts literals out of
physical plans so the 2nd..Nth literal-variant submission replays the
1st submission's traced+compiled whole-stage executables instead of
paying warmup again (BENCH_HEADLINE: q1 spends 27.9s compiling vs 1.3s
executing — the cache is what makes a second user cheap).

Entry point: `TpuSession.submit(df, priority=..., memory_need=...,
deadline_ms=...)` returns a `QueryFuture`; the blocking `collect()`
paths are untouched.  Query lifecycle robustness lives in lifecycle.py:
`QueryFuture.cancel()` (cooperative cancellation with owner-confined
cleanup), per-query deadlines with admission-time shedding, and
SLO-aware preemption that suspends a lower-priority query at a stage
boundary and resumes it bit-for-bit.
"""
from .lifecycle import (QueryCancelled, QueryDeadlineExceeded,
                        QueryLifecycle, QueryTimeout)
from .plan_cache import PlanCache, extract_parameters, plan_cache_key
from .scheduler import AdmissionRejected, QueryFuture, QueryScheduler

__all__ = ["PlanCache", "extract_parameters", "plan_cache_key",
           "AdmissionRejected", "QueryFuture", "QueryScheduler",
           "QueryCancelled", "QueryDeadlineExceeded", "QueryLifecycle",
           "QueryTimeout"]
