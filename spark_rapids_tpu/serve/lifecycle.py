"""Query lifecycle: cooperative cancellation, deadlines, preemption.

The serving half of ROADMAP item 4's robustness story: every admitted
query carries a `QueryLifecycle` token threaded through its memory-ledger
`QueryScope`, and the execution tiers consult it at their natural yield
points — `reserve()` (every whole-batch device allocation), the
`with_retry` attempt loop, the whole-stage per-batch dispatch loop and
the exchange write/read loops.  Three mechanisms ride the one token:

  * **Cancellation** — `QueryFuture.cancel()` (or scheduler shutdown)
    stamps a reason; the next checkpoint raises `QueryCancelled` into
    the query's OWN failure path.  A queued query dequeues for free.
    The engine then runs owner-confined cleanup (PR 10's `owner`
    stamps): the cancelled query's device/host/disk buffers and shuffle
    outputs are freed, so a cancel can never leak pool bytes
    (numCancelledQueries; journal kind `lifecycle`).
  * **Deadlines** — `submit(..., deadline_ms=)` sets an absolute
    monotonic deadline enforced at the same checkpoints
    (`QueryDeadlineExceeded`, typed, never a neighbor's failure path).
    Queue-side shedding is the scheduler's: a query whose remaining
    deadline cannot cover the estimated plan+compile cost is rejected
    at admission (numDeadlineSheds) instead of admitted doomed.
  * **Preemption** — the scheduler requests preemption of a
    lower-priority running query when a higher-priority one needs the
    pool/device gate; the victim suspends at its next STAGE boundary
    (suspension is only permitted where `checkpoint(allow_suspend=True)`
    says so — never inside a reserve()): its device-resident buffers
    are parked as spillable state charged to its own budget, the device
    semaphore slots and the admission share are released, and the thread
    blocks until the scheduler grants a FIFO-within-priority resume.
    Execution then continues in place, so the result is bit-for-bit
    identical to the unpreempted run (numPreemptions,
    numPreemptionResumes, SLO phase `preempt`).

Exception typing is load-bearing: neither `QueryCancelled` nor
`QueryDeadlineExceeded` subclasses MemoryError, so the retry ladder
(`with_retry` catches `MemoryError` only) can never swallow or
retry-loop a lifecycle signal — it propagates straight to the worker's
failure path.  `QueryTimeout` subclasses TimeoutError so callers that
caught the old bare `TimeoutError("query still running")` keep working.

Kill switch: spark.rapids.sql.tpu.serve.lifecycle.enabled=false makes
the scheduler install no token at all — every checkpoint then reads one
`None` attribute and does nothing, byte-identical to the pre-lifecycle
paths.
"""
from __future__ import annotations

import threading
import time
from typing import Optional


class QueryCancelled(RuntimeError):
    """The query was cancelled (QueryFuture.cancel() or scheduler
    shutdown) and stopped at its next lifecycle checkpoint.  NOT a
    MemoryError: the retry ladder must never retry a cancellation."""


class QueryDeadlineExceeded(RuntimeError):
    """The query ran past its submit(..., deadline_ms=) deadline (or was
    shed at admission because the remaining deadline could not cover the
    estimated plan+compile cost).  Raised into the query's OWN failure
    path at a lifecycle checkpoint — never a neighbor's."""


class QueryTimeout(TimeoutError):
    """QueryFuture.result()/exception() gave up waiting (the caller's
    `timeout=` elapsed).  The QUERY keeps running — this types the
    caller-side wait, unlike QueryCancelled/QueryDeadlineExceeded which
    terminate the query itself.  Subclasses TimeoutError for
    compatibility with callers of the old untyped wait."""


#: lifecycle checkpoints that may SUSPEND (preemption) — stage/batch
#: boundaries where no reservation is mid-flight; reserve()-level
#: checkpoints pass allow_suspend=False and only observe cancel/deadline
STAGE_BOUNDARY = True


class QueryLifecycle:
    """Per-query cancellation/deadline/preemption token (one per
    scheduler submission; installed on the query's ledger QueryScope by
    engine._collect_physical so every tier reaches it thread-locally)."""

    __slots__ = ("label", "priority", "deadline_at", "deadline_s",
                 "journal", "metrics", "resume_timeout_s",
                 "_cancel_reason", "_preempt_req", "_resume_evt",
                 "_sched", "_item", "suspended", "preemptions",
                 "preempt_seconds")

    def __init__(self, label: Optional[str] = None, priority: int = 0,
                 deadline_ms: Optional[float] = None):
        self.label = label
        self.priority = int(priority)
        self.deadline_s = (None if deadline_ms is None
                           else max(0.0, float(deadline_ms) / 1e3))
        self.deadline_at = (None if self.deadline_s is None
                            else time.monotonic() + self.deadline_s)
        self.journal = None        # query's EventJournal (engine installs)
        self.metrics = None        # runtime Metrics (scheduler installs)
        self.resume_timeout_s = 600.0
        self._cancel_reason: Optional[str] = None
        self._preempt_req = threading.Event()
        self._resume_evt = threading.Event()
        self._sched = None         # QueryScheduler (preemption hooks)
        self._item = None          # scheduler _Item (admission share)
        self.suspended = False
        self.preemptions = 0
        self.preempt_seconds = 0.0

    # -- cancellation / deadline --------------------------------------------

    def cancel(self, reason: str = "cancelled by caller") -> None:
        """Stamp the cancel reason; the query observes it at its next
        checkpoint (idempotent — the first reason wins)."""
        if self._cancel_reason is None:
            self._cancel_reason = str(reason)

    @property
    def cancel_requested(self) -> bool:
        return self._cancel_reason is not None

    def remaining_s(self) -> Optional[float]:
        """Seconds until the deadline (None = no deadline)."""
        if self.deadline_at is None:
            return None
        return self.deadline_at - time.monotonic()

    def check(self) -> None:
        """Raise the pending lifecycle signal, if any.  Two attribute
        reads on the fast path — cheap enough for reserve()."""
        if self._cancel_reason is not None:
            raise QueryCancelled(
                f"query {self.label or '?'} cancelled: "
                f"{self._cancel_reason}")
        if self.deadline_at is not None \
                and time.monotonic() > self.deadline_at:
            raise QueryDeadlineExceeded(
                f"query {self.label or '?'} exceeded its "
                f"{self.deadline_s:.3f}s deadline")

    # -- preemption ----------------------------------------------------------

    def request_preempt(self) -> None:
        """Scheduler-side: ask this query to suspend at its next stage
        boundary (idempotent; a no-op once the query finished)."""
        self._preempt_req.set()

    def checkpoint(self, runtime=None, allow_suspend: bool = False) -> None:
        """The ONE lifecycle yield point: raise a pending cancel/deadline
        signal, and — at stage boundaries only — honor a pending
        preemption request by suspending in place."""
        self.check()
        if allow_suspend and self._preempt_req.is_set():
            self._suspend(runtime)

    def _suspend(self, runtime) -> None:
        """Park this query: spill its own device buffers (charged to its
        budget), give back its device-semaphore slots and its admission
        share, then block until the scheduler grants a
        FIFO-within-priority resume.  Cancels/deadlines are still
        observed while suspended (a parked query must stay killable),
        and a resume-timeout forces progress so a scheduler bug can
        never hang the victim forever."""
        sched, item = self._sched, self._item
        self._preempt_req.clear()
        if sched is None or item is None:
            return  # not a scheduler-run query: preemption cannot apply
        self._resume_evt.clear()
        t0 = time.perf_counter()
        parked = 0
        sem_depth = 0
        if runtime is not None:
            owner = runtime.ledger.current_query()
            if owner:
                # park in-flight state: everything this query has
                # registered on-device becomes spillable checkpoints in
                # the lower tiers (still owner-charged, so its budget —
                # not its neighbors' — carries the parked bytes)
                parked = runtime.device_store.synchronous_spill(
                    0, owner=owner)
            sem_depth = runtime.semaphore.park()
        from ..metrics.journal import journal_event
        journal_event("lifecycle", "preemptSuspend",
                      q=self.label, priority=self.priority,
                      parked_bytes=parked, sem_depth=sem_depth)
        self.suspended = True
        sched._on_suspend(item)
        try:
            forced = False
            give_up_at = time.monotonic() + max(1.0, self.resume_timeout_s)
            while not self._resume_evt.wait(0.02):
                try:
                    self.check()  # suspended queries stay killable
                except BaseException:
                    sched._abort_suspended(item)
                    raise
                if time.monotonic() >= give_up_at:
                    sched._force_resume(item)
                    forced = True
                    break
        finally:
            self.suspended = False
        if runtime is not None and sem_depth:
            runtime.semaphore.unpark(sem_depth, metrics=self.metrics)
        dt = time.perf_counter() - t0
        self.preemptions += 1
        self.preempt_seconds += dt
        sched._on_resumed(item, dt)
        journal_event("lifecycle", "preemptResume", q=self.label,
                      priority=self.priority, seconds=round(dt, 6),
                      forced=forced)


def scope_checkpoint(ledger, runtime=None,
                     allow_suspend: bool = False) -> None:
    """Consult the calling thread's query scope for a lifecycle token
    and run its checkpoint.  The no-token path (blocking collect(),
    kill switch off, worker task threads) is two attribute reads."""
    scope = ledger.current_query_scope()
    if scope is None:
        return
    tok = scope.lifecycle
    if tok is not None:
        tok.checkpoint(runtime=runtime, allow_suspend=allow_suspend)


def ctx_checkpoint(ctx, allow_suspend: bool = False) -> None:
    """Exec-layer convenience: lifecycle checkpoint through an
    ExecContext (no-op without a runtime, e.g. bare CPU contexts)."""
    rt = getattr(ctx, "runtime", None)
    if rt is None:
        return
    scope_checkpoint(rt.ledger, runtime=rt, allow_suspend=allow_suspend)
