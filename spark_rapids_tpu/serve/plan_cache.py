"""Parameterized plan cache: normalize literals out of a query plan.

The warmup killer (ROADMAP item 2): BENCH_HEADLINE shows q1 spending
27.9s compiling vs 1.3s executing, and a second user running the SAME
query shape with different literals (a different date cutoff, a
different discount band) pays the whole warmup again, because literal
values are baked into every kernel-cache key (utils/kernel_cache.expr_key
keys Literal by repr(value)).

This module fixes the second user:

  * `extract_parameters(plan)` rewrites a LOGICAL plan, lifting eligible
    literals in row-local positions (Project/Filter/Expand expressions
    under value-safe operators — comparisons, arithmetic, boolean logic,
    CaseWhen/Coalesce/Least/Greatest, or a bare projected literal) into
    `ColumnExpr("param", (slot, dtype, value))` placeholders.  The
    current value rides INLINE, so scan pushdown still prunes row groups
    against concrete bounds and CPU twins evaluate the right constant —
    but the kernel layer resolves the placeholder to an
    `ops.expressions.Parameter`, whose value enters compiled programs as
    a RUNTIME argument on every parameter-threaded dispatch path
    (RowLocalExec, TpuWholeStageExec, the aggregate whole-stage
    absorption, the exchange bucketing fusion).  Result: a literal
    variant of a seen plan produces byte-identical stage keys and
    replays the cached traced+compiled executables — trace AND compile
    are skipped (`kernel_cache.stage_executable` hits).

  * `plan_cache_key(normalized, conf)` fingerprints the normalized tree
    (parameter slots + dtypes, never values) together with the input
    schemas/sources and the session conf, so a hit means "same plan
    shape, same inputs, same planning-relevant configuration".

  * `PlanCache` is the bookkeeping layer the QueryScheduler consults:
    LRU-bounded entries, hit/miss/lifted counters (surfaced as
    planCacheHits/planCacheMisses metrics and in BENCH_SERVE.json).
    Execution ALWAYS uses the incoming normalized plan — never a cached
    object — so a fingerprint collision can only miscount, never
    mis-execute, and concurrent submissions share no mutable plan state.

What invalidates a cached plan (docs/tuning-guide.md): any conf change,
a different input table/file set, a schema change, a literal whose
inferred dtype changes (5 vs 2**40), string/null literals, and literals
outside the value-safe positions (aggregate arguments, join conditions,
sort keys, limits) — those stay part of the key.
"""
from __future__ import annotations

import copy
import threading
from collections import OrderedDict
from typing import List, Tuple

import numpy as np

from ..plan.logical import (ColumnExpr, LogicalAggregate, LogicalExpand,
                            LogicalFilter, LogicalJoin, LogicalPlan,
                            LogicalProject, LogicalSort, SortOrder)

# ColumnExpr ops under which a literal child evaluates as a genuine
# columnar value (broadcast scalar flowing through jnp ops) — safe to
# feed from a traced runtime argument.  Ops that consume literals as
# STATIC kernel configuration (Substring lengths, Like patterns, Round
# decimals, In lists, Cast targets) are deliberately absent: their
# literals stay baked and key the cache.
_LIFT_UNDER = frozenset({
    "EqualTo", "LessThan", "GreaterThan", "LessThanOrEqual",
    "GreaterThanOrEqual", "EqualNullSafe",
    "Add", "Subtract", "Multiply", "Divide", "IntegralDivide",
    "Remainder", "Pmod",
    "And", "Or", "Not", "Coalesce", "Least", "Greatest",
    "UnaryMinus", "Abs", "CaseWhen", "NaNvl",
    "__root__",  # a bare projected literal / filter condition
})


def _eligible(v) -> bool:
    """Numeric/bool literal values only: strings have host-side eval
    paths (Column.from_strings) and Nones change null semantics — both
    stay baked Literals and key the plan."""
    return isinstance(v, (bool, int, float, np.integer, np.floating))


def _rewrite_expr(ce, parent_op: str, values: List):
    if isinstance(ce, SortOrder) or not isinstance(ce, ColumnExpr):
        return ce
    if ce.op == "lit":
        v = ce.args[0]
        if parent_op in _LIFT_UNDER and _eligible(v):
            from ..ops.expressions import _infer_literal_type
            slot = len(values)
            values.append(v)
            return ColumnExpr("param", (slot, _infer_literal_type(v), v),
                              alias=ce._alias)
        return ce
    if ce.op == "WindowExpr":
        # window specs carry frame/ordering objects the rewrite has no
        # business descending into; window kernels are not
        # parameter-threaded anyway
        return ce
    new_args, changed = [], False
    for a in ce.args:
        na = _rewrite_arg(a, ce.op, values)
        changed = changed or na is not a
        new_args.append(na)
    if not changed:
        return ce
    return ColumnExpr(ce.op, tuple(new_args), alias=ce._alias)


def _rewrite_arg(a, op: str, values: List):
    if isinstance(a, ColumnExpr):
        return _rewrite_expr(a, op, values)
    if isinstance(a, (list, tuple)):
        out = [_rewrite_arg(x, op, values) for x in a]
        if all(n is o for n, o in zip(out, a)):
            return a
        return type(a)(out)
    return a


def _copy_node(node: LogicalPlan, children, **attrs) -> LogicalPlan:
    """Shallow-copy with new children/attrs (never mutates the input —
    DataFrames share logical nodes, same contract as pushdown._rebuild)."""
    new = copy.copy(node)
    new.children = tuple(children)
    for k, v in attrs.items():
        setattr(new, k, v)
    new.__dict__.pop("_cached_schema", None)
    return new


def extract_parameters(plan: LogicalPlan) -> Tuple[LogicalPlan, List]:
    """(normalized plan, lifted values).  Slots number the lifted
    literals in tree order, so two structurally equal queries assign
    identical slots to corresponding literals.

    Two classes of position:

      * Project/Filter/Expand expressions lift under `"__root__"` — a
        bare projected literal qualifies, and these are the
        parameter-THREADED dispatch paths, so the lifted value enters
        the compiled program as a runtime argument (no recompile).
      * Aggregate, sort and join expressions lift only literals NESTED
        under value-safe operators (`"__guard__"` parent: `sum(price *
        (1 - discount))`'s constants qualify, `count(lit(1))`'s bare
        literal does not — bare literal agg children have count-star
        special-casing in analysis).  These kernels are not
        parameter-threaded: the Parameter evaluates as its baked value
        and keys kernel caches VALUE-INCLUSIVELY (always correct, one
        recompile per distinct value) — but the PLAN key is value-free,
        so literal variants still hit the plan cache and reuse every
        threaded stage around the aggregate."""
    values: List = []

    def guard_list(exprs):
        return [_rewrite_expr(e, "__guard__", values) for e in exprs]

    def walk(node: LogicalPlan) -> LogicalPlan:
        children = [walk(c) for c in node.children]
        kids_changed = any(n is not o
                           for n, o in zip(children, node.children))
        if isinstance(node, LogicalProject):
            exprs = [_rewrite_expr(e, "__root__", values)
                     for e in node.exprs]
            if kids_changed or any(n is not o
                                   for n, o in zip(exprs, node.exprs)):
                return _copy_node(node, children, exprs=exprs)
            return node
        if isinstance(node, LogicalFilter):
            cond = _rewrite_expr(node.condition, "__root__", values)
            if kids_changed or cond is not node.condition:
                return _copy_node(node, children, condition=cond)
            return node
        if isinstance(node, LogicalExpand):
            projections = [[_rewrite_expr(e, "__root__", values)
                            for e in proj] for proj in node.projections]
            changed = any(n is not o
                          for np_, op_ in zip(projections,
                                              node.projections)
                          for n, o in zip(np_, op_))
            if kids_changed or changed:
                return _copy_node(node, children, projections=projections)
            return node
        if isinstance(node, LogicalAggregate):
            grouping = guard_list(node.grouping)
            aggregates = guard_list(node.aggregates)
            changed = any(n is not o for n, o in
                          zip(grouping + aggregates,
                              list(node.grouping) + list(node.aggregates)))
            if kids_changed or changed:
                return _copy_node(node, children, grouping=grouping,
                                  aggregates=aggregates)
            return node
        if isinstance(node, LogicalSort):
            orders = [SortOrder(_rewrite_expr(o.child, "__guard__",
                                              values),
                                o.ascending, o.nulls_first)
                      if isinstance(o, SortOrder) else o
                      for o in node.orders]
            changed = any(isinstance(o, SortOrder)
                          and n.child is not o.child
                          for n, o in zip(orders, node.orders))
            if kids_changed or changed:
                return _copy_node(node, children, orders=orders)
            return node
        if isinstance(node, LogicalJoin) \
                and getattr(node, "condition", None) is not None:
            cond = _rewrite_expr(node.condition, "__guard__", values)
            if kids_changed or cond is not node.condition:
                return _copy_node(node, children, condition=cond)
            return node
        if kids_changed:
            return _copy_node(node, children)
        return node

    return walk(plan), values


# --------------------------------------------------------------------------
# fingerprinting
# --------------------------------------------------------------------------

def _val_fp(v, seen: set):
    if isinstance(v, ColumnExpr):
        if v.op == "param":
            slot, dtype, _value = v.args  # value-free: that is the point
            return ("param", slot, dtype.name, v._alias)
        return ("CE", v.op, v._alias,
                tuple(_val_fp(a, seen) for a in v.args))
    if isinstance(v, SortOrder):
        return ("SO", _val_fp(v.child, seen), v.ascending, v.nulls_first)
    if v is None or isinstance(v, (str, int, float, bool, bytes)):
        return v
    if isinstance(v, (list, tuple)):
        return ("seq", tuple(_val_fp(x, seen) for x in v))
    if isinstance(v, dict):
        return ("map", tuple(sorted((str(k), _val_fp(x, seen))
                                    for k, x in v.items())))
    if isinstance(v, (set, frozenset)):
        return ("set", tuple(sorted(map(repr, v))))
    from ..types import DataType, Schema
    if isinstance(v, DataType):
        return ("dt", v.name)
    if isinstance(v, Schema):
        return ("schema", tuple((f.name, f.dtype.name) for f in v))
    if type(v).__name__ == "Table" and hasattr(v, "column_names"):
        # pyarrow tables are immutable: identity implies content.  The
        # cache holds NO reference to the table (128 retained input
        # tables would be an unbounded-bytes leak in a long-lived
        # server), so a recycled id could in principle alias — shape and
        # schema ride along to make that a counters-only curiosity, and
        # execution always uses the submitted plan, never a cached one.
        return ("table", id(v), v.num_rows,
                tuple(str(t) for t in v.schema.types))
    if id(v) in seen:
        return ("cycle",)
    d = getattr(v, "__dict__", None)
    if d is not None:
        seen = seen | {id(v)}
        return ("obj", type(v).__name__,
                tuple(sorted((k, _val_fp(x, seen)) for k, x in d.items())))
    # last resort: type-only.  A collision here can only miscount a hit
    # (execution always uses the incoming plan), never mis-execute.
    return ("opaque", type(v).__name__)


def _plan_fp(node: LogicalPlan, seen: set) -> tuple:
    attrs = []
    skip = ("children", "_cached_schema")
    if getattr(node, "source_identity", None) is not None:
        # Streaming scans (streaming/source.py) stamp a stable
        # source_identity on the scan node: the source PAYLOAD changes
        # every epoch (an appended table object, a longer file list, a
        # bigger num_rows) while the plan is the same dashboard query —
        # baking the table fingerprint (id/rows) into the key would miss
        # the cache on every epoch and re-compile the stages incremental
        # execution exists to replay.  The identity string (which IS one
        # of the fingerprinted attrs below) plus the scan schema keys the
        # plan instead; offsets/row counts stay out of the key.
        skip = skip + ("source",)
    for k, v in sorted(vars(node).items()):
        if k in skip:
            continue
        attrs.append((k, _val_fp(v, seen)))
    return (type(node).__name__, tuple(attrs),
            tuple(_plan_fp(c, seen) for c in node.children))


def conf_fingerprint(conf) -> tuple:
    """Every explicitly-set key participates: a conf change (a new codec,
    a different batch size, a toggled rule) invalidates cached plans."""
    return tuple(sorted((k, str(v)) for k, v in conf._settings.items()))


def plan_cache_key(normalized: LogicalPlan, conf) -> tuple:
    return (_plan_fp(normalized, set()), conf_fingerprint(conf))


# --------------------------------------------------------------------------
# the cache
# --------------------------------------------------------------------------

class CachedPlan:
    """Bookkeeping only — deliberately NO reference to the plan or its
    input tables (execution always uses the submitted normalized tree,
    and pinning up to maxEntries input tables would leak unbounded
    bytes in a long-lived server)."""

    __slots__ = ("key", "n_params", "param_dtypes", "hits")

    def __init__(self, key, values):
        from ..ops.expressions import _infer_literal_type
        self.key = key
        self.n_params = len(values)
        self.param_dtypes = tuple(_infer_literal_type(v).name
                                  for v in values)
        self.hits = 0


class PlanCache:
    """LRU-bounded normalized-plan registry (one per QueryScheduler)."""

    def __init__(self, max_entries: int = 128):
        self.max_entries = max(1, int(max_entries))
        self._entries: "OrderedDict[tuple, CachedPlan]" = OrderedDict()
        self._lock = threading.Lock()
        self.hits = 0
        self.misses = 0
        self.lifted = 0

    def lookup(self, logical: LogicalPlan, conf
               ) -> Tuple[LogicalPlan, List, bool]:
        """Normalize `logical` and account the hit/miss.  Returns
        (normalized plan WITH this submission's values inline, values,
        hit).  The caller plans and executes the returned tree; the
        cached entry is pure bookkeeping."""
        normalized, values = extract_parameters(logical)
        key = plan_cache_key(normalized, conf)
        with self._lock:
            entry = self._entries.get(key)
            if entry is not None:
                self._entries.move_to_end(key)
                entry.hits += 1
                self.hits += 1
                hit = True
            else:
                self._entries[key] = CachedPlan(key, values)
                while len(self._entries) > self.max_entries:
                    self._entries.popitem(last=False)
                self.misses += 1
                hit = False
            self.lifted += len(values)
        return normalized, values, hit

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def stats(self) -> dict:
        with self._lock:
            return {"entries": len(self._entries), "hits": self.hits,
                    "misses": self.misses, "params_lifted": self.lifted,
                    "max_entries": self.max_entries}

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()


