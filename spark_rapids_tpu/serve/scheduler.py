"""Concurrent query scheduler + admission control over one TpuRuntime.

The serving half of ROADMAP item 2.  One `QueryScheduler` per TpuSession
multiplexes submitted queries over the session's single runtime:

  * **Priority queue** — `submit(df, priority=N)` enqueues; higher
    priority dispatches first, FIFO within a priority (Presto-style
    queue discipline).
  * **Admission control** — every query declares (or gets an estimated)
    memory need; the scheduler keeps the sum of in-flight needs under
    `admission.memoryFraction x` the accounted HBM pool, so a burst of
    heavy queries queues instead of shredding the spill tier.  A full
    queue rejects (`AdmissionRejected`, counted in
    numAdmissionRejections) — backpressure, not unbounded buffering.
    The device itself stays guarded one level down by the existing
    `TpuSemaphore` (spark.rapids.sql.concurrentTpuTasks): admission
    bounds MEMORY commitment, the semaphore bounds simultaneous device
    occupancy.
  * **Per-query budgets** — `serve.queryBudgetBytes` installs a
    `MemoryLedger` query scope around each execution; `reserve()`
    enforces the budget by spilling the query's OWN buffers first and
    raising RetryOOM into the query's own retry ladder, so one hog
    spills itself, not its neighbors (mem/runtime.py).
  * **Plan cache** — submissions run through `PlanCache.lookup`, so a
    literal variant of a seen query replays cached compiled stages
    (plan_cache.py) and the persistent XLA compile cache
    (utils/compile_cache.py) covers process restarts.

Metrics (lint-checked catalog): queueTime, numAdmitted,
numQueuedQueries, numAdmissionRejections, planCacheHits/Misses,
numBudgetOoms — all on the runtime Metrics, so pool_stats()/prometheus
and session_observability pick them up.  Each query's journal carries a
kind-`sched` "admitted" instant (queue time, priority, need, cache
state) under its own trace context.
"""
from __future__ import annotations

import heapq
import threading
import time
from typing import List, Optional

from .. import config as C
from ..metrics import names as MN
from .lifecycle import (QueryCancelled, QueryDeadlineExceeded,
                        QueryLifecycle, QueryTimeout)
from .plan_cache import PlanCache


class AdmissionRejected(RuntimeError):
    """The scheduler's queue is full; resubmit later (HTTP-429 moral)."""


class QueryFuture:
    """Handle for one submitted query (concurrent.futures shape, plus
    scheduling observability: queue/plan timings, plan-cache state)."""

    def __init__(self, priority: int, need_bytes: int):
        self.priority = priority
        self.need_bytes = need_bytes
        self.submitted_ns = time.monotonic_ns()
        self.admitted_ns: Optional[int] = None
        self.finished_ns: Optional[int] = None
        self.queue_seconds: Optional[float] = None
        self.plan_seconds: Optional[float] = None
        # per-phase breakdown of the execution (engine._collect_physical
        # fills these; the scheduler feeds them into the SLO histograms):
        # whole-stage trace+compile, synchronous-spill cascades, and the
        # physical execution wall clock
        self.compile_seconds: Optional[float] = None
        self.spill_seconds: Optional[float] = None
        self.exec_seconds: Optional[float] = None
        self.plan_cache: Optional[str] = None  # "hit" | "miss" | "off"
        self.n_params = 0
        self.query_id: Optional[int] = None
        self._event = threading.Event()
        self._table = None
        self._error: Optional[BaseException] = None
        self.cancelled = False
        # serve.lifecycle.QueryLifecycle token (None with the
        # serve.lifecycle.enabled kill switch off): cancel()/deadline/
        # preemption all route through it
        self.lifecycle: Optional[QueryLifecycle] = None
        self.deadline_ms: Optional[float] = None
        self._scheduler = None  # owning QueryScheduler (cancel routing)

    # -- completion (scheduler side) ----------------------------------------

    def _set_result(self, table) -> None:
        if self._event.is_set():
            return  # first resolution wins (cancel/complete races)
        self._table = table
        self.finished_ns = time.monotonic_ns()
        self._event.set()

    def _set_error(self, error: BaseException) -> None:
        if self._event.is_set():
            return  # first resolution wins (cancel/complete races)
        self._error = error
        self.finished_ns = time.monotonic_ns()
        self._event.set()

    # -- consumer side -------------------------------------------------------

    def done(self) -> bool:
        return self._event.is_set()

    def cancel(self, reason: str = "cancelled by caller") -> bool:
        """Request cooperative cancellation.  A still-QUEUED query is
        dequeued and resolved immediately (it never cost a worker); a
        RUNNING one stops at its next lifecycle checkpoint (reserve/
        retry/stage/exchange boundary) with QueryCancelled as its own
        error, followed by owner-confined cleanup of its buffers and
        shuffle outputs.  Returns True when the cancel was requested;
        False when the query already finished or the
        serve.lifecycle.enabled kill switch is off.  Cooperative: a
        query that completes before observing the request still delivers
        its result."""
        if self._event.is_set():
            return False
        tok = self.lifecycle
        sched = self._scheduler
        if tok is None or sched is None:
            return False
        return sched._cancel(self, reason)

    def result(self, timeout: Optional[float] = None):
        """The query's pyarrow Table (raises the query's error).  A
        timed-out WAIT raises QueryTimeout (a TimeoutError subclass) —
        the query itself keeps running; use cancel() to stop it."""
        if not self._event.wait(timeout):
            raise QueryTimeout(
                f"query still running after {timeout}s wait; the query "
                "was not stopped — cancel() it or wait again")
        if self._error is not None:
            raise self._error
        return self._table

    def collect(self, timeout: Optional[float] = None) -> list:
        """Row-tuple view of result(), like DataFrame.collect()."""
        table = self.result(timeout)
        return [tuple(r.values()) for r in table.to_pylist()]

    def exception(self, timeout: Optional[float] = None
                  ) -> Optional[BaseException]:
        """The query's error, or None on success.  Like result(), a
        timed-out wait raises QueryTimeout — timing out is a property of
        the WAIT, not a resolution of the query."""
        if not self._event.wait(timeout):
            raise QueryTimeout(
                f"query still running after {timeout}s wait; the query "
                "was not stopped — cancel() it or wait again")
        return self._error

    @property
    def latency_seconds(self) -> Optional[float]:
        if self.finished_ns is None:
            return None
        return (self.finished_ns - self.submitted_ns) / 1e9


class _Item:
    __slots__ = ("logical", "priority", "need", "future", "skips", "seq",
                 "need_released")

    def __init__(self, logical, priority: int, need: int,
                 future: QueryFuture, seq: int = 0):
        self.logical = logical
        self.priority = priority
        self.need = need
        self.future = future
        self.skips = 0  # admission bypass count (starvation bound)
        self.seq = seq  # submission order (FIFO-within-priority resume)
        # True while this item holds NO admission share: before
        # admission, after completion, and while preemption-suspended.
        # The worker's finally and the suspend path both settle the
        # in-flight need through this flag so it can never double-count.
        self.need_released = True


# a queued query smaller items have leapfrogged this many times becomes a
# BARRIER: nothing behind it is admitted until it fits.  Bounds starvation
# of big-memory-need queries under a sustained stream of small ones.
_MAX_ADMISSION_SKIPS = 64


class QueryScheduler:
    """Session-multiplexing scheduler (one per TpuSession; built lazily
    by TpuSession.submit)."""

    def __init__(self, session):
        self.session = session
        conf = session.conf
        # resolve the lazy singletons BEFORE worker threads exist: their
        # double-checked inits are not guarded against concurrent first
        # touch from N query threads
        self.runtime = session.runtime
        session.cluster
        self.max_concurrent = max(1, int(conf.get(C.SERVE_MAX_CONCURRENT)))
        self.queue_capacity = max(1, int(conf.get(C.SERVE_QUEUE_CAPACITY)))
        self.default_need = int(conf.get(C.SERVE_DEFAULT_NEED))
        self.query_budget = int(conf.get(C.SERVE_QUERY_BUDGET))
        from ..mem.runtime import configured_pool_bytes
        frac = float(conf.get(C.SERVE_ADMISSION_FRACTION))
        self.admission_budget = max(1, int(configured_pool_bytes(conf)
                                           * frac))
        self.plan_cache: Optional[PlanCache] = None
        if bool(conf.get(C.SERVE_PLAN_CACHE_ENABLED)):
            self.plan_cache = PlanCache(
                int(conf.get(C.SERVE_PLAN_CACHE_SIZE)))
        # serving path owns the persistent XLA compile-cache wiring: a
        # restarted server replays kernels from disk (platform-gated
        # helper; active_cache_dir() reports what actually took effect)
        from ..utils.compile_cache import (active_cache_dir,
                                           enable_compilation_cache)
        enable_compilation_cache(str(conf.get(C.COMPILATION_CACHE_DIR)))
        self.compile_cache_dir = active_cache_dir()
        self._metrics = self.runtime.metrics
        # query lifecycle layer (serve/lifecycle.py): the kill switch
        # gates token creation itself — off means no token anywhere, so
        # every checkpoint is a no-op byte-identical to pre-lifecycle
        self.lifecycle_enabled = bool(conf.get(C.SERVE_LIFECYCLE_ENABLED))
        self.preemption_enabled = self.lifecycle_enabled and \
            bool(conf.get(C.SERVE_PREEMPTION_ENABLED))
        self.resume_timeout = float(
            conf.get(C.SERVE_PREEMPTION_RESUME_TIMEOUT))
        self.shed_factor = float(conf.get(C.SERVE_DEADLINE_SHED_FACTOR))
        self._lock = threading.Condition()
        self._queue: List[tuple] = []  # heap of (-priority, seq, _Item)
        self._seq = 0
        self._inflight_need = 0
        self._running = 0
        self._shutdown = False
        self.admitted = 0
        self.rejected = 0
        self.completed = 0
        self.failed = 0
        self.cancelled_queries = 0
        self.deadline_sheds = 0
        self.deadline_exceeded = 0
        self.preemptions = 0
        self.preemption_resumes = 0
        # preemption-suspended victims: heap of (-priority, seq, _Item),
        # resumed FIFO-within-priority by _grant_resumes_locked; _active
        # maps seq -> _Item for every query currently inside _run_one
        # (suspended or not) — the victim pool preemption picks from
        self._suspended: List[tuple] = []
        self._active: dict = {}
        # EWMA of observed plan+compile seconds — the admission-time
        # shedding estimate (a query whose remaining deadline can't
        # cover it is rejected instead of admitted doomed)
        self._plan_compile_ewma = 0.0
        # fair-share observability (guarded by self._lock): per-priority
        # admission/rejection counters behind cluster_snapshot /
        # prometheus_serve_dump — the PR-10 fairness behavior, observable
        self.admitted_by_priority: dict = {}
        self.rejected_by_priority: dict = {}
        # per-(phase, priority) latency histograms (metrics/slo.py):
        # queue/plan/compile/execute/spill/total, p50/p95/p99 each
        from ..metrics.slo import SloTracker
        self.slo = SloTracker()
        # planning mutates no shared state by design, but logical nodes
        # are shared between submissions of one DataFrame — serialize the
        # (cheap, host-side) planning step rather than audit every pass
        self._plan_lock = threading.Lock()
        self._workers = [
            threading.Thread(target=self._worker_loop, daemon=True,
                             name=f"tpu-serve-{i}")
            for i in range(self.max_concurrent)]
        for w in self._workers:
            w.start()

    # -- submission ----------------------------------------------------------

    def _estimate_need(self, logical) -> int:
        try:
            from ..plan.physical import _estimate_plan_bytes
            est = _estimate_plan_bytes(logical, self.session.conf)
        except Exception:  # noqa: BLE001 — estimation is best-effort
            est = None
        if est is None or est <= 0:
            return self.default_need
        return int(est)

    def submit(self, logical, priority: int = 0,
               memory_need: Optional[int] = None,
               deadline_ms: Optional[float] = None) -> QueryFuture:
        """Enqueue a logical plan (or DataFrame via TpuSession.submit).
        Raises AdmissionRejected when the queue is at capacity.  With
        `deadline_ms` set the query carries a wall-clock budget from
        SUBMISSION: it is shed at admission when the remaining budget
        cannot cover the estimated plan+compile cost, and stopped at its
        next lifecycle checkpoint once the budget expires — either way
        QueryDeadlineExceeded lands in this query's own failure path."""
        if hasattr(logical, "plan") and hasattr(logical, "session"):
            logical = logical.plan  # a DataFrame
        need = int(memory_need) if memory_need else \
            self._estimate_need(logical)
        fut = QueryFuture(priority, need)
        with self._lock:
            if self._shutdown:
                raise RuntimeError("scheduler is shut down")
            if len(self._queue) >= self.queue_capacity:
                self.rejected += 1
                self.rejected_by_priority[int(priority)] = \
                    self.rejected_by_priority.get(int(priority), 0) + 1
                self._metrics.add(MN.NUM_ADMISSION_REJECTIONS, 1)
                raise AdmissionRejected(
                    f"queue full ({self.queue_capacity} queries pending); "
                    "resubmit later or raise "
                    f"{C.SERVE_QUEUE_CAPACITY.key}")
            self._seq += 1
            item = _Item(logical, int(priority), need, fut, seq=self._seq)
            if self.lifecycle_enabled:
                tok = QueryLifecycle(label=f"p{int(priority)}s{self._seq}",
                                     priority=int(priority),
                                     deadline_ms=deadline_ms)
                tok.metrics = self._metrics
                tok.resume_timeout_s = self.resume_timeout
                tok._sched = self
                tok._item = item
                fut.lifecycle = tok
                fut.deadline_ms = deadline_ms
                fut._scheduler = self
            heapq.heappush(self._queue, (-int(priority), self._seq, item))
            self._metrics.set_max(MN.NUM_QUEUED_QUERIES, len(self._queue))
            if self.preemption_enabled:
                # a higher-priority arrival may preempt a running
                # lower-priority victim at its next stage boundary
                self._maybe_preempt_locked(int(priority))
            self._lock.notify()
        return fut

    def _cancel(self, fut: QueryFuture, reason: str) -> bool:
        """QueryFuture.cancel() back end.  Marks the token, then — when
        the query is still QUEUED — dequeues and resolves it right here
        (it never cost a worker, so cancellation is free); a RUNNING
        query observes the token at its next checkpoint instead."""
        tok = fut.lifecycle
        tok.cancel(reason)
        removed = False
        with self._lock:
            for i, ent in enumerate(self._queue):
                if ent[2].future is fut:
                    del self._queue[i]
                    heapq.heapify(self._queue)
                    removed = True
                    break
            self._lock.notify_all()
        if removed:
            self._metrics.add(MN.NUM_CANCELLED_QUERIES, 1)
            with self._lock:
                self.cancelled_queries += 1
            fut.cancelled = True
            fut._set_error(QueryCancelled(
                f"query cancelled while queued: {reason}"))
        return True

    # -- dispatch ------------------------------------------------------------

    def _pop_admissible_locked(self) -> Optional[_Item]:
        """Highest-priority queued query whose declared need fits the
        admission budget given in-flight commitments.  With nothing in
        flight the head is admitted regardless (a query bigger than the
        budget must still make progress — the budget shapes concurrency,
        it is not a hard per-query cap; that is queryBudgetBytes).  An
        over-budget query smaller items have leapfrogged
        _MAX_ADMISSION_SKIPS times becomes a barrier: nothing behind it
        admits until in-flight work drains enough for it to fit, so a
        sustained stream of small queries cannot starve a big one."""
        if not self._queue:
            return None
        skipped = []
        picked = None
        # "nothing in flight" must look through preemption-suspended
        # victims: their worker threads still count in _running but they
        # hold no admission share, and an over-budget head must not
        # deadlock against a parked victim waiting for it to finish
        idle = self._running - len(self._suspended) <= 0
        while self._queue:
            ent = heapq.heappop(self._queue)
            item = ent[2]
            if idle or \
                    self._inflight_need + item.need <= self.admission_budget:
                picked = item
                break
            skipped.append(ent)
            if item.skips >= _MAX_ADMISSION_SKIPS:
                break  # barrier: admit nothing behind this query
            item.skips += 1
        for ent in skipped:
            heapq.heappush(self._queue, ent)
        return picked

    def _worker_loop(self) -> None:
        while True:
            with self._lock:
                item = None
                while not self._shutdown:
                    item = self._pop_admissible_locked()
                    if item is not None:
                        break
                    if self.preemption_enabled and self._queue:
                        # the head cannot be admitted: a waiting
                        # higher-priority query may still preempt a
                        # running lower-priority one to make room
                        self._maybe_preempt_locked()
                    self._lock.wait()
                if item is None:
                    return  # shutdown
                self._inflight_need += item.need
                item.need_released = False
                self._running += 1
                self._active[item.seq] = item
                if self.preemption_enabled:
                    self._maybe_preempt_locked(item.priority)
            try:
                self._run_one(item)
            finally:
                with self._lock:
                    self._active.pop(item.seq, None)
                    if not item.need_released:
                        self._inflight_need -= item.need
                        item.need_released = True
                    self._running -= 1
                    # a finished query frees admission budget: re-check
                    # suspended victims first, then every queued waiter
                    self._grant_resumes_locked()
                    self._lock.notify_all()

    def _run_one(self, item: _Item) -> None:
        fut = item.future
        tok = fut.lifecycle
        if tok is not None:
            # race backstop: a cancel that arrived between the queue
            # scan in _cancel and this worker's pop resolves here,
            # before the query costs any planning or device work
            if tok.cancel_requested:
                self._metrics.add(MN.NUM_CANCELLED_QUERIES, 1)
                with self._lock:
                    self.cancelled_queries += 1
                fut.cancelled = True
                fut._set_error(QueryCancelled(
                    f"query cancelled while queued: {tok._cancel_reason}"))
                return
            # deadline shedding: when the remaining budget cannot even
            # cover the estimated plan+compile cost, fail fast instead
            # of admitting a query that is already doomed — overload
            # sheds at the queue edge, not halfway through a compile
            rem = tok.remaining_s()
            if rem is not None:
                est = self._plan_compile_ewma * self.shed_factor \
                    if self.shed_factor > 0 else 0.0
                if rem <= 0 or rem < est:
                    self._metrics.add(MN.NUM_DEADLINE_SHEDS, 1)
                    with self._lock:
                        self.deadline_sheds += 1
                    if tok.journal is not None:
                        tok.journal.instant(
                            "lifecycle", "shed", q=tok.label,
                            remaining_s=round(max(rem, 0.0), 6),
                            estimate_s=round(est, 6))
                    fut._set_error(QueryDeadlineExceeded(
                        "shed at admission: remaining deadline "
                        f"{max(rem, 0.0):.3f}s cannot cover estimated "
                        f"plan+compile {est:.3f}s"))
                    return
        fut.admitted_ns = time.monotonic_ns()
        queue_s = (fut.admitted_ns - fut.submitted_ns) / 1e9
        fut.queue_seconds = queue_s
        self._metrics.add(MN.QUEUE_TIME, queue_s)
        self._metrics.add(MN.NUM_ADMITTED, 1)
        with self._lock:
            self.admitted += 1
            self.admitted_by_priority[item.priority] = \
                self.admitted_by_priority.get(item.priority, 0) + 1
        session = self.session
        try:
            logical = item.logical
            cache_state = "off"
            t0 = time.perf_counter()
            # normalization + fingerprinting + planning all under the
            # plan lock: logical nodes are SHARED between submissions of
            # one DataFrame, and planning lazily writes into their
            # __dict__ (plan_schema's _cached_schema) — fingerprinting
            # vars() concurrently would race that first-touch insert
            with self._plan_lock:
                if self.plan_cache is not None:
                    normalized, values, hit = self.plan_cache.lookup(
                        logical, session.conf)
                    self._metrics.add(
                        MN.PLAN_CACHE_HITS if hit else
                        MN.PLAN_CACHE_MISSES, 1)
                    logical = normalized
                    fut.n_params = len(values)
                    cache_state = "hit" if hit else "miss"
                fut.plan_cache = cache_state
                from ..plan.overrides import plan_schema
                out_schema = plan_schema(logical, session.conf)
                physical = session.plan(logical)
            fut.plan_seconds = time.perf_counter() - t0
            sched_attrs = {
                "queue_s": round(queue_s, 6),
                "plan_s": round(fut.plan_seconds, 6),
                "priority": item.priority,
                "need_bytes": item.need,
                "plan_cache": cache_state,
                "params": fut.n_params,
            }
            table = session._collect_physical(
                physical, out_schema, budget_bytes=self.query_budget,
                sched_attrs=sched_attrs, future=fut)
            fut._set_result(table)
            with self._lock:
                self.completed += 1
                # feed the deadline-shedding estimator: EWMA of observed
                # plan+compile seconds over successful queries
                dt = (fut.plan_seconds or 0.0) + (fut.compile_seconds
                                                  or 0.0)
                self._plan_compile_ewma = dt \
                    if self._plan_compile_ewma == 0.0 \
                    else 0.7 * self._plan_compile_ewma + 0.3 * dt
        except QueryCancelled as e:
            self._metrics.add(MN.NUM_CANCELLED_QUERIES, 1)
            fut.cancelled = True
            fut._set_error(e)
            with self._lock:
                self.cancelled_queries += 1
                self.failed += 1
        except QueryDeadlineExceeded as e:
            self._metrics.add(MN.NUM_DEADLINE_EXCEEDED, 1)
            fut._set_error(e)
            with self._lock:
                self.deadline_exceeded += 1
                self.failed += 1
        except BaseException as e:  # noqa: BLE001 — future carries it
            fut._set_error(e)
            with self._lock:
                self.failed += 1
        finally:
            # SLO histograms (metrics/slo.py): per-phase observations
            # for this query's priority class — success or failure, so
            # timeouts/errors still move the queue/total percentiles
            self.slo.observe_phases(
                item.priority,
                queue=queue_s,
                plan=fut.plan_seconds,
                compile=fut.compile_seconds,
                execute=fut.exec_seconds,
                spill=fut.spill_seconds,
                total=fut.latency_seconds)

    # -- preemption (serve/lifecycle.py drives the suspend side) -------------

    def _maybe_preempt_locked(self,
                              incoming_priority: Optional[int] = None
                              ) -> None:
        """Pick at most one running victim to suspend.  The bar is the
        highest priority that wants resources right now (the incoming
        submission and/or the queue head); the victim is the LOWEST-
        priority most-recently-admitted active query strictly below that
        bar.  The victim suspends cooperatively at its next stage
        boundary (exec/whole_stage.py, exec/exchange.py), releasing its
        semaphore depth and admission share until _grant_resumes_locked
        lets it back in."""
        if not self.preemption_enabled:
            return
        top = incoming_priority
        if self._queue:
            head_pri = -self._queue[0][0]
            top = head_pri if top is None else max(top, head_pri)
        if top is None:
            return
        victim = None
        victim_key = None
        for it in self._active.values():
            tok = it.future.lifecycle
            if tok is None or it.need_released or it.priority >= top:
                continue
            if tok.suspended or tok._preempt_req.is_set():
                continue
            key = (it.priority, -it.seq)
            if victim_key is None or key < victim_key:
                victim, victim_key = it, key
        if victim is not None:
            victim.future.lifecycle.request_preempt()

    def _on_suspend(self, item: _Item) -> None:
        """Called from the victim's own thread (lifecycle._suspend)
        AFTER it parked its buffers and semaphore depth: release its
        admission share and enqueue it for a FIFO-within-priority
        resume."""
        with self._lock:
            if not item.need_released:
                self._inflight_need -= item.need
                item.need_released = True
            heapq.heappush(self._suspended,
                           (-item.priority, item.seq, item))
            self.preemptions += 1
            self._metrics.add(MN.NUM_PREEMPTIONS, 1)
            # grant immediately when nothing actually outranks the
            # victim (the contender may have finished between the
            # preempt request and this suspend — without this, an
            # uncontested victim would park until the force-resume
            # timeout); then wake waiters: the freed share may admit
            # the query that triggered the preemption
            self._grant_resumes_locked()
            self._lock.notify_all()

    def _grant_resumes_locked(self) -> None:
        """Resume suspended victims — highest priority first, FIFO
        within a priority — whenever no strictly-higher-priority query
        is queued or active and the admission budget fits the victim
        again.  Caller holds self._lock."""
        while self._suspended:
            neg_pri, seq, item = self._suspended[0]
            # a queued query that outranks the victim gets the resources
            # first ((-priority, seq) ordering on both heaps) — but only
            # while a FREE worker exists to pop it: suspended victims
            # still occupy their worker threads, so when every worker is
            # parked the queued query cannot start no matter what, and
            # holding the victims for it would deadlock until the
            # force-resume timeout
            free_workers = self.max_concurrent - self._running
            if free_workers > 0 and self._queue \
                    and self._queue[0][:2] < (neg_pri, seq):
                return
            # an ACTIVE higher-priority query still runs: hold the
            # victim parked until it finishes
            if any(not it.need_released and it.priority > item.priority
                   for it in self._active.values()):
                return
            others = any(not it.need_released
                         for it in self._active.values())
            if others and self._inflight_need + item.need > \
                    self.admission_budget:
                return
            heapq.heappop(self._suspended)
            self._inflight_need += item.need
            item.need_released = False
            self.preemption_resumes += 1
            self._metrics.add(MN.NUM_PREEMPTION_RESUMES, 1)
            item.future.lifecycle._resume_evt.set()

    def _abort_suspended(self, item: _Item) -> None:
        """A suspended victim was cancelled / hit its deadline while
        parked: drop it from the resume queue (its need is already
        released; the worker finally settles the rest)."""
        with self._lock:
            self._suspended = [ent for ent in self._suspended
                               if ent[2] is not item]
            heapq.heapify(self._suspended)
            self._lock.notify_all()

    def _force_resume(self, item: _Item) -> None:
        """resumeTimeoutSeconds fired: resume the victim regardless of
        budget so a pathological priority stream cannot park a query
        forever (liveness beats fairness at this horizon)."""
        with self._lock:
            self._suspended = [ent for ent in self._suspended
                               if ent[2] is not item]
            heapq.heapify(self._suspended)
            if item.need_released:
                self._inflight_need += item.need
                item.need_released = False
            self.preemption_resumes += 1
            self._metrics.add(MN.NUM_PREEMPTION_RESUMES, 1)
            item.future.lifecycle._resume_evt.set()

    def _on_resumed(self, item: _Item, seconds: float) -> None:
        """Victim-side resume accounting: the suspend->resume latency is
        the cost half of the preemption SLO story."""
        self.slo.observe("preempt", item.priority, seconds)

    # -- lifecycle / observability -------------------------------------------

    def shutdown(self, wait: bool = True, timeout: float = 30.0) -> None:
        """Stop the workers.  Queued-but-never-admitted queries resolve
        with an error (a consumer blocked in result() must not hang
        forever on a future no worker will ever run); in-flight queries
        are cancel-signalled through their lifecycle tokens so they stop
        at the next checkpoint (reserve/retry/stage/exchange boundary)
        instead of running to completion — including victims parked in a
        preemption suspend, whose wait loop observes the token.  With
        the lifecycle kill switch off there are no tokens and in-flight
        queries finish normally, the pre-lifecycle behavior."""
        with self._lock:
            self._shutdown = True
            abandoned = [ent[2].future for ent in self._queue]
            self._queue.clear()
            running_toks = [it.future.lifecycle
                            for it in self._active.values()
                            if it.future.lifecycle is not None]
            self._lock.notify_all()
        for fut in abandoned:
            fut.cancelled = True
            fut._set_error(RuntimeError(
                "scheduler shut down before this query was admitted"))
        for tok in running_toks:
            tok.cancel("scheduler shutdown")
        if wait:
            deadline = time.monotonic() + timeout
            for w in self._workers:
                w.join(max(0.0, deadline - time.monotonic()))

    def fairness_snapshot(self) -> dict:
        """Per-priority-class fair-share observability: live queue depth
        plus cumulative admitted/rejected counters — the block
        cluster_snapshot/prometheus_serve_dump expose so the PR-10
        fair-share behavior is observable, not just implemented."""
        with self._lock:
            depth: dict = {}
            for ent in self._queue:
                p = ent[2].priority
                depth[p] = depth.get(p, 0) + 1
            return {
                "queue_depth_by_priority": dict(sorted(depth.items())),
                "admitted_by_priority":
                    dict(sorted(self.admitted_by_priority.items())),
                "rejected_by_priority":
                    dict(sorted(self.rejected_by_priority.items())),
                "running": self._running,
                "queued": sum(depth.values()),
            }

    def telemetry_gauges(self) -> dict:
        """The live gauge-sampler series this scheduler owns (the
        driver source metrics/ring.GaugeSampler snapshots; names from
        names.TELEMETRY_GAUGES): queries executing now and queries
        waiting in the priority queue."""
        fair = self.fairness_snapshot()
        return {"in_flight_tasks": float(fair["running"]),
                "queued_queries": float(fair["queued"])}

    def prometheus(self) -> str:
        """Serving-tier Prometheus exposition: fairness gauges + the
        per-phase SLO histograms (export.prometheus_serve_dump)."""
        from ..metrics.export import prometheus_serve_dump
        return prometheus_serve_dump(self)

    def stats(self) -> dict:
        with self._lock:
            out = {
                "max_concurrent": self.max_concurrent,
                "queued": len(self._queue),
                "running": self._running,
                "inflight_need_bytes": self._inflight_need,
                "admission_budget_bytes": self.admission_budget,
                "admitted": self.admitted,
                "rejected": self.rejected,
                "completed": self.completed,
                "failed": self.failed,
                "query_budget_bytes": self.query_budget,
                "compile_cache_dir": self.compile_cache_dir,
                "lifecycle": {
                    "enabled": self.lifecycle_enabled,
                    "preemption_enabled": self.preemption_enabled,
                    "cancelled": self.cancelled_queries,
                    "deadline_sheds": self.deadline_sheds,
                    "deadline_exceeded": self.deadline_exceeded,
                    "preemptions": self.preemptions,
                    "preemption_resumes": self.preemption_resumes,
                    "suspended": len(self._suspended),
                },
            }
        if self.plan_cache is not None:
            out["plan_cache"] = self.plan_cache.stats()
        out["fairness"] = self.fairness_snapshot()
        out["slo"] = self.slo.report()
        return out
