"""Concurrent query scheduler + admission control over one TpuRuntime.

The serving half of ROADMAP item 2.  One `QueryScheduler` per TpuSession
multiplexes submitted queries over the session's single runtime:

  * **Priority queue** — `submit(df, priority=N)` enqueues; higher
    priority dispatches first, FIFO within a priority (Presto-style
    queue discipline).
  * **Admission control** — every query declares (or gets an estimated)
    memory need; the scheduler keeps the sum of in-flight needs under
    `admission.memoryFraction x` the accounted HBM pool, so a burst of
    heavy queries queues instead of shredding the spill tier.  A full
    queue rejects (`AdmissionRejected`, counted in
    numAdmissionRejections) — backpressure, not unbounded buffering.
    The device itself stays guarded one level down by the existing
    `TpuSemaphore` (spark.rapids.sql.concurrentTpuTasks): admission
    bounds MEMORY commitment, the semaphore bounds simultaneous device
    occupancy.
  * **Per-query budgets** — `serve.queryBudgetBytes` installs a
    `MemoryLedger` query scope around each execution; `reserve()`
    enforces the budget by spilling the query's OWN buffers first and
    raising RetryOOM into the query's own retry ladder, so one hog
    spills itself, not its neighbors (mem/runtime.py).
  * **Plan cache** — submissions run through `PlanCache.lookup`, so a
    literal variant of a seen query replays cached compiled stages
    (plan_cache.py) and the persistent XLA compile cache
    (utils/compile_cache.py) covers process restarts.

Metrics (lint-checked catalog): queueTime, numAdmitted,
numQueuedQueries, numAdmissionRejections, planCacheHits/Misses,
numBudgetOoms — all on the runtime Metrics, so pool_stats()/prometheus
and session_observability pick them up.  Each query's journal carries a
kind-`sched` "admitted" instant (queue time, priority, need, cache
state) under its own trace context.
"""
from __future__ import annotations

import heapq
import threading
import time
from typing import List, Optional

from .. import config as C
from ..metrics import names as MN
from .plan_cache import PlanCache


class AdmissionRejected(RuntimeError):
    """The scheduler's queue is full; resubmit later (HTTP-429 moral)."""


class QueryFuture:
    """Handle for one submitted query (concurrent.futures shape, plus
    scheduling observability: queue/plan timings, plan-cache state)."""

    def __init__(self, priority: int, need_bytes: int):
        self.priority = priority
        self.need_bytes = need_bytes
        self.submitted_ns = time.monotonic_ns()
        self.admitted_ns: Optional[int] = None
        self.finished_ns: Optional[int] = None
        self.queue_seconds: Optional[float] = None
        self.plan_seconds: Optional[float] = None
        # per-phase breakdown of the execution (engine._collect_physical
        # fills these; the scheduler feeds them into the SLO histograms):
        # whole-stage trace+compile, synchronous-spill cascades, and the
        # physical execution wall clock
        self.compile_seconds: Optional[float] = None
        self.spill_seconds: Optional[float] = None
        self.exec_seconds: Optional[float] = None
        self.plan_cache: Optional[str] = None  # "hit" | "miss" | "off"
        self.n_params = 0
        self.query_id: Optional[int] = None
        self._event = threading.Event()
        self._table = None
        self._error: Optional[BaseException] = None
        self.cancelled = False

    # -- completion (scheduler side) ----------------------------------------

    def _set_result(self, table) -> None:
        self._table = table
        self.finished_ns = time.monotonic_ns()
        self._event.set()

    def _set_error(self, error: BaseException) -> None:
        self._error = error
        self.finished_ns = time.monotonic_ns()
        self._event.set()

    # -- consumer side -------------------------------------------------------

    def done(self) -> bool:
        return self._event.is_set()

    def result(self, timeout: Optional[float] = None):
        """The query's pyarrow Table (raises the query's error)."""
        if not self._event.wait(timeout):
            raise TimeoutError("query still running")
        if self._error is not None:
            raise self._error
        return self._table

    def collect(self, timeout: Optional[float] = None) -> list:
        """Row-tuple view of result(), like DataFrame.collect()."""
        table = self.result(timeout)
        return [tuple(r.values()) for r in table.to_pylist()]

    def exception(self, timeout: Optional[float] = None
                  ) -> Optional[BaseException]:
        if not self._event.wait(timeout):
            raise TimeoutError("query still running")
        return self._error

    @property
    def latency_seconds(self) -> Optional[float]:
        if self.finished_ns is None:
            return None
        return (self.finished_ns - self.submitted_ns) / 1e9


class _Item:
    __slots__ = ("logical", "priority", "need", "future", "skips")

    def __init__(self, logical, priority: int, need: int,
                 future: QueryFuture):
        self.logical = logical
        self.priority = priority
        self.need = need
        self.future = future
        self.skips = 0  # admission bypass count (starvation bound)


# a queued query smaller items have leapfrogged this many times becomes a
# BARRIER: nothing behind it is admitted until it fits.  Bounds starvation
# of big-memory-need queries under a sustained stream of small ones.
_MAX_ADMISSION_SKIPS = 64


class QueryScheduler:
    """Session-multiplexing scheduler (one per TpuSession; built lazily
    by TpuSession.submit)."""

    def __init__(self, session):
        self.session = session
        conf = session.conf
        # resolve the lazy singletons BEFORE worker threads exist: their
        # double-checked inits are not guarded against concurrent first
        # touch from N query threads
        self.runtime = session.runtime
        session.cluster
        self.max_concurrent = max(1, int(conf.get(C.SERVE_MAX_CONCURRENT)))
        self.queue_capacity = max(1, int(conf.get(C.SERVE_QUEUE_CAPACITY)))
        self.default_need = int(conf.get(C.SERVE_DEFAULT_NEED))
        self.query_budget = int(conf.get(C.SERVE_QUERY_BUDGET))
        from ..mem.runtime import configured_pool_bytes
        frac = float(conf.get(C.SERVE_ADMISSION_FRACTION))
        self.admission_budget = max(1, int(configured_pool_bytes(conf)
                                           * frac))
        self.plan_cache: Optional[PlanCache] = None
        if bool(conf.get(C.SERVE_PLAN_CACHE_ENABLED)):
            self.plan_cache = PlanCache(
                int(conf.get(C.SERVE_PLAN_CACHE_SIZE)))
        # serving path owns the persistent XLA compile-cache wiring: a
        # restarted server replays kernels from disk (platform-gated
        # helper; active_cache_dir() reports what actually took effect)
        from ..utils.compile_cache import (active_cache_dir,
                                           enable_compilation_cache)
        enable_compilation_cache(str(conf.get(C.COMPILATION_CACHE_DIR)))
        self.compile_cache_dir = active_cache_dir()
        self._metrics = self.runtime.metrics
        self._lock = threading.Condition()
        self._queue: List[tuple] = []  # heap of (-priority, seq, _Item)
        self._seq = 0
        self._inflight_need = 0
        self._running = 0
        self._shutdown = False
        self.admitted = 0
        self.rejected = 0
        self.completed = 0
        self.failed = 0
        # fair-share observability (guarded by self._lock): per-priority
        # admission/rejection counters behind cluster_snapshot /
        # prometheus_serve_dump — the PR-10 fairness behavior, observable
        self.admitted_by_priority: dict = {}
        self.rejected_by_priority: dict = {}
        # per-(phase, priority) latency histograms (metrics/slo.py):
        # queue/plan/compile/execute/spill/total, p50/p95/p99 each
        from ..metrics.slo import SloTracker
        self.slo = SloTracker()
        # planning mutates no shared state by design, but logical nodes
        # are shared between submissions of one DataFrame — serialize the
        # (cheap, host-side) planning step rather than audit every pass
        self._plan_lock = threading.Lock()
        self._workers = [
            threading.Thread(target=self._worker_loop, daemon=True,
                             name=f"tpu-serve-{i}")
            for i in range(self.max_concurrent)]
        for w in self._workers:
            w.start()

    # -- submission ----------------------------------------------------------

    def _estimate_need(self, logical) -> int:
        try:
            from ..plan.physical import _estimate_plan_bytes
            est = _estimate_plan_bytes(logical, self.session.conf)
        except Exception:  # noqa: BLE001 — estimation is best-effort
            est = None
        if est is None or est <= 0:
            return self.default_need
        return int(est)

    def submit(self, logical, priority: int = 0,
               memory_need: Optional[int] = None) -> QueryFuture:
        """Enqueue a logical plan (or DataFrame via TpuSession.submit).
        Raises AdmissionRejected when the queue is at capacity."""
        if hasattr(logical, "plan") and hasattr(logical, "session"):
            logical = logical.plan  # a DataFrame
        need = int(memory_need) if memory_need else \
            self._estimate_need(logical)
        fut = QueryFuture(priority, need)
        with self._lock:
            if self._shutdown:
                raise RuntimeError("scheduler is shut down")
            if len(self._queue) >= self.queue_capacity:
                self.rejected += 1
                self.rejected_by_priority[int(priority)] = \
                    self.rejected_by_priority.get(int(priority), 0) + 1
                self._metrics.add(MN.NUM_ADMISSION_REJECTIONS, 1)
                raise AdmissionRejected(
                    f"queue full ({self.queue_capacity} queries pending); "
                    "resubmit later or raise "
                    f"{C.SERVE_QUEUE_CAPACITY.key}")
            self._seq += 1
            heapq.heappush(self._queue,
                           (-int(priority), self._seq,
                            _Item(logical, int(priority), need, fut)))
            self._metrics.set_max(MN.NUM_QUEUED_QUERIES, len(self._queue))
            self._lock.notify()
        return fut

    # -- dispatch ------------------------------------------------------------

    def _pop_admissible_locked(self) -> Optional[_Item]:
        """Highest-priority queued query whose declared need fits the
        admission budget given in-flight commitments.  With nothing in
        flight the head is admitted regardless (a query bigger than the
        budget must still make progress — the budget shapes concurrency,
        it is not a hard per-query cap; that is queryBudgetBytes).  An
        over-budget query smaller items have leapfrogged
        _MAX_ADMISSION_SKIPS times becomes a barrier: nothing behind it
        admits until in-flight work drains enough for it to fit, so a
        sustained stream of small queries cannot starve a big one."""
        if not self._queue:
            return None
        skipped = []
        picked = None
        while self._queue:
            ent = heapq.heappop(self._queue)
            item = ent[2]
            if self._running == 0 or \
                    self._inflight_need + item.need <= self.admission_budget:
                picked = item
                break
            skipped.append(ent)
            if item.skips >= _MAX_ADMISSION_SKIPS:
                break  # barrier: admit nothing behind this query
            item.skips += 1
        for ent in skipped:
            heapq.heappush(self._queue, ent)
        return picked

    def _worker_loop(self) -> None:
        while True:
            with self._lock:
                item = None
                while not self._shutdown:
                    item = self._pop_admissible_locked()
                    if item is not None:
                        break
                    self._lock.wait()
                if item is None:
                    return  # shutdown
                self._inflight_need += item.need
                self._running += 1
            try:
                self._run_one(item)
            finally:
                with self._lock:
                    self._inflight_need -= item.need
                    self._running -= 1
                    # a finished query frees admission budget: re-check
                    # every waiter, not just one
                    self._lock.notify_all()

    def _run_one(self, item: _Item) -> None:
        fut = item.future
        fut.admitted_ns = time.monotonic_ns()
        queue_s = (fut.admitted_ns - fut.submitted_ns) / 1e9
        fut.queue_seconds = queue_s
        self._metrics.add(MN.QUEUE_TIME, queue_s)
        self._metrics.add(MN.NUM_ADMITTED, 1)
        with self._lock:
            self.admitted += 1
            self.admitted_by_priority[item.priority] = \
                self.admitted_by_priority.get(item.priority, 0) + 1
        session = self.session
        try:
            logical = item.logical
            cache_state = "off"
            t0 = time.perf_counter()
            # normalization + fingerprinting + planning all under the
            # plan lock: logical nodes are SHARED between submissions of
            # one DataFrame, and planning lazily writes into their
            # __dict__ (plan_schema's _cached_schema) — fingerprinting
            # vars() concurrently would race that first-touch insert
            with self._plan_lock:
                if self.plan_cache is not None:
                    normalized, values, hit = self.plan_cache.lookup(
                        logical, session.conf)
                    self._metrics.add(
                        MN.PLAN_CACHE_HITS if hit else
                        MN.PLAN_CACHE_MISSES, 1)
                    logical = normalized
                    fut.n_params = len(values)
                    cache_state = "hit" if hit else "miss"
                fut.plan_cache = cache_state
                from ..plan.overrides import plan_schema
                out_schema = plan_schema(logical, session.conf)
                physical = session.plan(logical)
            fut.plan_seconds = time.perf_counter() - t0
            sched_attrs = {
                "queue_s": round(queue_s, 6),
                "plan_s": round(fut.plan_seconds, 6),
                "priority": item.priority,
                "need_bytes": item.need,
                "plan_cache": cache_state,
                "params": fut.n_params,
            }
            table = session._collect_physical(
                physical, out_schema, budget_bytes=self.query_budget,
                sched_attrs=sched_attrs, future=fut)
            fut._set_result(table)
            with self._lock:
                self.completed += 1
        except BaseException as e:  # noqa: BLE001 — future carries it
            fut._set_error(e)
            with self._lock:
                self.failed += 1
        finally:
            # SLO histograms (metrics/slo.py): per-phase observations
            # for this query's priority class — success or failure, so
            # timeouts/errors still move the queue/total percentiles
            self.slo.observe_phases(
                item.priority,
                queue=queue_s,
                plan=fut.plan_seconds,
                compile=fut.compile_seconds,
                execute=fut.exec_seconds,
                spill=fut.spill_seconds,
                total=fut.latency_seconds)

    # -- lifecycle / observability -------------------------------------------

    def shutdown(self, wait: bool = True, timeout: float = 30.0) -> None:
        """Stop the workers.  Queued-but-never-admitted queries resolve
        with an error (a consumer blocked in result() must not hang
        forever on a future no worker will ever run); in-flight queries
        finish normally."""
        with self._lock:
            self._shutdown = True
            abandoned = [ent[2].future for ent in self._queue]
            self._queue.clear()
            self._lock.notify_all()
        for fut in abandoned:
            fut.cancelled = True
            fut._set_error(RuntimeError(
                "scheduler shut down before this query was admitted"))
        if wait:
            deadline = time.monotonic() + timeout
            for w in self._workers:
                w.join(max(0.0, deadline - time.monotonic()))

    def fairness_snapshot(self) -> dict:
        """Per-priority-class fair-share observability: live queue depth
        plus cumulative admitted/rejected counters — the block
        cluster_snapshot/prometheus_serve_dump expose so the PR-10
        fair-share behavior is observable, not just implemented."""
        with self._lock:
            depth: dict = {}
            for ent in self._queue:
                p = ent[2].priority
                depth[p] = depth.get(p, 0) + 1
            return {
                "queue_depth_by_priority": dict(sorted(depth.items())),
                "admitted_by_priority":
                    dict(sorted(self.admitted_by_priority.items())),
                "rejected_by_priority":
                    dict(sorted(self.rejected_by_priority.items())),
                "running": self._running,
                "queued": sum(depth.values()),
            }

    def telemetry_gauges(self) -> dict:
        """The live gauge-sampler series this scheduler owns (the
        driver source metrics/ring.GaugeSampler snapshots; names from
        names.TELEMETRY_GAUGES): queries executing now and queries
        waiting in the priority queue."""
        fair = self.fairness_snapshot()
        return {"in_flight_tasks": float(fair["running"]),
                "queued_queries": float(fair["queued"])}

    def prometheus(self) -> str:
        """Serving-tier Prometheus exposition: fairness gauges + the
        per-phase SLO histograms (export.prometheus_serve_dump)."""
        from ..metrics.export import prometheus_serve_dump
        return prometheus_serve_dump(self)

    def stats(self) -> dict:
        with self._lock:
            out = {
                "max_concurrent": self.max_concurrent,
                "queued": len(self._queue),
                "running": self._running,
                "inflight_need_bytes": self._inflight_need,
                "admission_budget_bytes": self.admission_budget,
                "admitted": self.admitted,
                "rejected": self.rejected,
                "completed": self.completed,
                "failed": self.failed,
                "query_budget_bytes": self.query_budget,
                "compile_cache_dir": self.compile_cache_dir,
            }
        if self.plan_cache is not None:
            out["plan_cache"] = self.plan_cache.stats()
        out["fairness"] = self.fairness_snapshot()
        out["slo"] = self.slo.report()
        return out
