"""Shuffle layer: device partitioners, device-resident shuffle manager,
transport SPI (loopback + ICI collectives).

The TPU analogue of the reference's L2 shuffle (SURVEY.md §2.8): baseline
columnar shuffle + RapidsShuffleManager with UCX transport become a
spillable device-resident block store with a loopback wire for host-driven
mode and XLA all_to_all over ICI for SPMD mesh mode.
"""
from ..mem.integrity import (BufferGone, ChecksumPolicy, CorruptBuffer,
                             CorruptShuffleBlock, FetchFailed)
from .catalog import (ShuffleBlockId, ShuffleBufferCatalog,
                      ShuffleReceivedBufferCatalog)
from .manager import ShuffleEnv, ShuffleServer, get_shuffle_env
from .partition import (hash_partition_ids, range_partition_ids,
                        round_robin_partition_ids, sample_range_bounds,
                        single_partition_ids, split_by_partition)
from .transport import (BounceBufferPool, InflightThrottle, LoopbackTransport,
                        MetadataRequest, MetadataResponse, ShuffleTransport,
                        Transaction, TransactionStatus)

__all__ = [
    "ShuffleBlockId", "ShuffleBufferCatalog", "ShuffleReceivedBufferCatalog",
    "ShuffleEnv", "ShuffleServer", "get_shuffle_env",
    "hash_partition_ids", "range_partition_ids", "round_robin_partition_ids",
    "sample_range_bounds", "single_partition_ids", "split_by_partition",
    "BounceBufferPool", "InflightThrottle", "LoopbackTransport",
    "MetadataRequest", "MetadataResponse", "ShuffleTransport",
    "Transaction", "TransactionStatus",
    "BufferGone", "ChecksumPolicy", "CorruptBuffer", "CorruptShuffleBlock",
    "FetchFailed",
]
