"""Shuffle block catalogs: block id -> spillable buffer ids + metadata.

TPU-native analogue of ShuffleBufferCatalog / ShuffleReceivedBufferCatalog
(sql-plugin/.../rapids/ShuffleBufferCatalog.scala:1-211,
ShuffleReceivedBufferCatalog.scala): the writer side maps each
(shuffle, map, reduce) block to the list of spillable buffers holding its
batches; the reader side registers buffers received from peers.  Both sit on
top of the mem.BufferCatalog, so shuffle data participates in
device->host->disk spill like everything else.
"""
from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Dict, List, Optional


@dataclass(frozen=True, order=True)
class ShuffleBlockId:
    shuffle_id: int
    map_id: int
    reduce_id: int


class ShuffleBufferCatalog:
    """Writer-side registry (one per executor/ShuffleEnv)."""

    def __init__(self):
        self._blocks: Dict[ShuffleBlockId, List[int]] = {}
        self._by_shuffle: Dict[int, List[ShuffleBlockId]] = {}
        self._lock = threading.Lock()

    def add_buffer(self, block: ShuffleBlockId, buffer_id: int) -> None:
        with self._lock:
            if block not in self._blocks:
                self._blocks[block] = []
                self._by_shuffle.setdefault(block.shuffle_id, []).append(block)
            self._blocks[block].append(buffer_id)

    def buffers_for(self, block: ShuffleBlockId) -> List[int]:
        with self._lock:
            return list(self._blocks.get(block, []))

    def blocks_for_reduce(self, shuffle_id: int,
                          reduce_id: int) -> List[ShuffleBlockId]:
        with self._lock:
            return sorted(b for b in self._by_shuffle.get(shuffle_id, [])
                          if b.reduce_id == reduce_id)

    def remove_shuffle(self, shuffle_id: int) -> List[int]:
        """Unregister every block of a shuffle; returns the buffer ids to
        free."""
        with self._lock:
            blocks = self._by_shuffle.pop(shuffle_id, [])
            freed: List[int] = []
            for blk in blocks:
                freed.extend(self._blocks.pop(blk, []))
            return freed

    def has_shuffle(self, shuffle_id: int) -> bool:
        with self._lock:
            return shuffle_id in self._by_shuffle

    def num_buffers(self) -> int:
        with self._lock:
            return sum(len(v) for v in self._blocks.values())


class ShuffleReceivedBufferCatalog:
    """Reader-side registry for buffers fetched from remote executors."""

    def __init__(self):
        self._received: Dict[int, List[int]] = {}   # shuffle_id -> buffer ids
        self._lock = threading.Lock()

    def add(self, shuffle_id: int, buffer_id: int) -> None:
        with self._lock:
            self._received.setdefault(shuffle_id, []).append(buffer_id)

    def snapshot(self, shuffle_id: int) -> int:
        """Mark for `drop_since`: the current receive count (retryable
        fetches roll back to it so a failed attempt's registrations do
        not accumulate across retries)."""
        with self._lock:
            return len(self._received.get(shuffle_id, []))

    def drop_since(self, shuffle_id: int, mark: int) -> List[int]:
        """Unregister (and return for freeing) every buffer received
        after `mark`."""
        with self._lock:
            lst = self._received.get(shuffle_id, [])
            new = lst[mark:]
            if new:
                self._received[shuffle_id] = lst[:mark]
            return new

    def remove_shuffle(self, shuffle_id: int) -> List[int]:
        with self._lock:
            return self._received.pop(shuffle_id, [])
