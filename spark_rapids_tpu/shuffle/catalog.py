"""Shuffle block catalogs: block id -> spillable buffer ids + metadata.

TPU-native analogue of ShuffleBufferCatalog / ShuffleReceivedBufferCatalog
(sql-plugin/.../rapids/ShuffleBufferCatalog.scala:1-211,
ShuffleReceivedBufferCatalog.scala): the writer side maps each
(shuffle, map, reduce) block to the list of spillable buffers holding its
batches; the reader side registers buffers received from peers.  Both sit on
top of the mem.BufferCatalog, so shuffle data participates in
device->host->disk spill like everything else.
The writer-side catalog also records each buffer's per-leaf checksums
(established at its first device->host materialization), the canonical
digests the fetch paths verify against and the corruption-diagnosis RPC
re-hashes the writer's live data against (SPARK-35275/36206 analogue).
"""
from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

#: map-id namespace stride per distributed map FRAGMENT: ProcCluster's
#: map task i writes blocks with map_id in [i*STRIDE, (i+1)*STRIDE), so a
#: worker holding its own fragment plus a speculative copy of another has
#: disjoint ranges, and the attempt-id guard (`remove_map_range` before a
#: re-run registers anything) can drop exactly one fragment's prior
#: attempt without touching its neighbors.
MAP_ID_STRIDE = 1 << 20


@dataclass(frozen=True, order=True)
class ShuffleBlockId:
    shuffle_id: int
    map_id: int
    reduce_id: int


class ShuffleBufferCatalog:
    """Writer-side registry (one per executor/ShuffleEnv)."""

    def __init__(self):
        self._blocks: Dict[ShuffleBlockId, List[int]] = {}
        self._by_shuffle: Dict[int, List[ShuffleBlockId]] = {}
        # buffer id -> (algorithm, per-leaf digests); populated at the
        # buffer's first host materialization (baseline write, spill, or
        # first serve) and dropped with the shuffle
        self._checksums: Dict[int, Tuple[str, Tuple[int, ...]]] = {}
        self._block_of: Dict[int, ShuffleBlockId] = {}
        self._lock = threading.Lock()

    def add_buffer(self, block: ShuffleBlockId, buffer_id: int) -> None:
        with self._lock:
            if block not in self._blocks:
                self._blocks[block] = []
                self._by_shuffle.setdefault(block.shuffle_id, []).append(block)
            self._blocks[block].append(buffer_id)
            self._block_of[buffer_id] = block

    def block_for_buffer(self, buffer_id: int) -> Optional[ShuffleBlockId]:
        """Reverse lookup: which block a buffer belongs to (the serve
        path uses it to mark the right map output lost when a buffer's
        stored bytes fail verification)."""
        with self._lock:
            return self._block_of.get(buffer_id)

    def buffers_for(self, block: ShuffleBlockId) -> List[int]:
        with self._lock:
            return list(self._blocks.get(block, []))

    def blocks_for_reduce(self, shuffle_id: int,
                          reduce_id: int) -> List[ShuffleBlockId]:
        with self._lock:
            return sorted(b for b in self._by_shuffle.get(shuffle_id, [])
                          if b.reduce_id == reduce_id)

    def set_checksums(self, buffer_id: int, algorithm: str,
                      leaf_sums) -> None:
        with self._lock:
            self._checksums[buffer_id] = (algorithm,
                                          tuple(int(s) for s in leaf_sums))

    def checksums_for(self, buffer_id: int
                      ) -> Optional[Tuple[str, Tuple[int, ...]]]:
        """(algorithm, per-leaf digests) or None when the buffer has not
        been host-materialized yet (still HBM-resident, never served)."""
        with self._lock:
            return self._checksums.get(buffer_id)

    def remove_shuffle(self, shuffle_id: int) -> List[int]:
        """Unregister every block of a shuffle; returns the buffer ids to
        free."""
        with self._lock:
            blocks = self._by_shuffle.pop(shuffle_id, [])
            freed: List[int] = []
            for blk in blocks:
                freed.extend(self._blocks.pop(blk, []))
            for bid in freed:
                self._checksums.pop(bid, None)
                self._block_of.pop(bid, None)
            return freed

    def remove_map_range(self, shuffle_id: int, map_lo: int,
                         map_hi: int) -> List[int]:
        """Unregister every block of one shuffle whose map_id falls in
        [map_lo, map_hi) — one map FRAGMENT's outputs (the attempt-id
        guard: a task re-run or a speculation loser's cleanup drops the
        prior attempt's registrations so the reduce side can never read a
        mix of attempts).  Returns the buffer ids to free."""
        with self._lock:
            blocks = [b for b in self._by_shuffle.get(shuffle_id, [])
                      if map_lo <= b.map_id < map_hi]
            freed: List[int] = []
            for blk in blocks:
                self._by_shuffle[shuffle_id].remove(blk)
                freed.extend(self._blocks.pop(blk, []))
            for bid in freed:
                self._checksums.pop(bid, None)
                self._block_of.pop(bid, None)
            return freed

    def has_shuffle(self, shuffle_id: int) -> bool:
        with self._lock:
            return shuffle_id in self._by_shuffle

    def num_buffers(self) -> int:
        with self._lock:
            return sum(len(v) for v in self._blocks.values())


class ShuffleReceivedBufferCatalog:
    """Reader-side registry for buffers fetched from remote executors."""

    def __init__(self):
        self._received: Dict[int, List[int]] = {}   # shuffle_id -> buffer ids
        self._lock = threading.Lock()

    def add(self, shuffle_id: int, buffer_id: int) -> None:
        with self._lock:
            self._received.setdefault(shuffle_id, []).append(buffer_id)

    def snapshot(self, shuffle_id: int) -> int:
        """Mark for `drop_since`: the current receive count (retryable
        fetches roll back to it so a failed attempt's registrations do
        not accumulate across retries)."""
        with self._lock:
            return len(self._received.get(shuffle_id, []))

    def drop_since(self, shuffle_id: int, mark: int) -> List[int]:
        """Unregister (and return for freeing) every buffer received
        after `mark`."""
        with self._lock:
            lst = self._received.get(shuffle_id, [])
            new = lst[mark:]
            if new:
                self._received[shuffle_id] = lst[:mark]
            return new

    def remove_shuffle(self, shuffle_id: int) -> List[int]:
        with self._lock:
            return self._received.pop(shuffle_id, [])
