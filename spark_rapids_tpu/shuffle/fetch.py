"""Pipelined shuffle read: producer thread + bounded-bytes queue.

The reference overlaps fetch with compute via a producer/consumer iterator
with inflight-bytes throttling (rapids/shuffle/RapidsShuffleIterator.scala:
17-258 — BufferReceiveState handoff — and RapidsShuffleTransport.scala:38-500
— `maxReceiveInflightBytes` throttle on issued receives).  Here a daemon
thread walks the partitions through `ShuffleEnv.fetch_partition` while the
consumer drains already-fetched batches, so fetch of partition k+1 overlaps
consumption of partition k; admission of new batches is bounded by
`spark.rapids.shuffle.maxReceiveInflightBytes` of un-consumed device bytes
(a batch larger than the cap is admitted alone rather than deadlocking, the
same degenerate case the reference's bounce-buffer pool absorbs).
"""
from __future__ import annotations

import queue
import threading
from typing import Iterator, List, Optional, Sequence, Tuple

from ..mem.retry import RetryExhausted


class AsyncFetchIterator:
    """Iterates (reduce_id, batch) across `reduce_ids` with prefetch.

    The producer thread fetches partitions IN ORDER; `prefetched_partitions`
    exposes which reduce ids the producer has started (test observability).
    Errors in the producer re-raise in the consumer."""

    _DONE = object()

    def __init__(self, env, shuffle_id: int, reduce_ids: Sequence[int],
                 remote_peers: Optional[List[str]] = None,
                 max_inflight_bytes: int = 1 << 30, route=None,
                 oom_retries: int = 2, flow=None):
        self._env = env
        self._sid = shuffle_id
        self._rids = list(reduce_ids)
        self._peers = remote_peers
        # cluster mode: `route(rid) -> (env, peers)` picks the serving
        # executor per partition (exchange._execute_partitions_cluster)
        self._route = route
        self._max = max(int(max_inflight_bytes), 1)
        # reduce-driven flow control (policy/flow.py FlowController):
        # consumption feeds its rate, admission caps at its window —
        # None (policy off) keeps the static _max cap exactly as before
        self._flow = flow
        # OOM retries per partition fetch; catalog reads are idempotent,
        # so a refetch is safe as long as NOTHING of that partition was
        # handed to the consumer yet (_produce enforces that)
        self._oom_retries = max(int(oom_retries), 0)
        self._q: "queue.Queue" = queue.Queue()
        self._cv = threading.Condition()
        self._inflight = 0
        self._stop = False
        self.prefetched_partitions: List[int] = []
        self._thread = threading.Thread(target=self._produce, daemon=True)
        self._thread.start()

    # ---- producer ----------------------------------------------------------

    def _cap(self) -> int:
        """Admission cap: the static max, tightened to the flow fetch
        window when a controller rides this iterator.  The fetch window
        floors at minWindowBytes from the rate side but may clamp BELOW
        it on device headroom (pool-aware admission) — readahead then
        collapses toward serial fetch; the oversized-batch-alone rule in
        _admit still guarantees progress, so the producer is never
        halted."""
        if self._flow is None:
            return self._max
        return min(self._max, max(self._flow.fetch_window_bytes(), 1))

    def _admit(self, nbytes: int) -> bool:
        """Block until `nbytes` fits under the inflight cap (or the queue is
        empty — a single oversized batch must still make progress).
        Returns False when the consumer shut down."""
        stalled = False
        with self._cv:
            # the cap re-evaluates per wait round: consumption events
            # widen the flow window while we sleep
            while not self._stop and self._inflight > 0 \
                    and self._inflight + nbytes > self._cap():
                stalled = True
                self._cv.wait(timeout=0.05 if self._flow is not None
                              else 0.5)
            if self._stop:
                return False
            self._inflight += nbytes
        if stalled and self._flow is not None:
            self._flow.note_stall("fetch")  # counted once per admission
        return True

    def _produce(self) -> None:
        try:
            for rid in self._rids:
                self.prefetched_partitions.append(rid)
                env, peers = (self._route(rid) if self._route is not None
                              else (self._env, self._peers))
                enqueued = 0
                attempt = 0
                while True:
                    mark = (env.received.snapshot(self._sid)
                            if hasattr(env, "received") else None)
                    try:
                        for batch in env.fetch_partition(self._sid, rid,
                                                         peers):
                            nb = batch.device_size_bytes()
                            if not self._admit(nb):
                                return
                            self._q.put((rid, batch, nb))
                            enqueued += 1
                        break
                    except MemoryError as e:
                        # free the failed attempt's remote registrations
                        # (a retry would re-fetch and duplicate them in
                        # the pool exactly while memory is tightest)
                        if mark is not None \
                                and hasattr(env, "rollback_received"):
                            env.rollback_received(self._sid, mark)
                        # retry the whole partition ONLY while none of it
                        # reached the consumer (a partial refetch would
                        # duplicate rows); the spill cascade already ran
                        # inside reserve()
                        attempt += 1
                        if enqueued or attempt > self._oom_retries:
                            if isinstance(e, RetryExhausted):
                                raise
                            # typed exhaustion so the exchange's CPU
                            # fallback (exec/retryable.py) engages on
                            # this (default) read path too
                            raise RetryExhausted(
                                f"shuffle fetch of partition {rid} "
                                f"exhausted OOM retries "
                                f"(attempts={attempt}): {e}",
                                cause=e) from e
            self._q.put(self._DONE)
        except BaseException as ex:  # surfaced in the consumer
            self._q.put(ex)

    # ---- consumer ----------------------------------------------------------

    def __iter__(self) -> Iterator[Tuple[int, "object"]]:
        try:
            while True:
                item = self._q.get()
                if item is self._DONE:
                    return
                if isinstance(item, BaseException):
                    raise item
                rid, batch, nb = item
                with self._cv:
                    self._inflight -= nb
                    self._cv.notify_all()
                if self._flow is not None:
                    # the reduce-side consumption signal the admission
                    # window is derived from
                    self._flow.on_consumed(nb)
                yield rid, batch
        finally:
            self.close()

    def close(self) -> None:
        with self._cv:
            self._stop = True
            self._cv.notify_all()


def iter_partition_groups(it):
    """Group an AsyncFetchIterator's (reduce_id, batch) stream into
    (reduce_id, [batches]) — the ONE place that encodes the producer's
    in-order emission contract (a rid change marks the previous
    partition complete).  Only non-empty partitions are yielded; callers
    needing every id walk the gaps themselves."""
    current, pending = None, []
    for rid, batch in it:
        if current is not None and rid != current:
            yield current, pending
            pending = []
        current = rid
        pending.append(batch)
    if current is not None:
        yield current, pending
