"""ICI shuffle transport: repartitioning as XLA collectives over the mesh.

The TPU-native replacement for the reference's UCX/RDMA transport
(shuffle-plugin/src/main/scala/.../shuffle/ucx/ — UCX.scala endpoint
handshake, UCXShuffleTransport.scala bounce pools).  The SPMD exchange
itself is NOT a method on this class: when a plan runs over a mesh, the
planner's distribute pass (plan/transitions.py) compiles the repartition
INTO the query program as an `all_to_all` over ICI
(parallel/distributed.py exchange_compact / exchange_by_bucket, used by
exec/distributed.py) — there is no control plane or staging copy for a
transport object to manage, which is exactly the point of the design.

What remains here is the host-driven block-fetch SPI for off-mesh task
mode and unit tests: the loopback wire, bounce-buffer pool, and throttle
inherited from LoopbackTransport.  This is the class named by the default
`spark.rapids.shuffle.transport.class`, so a deployment can swap in a
DCN-aware transport by conf (reference: RapidsConf.scala:505-510
shuffle.transport.classname) while mesh execution keeps riding ICI.
"""
from __future__ import annotations

from typing import Optional

from .transport import LoopbackTransport


class IciShuffleTransport(LoopbackTransport):
    """Block-fetch SPI for host-driven mode; mesh repartitions compile to
    collectives instead of passing through a transport (module docstring)."""

    def __init__(self, mesh=None, axis: Optional[str] = None, **kw):
        super().__init__(**kw)
        from ..parallel.mesh import DATA_AXIS
        self.mesh = mesh
        self.axis = axis or DATA_AXIS
