"""ICI shuffle transport: repartitioning as XLA collectives over the mesh.

The TPU-native replacement for the reference's UCX/RDMA transport
(shuffle-plugin/src/main/scala/.../shuffle/ucx/ — UCX.scala endpoint
handshake, UCXShuffleTransport.scala bounce pools): when a plan runs
SPMD over a `jax.sharding.Mesh`, a repartition-by-key is ONE
`all_to_all`/`all_gather` over ICI inside the compiled program
(parallel/distributed.py) — no control plane, no staging copies, and XLA
overlaps it with compute.  Cross-slice (DCN) traffic takes the same
collective path through XLA's DCN-aware lowering when the mesh spans
slices.

Off-mesh (host-driven task mode, and unit tests), the block-fetch SPI falls
back to the loopback wire, so one transport class serves both execution
modes — this is the class named by the default
`spark.rapids.shuffle.transport.class`.
"""
from __future__ import annotations

from typing import Optional, Sequence

from ..columnar import Column, ColumnarBatch
from .transport import LoopbackTransport


class IciShuffleTransport(LoopbackTransport):
    """Mesh-collective shuffle + loopback block SPI."""

    def __init__(self, mesh=None, axis: Optional[str] = None, **kw):
        super().__init__(**kw)
        from ..parallel.mesh import DATA_AXIS
        self.mesh = mesh
        self.axis = axis or DATA_AXIS

    # ---- SPMD path: one collective, traced into the program ----------------

    def exchange(self, batch: ColumnarBatch, bucket) -> ColumnarBatch:
        """Inside shard_map: route live rows to their owner device.  See
        parallel/distributed.exchange_by_bucket for the sel-mask trick that
        keeps this static-shape."""
        from ..parallel.distributed import exchange_by_bucket
        return exchange_by_bucket(batch, bucket, self.axis)

    def exchange_by_keys(self, batch: ColumnarBatch,
                         key_cols: Sequence[Column]) -> ColumnarBatch:
        """Inside shard_map: hash-repartition by key columns."""
        import jax
        from ..parallel.distributed import key_buckets
        n = jax.lax.psum(1, self.axis)
        bucket = key_buckets(list(key_cols), batch.sel, n)
        return self.exchange(batch, bucket)
