"""ICI shuffle transport: repartitioning as XLA collectives over the mesh.

The TPU-native replacement for the reference's UCX/RDMA transport
(shuffle-plugin/src/main/scala/.../shuffle/ucx/ — UCX.scala endpoint
handshake, UCXShuffleTransport.scala bounce pools).  The SPMD exchange
itself is NOT a method on this class: when a plan runs over a mesh, the
planner's distribute pass (plan/transitions.py) compiles the repartition
INTO the query program as an `all_to_all` over ICI
(parallel/distributed.py exchange_compact / exchange_by_bucket, used by
exec/distributed.py) — there is no control plane or staging copy for a
transport object to manage, which is exactly the point of the design.

What remains here is the host-driven block-fetch SPI for off-mesh task
mode and unit tests: the loopback wire, bounce-buffer pool, and throttle
inherited from LoopbackTransport.  This is the class named by the default
`spark.rapids.shuffle.transport.class`, so a deployment can swap in a
DCN-aware transport by conf (reference: RapidsConf.scala:505-510
shuffle.transport.classname) while mesh execution keeps riding ICI.
"""
from __future__ import annotations

from typing import Optional

from .transport import LoopbackTransport


class IciShuffleTransport(LoopbackTransport):
    """Block-fetch SPI for host-driven mode; mesh repartitions compile to
    collectives instead of passing through a transport (module docstring).

    Tier-selection observability lives here: mesh-lowered exchanges move
    no bytes through any transport, but WHICH tier served each exchange
    is transport-level information — `ici_exchanges` counts collective-
    served exchanges, `socket_fallbacks` counts mesh-eligible exchanges
    de-lowered after a collective retry ladder exhausted.  Both ride the
    standard `counters` dict into `transport_counters` RPCs and
    `session_observability`."""

    def __init__(self, mesh=None, axis: Optional[str] = None, **kw):
        super().__init__(**kw)
        from ..parallel.mesh import DATA_AXIS
        self.mesh = mesh
        self.axis = axis or DATA_AXIS

    def configure(self, conf) -> None:
        """Adopt the session conf (integrity/compression/faults, like
        every transport) and resolve the execution mesh ONCE: the conf
        names the mesh geometry (spark.rapids.sql.tpu.mesh.devices), and
        resolving it here means every exchange's tier check reads a
        settled capability instead of re-deriving one per materialize."""
        super().configure(conf)
        if self.mesh is None:
            from ..exec.distributed import resolve_mesh
            self.mesh = resolve_mesh(conf)
