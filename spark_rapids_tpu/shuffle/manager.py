"""Device-resident shuffle manager: writer, reader, server.

TPU-native analogue of RapidsShuffleInternalManager + RapidsCachingWriter /
RapidsCachingReader (org/.../rapids/RapidsShuffleInternalManager.scala:73-337,
RapidsCachingReader.scala:49-170) and GpuShuffleEnv (GpuShuffleEnv.scala:
57-107):

  * write side caches each partition's batch as a SPILLABLE buffer in the
    device store (shuffle data never leaves HBM unless memory pressure
    spills it) and registers it in the ShuffleBufferCatalog;
  * read side serves local blocks straight from the catalog (zero copy when
    still in HBM) and fetches remote blocks through the transport, which
    re-serves spilled buffers from whatever tier they occupy;
  * a baseline host-serialized path mirrors the reference's always-available
    non-UCX shuffle (GpuColumnarBatchSerializer.scala).
"""
from __future__ import annotations

import threading
from typing import Dict, Iterator, List, Optional, Tuple

import numpy as np

from ..columnar import ColumnarBatch
from ..config import (PINNED_POOL_SIZE, SHUFFLE_DEVICE_RESIDENT,
                      SHUFFLE_MAX_RECV_INFLIGHT, SHUFFLE_TRANSPORT_CLASS,
                      TpuConf)
from ..mem.buffer import (SpillPriorities, StorageTier, batch_to_host,
                          host_to_batch, read_leaves)
from ..mem.runtime import TpuRuntime
from .catalog import (ShuffleBlockId, ShuffleBufferCatalog,
                      ShuffleReceivedBufferCatalog)
from .transport import (LoopbackTransport, MetadataRequest, MetadataResponse,
                        BlockMeta, ShuffleTransport)


class ShuffleServer:
    """Serves this executor's shuffle buffers to peers, from ANY storage
    tier (RapidsShuffleServer.scala:67-671: BufferSendState acquires
    possibly-spilled buffers and streams them through bounce buffers)."""

    def __init__(self, env: "ShuffleEnv"):
        self.env = env
        self._cache: Dict[int, Tuple[list, object]] = {}
        self._lock = threading.Lock()

    def handle_metadata_request(self, request: MetadataRequest
                                ) -> MetadataResponse:
        blocks = request.blocks
        if blocks is None:  # wildcard discovery for one reduce partition
            blocks = self.env.catalog.blocks_for_reduce(
                request.shuffle_id, request.reduce_id)
            if request.map_lo is not None or request.map_hi is not None:
                # skew-slice discovery: only the requested map-id range
                lo = request.map_lo if request.map_lo is not None else 0
                hi = request.map_hi if request.map_hi is not None \
                    else float("inf")
                blocks = [b for b in blocks if lo <= b.map_id < hi]
        out: List[BlockMeta] = []
        for block in blocks:
            buffer_ids = self.env.catalog.buffers_for(block)
            metas, sizes = [], []
            for bid in buffer_ids:
                baseline = self.env.baseline_leaves(bid)
                if baseline is not None:
                    metas.append(baseline[1])
                    sizes.append(baseline[1].size_bytes)
                    continue
                buf = self.env.runtime.catalog.acquire(bid)
                try:
                    metas.append(buf.meta)
                    sizes.append(buf.size_bytes)
                finally:
                    self.env.runtime.catalog.release(buf)
            out.append(BlockMeta(block, buffer_ids, metas, sizes))
        return MetadataResponse(out)

    def _leaves(self, buffer_id: int):
        """Host-side leaves of a buffer, whatever its tier (no promotion —
        serving a spilled buffer must not re-inflate HBM)."""
        with self._lock:
            hit = self._cache.get(buffer_id)
            if hit is not None:
                return hit
        baseline = self.env.baseline_leaves(buffer_id)
        if baseline is not None:
            leaves, meta = baseline
        else:
            buf = self.env.runtime.catalog.acquire(buffer_id)
            try:
                with buf.lock:
                    if buf.tier == StorageTier.DEVICE:
                        leaves, meta = batch_to_host(buf.device_batch)
                    elif buf.tier == StorageTier.HOST:
                        leaves, meta = buf.host_leaves, buf.meta
                    else:
                        leaves, meta = read_leaves(buf.disk_path, buf.meta), \
                            buf.meta
            finally:
                self.env.runtime.catalog.release(buf)
        with self._lock:
            if len(self._cache) >= 4:  # bounded serving cache
                self._cache.pop(next(iter(self._cache)))
            self._cache[buffer_id] = (leaves, meta)
        return leaves, meta

    def buffer_layout(self, buffer_id: int):
        leaves, meta = self._leaves(buffer_id)
        layout = [(a.shape, a.dtype.str, a.nbytes) for a in leaves]
        return layout, meta

    def copy_leaf_chunk(self, buffer_id: int, leaf_idx: int, offset: int,
                        length: int, dest: np.ndarray) -> None:
        leaves, _ = self._leaves(buffer_id)
        flat = np.ascontiguousarray(leaves[leaf_idx]).view(np.uint8).reshape(-1)
        dest[:length] = flat[offset:offset + length]

    def done_serving(self, buffer_id: int) -> None:
        with self._lock:
            self._cache.pop(buffer_id, None)


class ShuffleEnv:
    """Per-executor shuffle wiring (GpuShuffleEnv equivalent)."""

    def __init__(self, runtime: TpuRuntime, conf: Optional[TpuConf] = None,
                 executor_id: str = "exec-0",
                 transport: Optional[ShuffleTransport] = None):
        self.runtime = runtime
        self.conf = conf or TpuConf()
        self.executor_id = executor_id
        self.device_resident = bool(self.conf.get(SHUFFLE_DEVICE_RESIDENT))
        self.catalog = ShuffleBufferCatalog()
        self.received = ShuffleReceivedBufferCatalog()
        # observed per-reduce-partition output sizes, recorded at write
        # time — what adaptive re-planning (adaptive/) runs on
        from ..adaptive.stats import MapOutputTracker
        self.map_stats = MapOutputTracker()
        if transport is None:
            transport = self._resolve_transport()
        self.transport = transport
        self.server = ShuffleServer(self)
        transport.register_server(executor_id, self.server)
        # baseline (host-serialized) buffers share the buffer-id space with
        # spillable ones so the catalog + server treat both uniformly
        self._baseline_buffers: Dict[int, Tuple[list, object]] = {}
        self._shuffle_counter = [0]
        self._write_seq = [0]
        self._lock = threading.Lock()

    def _resolve_transport(self) -> ShuffleTransport:
        """Instantiate the conf-named transport class by reflection
        (spark.rapids.shuffle.transport.class; reference:
        RapidsConf.scala:505-510 + UCXShuffleTransport loading).  The pinned
        host pool conf sizes the transport's bounce-buffer staging area."""
        import importlib
        name = str(self.conf.get(SHUFFLE_TRANSPORT_CLASS))
        mod_name, _, cls_name = name.rpartition(".")
        cls = getattr(importlib.import_module(mod_name), cls_name)
        kwargs = {"max_inflight_bytes":
                  int(self.conf.get(SHUFFLE_MAX_RECV_INFLIGHT))}
        pinned = int(self.conf.get(PINNED_POOL_SIZE))
        if pinned > 0:
            kwargs["pool_size"] = pinned
        transport = cls(**kwargs)
        if hasattr(transport, "configure"):
            # retry/backoff/deadline knobs + fault-injection arming
            transport.configure(self.conf)
        return transport

    def baseline_leaves(self, buffer_id: int):
        with self._lock:
            return self._baseline_buffers.get(buffer_id)

    # ---- lifecycle ---------------------------------------------------------

    def new_shuffle_id(self) -> int:
        with self._lock:
            self._shuffle_counter[0] += 1
            return self._shuffle_counter[0]

    def rollback_received(self, shuffle_id: int, mark: int) -> None:
        """Free every remote buffer registered after `mark` (a failed
        fetch attempt's partial registrations — a retry would otherwise
        re-fetch and duplicate them in the pool while memory is
        tightest)."""
        for bid in self.received.drop_since(shuffle_id, mark):
            self.runtime.free_batch(bid)

    def remove_shuffle(self, shuffle_id: int) -> None:
        # the shuffle's map statistics go with its buffers — a long-lived
        # session would otherwise accumulate stats for every query it
        # ever ran (regression-tested in tests/test_adaptive.py)
        self.map_stats.remove_shuffle(shuffle_id)
        for bid in self.catalog.remove_shuffle(shuffle_id):
            with self._lock:
                if self._baseline_buffers.pop(bid, None) is not None:
                    continue
            self.runtime.free_batch(bid)
        for bid in self.received.remove_shuffle(shuffle_id):
            self.runtime.free_batch(bid)

    # ---- write path (RapidsCachingWriter.write) ----------------------------

    def write_partition(self, shuffle_id: int, map_id: int, reduce_id: int,
                        batch: ColumnarBatch) -> None:
        block = ShuffleBlockId(shuffle_id, map_id, reduce_id)
        # map-output statistics: DATA bytes (live-row-proportional, so a
        # mostly-dead bucketed capacity does not read as a fat partition)
        # and rows.  split_by_partition stamps known_rows on every
        # sub-batch, so the common write path records without a device
        # sync; direct writers without the stamp pay one.  Recorded only
        # AFTER the buffer registers below — an OOM mid-write retries the
        # whole call, and recording first would double-count the attempt.
        nrows = batch.num_rows_host()
        cap = max(batch.capacity, 1)
        nbytes = int(batch.device_size_bytes() * min(nrows, cap) / cap)
        if self.device_resident:
            with self._lock:
                self._write_seq[0] += 1
                seq = self._write_seq[0]
            # oldest shuffle output spills first (SpillPriorities.scala)
            prio = (SpillPriorities.OUTPUT_FOR_SHUFFLE_INITIAL_PRIORITY
                    + float(seq))
            bid = self.runtime.add_batch(batch, prio)
            self.catalog.add_buffer(block, bid)
        else:
            from ..mem.buffer import fresh_buffer_id
            leaves, meta = batch_to_host(batch)
            bid = fresh_buffer_id()
            with self._lock:
                self._baseline_buffers[bid] = (leaves, meta)
            self.catalog.add_buffer(block, bid)
        self.map_stats.record(shuffle_id, map_id, reduce_id, nbytes, nrows)

    # ---- read path (RapidsCachingReader.read) ------------------------------

    def fetch_partition(self, shuffle_id: int, reduce_id: int,
                        remote_peers: Optional[List[str]] = None,
                        map_range: Optional[tuple] = None
                        ) -> Iterator[ColumnarBatch]:
        """Local blocks from the catalog; remote blocks via transport.
        `map_range=(lo, hi)` restricts the read to blocks written by map
        ids in [lo, hi) — the skew-join slice fetch
        (PartialReducerPartitionSpec, adaptive/stats.py)."""
        from ..metrics.journal import journal_event
        journal_event("fetch", "fetchPartition", shuffle=shuffle_id,
                      reduce=reduce_id, executor=self.executor_id,
                      remote_peers=len(remote_peers or []),
                      map_range=list(map_range) if map_range else None)
        for block in self.catalog.blocks_for_reduce(shuffle_id, reduce_id):
            if map_range is not None \
                    and not map_range[0] <= block.map_id < map_range[1]:
                continue
            for bid in self.catalog.buffers_for(block):
                baseline = self.baseline_leaves(bid)
                if baseline is not None:
                    leaves, meta = baseline
                    self.runtime.reserve(meta.size_bytes,
                                         site="fetch_baseline")
                    yield host_to_batch(leaves, meta)
                else:
                    yield self.runtime.get_batch(bid)
        for peer in remote_peers or []:
            yield from self._fetch_remote(peer, shuffle_id, reduce_id,
                                          map_range)

    def fetch_partitions_async(self, shuffle_id: int, reduce_ids,
                               remote_peers: Optional[List[str]] = None):
        """Pipelined multi-partition read: fetch of partition k+1 overlaps
        consumption of partition k, bounded by maxReceiveInflightBytes
        (shuffle/fetch.py; reference RapidsShuffleIterator.scala:17-258)."""
        from ..config import OOM_RETRY_MAX
        from .fetch import AsyncFetchIterator
        return AsyncFetchIterator(
            self, shuffle_id, reduce_ids, remote_peers,
            int(self.conf.get(SHUFFLE_MAX_RECV_INFLIGHT)),
            oom_retries=int(self.conf.get(OOM_RETRY_MAX)))

    def _fetch_remote(self, peer: str, shuffle_id: int, reduce_id: int,
                      map_range: Optional[tuple] = None
                      ) -> Iterator[ColumnarBatch]:
        """doFetch (RapidsShuffleClient.scala:350-770): wildcard metadata
        request discovers the peer's blocks for this reduce partition, then
        per-buffer receives register spillable buffers locally.  Everything
        goes through the transport SPI — no peer-object introspection."""
        from ..metrics.journal import journal_event
        client = self.transport.make_client(peer)
        resp = client.fetch_metadata(MetadataRequest(
            shuffle_id=shuffle_id, reduce_id=reduce_id,
            map_lo=map_range[0] if map_range else None,
            map_hi=map_range[1] if map_range else None))
        fetched_bytes = 0
        n_buffers = 0
        for bm in resp.block_metas:
            for bid in bm.buffer_ids:
                leaves, meta = client.fetch_buffer(bid)
                client.release_buffer(bid)
                batch = host_to_batch(leaves, meta)
                fetched_bytes += meta.size_bytes
                n_buffers += 1
                rid = self.runtime.add_batch(batch)
                self.received.add(shuffle_id, rid)
                yield self.runtime.get_batch(rid)
        journal_event("fetch", "fetchRemote", peer=peer,
                      shuffle=shuffle_id, reduce=reduce_id,
                      buffers=n_buffers, bytes=fetched_bytes)


def get_shuffle_env(runtime: TpuRuntime, conf: TpuConf) -> ShuffleEnv:
    """Lazily attach one ShuffleEnv to a runtime (executor singleton)."""
    env = getattr(runtime, "_shuffle_env", None)
    if env is None:
        env = ShuffleEnv(runtime, conf)
        runtime._shuffle_env = env
    return env
