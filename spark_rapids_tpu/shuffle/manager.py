"""Device-resident shuffle manager: writer, reader, server.

TPU-native analogue of RapidsShuffleInternalManager + RapidsCachingWriter /
RapidsCachingReader (org/.../rapids/RapidsShuffleInternalManager.scala:73-337,
RapidsCachingReader.scala:49-170) and GpuShuffleEnv (GpuShuffleEnv.scala:
57-107):

  * write side caches each partition's batch as a SPILLABLE buffer in the
    device store (shuffle data never leaves HBM unless memory pressure
    spills it) and registers it in the ShuffleBufferCatalog;
  * read side serves local blocks straight from the catalog (zero copy when
    still in HBM) and fetches remote blocks through the transport, which
    re-serves spilled buffers from whatever tier they occupy;
  * a baseline host-serialized path mirrors the reference's always-available
    non-UCX shuffle (GpuColumnarBatchSerializer.scala).
"""
from __future__ import annotations

import threading
from typing import Dict, Iterator, List, Optional, Tuple

import numpy as np

import logging

from ..columnar import ColumnarBatch
from ..config import (PINNED_POOL_SIZE, SHUFFLE_CHECKSUM_VERIFY_LOCAL,
                      SHUFFLE_DEVICE_RESIDENT, SHUFFLE_MAX_RECV_INFLIGHT,
                      SHUFFLE_MAX_REFETCH, SHUFFLE_TRANSPORT_CLASS,
                      TpuConf)
from ..mem.buffer import (SpillPriorities, StorageTier, batch_to_host,
                          host_to_batch)
from ..mem.integrity import (BufferGone, CorruptBuffer, CorruptShuffleBlock,
                             FetchFailed, policy_from_conf)
from ..mem.runtime import TpuRuntime
from ..mem.stores import verify_buffer_leaves
from ..metrics import names as MN
from ..utils import faults
from .catalog import (ShuffleBlockId, ShuffleBufferCatalog,
                      ShuffleReceivedBufferCatalog)
from .transport import (LoopbackTransport, MetadataRequest, MetadataResponse,
                        BlockMeta, ShuffleTransport)

log = logging.getLogger("spark_rapids_tpu.shuffle")


class ShuffleServer:
    """Serves this executor's shuffle buffers to peers, from ANY storage
    tier (RapidsShuffleServer.scala:67-671: BufferSendState acquires
    possibly-spilled buffers and streams them through bounce buffers)."""

    def __init__(self, env: "ShuffleEnv"):
        from ..compress import CompressedServeCache
        self.env = env
        self._cache: Dict[int, Tuple[list, object]] = {}
        # framed compressed forms per (buffer, codec), compressed ONCE at
        # first serve and re-served for every chunk/shm fill/refetch; the
        # compressed-frame digests the reader pre-verifies come from here
        self._comp_cache = CompressedServeCache(env.compression,
                                                integrity=env.integrity)
        self._lock = threading.Lock()

    def handle_metadata_request(self, request: MetadataRequest
                                ) -> MetadataResponse:
        # codec negotiation opener: the reader names its preferred codec
        # and every BlockMeta answers with what THIS server will actually
        # frame the block's buffers with (the requested codec when the
        # library is available here, raw otherwise); the layout response
        # at fetch time confirms with per-leaf framed sizes + digests
        from ..compress import is_codec_available
        req_codec = getattr(request, "codec", None)
        negotiated = (req_codec if req_codec not in (None, "none")
                      and is_codec_available(req_codec) else None)
        blocks = request.blocks
        if blocks is None:  # wildcard discovery for one reduce partition
            blocks = self.env.catalog.blocks_for_reduce(
                request.shuffle_id, request.reduce_id)
            if request.map_lo is not None or request.map_hi is not None:
                # skew-slice discovery: only the requested map-id range
                lo = request.map_lo if request.map_lo is not None else 0
                hi = request.map_hi if request.map_hi is not None \
                    else float("inf")
                blocks = [b for b in blocks if lo <= b.map_id < hi]
        out: List[BlockMeta] = []
        for block in blocks:
            buffer_ids = self.env.catalog.buffers_for(block)
            metas, sizes, sums = [], [], []
            for bid in buffer_ids:
                sums.append(self.env.catalog.checksums_for(bid))
                baseline = self.env.baseline_leaves(bid)
                if baseline is not None:
                    metas.append(baseline[1])
                    sizes.append(baseline[1].size_bytes)
                    continue
                buf = self.env.runtime.catalog.acquire(bid)
                try:
                    metas.append(buf.meta)
                    sizes.append(buf.size_bytes)
                finally:
                    self.env.runtime.catalog.release(buf)
            comp_sizes = [
                (e.sizes if (e := self._comp_cache.peek(bid, negotiated))
                 is not None else None)
                for bid in buffer_ids] if negotiated else None
            out.append(BlockMeta(block, buffer_ids, metas, sizes,
                                 checksums=sums, codec=negotiated,
                                 compressed_sizes=comp_sizes))
        return MetadataResponse(out)

    def _leaves(self, buffer_id: int):
        """Host-side leaves of a buffer, whatever its tier (no promotion —
        serving a spilled buffer must not re-inflate HBM).

        Integrity duties on the serve path: a spilled buffer's host/disk
        form is verified against its spill-time digests before serving
        (so the server never knowingly streams rotted bytes — the typed
        corrupt frame tells the reader to recompute, not refetch), and the
        buffer's canonical checksums are recorded in the writer catalog at
        its FIRST host materialization."""
        with self._lock:
            hit = self._cache.get(buffer_id)
            if hit is not None:
                return hit
        buf = None
        baseline = self.env.baseline_leaves(buffer_id)
        if baseline is not None:
            leaves, meta = baseline
        else:
            buf = self.env.runtime.catalog.acquire(buffer_id)
            try:
                with buf.lock:
                    if buf.tier == StorageTier.DEVICE:
                        leaves, meta = batch_to_host(buf.device_batch)
                    elif buf.tier == StorageTier.HOST:
                        leaves, meta = buf.host_leaves, buf.meta
                    else:
                        # decompresses a codec-spilled file, verifying
                        # the compressed image first (read_spilled_leaves)
                        from ..mem.stores import read_spilled_leaves
                        leaves, meta = read_spilled_leaves(
                            self.env.runtime.catalog, buf), buf.meta
                    if buf.tier != StorageTier.DEVICE:
                        try:
                            # raises a typed CorruptBuffer ->
                            # OP_GONE(corrupt) at the socket server
                            verify_buffer_leaves(self.env.runtime.catalog,
                                                 buf, leaves, site="serve")
                        except CorruptBuffer:
                            # the OWNER just learned its own stored copy
                            # rotted: drop that map output's statistics
                            # (and bump the epoch) so AQE never re-plans
                            # on sizes this buffer can no longer back
                            blk = self.env.catalog.block_for_buffer(
                                buffer_id)
                            if blk is not None:
                                self.env.map_stats.mark_lost(
                                    blk.shuffle_id, blk.map_id)
                            raise
            finally:
                self.env.runtime.catalog.release(buf)
        policy = self.env.integrity
        if policy.enabled \
                and self.env.catalog.checksums_for(buffer_id) is None:
            if buf is not None and buf.host_checksums is not None:
                sums = buf.host_checksums  # spill already digested them
            else:
                sums = policy.checksum_leaves(leaves)
            self.env.catalog.set_checksums(buffer_id, policy.algorithm,
                                           sums)
        if leaves and faults.INJECTOR.on_corruptible("writer"):
            # injected WRITER-side rot: the flip lands in the copy this
            # server will keep serving, AFTER its digests were recorded —
            # refetches keep failing until the reader escalates to a map
            # recompute.  Copy-swap: host leaves are read-only views.
            leaves = list(leaves)
            leaves[0] = faults.flip_bit(leaves[0])
        flow = self._flow()
        if flow is not None:
            # map-side serve window (policy/flow.py): bounded stall when
            # in-flight served bytes exceed the reduce-rate-driven
            # window — soft backpressure on the stager, never a deadlock
            flow.serve_acquire(buffer_id,
                               sum(int(a.nbytes) for a in leaves))
        evicted = None
        with self._lock:
            if len(self._cache) >= 4:  # bounded serving cache
                evicted = next(iter(self._cache))
                self._cache.pop(evicted)
            self._cache[buffer_id] = (leaves, meta)
        if evicted is not None and flow is not None:
            flow.serve_release(evicted)
        return leaves, meta

    def _flow(self):
        pol = getattr(self.env.runtime, "policy", None)
        return pol.flow_controller() if pol is not None else None

    def buffer_layout(self, buffer_id: int):
        leaves, meta = self._leaves(buffer_id)
        layout = [(a.shape, a.dtype.str, a.nbytes) for a in leaves]
        return layout, meta

    def buffer_checksums(self, buffer_id: int):
        """(algorithm, per-leaf digests) for a served buffer; populated by
        the _leaves call every layout request makes first."""
        return self.env.catalog.checksums_for(buffer_id)

    def compressed_layout(self, buffer_id: int,
                          codec_name: str) -> Optional[dict]:
        """Frame a buffer's leaves with the READER-requested codec and
        answer the negotiated wire contract: {codec, sizes, checksums,
        algorithm} — digests over the COMPRESSED frames, established
        right here at the compression boundary.  None when this process
        cannot encode the codec (the reader falls back to raw, counted):
        the typed negotiation miss, never an error."""
        leaves, _meta = self._leaves(buffer_id)
        entry = self._comp_cache.get(buffer_id, codec_name, leaves)
        return entry.descriptor() if entry is not None else None

    def copy_compressed_chunk(self, buffer_id: int, leaf_idx: int,
                              offset: int, length: int, dest: np.ndarray,
                              codec_name: str) -> None:
        """Stage one bounce-buffer chunk of a leaf's FRAMED form (the
        compressed analogue of copy_leaf_chunk)."""
        leaves, _ = self._leaves(buffer_id)
        entry = self._comp_cache.get(buffer_id, codec_name, leaves)
        if entry is None:
            # negotiation raced a codec going away (cannot happen in
            # practice: availability is static per process) — typed, so
            # the reader's ladder sees a clean buffer-gone
            raise KeyError(f"buffer {buffer_id} has no {codec_name} "
                           "compressed form")
        dest[:length] = entry.leaves[leaf_idx][offset:offset + length]

    def diagnose_buffer(self, buffer_id: int):
        """Writer-side half of the corruption-site diagnosis
        (SPARK-36206): re-hash the LIVE copy a refetch would serve and
        compare with the recorded digests.  writer_ok=False means the
        writer's own data rotted — the reader must recompute the map
        fragment, not refetch."""
        # a reader only asks for a diagnosis after ITS verify failed: if
        # the rot lives in our cached compressed frames (digested at
        # build time, so every re-serve fails identically), dropping the
        # entries here lets the refetch recompress from the raw leaves
        # and recover in ONE round instead of burning every refetch
        # attempt into a map-fragment recompute
        self._comp_cache.drop(buffer_id)
        policy = self.env.integrity
        rec = self.env.catalog.checksums_for(buffer_id)
        if not policy.enabled or rec is None:
            return None
        algo, recorded = rec
        if algo != policy.algorithm:
            return None
        try:
            leaves, _meta = self._leaves(buffer_id)
        except CorruptBuffer:
            # the serve-time verify itself tripped while re-reading the
            # buffer: the writer's stored copy is rotted, full stop
            return {"algorithm": algo,
                    "recorded": [int(s) for s in recorded],
                    "recomputed": None, "writer_ok": False}
        recomputed = policy.checksum_leaves(leaves)
        return {"algorithm": algo,
                "recorded": [int(s) for s in recorded],
                "recomputed": [int(s) for s in recomputed],
                "writer_ok": [int(s) for s in recomputed]
                             == [int(s) for s in recorded]}

    def copy_leaf_chunk(self, buffer_id: int, leaf_idx: int, offset: int,
                        length: int, dest: np.ndarray) -> None:
        leaves, _ = self._leaves(buffer_id)
        flat = np.ascontiguousarray(leaves[leaf_idx]).view(np.uint8).reshape(-1)
        dest[:length] = flat[offset:offset + length]

    def done_serving(self, buffer_id: int) -> None:
        with self._lock:
            self._cache.pop(buffer_id, None)
        self._comp_cache.drop(buffer_id)
        flow = self._flow()
        if flow is not None:
            # the reader's release is reduce-side consumption evidence
            # crossing the wire: it both frees the serve window and
            # feeds the consumption rate the window is derived from
            nb = flow.serve_release(buffer_id)
            if nb:
                flow.on_consumed(nb)

    def invalidate(self, buffer_ids) -> None:
        """Drop serving-cache entries for removed buffers: a fetch racing
        `remove_shuffle` must hit the catalog (and get the typed
        buffer-gone error), not a stale cache copy that silently outlives
        the shuffle."""
        with self._lock:
            for bid in buffer_ids:
                self._cache.pop(bid, None)
        self._comp_cache.invalidate(buffer_ids)
        flow = self._flow()
        if flow is not None:
            for bid in buffer_ids:
                flow.serve_release(bid)


class ShuffleEnv:
    """Per-executor shuffle wiring (GpuShuffleEnv equivalent)."""

    def __init__(self, runtime: TpuRuntime, conf: Optional[TpuConf] = None,
                 executor_id: str = "exec-0",
                 transport: Optional[ShuffleTransport] = None):
        self.runtime = runtime
        self.conf = conf or TpuConf()
        self.executor_id = executor_id
        self.device_resident = bool(self.conf.get(SHUFFLE_DEVICE_RESIDENT))
        self.catalog = ShuffleBufferCatalog()
        self.received = ShuffleReceivedBufferCatalog()
        # end-to-end integrity policy (mem/integrity.py): write paths
        # digest, every fetch/serve path verifies, mismatches run the
        # refetch/diagnose/recompute ladder in _fetch_remote
        self.integrity = policy_from_conf(self.conf,
                                          metrics=runtime.metrics)
        # wire compression policy (compress/): what this env's READS ask
        # peers for, and the chunk/min-size parameters its SERVER frames
        # with; spill compression is conf'd independently on the runtime
        from ..compress import compression_from_conf
        self.compression = compression_from_conf(self.conf,
                                                 metrics=runtime.metrics)
        self.max_refetch = max(0, int(self.conf.get(SHUFFLE_MAX_REFETCH)))
        self.verify_local = bool(
            self.conf.get(SHUFFLE_CHECKSUM_VERIFY_LOCAL))
        # observed per-reduce-partition output sizes, recorded at write
        # time — what adaptive re-planning (adaptive/) runs on
        from ..adaptive.stats import MapOutputTracker
        self.map_stats = MapOutputTracker()
        if transport is None:
            transport = self._resolve_transport()
        self.transport = transport
        # the transport's fetch-side compression/decompression metrics
        # land on this runtime's Metrics (shared transports aggregate
        # across their envs, exactly like transport counters do)
        tcomp = getattr(transport, "compression", None)
        if tcomp is not None and tcomp.metrics is None:
            tcomp.metrics = runtime.metrics
        self.server = ShuffleServer(self)
        transport.register_server(executor_id, self.server)
        # baseline (host-serialized) buffers share the buffer-id space with
        # spillable ones so the catalog + server treat both uniformly
        self._baseline_buffers: Dict[int, Tuple[list, object]] = {}
        self._shuffle_counter = [0]
        self._write_seq = [0]
        self._lock = threading.Lock()

    def _resolve_transport(self) -> ShuffleTransport:
        """Instantiate the conf-named transport class by reflection
        (spark.rapids.shuffle.transport.class; reference:
        RapidsConf.scala:505-510 + UCXShuffleTransport loading).  The pinned
        host pool conf sizes the transport's bounce-buffer staging area."""
        import importlib

        from ..config import (SHUFFLE_BOUNCE_CHUNK_SIZE,
                              SHUFFLE_BOUNCE_POOL_SIZE)
        name = str(self.conf.get(SHUFFLE_TRANSPORT_CLASS))
        mod_name, _, cls_name = name.rpartition(".")
        cls = getattr(importlib.import_module(mod_name), cls_name)
        # bounce geometry comes from the conf registry (single source of
        # truth, spark.rapids.shuffle.bounce.*); a configured pinned pool
        # still overrides the staging-pool size as before
        kwargs = {"max_inflight_bytes":
                  int(self.conf.get(SHUFFLE_MAX_RECV_INFLIGHT)),
                  "pool_size": int(self.conf.get(SHUFFLE_BOUNCE_POOL_SIZE)),
                  "chunk_size":
                  int(self.conf.get(SHUFFLE_BOUNCE_CHUNK_SIZE))}
        pinned = int(self.conf.get(PINNED_POOL_SIZE))
        if pinned > 0:
            kwargs["pool_size"] = pinned
        transport = cls(**kwargs)
        if hasattr(transport, "configure"):
            # retry/backoff/deadline knobs + fault-injection arming
            transport.configure(self.conf)
        return transport

    def baseline_leaves(self, buffer_id: int):
        with self._lock:
            return self._baseline_buffers.get(buffer_id)

    # ---- lifecycle ---------------------------------------------------------

    def new_shuffle_id(self) -> int:
        with self._lock:
            self._shuffle_counter[0] += 1
            return self._shuffle_counter[0]

    def rollback_received(self, shuffle_id: int, mark: int) -> None:
        """Free every remote buffer registered after `mark` (a failed
        fetch attempt's partial registrations — a retry would otherwise
        re-fetch and duplicate them in the pool while memory is
        tightest)."""
        for bid in self.received.drop_since(shuffle_id, mark):
            self.runtime.free_batch(bid)

    def remove_map_outputs(self, shuffle_id: int, map_lo: int,
                           map_hi: int) -> int:
        """Attempt-id guard: drop this executor's registered outputs for
        ONE map fragment (map ids in [map_lo, map_hi)) — buffers, serving
        cache, checksums and AQE statistics.  Called before a task re-run
        registers anything (so a retried or speculated attempt atomically
        supersedes a prior partial attempt on the same worker) and for a
        speculation loser's cleanup.  Returns the number of buffers
        dropped."""
        freed = self.catalog.remove_map_range(shuffle_id, map_lo, map_hi)
        if not freed:
            return 0
        # serving-cache eviction FIRST, same ordering as remove_shuffle:
        # a peer mid-stream must fall through to the catalog's typed
        # buffer-gone, not keep streaming a superseded attempt's bytes
        self.server.invalidate(freed)
        for bid in freed:
            with self._lock:
                if self._baseline_buffers.pop(bid, None) is not None:
                    continue
            self.runtime.free_batch(bid)
        self.map_stats.remove_map_range(shuffle_id, map_lo, map_hi)
        return len(freed)

    def remove_shuffle(self, shuffle_id: int) -> None:
        # the shuffle's map statistics go with its buffers — a long-lived
        # session would otherwise accumulate stats for every query it
        # ever ran (regression-tested in tests/test_adaptive.py)
        self.map_stats.remove_shuffle(shuffle_id)
        freed = self.catalog.remove_shuffle(shuffle_id)
        # evict serving-cache copies FIRST: a peer mid-stream on this
        # shuffle must fall through to the catalog and get the typed
        # buffer-gone frame, not keep streaming from a cache entry that
        # outlives the shuffle
        self.server.invalidate(freed)
        for bid in freed:
            with self._lock:
                if self._baseline_buffers.pop(bid, None) is not None:
                    continue
            self.runtime.free_batch(bid)
        for bid in self.received.remove_shuffle(shuffle_id):
            self.runtime.free_batch(bid)
        pol = getattr(self.runtime, "policy", None)
        if pol is not None:
            # drops next-use state AND settles wasted-prefetch accounting
            pol.shuffle_released(shuffle_id)

    # ---- write path (RapidsCachingWriter.write) ----------------------------

    def write_partition(self, shuffle_id: int, map_id: int, reduce_id: int,
                        batch: ColumnarBatch) -> None:
        block = ShuffleBlockId(shuffle_id, map_id, reduce_id)
        # map-output statistics: DATA bytes (live-row-proportional, so a
        # mostly-dead bucketed capacity does not read as a fat partition)
        # and rows.  split_by_partition stamps known_rows on every
        # sub-batch, so the common write path records without a device
        # sync; direct writers without the stamp pay one.  Recorded only
        # AFTER the buffer registers below — an OOM mid-write retries the
        # whole call, and recording first would double-count the attempt.
        nrows = batch.num_rows_host()
        nbytes = map_output_nbytes(batch.device_size_bytes(),
                                   batch.capacity, nrows)
        if self.device_resident:
            with self._lock:
                self._write_seq[0] += 1
                seq = self._write_seq[0]
            # oldest shuffle output spills first (SpillPriorities.scala)
            prio = (SpillPriorities.OUTPUT_FOR_SHUFFLE_INITIAL_PRIORITY
                    + float(seq))
            bid = self.runtime.add_batch(batch, prio)
            self.catalog.add_buffer(block, bid)
            pol = getattr(self.runtime, "policy", None)
            if pol is not None:
                # feeds victim scoring + proactive unspill: the buffer is
                # now known to be (shuffle, reduce) — dead once consumed,
                # prefetchable once an exchange declares its read order
                pol.note_shuffle_buffer(bid, shuffle_id, reduce_id, nbytes)
        else:
            from ..mem.buffer import fresh_buffer_id
            leaves, meta = batch_to_host(batch)
            bid = fresh_buffer_id()
            with self._lock:
                self._baseline_buffers[bid] = (leaves, meta)
            self.catalog.add_buffer(block, bid)
            if self.integrity.enabled:
                # host-serialized path: the host form exists right now,
                # so the per-block digest is established at WRITE time
                # (the device-resident path digests at first host
                # materialization instead — spill or first serve)
                self.catalog.set_checksums(
                    bid, self.integrity.algorithm,
                    self.integrity.checksum_leaves(leaves))
        self.map_stats.record(shuffle_id, map_id, reduce_id, nbytes, nrows)

    # ---- read path (RapidsCachingReader.read) ------------------------------

    def fetch_partition(self, shuffle_id: int, reduce_id: int,
                        remote_peers: Optional[List[str]] = None,
                        map_range: Optional[tuple] = None
                        ) -> Iterator[ColumnarBatch]:
        """Local blocks from the catalog; remote blocks via transport.
        `map_range=(lo, hi)` restricts the read to blocks written by map
        ids in [lo, hi) — the skew-join slice fetch
        (PartialReducerPartitionSpec, adaptive/stats.py)."""
        from ..metrics.journal import journal_event
        journal_event("fetch", "fetchPartition", shuffle=shuffle_id,
                      reduce=reduce_id, executor=self.executor_id,
                      remote_peers=len(remote_peers or []),
                      map_range=list(map_range) if map_range else None)
        for block in self.catalog.blocks_for_reduce(shuffle_id, reduce_id):
            if map_range is not None \
                    and not map_range[0] <= block.map_id < map_range[1]:
                continue
            for bid in self.catalog.buffers_for(block):
                baseline = self.baseline_leaves(bid)
                if baseline is not None:
                    leaves, meta = baseline
                    if self.verify_local:
                        self._verify_local_read(bid, leaves)
                    self.runtime.reserve(meta.size_bytes,
                                         site="fetch_baseline")
                    yield host_to_batch(leaves, meta)
                else:
                    # spilled tiers verify inside the runtime's
                    # materialize path (mem/runtime.py) under the spill
                    # policy; device-resident batches never left HBM
                    yield self.runtime.get_batch(bid)
        for peer in remote_peers or []:
            yield from self._fetch_remote(peer, shuffle_id, reduce_id,
                                          map_range)

    def _verify_local_read(self, bid: int, leaves) -> None:
        """verifyOnLocalRead: check a local baseline buffer against its
        write-time digest (a local read never crossed a wire, so a
        mismatch is this executor's own memory — classified `reader`)."""
        from ..metrics.journal import journal_event
        rec = self.catalog.checksums_for(bid)
        if not self.integrity.enabled or rec is None \
                or rec[0] != self.integrity.algorithm:
            return
        bad = self.integrity.verify_leaves(leaves, rec[1])
        if bad is None:
            return
        leaf, want, got = bad
        self.runtime.metrics.add(MN.NUM_CHECKSUM_MISMATCHES, 1)
        journal_event("corruption", "localReadMismatch", buffer=bid,
                      leaf=leaf, classification="reader",
                      expected=want, computed=got)
        raise CorruptShuffleBlock(
            f"local read of buffer {bid} leaf {leaf} failed "
            f"{self.integrity.algorithm} verification",
            buffer_id=bid, leaf=leaf, site="reader", expected=want,
            computed=got)

    def fetch_partitions_async(self, shuffle_id: int, reduce_ids,
                               remote_peers: Optional[List[str]] = None):
        """Pipelined multi-partition read: fetch of partition k+1 overlaps
        consumption of partition k, bounded by maxReceiveInflightBytes
        (shuffle/fetch.py; reference RapidsShuffleIterator.scala:17-258)."""
        from ..config import OOM_RETRY_MAX
        from .fetch import AsyncFetchIterator
        pol = getattr(self.runtime, "policy", None)
        return AsyncFetchIterator(
            self, shuffle_id, reduce_ids, remote_peers,
            int(self.conf.get(SHUFFLE_MAX_RECV_INFLIGHT)),
            oom_retries=int(self.conf.get(OOM_RETRY_MAX)),
            flow=pol.flow_controller() if pol is not None else None)

    def _fetch_remote(self, peer: str, shuffle_id: int, reduce_id: int,
                      map_range: Optional[tuple] = None
                      ) -> Iterator[ColumnarBatch]:
        """doFetch (RapidsShuffleClient.scala:350-770): wildcard metadata
        request discovers the peer's blocks for this reduce partition, then
        per-buffer receives register spillable buffers locally.  Everything
        goes through the transport SPI — no peer-object introspection.

        Integrity escalation ladder (SPARK-35275/36206 analogue, see
        docs/tuning-guide.md): a checksum mismatch runs the writer-side
        diagnosis and, for transit corruption, refetches up to
        `maxRefetchAttempts`; writer-side rot, a vanished buffer, or a
        dead/exhausted peer raises a typed FetchFailed that marks the map
        output lost so the cluster recomputes the fragment.

        Tracing: the whole remote read runs inside a `fetch` SPAN named
        fetchRemote, and that span's id becomes the `span` field of the
        trace context stamped on every wire request it issues — so the
        peer's serve record names THIS fetch span exactly and the merged
        timeline can flow-link the two (metrics/timeline.py)."""
        from ..metrics.journal import (active_journal, current_trace,
                                       trace_context)
        journal = active_journal()
        span_id = None
        if journal is not None:
            base = current_trace() or (None, None, None, None)
            span_id = journal.begin(
                "fetch", "fetchRemote", peer=peer, shuffle=shuffle_id,
                reduce=reduce_id, executor=self.executor_id,
                query=base[0], stage=base[1],
                map_range=list(map_range) if map_range else None)
        fetched_bytes = 0
        n_buffers = 0

        def on_wire(fn):
            # trace installed ONLY around non-yielding wire calls: a
            # with-block spanning a generator's yields would leak the
            # context into whatever the consumer runs between pulls
            with trace_context(span=span_id, executor=self.executor_id):
                return fn()

        try:
            try:
                tcomp = getattr(self.transport, "compression", None)
                client = self.transport.make_client(peer)
                if tcomp is None or not tcomp.enabled:
                    # roofline-driven re-selection (policy/codec.py): a
                    # session WITHOUT configured wire compression rides
                    # the advised codec through the same negotiation;
                    # clients are per-fetch objects, so the override
                    # never leaks past this read
                    pol = getattr(self.runtime, "policy", None)
                    if pol is not None \
                            and pol.wire_codec(shuffle_id) is not None:
                        client.compression_override = \
                            pol.codec.reader_policy()
                        tcomp = client.compression_override
                resp = on_wire(lambda: client.fetch_metadata(
                    MetadataRequest(
                        shuffle_id=shuffle_id, reduce_id=reduce_id,
                        map_lo=map_range[0] if map_range else None,
                        map_hi=map_range[1] if map_range else None,
                        codec=tcomp.codec_name
                        if tcomp is not None and tcomp.enabled
                        else None)))
            except (ConnectionError, OSError, KeyError) as e:
                raise self._map_output_lost(peer, shuffle_id,
                                            reduce_id, "peer", e)
            for bm in resp.block_metas:
                for bid in bm.buffer_ids:
                    leaves, meta = on_wire(
                        lambda b=bid: self._fetch_buffer_verified(
                            client, peer, shuffle_id, reduce_id, b))
                    try:
                        on_wire(lambda b=bid: client.release_buffer(b))
                    except (ConnectionError, OSError) as e:
                        # the data already arrived verified; a failed
                        # release only delays the peer's cache eviction
                        log.info("release of buffer %d at %s failed: %r",
                                 bid, peer, e)
                    batch = host_to_batch(leaves, meta)
                    fetched_bytes += meta.size_bytes
                    n_buffers += 1
                    rid = self.runtime.add_batch(batch)
                    self.received.add(shuffle_id, rid)
                    yield self.runtime.get_batch(rid)
        finally:
            if journal is not None:
                journal.end(span_id, buffers=n_buffers,
                            bytes=fetched_bytes)

    def _fetch_buffer_verified(self, client, peer: str, shuffle_id: int,
                               reduce_id: int, bid: int):
        """One buffer through the corruption-recovery ladder."""
        from ..metrics.journal import journal_event
        attempts = self.max_refetch + 1
        for attempt in range(attempts):
            try:
                return client.fetch_buffer(bid)
            except BufferGone as e:
                raise self._map_output_lost(peer, shuffle_id, reduce_id,
                                            "gone", e)
            except CorruptShuffleBlock as e:
                self.runtime.metrics.add(MN.NUM_CHECKSUM_MISMATCHES, 1)
                classification = e.site if e.site in ("writer", "reader") \
                    else self._diagnose(client, bid)
                journal_event("corruption", "checksumMismatch", peer=peer,
                              shuffle=shuffle_id, reduce=reduce_id,
                              buffer=bid, leaf=e.leaf, path=e.site,
                              classification=classification,
                              expected=e.expected, computed=e.computed)
                log.warning(
                    "corrupt shuffle block from %s (buffer %d leaf %s, "
                    "classified %s, attempt %d/%d): %s", peer, bid,
                    e.leaf, classification, attempt + 1, attempts, e)
                if classification == "writer" or attempt + 1 >= attempts:
                    raise self._map_output_lost(peer, shuffle_id,
                                                reduce_id, classification,
                                                e)
                self.runtime.metrics.add(MN.NUM_CORRUPTION_REFETCHES, 1)
                journal_event("refetch", "corruptionRefetch", peer=peer,
                              buffer=bid, attempt=attempt + 1,
                              classification=classification)
            except (ConnectionError, OSError) as e:
                # the transport already burned its own socket retries; a
                # peer that still cannot serve is as good as dead
                raise self._map_output_lost(peer, shuffle_id, reduce_id,
                                            "peer", e)
        raise AssertionError("unreachable")  # pragma: no cover

    def _diagnose(self, client, bid: int) -> str:
        """Classify a reader-detected mismatch with the writer-side
        re-hash: writer (its live data no longer matches its recorded
        digest) vs wire (writer data fine -> corruption was in transit)."""
        diag = getattr(client, "diagnose_buffer", None)
        result = diag(bid) if diag is not None else None
        if result is None:
            return "wire"  # no writer evidence; transit is the default
        return "wire" if result.get("writer_ok", True) else "writer"

    def _map_output_lost(self, peer: str, shuffle_id: int, reduce_id: int,
                         classification: str, cause) -> FetchFailed:
        """Mark a peer's map output lost and build the typed FetchFailed:
        bumps the tracker epoch so any AQE statistics captured from the
        pre-loss map stage are invalidated (re-plan rules never act on a
        dead map stage), counts numLostMapOutputs, and journals the
        recompute trigger.

        Epoch-only on THIS tracker by design: the lost records live in
        the PEER's tracker, which the reader cannot reach through the
        transport SPI — ProcCluster recovery replaces the peer process
        (its tracker dies with it, so post-recompute re-aggregation is
        clean), and an owner that detects its OWN rot drops the records
        itself via `mark_lost` on the serve path (_leaves)."""
        from ..metrics.journal import journal_event
        self.runtime.metrics.add(MN.NUM_LOST_MAP_OUTPUTS, 1)
        self.map_stats.bump_epoch()
        journal_event("recompute", "mapOutputLost", peer=peer,
                      shuffle=shuffle_id, reduce=reduce_id,
                      classification=classification, cause=repr(cause))
        log.error("map output lost: shuffle %d reduce %d at %s (%s): %r",
                  shuffle_id, reduce_id, peer, classification, cause)
        return FetchFailed(
            f"shuffle {shuffle_id} reduce {reduce_id} fetch from {peer} "
            f"failed unrecoverably ({classification}): {cause}",
            peer=peer, shuffle_id=shuffle_id, reduce_id=reduce_id,
            classification=classification)


def map_output_nbytes(device_size_bytes: int, capacity: int,
                      nrows: int) -> int:
    """Map-output-statistics DATA bytes of one written sub-batch:
    live-row-proportional, so a mostly-dead bucketed capacity does not
    read as a fat partition.  ONE formula for both shuffle tiers — the
    socket write path calls it with a real sub-batch's footprint, the
    mesh tier (shuffle/mesh_exchange.py) with the synthetic footprint of
    the sub-batch `split_by_partition` WOULD build — so AQE rules see
    bit-identical statistics wherever the exchange ran (capacities are
    power-of-two buckets, so the division is exact in float)."""
    cap = max(capacity, 1)
    return int(device_size_bytes * min(nrows, cap) / cap)


def get_shuffle_env(runtime: TpuRuntime, conf: TpuConf) -> ShuffleEnv:
    """Lazily attach one ShuffleEnv to a runtime (executor singleton)."""
    env = getattr(runtime, "_shuffle_env", None)
    if env is None:
        env = ShuffleEnv(runtime, conf)
        runtime._shuffle_env = env
    return env
