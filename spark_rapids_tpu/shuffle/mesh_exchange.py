"""Mesh-native generic exchange: the ICI lowering of TpuShuffleExchangeExec.

ROADMAP item 1.  The socket tier pays D2H -> wire (~1 GB/s loopback per
BENCH_WIRE) -> H2D for every generic exchange even when every
participating partition lives on devices of ONE jax Mesh — exactly the
data-movement tax the reference's UCX shuffle plugin exists to eliminate.
This module keeps the data in device memory instead: when the exchange's
producer and consumer are co-resident on a mesh (single process,
hash/round_robin/single partitioning, `spark.rapids.sql.tpu.shuffle.ici
.enabled`), the map phase runs as ONE compiled `shard_map` program per
map batch — fused row-local chain + partition-id compute + a quota-block
`all_to_all` (parallel/distributed.exchange_partition_step, built on the
same `exchange_compact`/`exchange_by_bucket` primitives every SPMD
operator rides) — and the reduce phase serves per-partition sub-batches
by splitting the mesh-resident exchanged chunks on device.

Contract parity with the socket tier (tests/test_mesh_exchange.py pins
all of it down):

  * **results** are bit-for-bit identical: within one map task the
    compact exchange preserves original row order per partition (stable
    sort by destination, shards are contiguous row ranges), so partition
    p reads as the same rows in the same order either tier serves them;
  * **AQE map statistics** are bit-identical: per-destination live-row
    counts come back FROM the collective program (a psum'd bincount), and
    bytes use the one shared `manager.map_output_nbytes` formula over the
    synthetic footprint of the sub-batch `split_by_partition` would
    build — so every adaptive rule sees the same numbers on either tier;
  * **memory pressure** re-enters the standard ladder: each collective
    dispatch reserves pool space (site ``exchange.collective``) inside a
    retryable block (RetryOOM -> spill/retry/split); exhaustion
    DE-LOWERS the whole exchange to the socket tier (counted in the
    transport's ``socket_fallbacks``), replaying the already-drained
    child batches — never wrong, at worst slower;
  * the kill switch (`shuffle.ici.enabled=false`) leaves the socket path
    byte-identical to the pre-mesh behavior, integrity/compression
    ladder untouched.
"""
from __future__ import annotations

import itertools
import threading
from typing import Dict, List, Optional

import numpy as np

from ..columnar import ColumnarBatch, bucket_rows, concat_batches
from ..metrics import names as MN
from ..metrics.journal import journal_span
from ..parallel.distributed import (DATA_AXIS, default_quota,
                                    exchange_partition_step)
from ..parallel.mesh import shard_batch
from .manager import map_output_nbytes
from .partition import split_by_partition

_SID_LOCK = threading.Lock()
_SID = [0]


def _next_sid() -> int:
    with _SID_LOCK:
        _SID[0] += 1
        return _SID[0]


def _row_width(batch: ColumnarBatch) -> int:
    """Static bytes per capacity row — chosen so that a sub-batch of
    capacity `cap` taken from `batch` has device_size_bytes() == cap * w
    EXACTLY (sel byte + per fixed column data+valid + per string column
    max_len+valid+lengths).  The mesh tier's map statistics are computed
    from device-side counts against this synthetic footprint through the
    shared map_output_nbytes formula, so they equal the socket tier's."""
    w = 1  # selection mask
    for c in batch.columns:
        if c.dtype.is_string:
            w += c.max_len + 1 + 4
        else:
            w += c.data.dtype.itemsize + 1
    return w


class MeshShuffleHandle:
    """A materialized MESH-tier shuffle stage: exchanged chunks (one per
    map task) sit sharded in device memory with their partition ids
    carried as a trailing column, and observed map-output statistics are
    available for adaptive re-planning.  Mirrors `_ShuffleHandle`'s
    route/stats/fetch/release surface (exec/exchange.py) so the read
    side, the AQE rules and the coalesced shuffle reader drive both
    tiers through one interface."""

    is_mesh = True

    def __init__(self, num_partitions: int, schema, n_devices: int = 0):
        from ..adaptive.stats import MapOutputTracker
        self.sid = _next_sid()
        self.num_partitions = num_partitions
        self.schema = schema
        self.n_devices = n_devices
        self.tracker = MapOutputTracker()
        self._chunks: List[ColumnarBatch] = []  # exchanged, +__ici_pid__
        self._chunk_counts: List[np.ndarray] = []
        self._parts: Dict[int, dict] = {}       # chunk -> {p: sub_batch}
        self._released = False

    # -- write side ----------------------------------------------------------

    def add_chunk(self, ex: ColumnarBatch, counts: np.ndarray) -> int:
        """Register one map task's exchanged output and record its map
        statistics from the DEVICE-computed per-partition live counts."""
        map_id = len(self._chunks)
        self._chunks.append(ex)
        self._chunk_counts.append(counts)
        w = _row_width(self._strip(ex))
        for p in range(self.num_partitions):
            cnt = int(counts[p])
            if cnt == 0:
                continue
            pcap = bucket_rows(cnt, 1024)
            self.tracker.record(self.sid, map_id, p,
                                map_output_nbytes(pcap * w, pcap, cnt),
                                cnt)
        return map_id

    # -- the _ShuffleHandle surface ------------------------------------------

    def map_epoch(self) -> int:
        return self.tracker.epoch

    def stats(self):
        return self.tracker.stats(self.sid, self.num_partitions)

    def fetch(self, p: int, map_range=None) -> List[ColumnarBatch]:
        """Partition p's sub-batches (one per contributing map task, in
        map order), split ON DEVICE from the mesh-resident exchanged
        chunks.  `map_range=(lo, hi)` restricts to map tasks in range —
        the AQE skew-slice read, map ids being chunk indexes here."""
        lo, hi = (0, len(self._chunks)) if map_range is None else map_range
        out: List[ColumnarBatch] = []
        for m in range(int(lo), min(int(hi), len(self._chunks))):
            sub = self._split(m).get(p)
            if sub is not None:
                out.append(sub)
        return out

    def _split(self, m: int) -> dict:
        """Per-partition sub-batches of chunk m, split once and cached:
        one stable device sort by partition id + one host count sync,
        amortized over every partition this chunk serves (the device
        twin of the socket tier's write-side split).

        Float columns cross the split BITCAST to same-width unsigned
        ints: the gathers here run EAGERLY over the mesh-sharded
        exchanged batch, and XLA:CPU's cross-shard data movement routes
        float elements through fast-math arithmetic that flushes
        denormals and quiets signaling-NaN payloads (measured — the
        compiled all_to_all itself is bit-exact).  Integer lanes are
        exact on every backend, and the bitcasts are free, so the mesh
        tier stays bit-for-bit with the socket tier's host-memcpy path
        for every float value including the pathological ones."""
        cached = self._parts.get(m)
        if cached is None:
            ex = self._chunks[m]
            if self.num_partitions == self.n_devices:
                # the common mesh-native shape (one reduce partition per
                # device): the owner mapping is the identity, so
                # partition p IS device p's shard of the exchanged batch
                # — zero-copy per-device views, no sort, no gather
                cached = self._split_by_shard(m, ex)
            else:
                pids = ex.columns[-1].data
                armored = _bitcast_floats_to_uint(self._strip(ex))
                cached = {
                    p: _bitcast_floats_back(sub, self.schema)
                    for p, sub in split_by_partition(
                        armored, pids, self.num_partitions)}
            self._parts[m] = cached
        return cached

    def _split_by_shard(self, m: int, ex: ColumnarBatch) -> dict:
        """num_partitions == n_devices fast path: per-device addressable
        shards of every leaf ARE the per-partition sub-batches (live
        rows flagged by the shard's selection mask, in map-original
        order — same order the socket tier serves)."""
        from ..columnar import Column

        def shards_of(arr):
            byrow = sorted(ex_shards(arr), key=lambda s: s[0])
            return [a for _start, a in byrow]

        def ex_shards(arr):
            for sh in arr.addressable_shards:
                idx = sh.index[0] if sh.index else slice(0, 0)
                yield (idx.start or 0), sh.data

        base = self._strip(ex)
        col_shards = []
        for c in base.columns:
            data = shards_of(c.data)
            valid = shards_of(c.valid)
            lengths = (shards_of(c.lengths)
                       if c.lengths is not None else None)
            col_shards.append((data, valid, lengths))
        sel = shards_of(base.sel)
        counts = self._chunk_counts[m]
        out = {}
        for p in range(self.num_partitions):
            cnt = int(counts[p])
            if cnt == 0:
                continue
            cols = [Column(d[p], v[p], c.dtype,
                           ln[p] if ln is not None else None)
                    for (d, v, ln), c in zip(col_shards, base.columns)]
            sub = ColumnarBatch(cols, sel[p], self.schema)
            sub.known_rows = cnt
            out[p] = sub
        return out

    def _strip(self, ex: ColumnarBatch) -> ColumnarBatch:
        """Drop the trailing __ici_pid__ routing column."""
        return ColumnarBatch(list(ex.columns[:-1]), ex.sel, self.schema)

    def release(self) -> None:
        if self._released:
            return
        self._released = True
        self.tracker.remove_shuffle(self.sid)
        self._chunks = []
        self._parts = {}


def _bitcast_floats_to_uint(batch: ColumnarBatch) -> ColumnarBatch:
    """Float column data viewed as same-width unsigned ints (dtype
    METADATA untouched — only the device array changes); see _split."""
    import jax
    import jax.numpy as jnp
    from ..columnar import Column
    uint_of = {4: jnp.uint32, 8: jnp.uint64}
    cols = []
    for c in batch.columns:
        if not c.dtype.is_string and c.data.dtype.kind == "f":
            u = jax.lax.bitcast_convert_type(
                c.data, uint_of[c.data.dtype.itemsize])
            cols.append(Column(u, c.valid, c.dtype))
        else:
            cols.append(c)
    return ColumnarBatch(cols, batch.sel, batch.schema)


def _bitcast_floats_back(batch: ColumnarBatch, schema) -> ColumnarBatch:
    """Undo _bitcast_floats_to_uint after the gathers: restore each
    float column's device array from its uint view (exact, elementwise
    — no cross-shard movement, so no fast-math in the path)."""
    import jax
    import jax.numpy as jnp
    from ..columnar import Column
    # width-matched restore: on an x64-less backend a "float64" column's
    # device array is really float32, so follow the ARRAY's width
    float_of = {4: jnp.float32, 8: jnp.float64}
    cols = []
    for c in batch.columns:
        if not c.dtype.is_string and c.data.dtype.kind == "u" \
                and c.dtype.np_dtype is not None \
                and c.dtype.np_dtype.kind == "f":
            f = jax.lax.bitcast_convert_type(
                c.data, float_of[c.data.dtype.itemsize])
            cols.append(Column(f, c.valid, c.dtype))
        else:
            cols.append(c)
    out = ColumnarBatch(cols, batch.sel, schema)
    out.known_rows = batch.known_rows
    return out


def lower_exchange(exchange, ctx, mesh):
    """Run the exchange's write phase as jitted ICI collectives over
    `mesh`.  Returns ``(handle, None)`` on success, or ``(None,
    batches)`` after a de-lower — the collective retry ladder exhausted
    on some chunk, and `batches` replays the already-drained child
    output (plus the untouched remainder of the iterator) into the
    socket tier's write phase so no child work re-executes.

    One map task per child batch, exactly like the socket tier, so map
    ids — and therefore the per-map statistics AQE's skew rule slices
    on — line up across tiers."""
    import jax.numpy as jnp

    from .. import config as C
    from ..exec.retryable import run_retryable
    from ..mem.retry import RetryExhausted, split_batch_rows
    from ..metrics.journal import journal_event
    from ..ops import expressions as PE
    from ..utils.kernel_cache import (expr_key, param_free_keys,
                                      record_dispatch, schema_key,
                                      stage_executable)

    n_dev = mesh.shape[DATA_AXIS]
    n_parts = exchange.num_partitions
    use_allgather = bool(ctx.conf.get(C.MESH_USE_ALLGATHER))
    fused_stage = exchange._fused_stage_child(ctx)
    if fused_stage is not None:
        source = fused_stage.children[0]
        can_split = fused_stage._can_split()
    else:
        source = exchange.children[0]
        can_split = True
    # plan-cache parameters may live in the fused chain AND the partition
    # key expressions; both bind as a trailing traced argument so the
    # value-free key replays one compiled collective across literal
    # variants (same contract as the socket tier's bucketing fusion)
    p_exprs = list(exchange.keys)
    if fused_stage is not None:
        p_exprs = fused_stage.expressions() + p_exprs
    params = PE.collect_parameters(p_exprs)
    with param_free_keys():
        # EVERY expression-derived component builds inside this scope —
        # a plan-cache Parameter keyed by value here would make each
        # literal variant recompile the collective (the values thread as
        # a traced argument below instead).  schema_key matters beyond
        # hygiene: input_signature alone cannot tell apart logical
        # dtypes sharing one device representation (date vs int32,
        # timestamp vs int64), and an AOT executable compiled for one
        # pytree REJECTS the other
        pre_key = (fused_stage.kernel_key() if fused_stage is not None
                   else None)
        base_key = ("ici_exchange", exchange.mode, n_parts, n_dev,
                    use_allgather, mesh, pre_key,
                    schema_key(source.schema),
                    tuple(expr_key(k) for k in exchange.keys))
    pvals = None
    slots = None
    if params:
        base_key += ("params", PE.parameter_signature(params))
        pvals = PE.parameter_values(params)
        slots = [p.slot for p in params]

    handle = MeshShuffleHandle(n_parts, exchange.schema,
                               n_devices=n_dev)
    quota_by_cap: Dict[int, int] = {}
    metrics = exchange.metrics
    batches = source.execute(ctx)
    drained: List[ColumnarBatch] = []

    def pid_builder(quota):
        def build():
            pre = fused_stage.batch_fn() if fused_stage is not None \
                else None
            return exchange_partition_step(
                mesh, n_parts, _pid_fn(exchange), quota, pre=pre,
                param_slots=slots, use_allgather=use_allgather)
        return build

    def exchange_chunk(b: ColumnarBatch, map_id: int):
        if ctx.runtime is not None:
            est = (fused_stage._reserve_estimate(b)
                   if fused_stage is not None else b.device_size_bytes())
            ctx.runtime.reserve(3 * est, site="exchange.collective")
        if b.capacity % n_dev != 0 or b.capacity < n_dev:
            # bucket capacities are powers of two >= 1024, so this only
            # fires for hand-built odd capacities; re-bucket to shard
            b = concat_batches(
                [b], capacity=max(bucket_rows(max(b.num_rows_host(), 1)),
                                  n_dev))
        local_cap = b.capacity // n_dev
        sharded = shard_batch(b, mesh)
        quota = quota_by_cap.get(local_cap)
        if quota is None:
            quota = default_quota(local_cap, n_dev)
        while True:
            args = (sharded, jnp.int32(map_id))
            if pvals is not None:
                args += (pvals,)
            fn = stage_executable(base_key + (local_cap, quota),
                                  pid_builder(quota), args,
                                  metrics=metrics, name="iciExchange")
            with metrics.timer(MN.COLLECTIVE_TIME), \
                    journal_span("collective", "iciExchange",
                                 shuffle=handle.sid, map=map_id,
                                 devices=n_dev, quota=quota):
                record_dispatch()
                with mesh:
                    ex, overflow, counts = fn(*args)
            if use_allgather or int(overflow) == 0:
                break
            if quota >= local_cap:  # pragma: no cover - cap always fits
                raise AssertionError(
                    "exchange overflow with quota == local capacity")
            quota = min(local_cap, quota * 2)
        quota_by_cap[local_cap] = quota
        handle.add_chunk(ex, np.asarray(counts))  # tpulint: disable=TPU001 the ONE host sync per map task: the device-computed per-partition counts become AQE map statistics, same boundary sync split_by_partition pays on the socket tier
        return 1

    try:
        with metrics.timer(MN.SHUFFLE_WRITE_TIME):
            for map_id, batch in enumerate(batches):
                drained.append(batch)

                def attempt(b, map_id=map_id):
                    return exchange_chunk(b, map_id)

                run_retryable(ctx, metrics, "exchangeCollective", attempt,
                              [batch],
                              split=split_batch_rows if can_split
                              else None)
    except RetryExhausted:
        # de-lower: the socket tier replays the drained batches (and
        # whatever the source iterator still holds); the partial mesh
        # handle is dropped, nothing was registered outside it
        handle.release()
        journal_event("fallback", exchange.name,
                      reason="collective_retry_exhausted",
                      shuffle=handle.sid)
        _count_tier(ctx, "socket_fallbacks")
        return None, itertools.chain(drained, batches)
    if fused_stage is not None:
        # counted on SUCCESS only: a de-lower replays through
        # _write_phase, which counts the same fused stage itself
        from ..metrics import names as MNN
        fused_stage.metrics.add(MNN.NUM_FUSED_STAGES, 1)
    _count_tier(ctx, "ici_exchanges")
    return handle, None


def _pid_fn(exchange):
    """Traced per-row partition ids of one DEVICE shard: `offset` is the
    shard's global row position plus the map task's round-robin start, so
    position-based modes match the socket tier's whole-batch ids."""
    from .partition import hash_partition_ids, single_partition_ids
    mode = exchange.mode
    n = exchange.num_partitions
    keys = exchange.keys

    def pid_fn(local, offset):
        import jax.numpy as jnp
        if n == 1 or mode == "single":
            return single_partition_ids(local.capacity)
        if mode == "hash":
            return hash_partition_ids([e.eval(local) for e in keys], n)
        iota = jnp.arange(local.capacity, dtype=jnp.int32)  # round robin
        return (iota + offset) % jnp.int32(n)

    return pid_fn


def _count_tier(ctx, key: str) -> None:
    """Tier-selection counters live on the session's shuffle transport
    (`transport_counters`/`session_observability` satellite): the mesh
    tier moves no bytes through it, but the SELECTION is transport-level
    observability — which tier served each exchange, and why."""
    if ctx.runtime is None:
        return
    from .manager import get_shuffle_env
    env = get_shuffle_env(ctx.runtime, ctx.conf)
    count = getattr(env.transport, "count", None)
    if count is not None:
        count(key)


def ici_mesh_for(exchange, ctx) -> Optional[object]:
    """The mesh this exchange's collective lowering would run over, or
    None when the socket tier must serve it.  The planner's distribute
    pass stamps `ici_mesh` on every generic exchange it leaves in a mesh
    plan (plan/transitions.mark_ici_exchanges — re-run by AQE `_replan`
    so rule-created exchanges get the same, idempotent decision); an
    unstamped exchange re-resolves from conf so adaptive rewrites can
    never silently drop the lowering.

    Socket-tier forcers: the kill switch, a multi-executor cluster (the
    partitions are NOT co-resident — the socket path is the cross-host
    tier, integrity/compression ladder untouched), range partitioning
    (bounds sampling needs the materialized child output), and a missing
    / too-small device mesh."""
    from .. import config as C
    if exchange.mode == "range":
        return None
    if ctx.cluster is not None:
        return None
    if not ctx.conf.get(C.ICI_SHUFFLE_ENABLED):
        return None
    mesh = getattr(exchange, "ici_mesh", None)
    if mesh is None and ctx.runtime is not None:
        # the session transport resolved the mesh once at configure()
        # (shuffle/ici.py) — prefer that settled capability
        env = getattr(ctx.runtime, "_shuffle_env", None)
        if env is not None:
            mesh = getattr(env.transport, "mesh", None)
    if mesh is None:
        from ..exec.distributed import resolve_mesh
        mesh = resolve_mesh(ctx.conf)
    return mesh
