"""Cross-process shuffle transport over TCP sockets (the DCN wire).

TPU-native analogue of the reference's UCX network stack
(shuffle-plugin/.../ucx/UCX.scala:54-533 — endpoint bring-up + tagged
sends over a management-port handshake; UCXShuffleTransport.scala:47-507 —
client/server factory, bounce-buffer pools, inflight throttle).  On TPU
pods the *intra-query* exchange rides ICI collectives inside the mesh
program (shuffle/ici.py); this socket transport is the host-side DCN path
between executor PROCESSES — the role UCX-over-IB plays for the reference —
so shuffle bytes genuinely cross a process/host boundary.

Wire protocol: length-prefixed frames `u32 length | u8 opcode | payload`.
Control payloads (metadata request/response, buffer layouts) are pickled
dataclasses — this is a Python-to-Python control plane, the analogue of
the reference's flatbuffers messages (shuffle-plugin/.../fbs).  Data moves
as raw frames in bounce-buffer-sized chunks: the serving side stages every
chunk through its BounceBufferPool slice before the socket send, and the
receiving side caps concurrent fetch bytes with the InflightThrottle, so
both ends keep the reference's flow-control structure on a real wire.

The same port also carries a tiny RPC opcode used by the worker control
plane (shuffle/worker.py) — the analogue of UCX's management port.
"""
from __future__ import annotations

import os
import pickle
import socket
import struct
import threading
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from .transport import (BounceBufferPool, InflightThrottle, MetadataRequest,
                        MetadataResponse, ShuffleTransport,
                        ShuffleTransportClient)

# opcodes
OP_META, OP_META_RESP = 1, 2
OP_LAYOUT, OP_LAYOUT_RESP = 3, 4
OP_FETCH, OP_DATA, OP_END = 5, 6, 7
OP_DONE, OP_ACK = 8, 9
OP_FETCH_SHM = 10
# same-host segment path prefix; the server refuses to open anything else
SHM_PREFIX = "/dev/shm/srtpu_shm_"
OP_RPC, OP_RPC_RESP, OP_RPC_ERR = 20, 21, 22

_HDR = struct.Struct(">IB")


def send_frame(sock: socket.socket, op: int, payload) -> None:
    """payload: bytes-like (memoryview over a bounce slice for data)."""
    sock.sendall(_HDR.pack(len(payload), op))
    if len(payload):
        sock.sendall(payload)


def _recv_exact(sock: socket.socket, n: int) -> bytearray:
    buf = bytearray(n)
    view = memoryview(buf)
    got = 0
    while got < n:
        r = sock.recv_into(view[got:], n - got)
        if r == 0:
            raise ConnectionError("peer closed mid-frame")
        got += r
    return buf


def recv_frame(sock: socket.socket) -> Tuple[int, bytes]:
    hdr = _recv_exact(sock, _HDR.size)
    length, op = _HDR.unpack(hdr)
    payload = _recv_exact(sock, length) if length else b""
    return op, bytes(payload)


def recv_frame_into(sock: socket.socket, dest: np.ndarray, offset: int
                    ) -> Tuple[int, int]:
    """Receive one frame; DATA payload lands directly in dest[offset:].
    Returns (opcode, payload_length)."""
    hdr = _recv_exact(sock, _HDR.size)
    length, op = _HDR.unpack(hdr)
    if op != OP_DATA:
        payload = _recv_exact(sock, length) if length else b""
        return op, len(payload)
    view = memoryview(dest)[offset:offset + length]
    got = 0
    while got < length:
        r = sock.recv_into(view[got:], length - got)
        if r == 0:
            raise ConnectionError("peer closed mid-data")
        got += r
    return op, length


class ShuffleSocketServer:
    """Serves one executor's shuffle buffers on a TCP port.

    Each accepted connection gets a handler thread (the reference's UCX
    progress thread pool; RapidsShuffleServer.scala:67-150).  Data chunks
    are staged through the transport's BounceBufferPool before each send,
    so serving a spilled buffer never inflates memory beyond the pool."""

    def __init__(self, transport: "SocketTransport", server_obj,
                 rpc_handler: Optional[Callable] = None,
                 host: str = "127.0.0.1", port: int = 0):
        self.transport = transport
        self.server_obj = server_obj
        self.rpc_handler = rpc_handler
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind((host, port))
        self._listener.listen(16)
        self.address = self._listener.getsockname()
        self._closing = False
        self._threads: List[threading.Thread] = []
        t = threading.Thread(target=self._accept_loop, daemon=True,
                             name="shuffle-accept")
        t.start()
        self._threads.append(t)

    def _accept_loop(self) -> None:
        while not self._closing:
            try:
                conn, _ = self._listener.accept()
            except OSError:
                return
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            t = threading.Thread(target=self._serve, args=(conn,),
                                 daemon=True, name="shuffle-serve")
            t.start()
            self._threads.append(t)

    def _serve(self, conn: socket.socket) -> None:
        try:
            while True:
                op, payload = recv_frame(conn)
                if op == OP_META:
                    req: MetadataRequest = pickle.loads(payload)
                    resp = self.server_obj.handle_metadata_request(req)
                    self.transport.count("metadata_served")
                    send_frame(conn, OP_META_RESP, pickle.dumps(resp))
                elif op == OP_LAYOUT:
                    (bid,) = struct.unpack(">Q", payload)
                    layout, meta = self.server_obj.buffer_layout(bid)
                    send_frame(conn, OP_LAYOUT_RESP,
                               pickle.dumps((layout, meta)))
                elif op == OP_FETCH:
                    (bid,) = struct.unpack(">Q", payload)
                    self._stream_buffer(conn, bid)
                elif op == OP_FETCH_SHM:
                    bid, shm_name = pickle.loads(payload)
                    self._fill_shm(conn, bid, shm_name)
                elif op == OP_DONE:
                    (bid,) = struct.unpack(">Q", payload)
                    self.server_obj.done_serving(bid)
                    send_frame(conn, OP_ACK, b"")
                elif op == OP_RPC:
                    self._handle_rpc(conn, payload)
                else:
                    raise ValueError(f"bad opcode {op}")
        except (ConnectionError, OSError):
            pass  # peer went away; its requests die with the connection
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def _stream_buffer(self, conn: socket.socket, bid: int) -> None:
        """Send every leaf of a buffer as bounce-buffer-sized DATA frames,
        in leaf order, then END (BufferSendState: acquire buffer from any
        tier -> stage through send bounce buffers -> tagged sends)."""
        layout, _meta = self.server_obj.buffer_layout(bid)
        pool = self.transport.pool
        chunk = self.transport.chunk_size
        for leaf_idx, (_shape, _dtype, nbytes) in enumerate(layout):
            off = 0
            while off < nbytes:
                length = min(chunk, nbytes - off)
                addr = pool.acquire(length)
                try:
                    view = pool.view(addr, length)
                    self.server_obj.copy_leaf_chunk(bid, leaf_idx, off,
                                                    length, view)
                    send_frame(conn, OP_DATA, memoryview(view))
                finally:
                    pool.release(addr)
                off += length
                self.transport.count("bytes_sent", length)
        send_frame(conn, OP_END, b"")

    def _fill_shm(self, conn: socket.socket, bid: int,
                  shm_path: str) -> None:
        """Same-host fast path: copy each leaf ONCE into the client-owned
        /dev/shm segment instead of chunking through bounce buffers and
        the socket (the local-peer analogue of the reference's UCX
        zero-copy RDMA).  The socket carries only the END ack.  A plain
        tmpfs file + mmap, NOT multiprocessing.shared_memory — the stdlib
        resource tracker logs a KeyError per cross-process segment on
        this python version."""
        import mmap
        if not shm_path.startswith(SHM_PREFIX):
            send_frame(conn, OP_RPC_ERR,
                       pickle.dumps(f"bad shm path {shm_path!r}"))
            return
        try:
            fd = os.open(shm_path, os.O_RDWR)
            try:
                mm = mmap.mmap(fd, 0)
            finally:
                os.close(fd)
        except OSError as e:
            send_frame(conn, OP_RPC_ERR, pickle.dumps(f"shm open: {e!r}"))
            return
        try:
            layout, _meta = self.server_obj.buffer_layout(bid)
            off = 0
            for leaf_idx, (_shape, _dtype, nbytes) in enumerate(layout):
                view = np.frombuffer(mm, np.uint8, count=nbytes,
                                     offset=off)
                try:
                    self.server_obj.copy_leaf_chunk(bid, leaf_idx, 0,
                                                    nbytes, view)
                finally:
                    # the view exports the mmap; it must die before
                    # mm.close() (BufferError otherwise)
                    del view
                off += nbytes
            self.transport.count("bytes_sent", off)
            self.transport.count("shm_fills")
            send_frame(conn, OP_END, b"")
        finally:
            mm.close()

    def _handle_rpc(self, conn: socket.socket, payload: bytes) -> None:
        if self.rpc_handler is None:
            send_frame(conn, OP_RPC_ERR,
                       pickle.dumps("no rpc handler registered"))
            return
        try:
            method, kwargs = pickle.loads(payload)
            result = self.rpc_handler(method, kwargs)
            send_frame(conn, OP_RPC_RESP, pickle.dumps(result))
        except Exception as e:  # noqa: BLE001 — crosses the wire
            import traceback
            send_frame(conn, OP_RPC_ERR,
                       pickle.dumps(f"{e!r}\n{traceback.format_exc()}"))

    def close(self) -> None:
        self._closing = True
        try:
            self._listener.close()
        except OSError:
            pass


class SocketClient(ShuffleTransportClient):
    """Fetch path to one remote executor over its TCP port.  One socket,
    requests serialized under a lock (the reference serializes per-endpoint
    through UCX's tag space)."""

    def __init__(self, transport: "SocketTransport",
                 addr: Tuple[str, int]):
        self.transport = transport
        self.addr = tuple(addr)
        self._sock: Optional[socket.socket] = None
        self._lock = threading.Lock()

    def _conn(self) -> socket.socket:
        if self._sock is None:
            s = socket.create_connection(self.addr, timeout=30)
            # the 30s bound is for CONNECT only; requests block as long as
            # the peer needs (first-query compiles exceed fixed timeouts)
            s.settimeout(None)
            s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            self._sock = s
        return self._sock

    def _request(self, op: int, payload, expect: int) -> bytes:
        sock = self._conn()
        send_frame(sock, op, payload)
        got, resp = recv_frame(sock)
        if got == OP_RPC_ERR:
            raise RuntimeError(f"remote error: {pickle.loads(resp)}")
        if got != expect:
            raise ConnectionError(f"expected opcode {expect}, got {got}")
        return resp

    def fetch_metadata(self, request: MetadataRequest) -> MetadataResponse:
        with self._lock:
            resp = self._request(OP_META, pickle.dumps(request),
                                 OP_META_RESP)
        self.transport.count("metadata_fetched")
        return pickle.loads(resp)

    def _fetch_buffer_shm(self, layout, meta, buffer_id: int, total: int):
        """Local-peer fetch through a client-owned /dev/shm segment: one
        server-side copy per leaf, no socket data frames.  Returns
        (leaves, meta) or None when shm is unavailable (caller streams)."""
        import mmap
        import tempfile
        try:
            fd, path = tempfile.mkstemp(prefix=os.path.basename(SHM_PREFIX),
                                        dir=os.path.dirname(SHM_PREFIX))
        except OSError:
            return None
        mm = None
        try:
            os.ftruncate(fd, max(total, 1))
            mm = mmap.mmap(fd, max(total, 1))
            with self._lock:
                sock = self._conn()
                send_frame(sock, OP_FETCH_SHM,
                           pickle.dumps((buffer_id, path)))
                op, _length = recv_frame(sock)
            if op != OP_END:
                return None
            # copy out of the segment: a zero-copy variant (arrays
            # viewing the mmap with finalizer-managed lifetime) measured
            # no faster on loopback and leaked one fd per fetch — one
            # bounded memcpy per leaf is the honest cost
            out: List[np.ndarray] = []
            off = 0
            for (shape, dtype_str, nbytes) in layout:
                a = np.empty(nbytes, dtype=np.uint8)
                src = np.frombuffer(mm, np.uint8, count=nbytes,
                                    offset=off)
                try:
                    a[:] = src
                finally:
                    del src  # release the mmap export before mm.close()
                out.append(a.view(np.dtype(dtype_str)).reshape(shape))
                off += nbytes
            self.transport.count("bytes_received", off)
            return out, meta
        finally:
            if mm is not None:
                mm.close()
            os.close(fd)
            try:
                os.unlink(path)
            except OSError:
                pass

    def fetch_buffer(self, buffer_id: int):
        with self._lock:
            resp = self._request(OP_LAYOUT,
                                 struct.pack(">Q", buffer_id),
                                 OP_LAYOUT_RESP)
        layout, meta = pickle.loads(resp)
        total = sum(nb for _, _, nb in layout)
        self.transport.throttle.acquire(total)
        try:
            if self.addr[0] in ("127.0.0.1", "localhost", "::1") \
                    and self.transport.shm_local:
                got = self._fetch_buffer_shm(layout, meta, buffer_id,
                                             total)
                if got is not None:
                    return got
            with self._lock:
                sock = self._conn()
                send_frame(sock, OP_FETCH, struct.pack(">Q", buffer_id))
                out: List[np.ndarray] = []
                for (shape, dtype_str, nbytes) in layout:
                    dest = np.empty(nbytes, dtype=np.uint8)
                    off = 0
                    while off < nbytes:
                        op, length = recv_frame_into(sock, dest, off)
                        if op != OP_DATA:
                            raise ConnectionError(
                                f"short buffer stream (op {op} at "
                                f"{off}/{nbytes})")
                        off += length
                        self.transport.count("bytes_received", length)
                    out.append(dest.view(np.dtype(dtype_str)).reshape(shape))
                op, _ = recv_frame(sock)
                if op != OP_END:
                    raise ConnectionError(f"expected END, got {op}")
            return out, meta
        finally:
            self.transport.throttle.release(total)

    def release_buffer(self, buffer_id: int) -> None:
        with self._lock:
            self._request(OP_DONE, struct.pack(">Q", buffer_id), OP_ACK)

    def rpc(self, method: str, **kwargs):
        """Control-plane call (worker management; UCX mgmt-port analogue)."""
        with self._lock:
            sock = self._conn()
            send_frame(sock, OP_RPC, pickle.dumps((method, kwargs)))
            op, resp = recv_frame(sock)
        if op == OP_RPC_ERR:
            raise RuntimeError(f"worker rpc {method} failed: "
                               f"{pickle.loads(resp)}")
        if op != OP_RPC_RESP:
            raise ConnectionError(f"expected RPC_RESP, got {op}")
        return pickle.loads(resp)

    def close(self) -> None:
        with self._lock:
            if self._sock is not None:
                try:
                    self._sock.close()
                except OSError:
                    pass
                self._sock = None


class SocketTransport(ShuffleTransport):
    """Client/server factory over TCP (UCXShuffleTransport analogue).

    Peers are discovered through an explicit address map (executor_id ->
    (host, port)) distributed by the cluster driver — the role MapStatus /
    the UCX management handshake plays for the reference."""

    def __init__(self, pool_size: int = 8 << 20, chunk_size: int = 1 << 20,
                 max_inflight_bytes: int = 4 << 20,
                 host: str = "127.0.0.1", port: int = 0,
                 rpc_handler: Optional[Callable] = None,
                 shm_local: bool = False):
        # measured on 128MB partitions (BENCH_WIRE.json): the pipelined
        # chunked stream does ~1.05 GB/s on loopback while the serial
        # fill-then-copy shm path does ~0.7 GB/s — so the stream is the
        # default and shm stays an option for CPU-constrained hosts
        # (2 copies + no socket syscalls vs 3 copies through the kernel)
        self.shm_local = shm_local
        self.pool = BounceBufferPool(pool_size, chunk_size)
        self.chunk_size = chunk_size
        self.throttle = InflightThrottle(max_inflight_bytes)
        self._host, self._port = host, port
        self.rpc_handler = rpc_handler
        self._peers: Dict[str, Tuple[str, int]] = {}
        self._clients: Dict[str, SocketClient] = {}
        self._server: Optional[ShuffleSocketServer] = None
        self.address: Optional[Tuple[str, int]] = None
        self._lock = threading.Lock()
        self.counters: Dict[str, int] = {}

    def count(self, key: str, n: int = 1) -> None:
        with self._lock:
            self.counters[key] = self.counters.get(key, 0) + n

    def register_server(self, executor_id: str, server) -> None:
        self._server = ShuffleSocketServer(self, server, self.rpc_handler,
                                           self._host, self._port)
        self.address = self._server.address
        self._peers[executor_id] = self.address

    def set_peers(self, peers: Dict[str, Tuple[str, int]]) -> None:
        stale = []
        with self._lock:
            for k, v in peers.items():
                addr = tuple(v)
                if self._peers.get(k) not in (None, addr):
                    # peer re-addressed (executor-loss replacement): any
                    # cached client holds a socket to the DEAD process
                    stale.append(self._clients.pop(k, None))
                self._peers[k] = addr
        for client in stale:
            if client is not None:
                client.close()

    def make_client(self, peer_executor_id: str) -> SocketClient:
        with self._lock:
            client = self._clients.get(peer_executor_id)
            if client is None:
                addr = self._peers.get(peer_executor_id)
                if addr is None:
                    raise KeyError(
                        f"no address for peer {peer_executor_id}; "
                        f"known: {sorted(self._peers)}")
                client = SocketClient(self, addr)
                self._clients[peer_executor_id] = client
            return client

    def drop_client(self, peer_executor_id: str) -> None:
        """Forget a peer's cached client (executor-loss recovery: the
        replacement worker listens on a NEW port; the stale client holds
        a socket to the dead one)."""
        with self._lock:
            client = self._clients.pop(peer_executor_id, None)
        if client is not None:
            client.close()

    def shutdown(self) -> None:
        for c in list(self._clients.values()):
            c.close()
        self._clients.clear()
        if self._server is not None:
            self._server.close()
