"""Cross-process shuffle transport over TCP sockets (the DCN wire).

TPU-native analogue of the reference's UCX network stack
(shuffle-plugin/.../ucx/UCX.scala:54-533 — endpoint bring-up + tagged
sends over a management-port handshake; UCXShuffleTransport.scala:47-507 —
client/server factory, bounce-buffer pools, inflight throttle).  On TPU
pods the *intra-query* exchange rides ICI collectives inside the mesh
program (shuffle/ici.py); this socket transport is the host-side DCN path
between executor PROCESSES — the role UCX-over-IB plays for the reference —
so shuffle bytes genuinely cross a process/host boundary.

Wire protocol: length-prefixed frames `u32 length | u8 opcode | payload`.
Control payloads (metadata request/response, buffer layouts) are pickled
dataclasses — this is a Python-to-Python control plane, the analogue of
the reference's flatbuffers messages (shuffle-plugin/.../fbs).  Data moves
as raw frames in bounce-buffer-sized chunks: the serving side stages every
chunk through its BounceBufferPool slice before the socket send, and the
receiving side caps concurrent fetch bytes with the InflightThrottle, so
both ends keep the reference's flow-control structure on a real wire.

The same port also carries a tiny RPC opcode used by the worker control
plane (shuffle/worker.py) — the analogue of UCX's management port.
"""
from __future__ import annotations

import logging
import os
import pickle
import random
import socket
import struct
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from ..compress import CompressionPolicy, resolve_codec
from ..mem.integrity import (BufferGone, CorruptBuffer, CorruptShuffleBlock)
from ..utils import faults
from .transport import (AsyncFramedReader, AsyncLeafVerifier,
                        BounceBufferPool, ChecksumPolicy,
                        InflightThrottle, MetadataRequest, MetadataResponse,
                        ShuffleTransport, ShuffleTransportClient, Transaction,
                        TransactionCancelled, TransactionStatus,
                        decode_compressed_leaves, verify_fetched_leaf)

log = logging.getLogger("spark_rapids_tpu.shuffle")

# opcodes
OP_META, OP_META_RESP = 1, 2
OP_LAYOUT, OP_LAYOUT_RESP = 3, 4
OP_FETCH, OP_DATA, OP_END = 5, 6, 7
OP_DONE, OP_ACK = 8, 9
OP_FETCH_SHM = 10
# typed "this buffer no longer exists / cannot be served" frame: legal at
# any point a serving opcode's response or stream is expected, so a fetch
# racing remove_shuffle gets a clean error instead of a hang or a
# poisoned half-frame (payload: pickled {"reason": "gone"|"corrupt",
# "msg": str})
OP_GONE = 11
# writer-side corruption diagnosis (SPARK-36206): re-hash the live buffer
# against its recorded digests
OP_DIAG, OP_DIAG_RESP = 12, 13
# same-host segment path prefix; the server refuses to open anything else
SHM_PREFIX = "/dev/shm/srtpu_shm_"
OP_RPC, OP_RPC_RESP, OP_RPC_ERR = 20, 21, 22

_HDR = struct.Struct(">IB")


def send_frame(sock: socket.socket, op: int, payload) -> None:
    """payload: bytes-like (memoryview over a bounce slice for data)."""
    sock.sendall(_HDR.pack(len(payload), op))
    if len(payload):
        sock.sendall(payload)


def _recv_exact(sock: socket.socket, n: int) -> bytearray:
    buf = bytearray(n)
    view = memoryview(buf)
    got = 0
    while got < n:
        r = sock.recv_into(view[got:], n - got)
        if r == 0:
            raise ConnectionError("peer closed mid-frame")
        got += r
    return buf


def recv_frame(sock: socket.socket) -> Tuple[int, bytes]:
    hdr = _recv_exact(sock, _HDR.size)
    length, op = _HDR.unpack(hdr)
    payload = _recv_exact(sock, length) if length else b""
    return op, bytes(payload)


def recv_frame_into(sock: socket.socket, dest: np.ndarray, offset: int
                    ) -> Tuple[int, int, Optional[bytes]]:
    """Receive one frame; DATA payload lands directly in dest[offset:].
    Returns (opcode, payload_length, payload) — payload is None for DATA
    frames (it went into dest) and the raw bytes otherwise (an OP_GONE
    mid-stream carries its typed reason there)."""
    hdr = _recv_exact(sock, _HDR.size)
    length, op = _HDR.unpack(hdr)
    if op != OP_DATA:
        payload = bytes(_recv_exact(sock, length)) if length else b""
        return op, len(payload), payload
    view = memoryview(dest)[offset:offset + length]
    got = 0
    while got < length:
        r = sock.recv_into(view[got:], length - got)
        if r == 0:
            raise ConnectionError("peer closed mid-data")
        got += r
    return op, length, None


def _unpack_fetch(payload: bytes
                  ) -> Tuple[int, Optional[str], Optional[tuple]]:
    """OP_LAYOUT/OP_FETCH/OP_DIAG payload: a bare big-endian u64 buffer
    id (the raw wire format, and what pre-compression peers send), a
    pickled (buffer_id, codec_name) pair (pre-trace peers), or a pickled
    (buffer_id, codec_name, trace) triple carrying the requesting task's
    distributed-trace context — parsed back-compat like PR 5's codec
    field."""
    if len(payload) == 8:
        return struct.unpack(">Q", payload)[0], None, None
    rec = pickle.loads(payload)
    bid, codec = rec[0], rec[1]
    trace = rec[2] if len(rec) > 2 else None
    return int(bid), codec, trace


def _pack_fetch(buffer_id: int, codec: Optional[str],
                trace: Optional[tuple] = None) -> bytes:
    if codec in (None, "none") and trace is None:
        return struct.pack(">Q", buffer_id)
    if trace is None:
        return pickle.dumps((buffer_id, codec))
    return pickle.dumps((buffer_id, codec, tuple(trace)))


def _raise_gone(payload: bytes, buffer_id: int) -> None:
    """Decode an OP_GONE frame into its typed error."""
    try:
        rec = pickle.loads(payload) if payload else {}
    except Exception:  # noqa: BLE001 — a garbled reason is still "gone"
        rec = {}
    reason = rec.get("reason", "gone")
    msg = rec.get("msg", f"buffer {buffer_id} gone at the peer")
    if reason == "corrupt":
        # the PEER found its own stored copy failing verification while
        # serving: writer-site corruption, refetching cannot help
        raise CorruptShuffleBlock(msg, buffer_id=buffer_id, site="writer")
    raise BufferGone(msg)


class ShuffleSocketServer:
    """Serves one executor's shuffle buffers on a TCP port.

    Each accepted connection gets a handler thread (the reference's UCX
    progress thread pool; RapidsShuffleServer.scala:67-150).  Data chunks
    are staged through the transport's BounceBufferPool before each send,
    so serving a spilled buffer never inflates memory beyond the pool."""

    def __init__(self, transport: "SocketTransport", server_obj,
                 rpc_handler: Optional[Callable] = None,
                 host: str = "127.0.0.1", port: int = 0):
        self.transport = transport
        self.server_obj = server_obj
        self.rpc_handler = rpc_handler
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind((host, port))
        self._listener.listen(16)
        self.address = self._listener.getsockname()
        self._closing = False
        self._threads: List[threading.Thread] = []
        t = threading.Thread(target=self._accept_loop, daemon=True,
                             name="shuffle-accept")
        t.start()
        self._threads.append(t)

    def _accept_loop(self) -> None:
        consecutive_errors = 0
        while not self._closing:
            try:
                conn, _ = self._listener.accept()
            except OSError as e:
                if self._closing:
                    return
                # transient accept failures (ECONNABORTED from a client
                # abort, EMFILE during an fd burst) must not kill the
                # server while the executor lives on looking healthy —
                # count, log, and keep accepting; only a persistently
                # broken listener stops the loop
                self.transport.count("accept_errors")
                consecutive_errors += 1
                # generous tolerance: reconnect-per-retry clients churn
                # connections during fault storms, and an fd burst
                # (EMFILE) can persist for seconds — an executor that
                # stops accepting while "looking healthy" costs every
                # peer ioTimeout * maxAttempts per fetch until restart
                if consecutive_errors > 20 or self._listener.fileno() < 0:
                    log.error("shuffle server %s stopping after repeated "
                              "accept failures: %r", self.address, e)
                    return
                log.warning("shuffle server %s accept failed "
                            "(%d consecutive): %r", self.address,
                            consecutive_errors, e)
                time.sleep(min(1.0, 0.05 * consecutive_errors))
                continue
            consecutive_errors = 0
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            t = threading.Thread(target=self._serve, args=(conn,),
                                 daemon=True, name="shuffle-serve")
            t.start()
            # prune finished handlers: reconnect-per-retry clients churn
            # connections, and retaining every dead Thread forever is an
            # unbounded leak in exactly the fault-heavy regime
            self._threads = [x for x in self._threads if x.is_alive()]
            self._threads.append(t)

    def _serve(self, conn: socket.socket) -> None:
        try:
            peer = conn.getpeername()
        except OSError:
            peer = "<unknown>"
        try:
            while True:
                op, payload = recv_frame(conn)
                if op == OP_META:
                    req: MetadataRequest = pickle.loads(payload)
                    resp = self.server_obj.handle_metadata_request(req)
                    # advertise trace capability: the client stamps trace
                    # context on later per-buffer ops only after seeing
                    # this (back-compat with pre-trace peers both ways)
                    resp.traced = True
                    self.transport.count("metadata_served")
                    self._journal_serve("serveMetadata",
                                        getattr(req, "trace", None),
                                        shuffle=req.shuffle_id,
                                        reduce=req.reduce_id)
                    send_frame(conn, OP_META_RESP, pickle.dumps(resp))
                elif op == OP_LAYOUT:
                    bid, codec, _trace = _unpack_fetch(payload)
                    try:
                        layout, meta = self.server_obj.buffer_layout(bid)
                        sums = self._checksums_of(bid)
                        comp = self._compressed_of(bid, codec)
                    except (KeyError, CorruptBuffer) as e:
                        self._send_gone(conn, bid, e)
                        continue
                    send_frame(conn, OP_LAYOUT_RESP,
                               pickle.dumps((layout, meta, sums, comp)))
                elif op == OP_FETCH:
                    bid, codec, trace = _unpack_fetch(payload)
                    self._stream_buffer(conn, bid, codec, trace)
                elif op == OP_FETCH_SHM:
                    rec = pickle.loads(payload)
                    bid, shm_name = rec[0], rec[1]
                    codec = rec[2] if len(rec) > 2 else None
                    trace = rec[3] if len(rec) > 3 else None
                    self._fill_shm(conn, bid, shm_name, codec, trace)
                elif op == OP_DIAG:
                    bid, _codec, trace = _unpack_fetch(payload)
                    self._journal_serve("serveDiagnosis", trace,
                                        buffer=bid)
                    self._handle_diag(conn, bid)
                elif op == OP_DONE:
                    (bid,) = struct.unpack(">Q", payload)
                    self.server_obj.done_serving(bid)
                    send_frame(conn, OP_ACK, b"")
                elif op == OP_RPC:
                    self._handle_rpc(conn, payload)
                else:
                    raise ValueError(f"bad opcode {op}")
        except (ConnectionError, OSError) as e:
            # peer went away; its requests die with the connection — but
            # the event is counted and logged with the peer address, not
            # silently dropped (a flapping peer shows up in the counters)
            self.transport.count("peer_disconnects")
            if not self._closing:
                log.info("shuffle peer %s disconnected: %r", peer, e)
        finally:
            try:
                conn.close()
            except OSError as e:
                log.debug("closing connection from %s: %r", peer, e)

    def _server_executor(self) -> str:
        env = getattr(self.server_obj, "env", None)
        return getattr(env, "executor_id", "?")

    def _journal_serve(self, name: str, trace, **attrs) -> None:
        """Instant serve record carrying the REQUESTER's wire trace
        context (o_q/o_st/o_sp/o_ex) — the mapper-side half of the
        fetch<->serve flow link (metrics/timeline.py)."""
        from ..metrics.journal import journal_event, trace_attrs
        journal_event("serve", name, executor=self._server_executor(),
                      **{k: v for k, v in attrs.items() if v is not None},
                      **trace_attrs(trace))

    def _checksums_of(self, bid: int):
        """The server's recorded (algorithm, per-leaf digests) for a
        buffer, or None for servers without integrity support (the wire
        benchmark's bare fixture)."""
        get = getattr(self.server_obj, "buffer_checksums", None)
        return get(bid) if get is not None else None

    def _compressed_of(self, bid: int, codec: Optional[str]):
        """Negotiation answer: the framed-compression descriptor
        ({codec, sizes, checksums, algorithm}) when the reader asked for
        a codec this server can encode, else None — the reader falls
        back to the raw wire format and counts the miss."""
        if codec in (None, "none"):
            return None
        get = getattr(self.server_obj, "compressed_layout", None)
        # the fallback is counted by the CLIENT (the side whose request
        # went unmet, matching the counter's documented semantics) —
        # counting here too would double cluster-wide rollups
        return get(bid, codec) if get is not None else None

    def _send_gone(self, conn: socket.socket, bid: int,
                   err: Exception) -> None:
        """Typed buffer-gone/corrupt frame for a serve that raced
        remove_shuffle (or found its own copy corrupt at serve time)."""
        reason = "corrupt" if isinstance(err, CorruptBuffer) else "gone"
        self.transport.count("buffer_gone")
        log.info("shuffle buffer %d unservable (%s): %r", bid, reason, err)
        send_frame(conn, OP_GONE,
                   pickle.dumps({"reason": reason,
                                 "msg": f"buffer {bid}: {err}"}))

    def _handle_diag(self, conn: socket.socket, bid: int) -> None:
        diag = getattr(self.server_obj, "diagnose_buffer", None)
        try:
            result = diag(bid) if diag is not None else None
        except KeyError:
            result = None
        except CorruptBuffer:
            # re-hashing tripped the serve-time verify: conclusive
            # writer-side evidence (and the connection must survive to
            # carry the verdict — a crashed handler would misclassify
            # this as a wire fault after client timeouts)
            result = {"writer_ok": False}
        self.transport.count("corruption_diagnoses")
        send_frame(conn, OP_DIAG_RESP, pickle.dumps(result))

    def _stream_buffer(self, conn: socket.socket, bid: int,
                       codec: Optional[str] = None,
                       trace: Optional[tuple] = None) -> None:
        """Send every leaf of a buffer as bounce-buffer-sized DATA frames,
        in leaf order, then END (BufferSendState: acquire buffer from any
        tier -> stage through send bounce buffers -> tagged sends).  With
        a negotiated codec the staged chunks come out of each leaf's
        FRAMED COMPRESSED form (built once per buffer+codec, served for
        every chunk and refetch) — the layout response already told the
        reader the framed sizes and frame digests.

        A KeyError from the server object mid-stream (the buffer's shuffle
        was removed while we were serving it) becomes a typed OP_GONE
        frame — the client sees a clean `BufferGone` instead of a
        half-frame crash or a hang."""
        from ..metrics.journal import journal_span, trace_attrs
        try:
            layout, _meta = self.server_obj.buffer_layout(bid)
            comp = self._compressed_of(bid, codec)
        except (KeyError, CorruptBuffer) as e:
            self._send_gone(conn, bid, e)
            return
        with journal_span("serve", "serveBuffer",
                          executor=self._server_executor(), buffer=bid,
                          **trace_attrs(trace)):
            self._stream_buffer_body(conn, bid, layout, comp)

    def _stream_buffer_body(self, conn, bid, layout, comp) -> None:
        if comp is not None:
            wire_sizes = comp["sizes"]

            def copy_chunk(leaf_idx, off, length, view):
                self.server_obj.copy_compressed_chunk(
                    bid, leaf_idx, off, length, view, comp["codec"])
        else:
            wire_sizes = [nbytes for _shape, _dtype, nbytes in layout]

            def copy_chunk(leaf_idx, off, length, view):
                self.server_obj.copy_leaf_chunk(bid, leaf_idx, off,
                                                length, view)
        pool = self.transport.pool
        chunk = self.transport.chunk_size
        sent = 0
        for leaf_idx, nbytes in enumerate(wire_sizes):
            off = 0
            while off < nbytes:
                length = min(chunk, nbytes - off)
                addr = pool.acquire(length)
                try:
                    view = pool.view(addr, length)
                    try:
                        copy_chunk(leaf_idx, off, length, view)
                    except (KeyError, CorruptBuffer) as e:
                        self._send_gone(conn, bid, e)
                        return
                    # corruption injection point: the staged chunk IS the
                    # wire payload (anything flipped here crosses the
                    # socket and must be caught by the reader's verify —
                    # with compression on, a flipped COMPRESSED byte must
                    # fail the frame digest before any decompressor)
                    faults.INJECTOR.on_corruptible("wire", view[:length])
                    send_frame(conn, OP_DATA, memoryview(view))
                finally:
                    pool.release(addr)
                off += length
                sent += length
                self.transport.count("bytes_sent", length)
        if comp is not None:
            self.transport.count("compressed_bytes_sent", sent)
        send_frame(conn, OP_END, b"")

    def _fill_shm(self, conn: socket.socket, bid: int,
                  shm_path: str, codec: Optional[str] = None,
                  trace: Optional[tuple] = None) -> None:
        """Same-host fast path: copy each leaf ONCE into the client-owned
        /dev/shm segment instead of chunking through bounce buffers and
        the socket (the local-peer analogue of the reference's UCX
        zero-copy RDMA).  The socket carries only the END ack.  A plain
        tmpfs file + mmap, NOT multiprocessing.shared_memory — the stdlib
        resource tracker logs a KeyError per cross-process segment on
        this python version."""
        import mmap

        from ..metrics.journal import journal_span, trace_attrs
        if not shm_path.startswith(SHM_PREFIX):
            send_frame(conn, OP_RPC_ERR,
                       pickle.dumps(f"bad shm path {shm_path!r}"))
            return
        try:
            fd = os.open(shm_path, os.O_RDWR)
            try:
                mm = mmap.mmap(fd, 0)
            finally:
                os.close(fd)
        except OSError as e:
            send_frame(conn, OP_RPC_ERR, pickle.dumps(f"shm open: {e!r}"))
            return
        try:
            try:
                layout, _meta = self.server_obj.buffer_layout(bid)
                comp = self._compressed_of(bid, codec)
            except (KeyError, CorruptBuffer) as e:
                self._send_gone(conn, bid, e)
                return
            if comp is not None:
                wire_sizes = comp["sizes"]

                def copy_leaf(leaf_idx, nbytes, view):
                    self.server_obj.copy_compressed_chunk(
                        bid, leaf_idx, 0, nbytes, view, comp["codec"])
            else:
                wire_sizes = [nb for _shape, _dtype, nb in layout]

                def copy_leaf(leaf_idx, nbytes, view):
                    self.server_obj.copy_leaf_chunk(bid, leaf_idx, 0,
                                                    nbytes, view)
            off = 0
            with journal_span("serve", "serveBuffer",
                              executor=self._server_executor(),
                              buffer=bid, path="shm",
                              **trace_attrs(trace)):
                for leaf_idx, nbytes in enumerate(wire_sizes):
                    view = np.frombuffer(mm, np.uint8, count=nbytes,
                                         offset=off)
                    try:
                        try:
                            copy_leaf(leaf_idx, nbytes, view)
                        except (KeyError, CorruptBuffer) as e:
                            self._send_gone(conn, bid, e)
                            return
                        # corruption injection point for the shared-memory
                        # leaf fill (the same-host zero-copy "wire")
                        faults.INJECTOR.on_corruptible("shm", view)
                    finally:
                        # the view exports the mmap; it must die before
                        # mm.close() (BufferError otherwise)
                        del view
                    off += nbytes
            self.transport.count("bytes_sent", off)
            if comp is not None:
                self.transport.count("compressed_bytes_sent", off)
            self.transport.count("shm_fills")
            send_frame(conn, OP_END, b"")
        finally:
            mm.close()

    def _handle_rpc(self, conn: socket.socket, payload: bytes) -> None:
        if self.rpc_handler is None:
            send_frame(conn, OP_RPC_ERR,
                       pickle.dumps("no rpc handler registered"))
            return
        try:
            method, kwargs = pickle.loads(payload)
            result = self.rpc_handler(method, kwargs)
            send_frame(conn, OP_RPC_RESP, pickle.dumps(result))
        except Exception as e:  # noqa: BLE001 — crosses the wire
            import traceback
            # counted and logged server-side too: the client may be gone
            # by the time the error frame would reach it
            self.transport.count("rpc_errors")
            log.warning("shuffle rpc failed server-side: %r", e)
            send_frame(conn, OP_RPC_ERR,
                       pickle.dumps(f"{e!r}\n{traceback.format_exc()}"))

    def close(self) -> None:
        self._closing = True
        # shutdown() BEFORE close(): on Linux, close() does not wake a
        # thread blocked in accept() — the kernel keeps the listening
        # socket alive for the in-flight syscall and KEEPS ACCEPTING,
        # so a "closed" server would silently serve forever.  shutdown
        # forces the blocked accept to return.
        try:
            self._listener.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass  # not connected / already gone — nothing to wake  # tpulint: disable=TPU006 shutdown of an unconnected listener is the idle-server close path, not a failure
        try:
            self._listener.close()
        except OSError as e:
            log.debug("closing shuffle listener %s: %r", self.address, e)


class SocketClient(ShuffleTransportClient):
    """Fetch path to one remote executor over its TCP port.  One socket,
    requests serialized under a lock (the reference serializes per-endpoint
    through UCX's tag space).

    Robustness contract (reference: UCX endpoint error handler + the
    RapidsShuffleClient retry/reissue path):

      * every DATA-plane operation (metadata, layout, fetch, done) runs
        under a per-op I/O deadline (`spark.rapids.shuffle.ioTimeoutMs`),
        so a dead peer surfaces as a timeout instead of a hang;
      * failed operations reconnect and retry with exponential backoff +
        deterministic jitter, up to `spark.rapids.shuffle.retry.maxAttempts`
        (requests restart from scratch on a FRESH socket — a half-read
        frame poisons the stream);
      * a whole fetch runs as a Transaction with an overall deadline
        (`transactionTimeoutMs`); past it the transaction is CANCELLED and
        no further retries are attempted;
      * control-plane RPCs are exempt from the I/O deadline: task dispatch
        legitimately blocks on the peer's first-query compilation.
    """

    def __init__(self, transport: "SocketTransport",
                 addr: Tuple[str, int], inject_faults: bool = True,
                 connect_timeout: Optional[float] = None):
        self.transport = transport
        self.addr = tuple(addr)
        # inject_faults=False exempts this client from the deterministic
        # net-fault injector: background pollers (the heartbeat monitor)
        # must not consume test-armed ordinals out from under the
        # data-plane ops the test aimed them at
        self.inject_faults = inject_faults
        # per-client connect bound override: liveness pollers cannot
        # afford the transport's data-plane default (30s) — one
        # blackholed worker would starve every other worker's heartbeat
        self.connect_timeout = connect_timeout
        self._sock: Optional[socket.socket] = None
        self._lock = threading.Lock()
        # trace capability of the peer, learned from the metadata
        # handshake (MetadataResponse.traced): until a trace-aware server
        # confirms, per-buffer ops ride the pre-trace wire shapes — a
        # pre-trace peer cannot parse the pickled trace triple
        self._peer_traced = False
        # deterministic jitter: seeded per peer address, not wall clock
        self._rng = random.Random(f"shuffle-retry:{self.addr}")

    def _conn_locked(self) -> socket.socket:
        if self._sock is None:
            t = self.transport
            s = socket.create_connection(
                self.addr,
                timeout=(self.connect_timeout
                         if self.connect_timeout is not None
                         else t.connect_timeout))
            # the connect bound above is per-attempt; steady-state requests
            # run under the (configurable) I/O deadline so a peer that dies
            # mid-request raises instead of blocking forever
            s.settimeout(t.io_timeout if t.io_timeout > 0 else None)
            s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            self._sock = s
        return self._sock

    def _drop_socket_locked(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError as e:
                log.debug("closing shuffle socket to %s: %r", self.addr, e)
            self._sock = None

    def _backoff(self, attempt: int) -> float:
        t = self.transport
        raw = min(t.backoff_cap, t.backoff_base * (2 ** attempt))
        return raw * (0.5 + self._rng.random() / 2)  # jittered

    def _retrying(self, label: str, body, deadline: Optional[float] = None,
                  txn: Optional[Transaction] = None):
        """Run `body(sock)` with reconnect-and-retry.  Takes self._lock
        per ATTEMPT and sleeps the backoff unlocked, so a concurrent
        control-plane rpc() or close() to the same peer fails/finishes
        fast instead of stalling behind the backoff series.  `deadline`
        (monotonic) bounds the WHOLE operation including retries;
        crossing it cancels the transaction."""
        attempts = max(1, self.transport.max_attempts)
        last: Optional[BaseException] = None
        for attempt in range(attempts):
            if deadline is not None and time.monotonic() > deadline:
                with self._lock:
                    self._drop_socket_locked()
                raise (txn.cancel(f"{label} to {self.addr} exceeded "
                                  "the transaction deadline") if txn
                       else TransactionCancelled(
                           f"{label} to {self.addr} exceeded deadline"))
            try:
                with self._lock:
                    if self.inject_faults:
                        faults.INJECTOR.on_net_op(label)
                    return body(self._conn_locked())
            except TransactionCancelled:
                with self._lock:
                    self._drop_socket_locked()  # the stream is poisoned mid-frame
                raise
            except (TimeoutError, ConnectionError, OSError) as e:
                # socket.timeout is a TimeoutError (itself an OSError);
                # injected faults are ConnectionErrors.  All of them tear
                # the socket down so the next attempt starts clean.
                with self._lock:
                    self._drop_socket_locked()
                last = e
                self.transport.count("net_op_failures")
                log.warning("shuffle %s to %s failed "
                            "(attempt %d/%d): %r", label, self.addr,
                            attempt + 1, attempts, e)
                if attempt + 1 >= attempts:
                    break
                self.transport.count("net_op_retries")
                time.sleep(self._backoff(attempt))
        if txn is not None:
            txn.fail(repr(last))
        raise ConnectionError(
            f"shuffle {label} to {self.addr} failed after "
            f"{attempts} attempts: {last!r}") from last

    def _request_locked(self, op: int, payload, expect: int,
                 buffer_id: int = -1) -> bytes:
        sock = self._conn_locked()
        send_frame(sock, op, payload)
        got, resp = recv_frame(sock)
        if got == OP_RPC_ERR:
            raise RuntimeError(f"remote error: {pickle.loads(resp)}")
        if got == OP_GONE:
            _raise_gone(resp, buffer_id)
        if got != expect:
            raise ConnectionError(f"expected opcode {expect}, got {got}")
        return resp

    def fetch_metadata(self, request: MetadataRequest) -> MetadataResponse:
        if getattr(request, "trace", None) is None \
                and getattr(self.transport, "trace_enabled", True):
            from ..metrics.journal import current_trace
            request.trace = current_trace()
        blob = pickle.dumps(request)
        resp = self._retrying(
            "metadata", lambda _s: self._request_locked(OP_META, blob,
                                                 OP_META_RESP))
        self.transport.count("metadata_fetched")
        meta = pickle.loads(resp)
        with self._lock:
            self._peer_traced = bool(getattr(meta, "traced", False))
        return meta

    def _wire_trace(self):
        """Trace context to stamp on per-buffer ops: only once the peer
        advertised trace support through the metadata handshake."""
        if not self._peer_traced \
                or not getattr(self.transport, "trace_enabled", True):
            return None
        from ..metrics.journal import current_trace
        return current_trace()

    def _fetch_buffer_shm(self, layout, meta, buffer_id: int, total: int,
                          sums=None, comp=None, comp_sums=None):
        """Local-peer fetch through a client-owned /dev/shm segment: one
        server-side copy per leaf, no socket data frames.  With a
        negotiated codec the segment holds FRAMED COMPRESSED leaves
        (`total` is the framed size); frames verify against their
        compression-boundary digests BEFORE decompression, and the
        decompressed bytes against the canonical digests after.  Returns
        (leaves, meta) or None when shm is unavailable (caller streams)."""
        import mmap
        import tempfile
        try:
            fd, path = tempfile.mkstemp(prefix=os.path.basename(SHM_PREFIX),
                                        dir=os.path.dirname(SHM_PREFIX))
        except OSError as e:
            log.info("shm fetch unavailable (%r); falling back to the "
                     "socket stream", e)
            self.transport.count("shm_unavailable")
            return None
        mm = None
        try:
            os.ftruncate(fd, max(total, 1))
            mm = mmap.mmap(fd, max(total, 1))
            trace = self._wire_trace()
            try:
                with self._lock:
                    faults.INJECTOR.on_net_op("fetch_shm")
                    sock = self._conn_locked()
                    send_frame(sock, OP_FETCH_SHM,
                               pickle.dumps(
                                   (buffer_id, path,
                                    comp["codec"] if comp is not None
                                    else None, trace)
                                   if trace is not None
                                   else ((buffer_id, path, comp["codec"])
                                         if comp is not None
                                         else (buffer_id, path))))
                    op, resp = recv_frame(sock)
            except (TimeoutError, ConnectionError, OSError) as e:
                # single attempt: the caller streams over the socket
                # instead (which carries the full retry machinery)
                log.warning("shm fetch of buffer %d from %s failed: %r",
                            buffer_id, self.addr, e)
                self.transport.count("net_op_failures")
                with self._lock:
                    self._drop_socket_locked()
                return None
            if op == OP_GONE:
                _raise_gone(resp, buffer_id)
            if op != OP_END:
                return None
            wire_sizes = (comp["sizes"] if comp is not None
                          else [nb for _, _, nb in layout])
            # copy out of the segment: a zero-copy variant (arrays
            # viewing the mmap with finalizer-managed lifetime) measured
            # no faster on loopback and leaked one fd per fetch — one
            # bounded memcpy per leaf is the honest cost
            flats: List[np.ndarray] = []
            off = 0
            for leaf_idx, nbytes in enumerate(wire_sizes):
                a = np.empty(nbytes, dtype=np.uint8)
                src = np.frombuffer(mm, np.uint8, count=nbytes,
                                    offset=off)
                try:
                    a[:] = src
                finally:
                    del src  # release the mmap export before mm.close()
                flats.append(a)
                off += nbytes
            self.transport.count("bytes_received", off)
            policy = self.transport.integrity
            out: List[np.ndarray] = []
            if comp is not None:
                # mismatches propagate to fetch_buffer's outer handler
                # (counted + socket dropped there); a corrupt frame
                # never reaches the decompressor
                out = decode_compressed_leaves(
                    flats, layout, resolve_codec(comp["codec"]),
                    comp_sums, sums, policy, self._wire_compression(),
                    buffer_id, "shm")
                self.transport.count("compressed_bytes_received", off)
                cmetrics = self._wire_compression().metrics
                if cmetrics is not None:
                    from ..metrics import names as MN
                    cmetrics.add(MN.COMPRESSED_SHUFFLE_BYTES_READ, off)
                return out, meta
            for leaf_idx, (shape, dtype_str, nbytes) in enumerate(layout):
                a = flats[leaf_idx]
                if sums is not None:
                    # a mismatch propagates to fetch_buffer's outer
                    # handler (counted + socket dropped there)
                    verify_fetched_leaf(policy, a,
                                        sums[leaf_idx], buffer_id,
                                        leaf_idx, "shm")
                out.append(a.view(np.dtype(dtype_str)).reshape(shape))
            return out, meta
        finally:
            if mm is not None:
                mm.close()
            os.close(fd)
            try:
                os.unlink(path)
            except OSError as e:
                log.debug("unlinking shm segment %s: %r", path, e)

    def fetch_buffer(self, buffer_id: int):
        # one fetch == one Transaction: layout + every data frame + END
        # under a single overall deadline, so a peer that dies mid-stream
        # cancels the transaction instead of hanging the reduce task
        txn = self.transport.next_txn()
        deadline = (time.monotonic() + self.transport.txn_timeout
                    if self.transport.txn_timeout > 0 else None)
        cpol = self._wire_compression()
        req_codec = (cpol.codec_name
                     if cpol is not None and cpol.enabled else None)
        # trace context of the requesting task: rides the layout + fetch
        # payloads (once the metadata handshake confirmed the peer parses
        # them) so the peer's serve span links back to our fetch span
        trace = self._wire_trace()
        try:
            resp = self._retrying(
                "layout",
                lambda _s: self._request_locked(OP_LAYOUT,
                                         _pack_fetch(buffer_id, req_codec,
                                                     trace),
                                         OP_LAYOUT_RESP, buffer_id),
                deadline=deadline, txn=txn)
            unpacked = pickle.loads(resp)
            layout, meta = unpacked[0], unpacked[1]
            # pre-integrity peers answer with a 2-tuple — no digests, no
            # verification, same data plane
            rec = unpacked[2] if len(unpacked) > 2 else None
            policy = self.transport.integrity
            sums = None
            if policy is not None and policy.enabled and rec is not None \
                    and rec[0] == policy.algorithm:
                sums = rec[1]
            # codec negotiation outcome: the peer either confirmed our
            # requested codec with framed sizes + frame digests, or it
            # cannot encode it (no compress support / missing library)
            # and we ride the raw wire format — typed fallback, counted
            comp = unpacked[3] if len(unpacked) > 3 else None
            if comp is not None and comp.get("codec") in (None, "none"):
                comp = None
            if req_codec is not None and comp is None:
                self.transport.count("compression_fallbacks")
                if cpol.metrics is not None:
                    from ..metrics import names as MN
                    cpol.metrics.add(MN.NUM_COMPRESSION_FALLBACKS, 1)
            comp_sums = None
            if comp is not None and policy is not None and policy.enabled \
                    and comp.get("checksums") is not None \
                    and comp.get("algorithm") == policy.algorithm:
                comp_sums = comp["checksums"]
            wire_sizes = (comp["sizes"] if comp is not None
                          else [nb for _, _, nb in layout])
            # inflight accounting covers what actually crosses the wire:
            # framed (compressed) bytes when a codec was negotiated
            total = sum(wire_sizes)
            self.transport.throttle.acquire(total)
            try:
                if self.addr[0] in ("127.0.0.1", "localhost", "::1") \
                        and self.transport.shm_local:
                    got = self._fetch_buffer_shm(layout, meta, buffer_id,
                                                 total, sums, comp,
                                                 comp_sums)
                    if got is not None:
                        txn.complete(total)
                        return got

                def stream(sock) -> List[np.ndarray]:
                    send_frame(sock, OP_FETCH,
                               _pack_fetch(buffer_id,
                                           comp["codec"]
                                           if comp is not None else None,
                                           trace))
                    # chunk hashing (and, with a codec, per-leaf verify +
                    # decompress) rides a side thread, overlapped with
                    # the recv loop — verification still completes BEFORE
                    # the bytes become a batch (finish() below), it just
                    # never serializes behind the wire; a corrupt frame
                    # is rejected before any decompressor touches it
                    if comp is not None:
                        sink = AsyncFramedReader(
                            policy, comp_sums, sums,
                            resolve_codec(comp["codec"]), buffer_id,
                            "wire")
                    elif sums is not None:
                        sink = AsyncLeafVerifier(policy, sums, buffer_id,
                                                 "wire")
                    else:
                        sink = None
                    dests: List[np.ndarray] = []
                    try:
                        for leaf_idx, nbytes in enumerate(wire_sizes):
                            dest = np.empty(nbytes, dtype=np.uint8)
                            off = 0
                            while off < nbytes:
                                if deadline is not None \
                                        and time.monotonic() > deadline:
                                    raise txn.cancel(
                                        f"fetch of buffer {buffer_id} "
                                        f"from {self.addr} mid-stream at "
                                        f"{off}/{nbytes}")
                                op, length, payload = recv_frame_into(
                                    sock, dest, off)
                                if op == OP_GONE:
                                    _raise_gone(payload, buffer_id)
                                if op != OP_DATA:
                                    raise ConnectionError(
                                        f"short buffer stream (op {op} "
                                        f"at {off}/{nbytes})")
                                if sink is not None:
                                    sink.feed(leaf_idx,
                                              dest[off:off + length])
                                off += length
                                self.transport.count("bytes_received",
                                                     length)
                            if sink is not None:
                                sink.leaf_done(leaf_idx, dest)
                            dests.append(dest)
                        op, _ = recv_frame(sock)
                        if op != OP_END:
                            raise ConnectionError(
                                f"expected END, got {op}")
                        if comp is not None:
                            flats = sink.finish()  # raises on mismatch
                            sink = None
                            self.transport.count(
                                "compressed_bytes_received", total)
                            if cpol.metrics is not None:
                                from ..metrics import names as MN
                                cpol.metrics.add(
                                    MN.COMPRESSED_SHUFFLE_BYTES_READ,
                                    total)
                            return [flats[i].view(np.dtype(ds))
                                    .reshape(sh)
                                    for i, (sh, ds, _nb)
                                    in enumerate(layout)]
                        if sink is not None:
                            sink.finish()  # raises on mismatch
                            sink = None
                        return [d.view(np.dtype(ds)).reshape(sh)
                                for d, (sh, ds, _nb)
                                in zip(dests, layout)]
                    finally:
                        if sink is not None:
                            sink.abort()

                out = self._retrying("fetch", stream, deadline=deadline,
                                     txn=txn)
                txn.complete(total)
                return out, meta
            finally:
                self.transport.throttle.release(total)
        except CorruptShuffleBlock as e:
            # remaining stream frames are unread: the socket is poisoned
            # for any next request — tear it down before escalating to
            # the refetch/diagnosis ladder (manager._fetch_remote)
            self.transport.count("checksum_mismatches")
            txn.fail(repr(e))
            with self._lock:
                self._drop_socket_locked()
            raise
        except BufferGone as e:
            txn.fail(repr(e))
            raise

    def release_buffer(self, buffer_id: int) -> None:
        # done_serving is idempotent at the server, so the retry is safe
        self._retrying(
            "done", lambda _s: self._request_locked(
                OP_DONE, struct.pack(">Q", buffer_id), OP_ACK))

    def diagnose_buffer(self, buffer_id: int):
        """Writer-side corruption diagnosis (SPARK-36206): the peer
        re-hashes its live copy against its recorded digests.  Returns the
        diagnosis dict or None — never raises; a peer too broken to answer
        is classified by the caller from the absence of evidence."""
        try:
            resp = self._retrying(
                "diag", lambda _s: self._request_locked(
                    OP_DIAG,
                    _pack_fetch(buffer_id, None, self._wire_trace()),
                    OP_DIAG_RESP, buffer_id))
            return pickle.loads(resp)
        except (ConnectionError, OSError, RuntimeError) as e:
            log.warning("corruption diagnosis of buffer %d at %s "
                        "unavailable: %r", buffer_id, self.addr, e)
            return None

    def rpc(self, method: str, _rpc_timeout: Optional[float] = None,
            **kwargs):
        """Control-plane call (worker management; UCX mgmt-port analogue).

        Deliberately NOT retried (run_map/run_reduce are not idempotent)
        and exempt from the data-plane I/O deadline: the first dispatch of
        a plan fragment blocks on the PEER's query compilation, which can
        legitimately exceed any fixed bound.  `_rpc_timeout` opts back
        INTO a deadline for calls that must never hang — the heartbeat
        monitor's liveness polls ride a dedicated client with one."""
        with self._lock:
            if self.inject_faults:
                # method-qualified site so the injectNetFault sweep can
                # aim at ONE control-plane rpc ('rpc:run_reduce@1')
                faults.INJECTOR.on_net_op(f"rpc:{method}")
            try:
                sock = self._conn_locked()
                # compile-friendly: no I/O deadline unless opted in
                sock.settimeout(_rpc_timeout)
                try:
                    send_frame(sock, OP_RPC, pickle.dumps((method, kwargs)))
                    op, resp = recv_frame(sock)
                finally:
                    if self._sock is not None:
                        try:
                            self._sock.settimeout(
                                self.transport.io_timeout
                                if self.transport.io_timeout > 0 else None)
                        except OSError:
                            self._drop_socket_locked()  # broken mid-rpc
            except (TimeoutError, ConnectionError, OSError) as e:
                self._drop_socket_locked()
                self.transport.count("net_op_failures")
                log.warning("shuffle rpc %s to %s failed: %r", method,
                            self.addr, e)
                raise
        if op == OP_RPC_ERR:
            raise RuntimeError(f"worker rpc {method} failed: "
                               f"{pickle.loads(resp)}")
        if op != OP_RPC_RESP:
            raise ConnectionError(f"expected RPC_RESP, got {op}")
        return pickle.loads(resp)

    def close(self) -> None:
        with self._lock:
            self._drop_socket_locked()


class SocketTransport(ShuffleTransport):
    """Client/server factory over TCP (UCXShuffleTransport analogue).

    Peers are discovered through an explicit address map (executor_id ->
    (host, port)) distributed by the cluster driver — the role MapStatus /
    the UCX management handshake plays for the reference."""

    def __init__(self, pool_size: Optional[int] = None,
                 chunk_size: Optional[int] = None,
                 max_inflight_bytes: int = 4 << 20,
                 host: str = "127.0.0.1", port: int = 0,
                 rpc_handler: Optional[Callable] = None,
                 shm_local: bool = False,
                 connect_timeout: float = 30.0, io_timeout: float = 60.0,
                 max_attempts: int = 4, backoff_base: float = 0.05,
                 backoff_cap: float = 2.0, txn_timeout: float = 600.0):
        # bounce-pool geometry: ONE source of truth, the conf registry
        # (spark.rapids.shuffle.bounce.poolSizeBytes/chunkSizeBytes);
        # explicit arguments (tests, pinned-pool override) still win
        from .. import config as C
        if pool_size is None:
            pool_size = int(C.SHUFFLE_BOUNCE_POOL_SIZE.default)
        if chunk_size is None:
            chunk_size = int(C.SHUFFLE_BOUNCE_CHUNK_SIZE.default)
        # measured on 128MB partitions (BENCH_WIRE.json): the pipelined
        # chunked stream does ~1.05 GB/s on loopback while the serial
        # fill-then-copy shm path does ~0.7 GB/s — so the stream is the
        # default and shm stays an option for CPU-constrained hosts
        # (2 copies + no socket syscalls vs 3 copies through the kernel)
        self.shm_local = shm_local
        self.pool = BounceBufferPool(pool_size, chunk_size)
        self.chunk_size = chunk_size
        self.throttle = InflightThrottle(max_inflight_bytes)
        self._host, self._port = host, port
        self.rpc_handler = rpc_handler
        # retry/deadline policy (seconds); configure(conf) overrides from
        # the spark.rapids.shuffle.* knobs
        self.connect_timeout = connect_timeout
        self.io_timeout = io_timeout
        self.max_attempts = max_attempts
        self.backoff_base = backoff_base
        self.backoff_cap = backoff_cap
        self.txn_timeout = txn_timeout
        self._peers: Dict[str, Tuple[str, int]] = {}
        self._clients: Dict[str, SocketClient] = {}
        self._server: Optional[ShuffleSocketServer] = None
        self.address: Optional[Tuple[str, int]] = None
        self._lock = threading.Lock()
        self._txn_counter = 0
        self.counters: Dict[str, int] = {}
        # end-to-end wire integrity (mem/integrity.py): the client
        # verifies every received leaf against the digests the layout
        # response carries; configure() adopts the session's conf
        self.integrity = ChecksumPolicy()
        # wire compression (compress/): what this side's fetches request
        # from peers; default none, configure() adopts
        # spark.rapids.shuffle.compression.codec
        self.compression = CompressionPolicy()
        # distributed-trace wire stamping (spark.rapids.sql.tpu.trace.
        # enabled): clients attach the current trace context to fetch
        # requests; configure() adopts the conf
        self.trace_enabled = True

    def configure(self, conf) -> None:
        """Adopt retry/deadline knobs from a TpuConf (and arm the fault
        injector from its test confs)."""
        from .. import config as C
        from ..compress import compression_from_conf
        from ..mem.integrity import policy_from_conf
        faults.INJECTOR.configure_from_conf(conf)
        self.connect_timeout = int(conf.get(C.SHUFFLE_CONNECT_TIMEOUT)) / 1e3
        self.io_timeout = int(conf.get(C.SHUFFLE_IO_TIMEOUT)) / 1e3
        self.max_attempts = int(conf.get(C.SHUFFLE_RETRY_ATTEMPTS))
        self.backoff_base = int(conf.get(C.SHUFFLE_RETRY_BACKOFF_BASE)) / 1e3
        self.backoff_cap = int(conf.get(C.SHUFFLE_RETRY_BACKOFF_CAP)) / 1e3
        self.txn_timeout = int(conf.get(C.SHUFFLE_TXN_TIMEOUT)) / 1e3
        self.integrity = policy_from_conf(conf)
        self.compression = compression_from_conf(
            conf, metrics=self.compression.metrics)
        self.trace_enabled = bool(conf.get(C.TRACE_ENABLED))

    def next_txn(self) -> Transaction:
        with self._lock:
            self._txn_counter += 1
            return Transaction(self._txn_counter,
                               TransactionStatus.IN_PROGRESS)

    def count(self, key: str, n: int = 1) -> None:
        with self._lock:
            self.counters[key] = self.counters.get(key, 0) + n

    def register_server(self, executor_id: str, server) -> None:
        # single-owner wiring: runs once at worker startup, before any
        # serve/fetch thread exists (the server it builds STARTS them)
        # tpulint: disable=TPU009 startup wiring precedes every thread that could race it
        self._server = ShuffleSocketServer(self, server, self.rpc_handler,
                                           self._host, self._port)
        self.address = self._server.address  # tpulint: disable=TPU009 startup wiring precedes every thread that could race it
        self._peers[executor_id] = self.address

    def set_peers(self, peers: Dict[str, Tuple[str, int]],
                  replace: bool = False) -> None:
        """Adopt a peer address map.  `replace=True` additionally PRUNES
        peers absent from the new map (a worker slot the driver shrunk
        away under graceful degradation) — their cached clients close so
        no future fetch dials the dead address.  The transport's OWN
        entry survives a replace: the driver's full map always names
        every live worker including the recipient."""
        stale = []
        with self._lock:
            for k, v in peers.items():
                addr = tuple(v)
                if self._peers.get(k) not in (None, addr):
                    # peer re-addressed (executor-loss replacement): any
                    # cached client holds a socket to the DEAD process
                    stale.append(self._clients.pop(k, None))
                self._peers[k] = addr
            if replace:
                for k in [k for k in self._peers if k not in peers]:
                    del self._peers[k]
                    stale.append(self._clients.pop(k, None))
        for client in stale:
            if client is not None:
                client.close()

    def make_client(self, peer_executor_id: str) -> SocketClient:
        with self._lock:
            client = self._clients.get(peer_executor_id)
            if client is None:
                addr = self._peers.get(peer_executor_id)
                if addr is None:
                    raise KeyError(
                        f"no address for peer {peer_executor_id}; "
                        f"known: {sorted(self._peers)}")
                client = SocketClient(self, addr)
                self._clients[peer_executor_id] = client
            return client

    def drop_client(self, peer_executor_id: str) -> None:
        """Forget a peer's cached client (executor-loss recovery: the
        replacement worker listens on a NEW port; the stale client holds
        a socket to the dead one)."""
        with self._lock:
            client = self._clients.pop(peer_executor_id, None)
        if client is not None:
            client.close()

    def shutdown(self) -> None:
        for c in list(self._clients.values()):
            c.close()
        self._clients.clear()
        if self._server is not None:
            self._server.close()
