"""Cross-process shuffle transport over TCP sockets (the DCN wire).

TPU-native analogue of the reference's UCX network stack
(shuffle-plugin/.../ucx/UCX.scala:54-533 — endpoint bring-up + tagged
sends over a management-port handshake; UCXShuffleTransport.scala:47-507 —
client/server factory, bounce-buffer pools, inflight throttle).  On TPU
pods the *intra-query* exchange rides ICI collectives inside the mesh
program (shuffle/ici.py); this socket transport is the host-side DCN path
between executor PROCESSES — the role UCX-over-IB plays for the reference —
so shuffle bytes genuinely cross a process/host boundary.

Wire protocol: length-prefixed frames `u32 length | u8 opcode | payload`.
Control payloads (metadata request/response, buffer layouts) are pickled
dataclasses — this is a Python-to-Python control plane, the analogue of
the reference's flatbuffers messages (shuffle-plugin/.../fbs).  Data moves
as raw frames in bounce-buffer-sized chunks: the serving side stages every
chunk through its BounceBufferPool slice before the socket send, and the
receiving side caps concurrent fetch bytes with the InflightThrottle, so
both ends keep the reference's flow-control structure on a real wire.

The same port also carries a tiny RPC opcode used by the worker control
plane (shuffle/worker.py) — the analogue of UCX's management port.
"""
from __future__ import annotations

import logging
import os
import pickle
import random
import socket
import struct
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from ..utils import faults
from .transport import (BounceBufferPool, InflightThrottle, MetadataRequest,
                        MetadataResponse, ShuffleTransport,
                        ShuffleTransportClient, Transaction,
                        TransactionCancelled, TransactionStatus)

log = logging.getLogger("spark_rapids_tpu.shuffle")

# opcodes
OP_META, OP_META_RESP = 1, 2
OP_LAYOUT, OP_LAYOUT_RESP = 3, 4
OP_FETCH, OP_DATA, OP_END = 5, 6, 7
OP_DONE, OP_ACK = 8, 9
OP_FETCH_SHM = 10
# same-host segment path prefix; the server refuses to open anything else
SHM_PREFIX = "/dev/shm/srtpu_shm_"
OP_RPC, OP_RPC_RESP, OP_RPC_ERR = 20, 21, 22

_HDR = struct.Struct(">IB")


def send_frame(sock: socket.socket, op: int, payload) -> None:
    """payload: bytes-like (memoryview over a bounce slice for data)."""
    sock.sendall(_HDR.pack(len(payload), op))
    if len(payload):
        sock.sendall(payload)


def _recv_exact(sock: socket.socket, n: int) -> bytearray:
    buf = bytearray(n)
    view = memoryview(buf)
    got = 0
    while got < n:
        r = sock.recv_into(view[got:], n - got)
        if r == 0:
            raise ConnectionError("peer closed mid-frame")
        got += r
    return buf


def recv_frame(sock: socket.socket) -> Tuple[int, bytes]:
    hdr = _recv_exact(sock, _HDR.size)
    length, op = _HDR.unpack(hdr)
    payload = _recv_exact(sock, length) if length else b""
    return op, bytes(payload)


def recv_frame_into(sock: socket.socket, dest: np.ndarray, offset: int
                    ) -> Tuple[int, int]:
    """Receive one frame; DATA payload lands directly in dest[offset:].
    Returns (opcode, payload_length)."""
    hdr = _recv_exact(sock, _HDR.size)
    length, op = _HDR.unpack(hdr)
    if op != OP_DATA:
        payload = _recv_exact(sock, length) if length else b""
        return op, len(payload)
    view = memoryview(dest)[offset:offset + length]
    got = 0
    while got < length:
        r = sock.recv_into(view[got:], length - got)
        if r == 0:
            raise ConnectionError("peer closed mid-data")
        got += r
    return op, length


class ShuffleSocketServer:
    """Serves one executor's shuffle buffers on a TCP port.

    Each accepted connection gets a handler thread (the reference's UCX
    progress thread pool; RapidsShuffleServer.scala:67-150).  Data chunks
    are staged through the transport's BounceBufferPool before each send,
    so serving a spilled buffer never inflates memory beyond the pool."""

    def __init__(self, transport: "SocketTransport", server_obj,
                 rpc_handler: Optional[Callable] = None,
                 host: str = "127.0.0.1", port: int = 0):
        self.transport = transport
        self.server_obj = server_obj
        self.rpc_handler = rpc_handler
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind((host, port))
        self._listener.listen(16)
        self.address = self._listener.getsockname()
        self._closing = False
        self._threads: List[threading.Thread] = []
        t = threading.Thread(target=self._accept_loop, daemon=True,
                             name="shuffle-accept")
        t.start()
        self._threads.append(t)

    def _accept_loop(self) -> None:
        consecutive_errors = 0
        while not self._closing:
            try:
                conn, _ = self._listener.accept()
            except OSError as e:
                if self._closing:
                    return
                # transient accept failures (ECONNABORTED from a client
                # abort, EMFILE during an fd burst) must not kill the
                # server while the executor lives on looking healthy —
                # count, log, and keep accepting; only a persistently
                # broken listener stops the loop
                self.transport.count("accept_errors")
                consecutive_errors += 1
                # generous tolerance: reconnect-per-retry clients churn
                # connections during fault storms, and an fd burst
                # (EMFILE) can persist for seconds — an executor that
                # stops accepting while "looking healthy" costs every
                # peer ioTimeout * maxAttempts per fetch until restart
                if consecutive_errors > 20 or self._listener.fileno() < 0:
                    log.error("shuffle server %s stopping after repeated "
                              "accept failures: %r", self.address, e)
                    return
                log.warning("shuffle server %s accept failed "
                            "(%d consecutive): %r", self.address,
                            consecutive_errors, e)
                time.sleep(min(1.0, 0.05 * consecutive_errors))
                continue
            consecutive_errors = 0
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            t = threading.Thread(target=self._serve, args=(conn,),
                                 daemon=True, name="shuffle-serve")
            t.start()
            # prune finished handlers: reconnect-per-retry clients churn
            # connections, and retaining every dead Thread forever is an
            # unbounded leak in exactly the fault-heavy regime
            self._threads = [x for x in self._threads if x.is_alive()]
            self._threads.append(t)

    def _serve(self, conn: socket.socket) -> None:
        try:
            peer = conn.getpeername()
        except OSError:
            peer = "<unknown>"
        try:
            while True:
                op, payload = recv_frame(conn)
                if op == OP_META:
                    req: MetadataRequest = pickle.loads(payload)
                    resp = self.server_obj.handle_metadata_request(req)
                    self.transport.count("metadata_served")
                    send_frame(conn, OP_META_RESP, pickle.dumps(resp))
                elif op == OP_LAYOUT:
                    (bid,) = struct.unpack(">Q", payload)
                    layout, meta = self.server_obj.buffer_layout(bid)
                    send_frame(conn, OP_LAYOUT_RESP,
                               pickle.dumps((layout, meta)))
                elif op == OP_FETCH:
                    (bid,) = struct.unpack(">Q", payload)
                    self._stream_buffer(conn, bid)
                elif op == OP_FETCH_SHM:
                    bid, shm_name = pickle.loads(payload)
                    self._fill_shm(conn, bid, shm_name)
                elif op == OP_DONE:
                    (bid,) = struct.unpack(">Q", payload)
                    self.server_obj.done_serving(bid)
                    send_frame(conn, OP_ACK, b"")
                elif op == OP_RPC:
                    self._handle_rpc(conn, payload)
                else:
                    raise ValueError(f"bad opcode {op}")
        except (ConnectionError, OSError) as e:
            # peer went away; its requests die with the connection — but
            # the event is counted and logged with the peer address, not
            # silently dropped (a flapping peer shows up in the counters)
            self.transport.count("peer_disconnects")
            if not self._closing:
                log.info("shuffle peer %s disconnected: %r", peer, e)
        finally:
            try:
                conn.close()
            except OSError as e:
                log.debug("closing connection from %s: %r", peer, e)

    def _stream_buffer(self, conn: socket.socket, bid: int) -> None:
        """Send every leaf of a buffer as bounce-buffer-sized DATA frames,
        in leaf order, then END (BufferSendState: acquire buffer from any
        tier -> stage through send bounce buffers -> tagged sends)."""
        layout, _meta = self.server_obj.buffer_layout(bid)
        pool = self.transport.pool
        chunk = self.transport.chunk_size
        for leaf_idx, (_shape, _dtype, nbytes) in enumerate(layout):
            off = 0
            while off < nbytes:
                length = min(chunk, nbytes - off)
                addr = pool.acquire(length)
                try:
                    view = pool.view(addr, length)
                    self.server_obj.copy_leaf_chunk(bid, leaf_idx, off,
                                                    length, view)
                    send_frame(conn, OP_DATA, memoryview(view))
                finally:
                    pool.release(addr)
                off += length
                self.transport.count("bytes_sent", length)
        send_frame(conn, OP_END, b"")

    def _fill_shm(self, conn: socket.socket, bid: int,
                  shm_path: str) -> None:
        """Same-host fast path: copy each leaf ONCE into the client-owned
        /dev/shm segment instead of chunking through bounce buffers and
        the socket (the local-peer analogue of the reference's UCX
        zero-copy RDMA).  The socket carries only the END ack.  A plain
        tmpfs file + mmap, NOT multiprocessing.shared_memory — the stdlib
        resource tracker logs a KeyError per cross-process segment on
        this python version."""
        import mmap
        if not shm_path.startswith(SHM_PREFIX):
            send_frame(conn, OP_RPC_ERR,
                       pickle.dumps(f"bad shm path {shm_path!r}"))
            return
        try:
            fd = os.open(shm_path, os.O_RDWR)
            try:
                mm = mmap.mmap(fd, 0)
            finally:
                os.close(fd)
        except OSError as e:
            send_frame(conn, OP_RPC_ERR, pickle.dumps(f"shm open: {e!r}"))
            return
        try:
            layout, _meta = self.server_obj.buffer_layout(bid)
            off = 0
            for leaf_idx, (_shape, _dtype, nbytes) in enumerate(layout):
                view = np.frombuffer(mm, np.uint8, count=nbytes,
                                     offset=off)
                try:
                    self.server_obj.copy_leaf_chunk(bid, leaf_idx, 0,
                                                    nbytes, view)
                finally:
                    # the view exports the mmap; it must die before
                    # mm.close() (BufferError otherwise)
                    del view
                off += nbytes
            self.transport.count("bytes_sent", off)
            self.transport.count("shm_fills")
            send_frame(conn, OP_END, b"")
        finally:
            mm.close()

    def _handle_rpc(self, conn: socket.socket, payload: bytes) -> None:
        if self.rpc_handler is None:
            send_frame(conn, OP_RPC_ERR,
                       pickle.dumps("no rpc handler registered"))
            return
        try:
            method, kwargs = pickle.loads(payload)
            result = self.rpc_handler(method, kwargs)
            send_frame(conn, OP_RPC_RESP, pickle.dumps(result))
        except Exception as e:  # noqa: BLE001 — crosses the wire
            import traceback
            # counted and logged server-side too: the client may be gone
            # by the time the error frame would reach it
            self.transport.count("rpc_errors")
            log.warning("shuffle rpc failed server-side: %r", e)
            send_frame(conn, OP_RPC_ERR,
                       pickle.dumps(f"{e!r}\n{traceback.format_exc()}"))

    def close(self) -> None:
        self._closing = True
        # shutdown() BEFORE close(): on Linux, close() does not wake a
        # thread blocked in accept() — the kernel keeps the listening
        # socket alive for the in-flight syscall and KEEPS ACCEPTING,
        # so a "closed" server would silently serve forever.  shutdown
        # forces the blocked accept to return.
        try:
            self._listener.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass  # not connected / already gone — nothing to wake
        try:
            self._listener.close()
        except OSError as e:
            log.debug("closing shuffle listener %s: %r", self.address, e)


class SocketClient(ShuffleTransportClient):
    """Fetch path to one remote executor over its TCP port.  One socket,
    requests serialized under a lock (the reference serializes per-endpoint
    through UCX's tag space).

    Robustness contract (reference: UCX endpoint error handler + the
    RapidsShuffleClient retry/reissue path):

      * every DATA-plane operation (metadata, layout, fetch, done) runs
        under a per-op I/O deadline (`spark.rapids.shuffle.ioTimeoutMs`),
        so a dead peer surfaces as a timeout instead of a hang;
      * failed operations reconnect and retry with exponential backoff +
        deterministic jitter, up to `spark.rapids.shuffle.retry.maxAttempts`
        (requests restart from scratch on a FRESH socket — a half-read
        frame poisons the stream);
      * a whole fetch runs as a Transaction with an overall deadline
        (`transactionTimeoutMs`); past it the transaction is CANCELLED and
        no further retries are attempted;
      * control-plane RPCs are exempt from the I/O deadline: task dispatch
        legitimately blocks on the peer's first-query compilation.
    """

    def __init__(self, transport: "SocketTransport",
                 addr: Tuple[str, int]):
        self.transport = transport
        self.addr = tuple(addr)
        self._sock: Optional[socket.socket] = None
        self._lock = threading.Lock()
        # deterministic jitter: seeded per peer address, not wall clock
        self._rng = random.Random(f"shuffle-retry:{self.addr}")

    def _conn(self) -> socket.socket:
        if self._sock is None:
            t = self.transport
            s = socket.create_connection(self.addr,
                                         timeout=t.connect_timeout)
            # the connect bound above is per-attempt; steady-state requests
            # run under the (configurable) I/O deadline so a peer that dies
            # mid-request raises instead of blocking forever
            s.settimeout(t.io_timeout if t.io_timeout > 0 else None)
            s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            self._sock = s
        return self._sock

    def _drop_socket(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError as e:
                log.debug("closing shuffle socket to %s: %r", self.addr, e)
            self._sock = None

    def _backoff(self, attempt: int) -> float:
        t = self.transport
        raw = min(t.backoff_cap, t.backoff_base * (2 ** attempt))
        return raw * (0.5 + self._rng.random() / 2)  # jittered

    def _retrying(self, label: str, body, deadline: Optional[float] = None,
                  txn: Optional[Transaction] = None):
        """Run `body(sock)` with reconnect-and-retry.  Takes self._lock
        per ATTEMPT and sleeps the backoff unlocked, so a concurrent
        control-plane rpc() or close() to the same peer fails/finishes
        fast instead of stalling behind the backoff series.  `deadline`
        (monotonic) bounds the WHOLE operation including retries;
        crossing it cancels the transaction."""
        attempts = max(1, self.transport.max_attempts)
        last: Optional[BaseException] = None
        for attempt in range(attempts):
            if deadline is not None and time.monotonic() > deadline:
                with self._lock:
                    self._drop_socket()
                raise (txn.cancel(f"{label} to {self.addr} exceeded "
                                  "the transaction deadline") if txn
                       else TransactionCancelled(
                           f"{label} to {self.addr} exceeded deadline"))
            try:
                with self._lock:
                    faults.INJECTOR.on_net_op(label)
                    return body(self._conn())
            except TransactionCancelled:
                with self._lock:
                    self._drop_socket()  # the stream is poisoned mid-frame
                raise
            except (TimeoutError, ConnectionError, OSError) as e:
                # socket.timeout is a TimeoutError (itself an OSError);
                # injected faults are ConnectionErrors.  All of them tear
                # the socket down so the next attempt starts clean.
                with self._lock:
                    self._drop_socket()
                last = e
                self.transport.count("net_op_failures")
                log.warning("shuffle %s to %s failed "
                            "(attempt %d/%d): %r", label, self.addr,
                            attempt + 1, attempts, e)
                if attempt + 1 >= attempts:
                    break
                self.transport.count("net_op_retries")
                time.sleep(self._backoff(attempt))
        if txn is not None:
            txn.fail(repr(last))
        raise ConnectionError(
            f"shuffle {label} to {self.addr} failed after "
            f"{attempts} attempts: {last!r}") from last

    def _request(self, op: int, payload, expect: int) -> bytes:
        sock = self._conn()
        send_frame(sock, op, payload)
        got, resp = recv_frame(sock)
        if got == OP_RPC_ERR:
            raise RuntimeError(f"remote error: {pickle.loads(resp)}")
        if got != expect:
            raise ConnectionError(f"expected opcode {expect}, got {got}")
        return resp

    def fetch_metadata(self, request: MetadataRequest) -> MetadataResponse:
        blob = pickle.dumps(request)
        resp = self._retrying(
            "metadata", lambda _s: self._request(OP_META, blob,
                                                 OP_META_RESP))
        self.transport.count("metadata_fetched")
        return pickle.loads(resp)

    def _fetch_buffer_shm(self, layout, meta, buffer_id: int, total: int):
        """Local-peer fetch through a client-owned /dev/shm segment: one
        server-side copy per leaf, no socket data frames.  Returns
        (leaves, meta) or None when shm is unavailable (caller streams)."""
        import mmap
        import tempfile
        try:
            fd, path = tempfile.mkstemp(prefix=os.path.basename(SHM_PREFIX),
                                        dir=os.path.dirname(SHM_PREFIX))
        except OSError as e:
            log.info("shm fetch unavailable (%r); falling back to the "
                     "socket stream", e)
            self.transport.count("shm_unavailable")
            return None
        mm = None
        try:
            os.ftruncate(fd, max(total, 1))
            mm = mmap.mmap(fd, max(total, 1))
            try:
                with self._lock:
                    faults.INJECTOR.on_net_op("fetch_shm")
                    sock = self._conn()
                    send_frame(sock, OP_FETCH_SHM,
                               pickle.dumps((buffer_id, path)))
                    op, _length = recv_frame(sock)
            except (TimeoutError, ConnectionError, OSError) as e:
                # single attempt: the caller streams over the socket
                # instead (which carries the full retry machinery)
                log.warning("shm fetch of buffer %d from %s failed: %r",
                            buffer_id, self.addr, e)
                self.transport.count("net_op_failures")
                with self._lock:
                    self._drop_socket()
                return None
            if op != OP_END:
                return None
            # copy out of the segment: a zero-copy variant (arrays
            # viewing the mmap with finalizer-managed lifetime) measured
            # no faster on loopback and leaked one fd per fetch — one
            # bounded memcpy per leaf is the honest cost
            out: List[np.ndarray] = []
            off = 0
            for (shape, dtype_str, nbytes) in layout:
                a = np.empty(nbytes, dtype=np.uint8)
                src = np.frombuffer(mm, np.uint8, count=nbytes,
                                    offset=off)
                try:
                    a[:] = src
                finally:
                    del src  # release the mmap export before mm.close()
                out.append(a.view(np.dtype(dtype_str)).reshape(shape))
                off += nbytes
            self.transport.count("bytes_received", off)
            return out, meta
        finally:
            if mm is not None:
                mm.close()
            os.close(fd)
            try:
                os.unlink(path)
            except OSError as e:
                log.debug("unlinking shm segment %s: %r", path, e)

    def fetch_buffer(self, buffer_id: int):
        # one fetch == one Transaction: layout + every data frame + END
        # under a single overall deadline, so a peer that dies mid-stream
        # cancels the transaction instead of hanging the reduce task
        txn = self.transport.next_txn()
        deadline = (time.monotonic() + self.transport.txn_timeout
                    if self.transport.txn_timeout > 0 else None)
        resp = self._retrying(
            "layout",
            lambda _s: self._request(OP_LAYOUT,
                                     struct.pack(">Q", buffer_id),
                                     OP_LAYOUT_RESP),
            deadline=deadline, txn=txn)
        layout, meta = pickle.loads(resp)
        total = sum(nb for _, _, nb in layout)
        self.transport.throttle.acquire(total)
        try:
            if self.addr[0] in ("127.0.0.1", "localhost", "::1") \
                    and self.transport.shm_local:
                got = self._fetch_buffer_shm(layout, meta, buffer_id,
                                             total)
                if got is not None:
                    txn.complete(total)
                    return got

            def stream(sock) -> List[np.ndarray]:
                send_frame(sock, OP_FETCH, struct.pack(">Q", buffer_id))
                out: List[np.ndarray] = []
                for (shape, dtype_str, nbytes) in layout:
                    dest = np.empty(nbytes, dtype=np.uint8)
                    off = 0
                    while off < nbytes:
                        if deadline is not None \
                                and time.monotonic() > deadline:
                            raise txn.cancel(
                                f"fetch of buffer {buffer_id} from "
                                f"{self.addr} mid-stream at {off}/{nbytes}")
                        op, length = recv_frame_into(sock, dest, off)
                        if op != OP_DATA:
                            raise ConnectionError(
                                f"short buffer stream (op {op} at "
                                f"{off}/{nbytes})")
                        off += length
                        self.transport.count("bytes_received", length)
                    out.append(dest.view(np.dtype(dtype_str)).reshape(shape))
                op, _ = recv_frame(sock)
                if op != OP_END:
                    raise ConnectionError(f"expected END, got {op}")
                return out

            out = self._retrying("fetch", stream, deadline=deadline,
                                 txn=txn)
            txn.complete(total)
            return out, meta
        finally:
            self.transport.throttle.release(total)

    def release_buffer(self, buffer_id: int) -> None:
        # done_serving is idempotent at the server, so the retry is safe
        self._retrying(
            "done", lambda _s: self._request(
                OP_DONE, struct.pack(">Q", buffer_id), OP_ACK))

    def rpc(self, method: str, **kwargs):
        """Control-plane call (worker management; UCX mgmt-port analogue).

        Deliberately NOT retried (run_map/run_reduce are not idempotent)
        and exempt from the data-plane I/O deadline: the first dispatch of
        a plan fragment blocks on the PEER's query compilation, which can
        legitimately exceed any fixed bound."""
        with self._lock:
            faults.INJECTOR.on_net_op("rpc")
            try:
                sock = self._conn()
                sock.settimeout(None)  # compile-friendly: no I/O deadline
                try:
                    send_frame(sock, OP_RPC, pickle.dumps((method, kwargs)))
                    op, resp = recv_frame(sock)
                finally:
                    if self._sock is not None:
                        try:
                            self._sock.settimeout(
                                self.transport.io_timeout
                                if self.transport.io_timeout > 0 else None)
                        except OSError:
                            self._drop_socket()  # broken mid-rpc
            except (TimeoutError, ConnectionError, OSError) as e:
                self._drop_socket()
                self.transport.count("net_op_failures")
                log.warning("shuffle rpc %s to %s failed: %r", method,
                            self.addr, e)
                raise
        if op == OP_RPC_ERR:
            raise RuntimeError(f"worker rpc {method} failed: "
                               f"{pickle.loads(resp)}")
        if op != OP_RPC_RESP:
            raise ConnectionError(f"expected RPC_RESP, got {op}")
        return pickle.loads(resp)

    def close(self) -> None:
        with self._lock:
            self._drop_socket()


class SocketTransport(ShuffleTransport):
    """Client/server factory over TCP (UCXShuffleTransport analogue).

    Peers are discovered through an explicit address map (executor_id ->
    (host, port)) distributed by the cluster driver — the role MapStatus /
    the UCX management handshake plays for the reference."""

    def __init__(self, pool_size: int = 8 << 20, chunk_size: int = 1 << 20,
                 max_inflight_bytes: int = 4 << 20,
                 host: str = "127.0.0.1", port: int = 0,
                 rpc_handler: Optional[Callable] = None,
                 shm_local: bool = False,
                 connect_timeout: float = 30.0, io_timeout: float = 60.0,
                 max_attempts: int = 4, backoff_base: float = 0.05,
                 backoff_cap: float = 2.0, txn_timeout: float = 600.0):
        # measured on 128MB partitions (BENCH_WIRE.json): the pipelined
        # chunked stream does ~1.05 GB/s on loopback while the serial
        # fill-then-copy shm path does ~0.7 GB/s — so the stream is the
        # default and shm stays an option for CPU-constrained hosts
        # (2 copies + no socket syscalls vs 3 copies through the kernel)
        self.shm_local = shm_local
        self.pool = BounceBufferPool(pool_size, chunk_size)
        self.chunk_size = chunk_size
        self.throttle = InflightThrottle(max_inflight_bytes)
        self._host, self._port = host, port
        self.rpc_handler = rpc_handler
        # retry/deadline policy (seconds); configure(conf) overrides from
        # the spark.rapids.shuffle.* knobs
        self.connect_timeout = connect_timeout
        self.io_timeout = io_timeout
        self.max_attempts = max_attempts
        self.backoff_base = backoff_base
        self.backoff_cap = backoff_cap
        self.txn_timeout = txn_timeout
        self._peers: Dict[str, Tuple[str, int]] = {}
        self._clients: Dict[str, SocketClient] = {}
        self._server: Optional[ShuffleSocketServer] = None
        self.address: Optional[Tuple[str, int]] = None
        self._lock = threading.Lock()
        self._txn_counter = 0
        self.counters: Dict[str, int] = {}

    def configure(self, conf) -> None:
        """Adopt retry/deadline knobs from a TpuConf (and arm the fault
        injector from its test confs)."""
        from .. import config as C
        faults.INJECTOR.configure_from_conf(conf)
        self.connect_timeout = int(conf.get(C.SHUFFLE_CONNECT_TIMEOUT)) / 1e3
        self.io_timeout = int(conf.get(C.SHUFFLE_IO_TIMEOUT)) / 1e3
        self.max_attempts = int(conf.get(C.SHUFFLE_RETRY_ATTEMPTS))
        self.backoff_base = int(conf.get(C.SHUFFLE_RETRY_BACKOFF_BASE)) / 1e3
        self.backoff_cap = int(conf.get(C.SHUFFLE_RETRY_BACKOFF_CAP)) / 1e3
        self.txn_timeout = int(conf.get(C.SHUFFLE_TXN_TIMEOUT)) / 1e3

    def next_txn(self) -> Transaction:
        with self._lock:
            self._txn_counter += 1
            return Transaction(self._txn_counter,
                               TransactionStatus.IN_PROGRESS)

    def count(self, key: str, n: int = 1) -> None:
        with self._lock:
            self.counters[key] = self.counters.get(key, 0) + n

    def register_server(self, executor_id: str, server) -> None:
        self._server = ShuffleSocketServer(self, server, self.rpc_handler,
                                           self._host, self._port)
        self.address = self._server.address
        self._peers[executor_id] = self.address

    def set_peers(self, peers: Dict[str, Tuple[str, int]]) -> None:
        stale = []
        with self._lock:
            for k, v in peers.items():
                addr = tuple(v)
                if self._peers.get(k) not in (None, addr):
                    # peer re-addressed (executor-loss replacement): any
                    # cached client holds a socket to the DEAD process
                    stale.append(self._clients.pop(k, None))
                self._peers[k] = addr
        for client in stale:
            if client is not None:
                client.close()

    def make_client(self, peer_executor_id: str) -> SocketClient:
        with self._lock:
            client = self._clients.get(peer_executor_id)
            if client is None:
                addr = self._peers.get(peer_executor_id)
                if addr is None:
                    raise KeyError(
                        f"no address for peer {peer_executor_id}; "
                        f"known: {sorted(self._peers)}")
                client = SocketClient(self, addr)
                self._clients[peer_executor_id] = client
            return client

    def drop_client(self, peer_executor_id: str) -> None:
        """Forget a peer's cached client (executor-loss recovery: the
        replacement worker listens on a NEW port; the stale client holds
        a socket to the dead one)."""
        with self._lock:
            client = self._clients.pop(peer_executor_id, None)
        if client is not None:
            client.close()

    def shutdown(self) -> None:
        for c in list(self._clients.values()):
            c.close()
        self._clients.clear()
        if self._server is not None:
            self._server.close()
