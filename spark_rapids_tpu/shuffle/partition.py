"""Device partitioners: per-row partition ids + batch split.

TPU-native analogue of the reference's partitioner family
(rapids/GpuHashPartitioning.scala — murmur3 on device matching Spark;
GpuRangePartitioner.scala:42-216 — host reservoir sampling for bounds,
device searchsorted; GpuRoundRobinPartitioning.scala; GpuSinglePartitioning
.scala) and of `Table.contiguousSplit` (Plugin.scala:54-83): one device sort
by partition id splits a batch into per-partition contiguous sub-batches.

All id kernels are pure jnp and trace into the surrounding program; the
split syncs ONCE to the host for the per-partition counts (the same sync
contiguousSplit's size array implies).
"""
from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import jax.numpy as jnp
import numpy as np

from ..columnar import Column, ColumnarBatch, bucket_rows
from ..ops.hashing import spark_hash_columns
from ..exec.sort import column_sort_keys


# ---- partition id kernels (traced) -----------------------------------------

def hash_partition_ids(key_cols: Sequence[Column], n: int) -> jnp.ndarray:
    """Spark semantics: Pmod(Murmur3Hash(keys, 42), n) — non-negative."""
    h = spark_hash_columns(list(key_cols), seed=42)
    return ((h % jnp.int32(n)) + jnp.int32(n)) % jnp.int32(n)


def round_robin_partition_ids(capacity: int, n: int, start: int
                              ) -> jnp.ndarray:
    """Row-position round robin from a per-task start offset."""
    iota = jnp.arange(capacity, dtype=jnp.int32)
    return (iota + jnp.int32(start)) % jnp.int32(n)


def single_partition_ids(capacity: int) -> jnp.ndarray:
    return jnp.zeros(capacity, dtype=jnp.int32)


def range_partition_ids(batch: ColumnarBatch,
                        sort_exprs, ascending: Sequence[bool],
                        nulls_first: Sequence[bool],
                        bounds_batch: ColumnarBatch) -> jnp.ndarray:
    """Partition id = number of range bounds strictly below the row, under
    the sort-key ordering (nulls placed per spec).  The B bounds live in a
    small device batch; the compare is a static loop over B reusing the sort
    module's order-preserving key encoding — O(cap*B) elementwise, no
    searchsorted with dynamic shapes."""
    row_keys = _encoded_keys(batch, sort_exprs, ascending, nulls_first)
    # the bounds batch's columns are POSITIONAL (k0..km-1), not the child
    # schema — re-bind by ordinal, never by the original expressions
    bound_refs = [_bound_ref(i, e.dtype) for i, e in enumerate(sort_exprs)]
    bnd_keys = _encoded_keys(bounds_batch, bound_refs, ascending, nulls_first)
    B = bounds_batch.capacity
    nbounds = int(bounds_batch.num_rows_host())
    pid = jnp.zeros(batch.capacity, dtype=jnp.int32)
    for b in range(nbounds):
        gt = jnp.zeros(batch.capacity, dtype=jnp.bool_)
        eq = jnp.ones(batch.capacity, dtype=jnp.bool_)
        for rk, bk in zip(row_keys, bnd_keys):
            bkb = bk[b]
            gt = gt | (eq & (rk > bkb))
            eq = eq & (rk == bkb)
        # row beyond bound b (ties stay in the lower partition, like
        # Spark's RangePartitioner binary search with <=)
        pid = pid + gt.astype(jnp.int32)
    return pid


def _encoded_keys(batch: ColumnarBatch, sort_exprs, ascending,
                  nulls_first) -> List[jnp.ndarray]:
    keys: List[jnp.ndarray] = []
    for e, asc, nf in zip(sort_exprs, ascending, nulls_first):
        c = e.eval(batch)
        null_rank = jnp.where(c.valid, jnp.int32(1),
                              jnp.int32(0) if nf else jnp.int32(2))
        keys.append(null_rank)
        keys.extend(column_sort_keys(c, asc))
    return keys


# ---- range bound sampling (host side) --------------------------------------

def sample_range_bounds(batches: Sequence[ColumnarBatch], sort_exprs,
                        ascending: Sequence[bool],
                        nulls_first: Sequence[bool], n_parts: int,
                        sample_size: int = 4096,
                        seed: int = 42) -> Optional[ColumnarBatch]:
    """Reservoir-sample sort-key rows across batches on the HOST, order them
    with the device sort kernel, and pick n_parts-1 evenly spaced bounds
    (reference: GpuRangePartitioner.sketch/determineBounds,
    GpuRangePartitioner.scala:42-216 + SamplingUtils.scala).  Returns a
    small device batch of bound rows, or None when there is no data."""
    from ..exec.sort import sort_order
    from ..types import Schema, StructField

    key_schema = Schema([StructField(f"k{i}", e.dtype)
                         for i, e in enumerate(sort_exprs)])
    rng = np.random.RandomState(seed)
    reservoir: List[tuple] = []
    seen = 0
    for b in batches:
        cols = [e.eval(b) for e in sort_exprs]
        kb = ColumnarBatch(cols, b.sel, key_schema)
        for row in kb.to_pylist():
            seen += 1
            if len(reservoir) < sample_size:
                reservoir.append(row)
            else:
                j = rng.randint(0, seen)
                if j < sample_size:
                    reservoir[j] = row
    if not reservoir:
        return None
    sample = ColumnarBatch.from_pydict(
        {f.name: [r[i] for r in reservoir]
         for i, f in enumerate(key_schema)}, key_schema)
    refs = [_bound_ref(i, e.dtype) for i, e in enumerate(sort_exprs)]
    order = sort_order(sample, refs, list(ascending), list(nulls_first))
    ordered = sample.take(order).compact()
    cnt = ordered.num_rows_host()
    picks = [min(cnt - 1, max(0, round((b + 1) * cnt / n_parts) - 1))
             for b in range(n_parts - 1)]
    rows = ordered.to_pylist()
    chosen = [rows[p] for p in picks]
    return ColumnarBatch.from_pydict(
        {f.name: [r[i] for r in chosen] for i, f in enumerate(key_schema)},
        key_schema, capacity=bucket_rows(max(len(chosen), 1)))


def _bound_ref(i: int, dtype):
    from ..ops import expressions as E
    return E.BoundReference(i, dtype, f"k{i}")


# ---- split (contiguousSplit equivalent) ------------------------------------

def split_by_partition(batch: ColumnarBatch, pids: jnp.ndarray, n: int,
                       min_bucket: int = 1024
                       ) -> List[Tuple[int, ColumnarBatch]]:
    """Split into per-partition compacted sub-batches.

    One stable device sort groups rows by partition id (dead rows pushed
    past all partitions), one host sync reads the n counts, then each
    non-empty partition is a clipped gather into a bucketed capacity.
    Returns [(partition_id, batch)] for non-empty partitions."""
    cap = batch.capacity
    live = batch.sel
    key = jnp.where(live, pids.astype(jnp.int64), jnp.int64(n))
    from ..exec.sort import _packed_or_argsort
    order = _packed_or_argsort(key, max(1, int(n).bit_length()), cap)
    sorted_batch = batch.take(order)
    counts = np.asarray(jnp.bincount(
        jnp.where(live, pids, jnp.int32(n)), length=n + 1))[:n]
    out: List[Tuple[int, ColumnarBatch]] = []
    off = 0
    for p in range(n):
        cnt = int(counts[p])
        if cnt == 0:
            continue
        pcap = bucket_rows(cnt, min_bucket)
        idx = off + jnp.arange(pcap, dtype=jnp.int32)
        sel = jnp.arange(pcap, dtype=jnp.int32) < cnt
        sub = sorted_batch.take(idx, sel=sel)
        # the count is already host-known here: stamping it lets the
        # shuffle write path record map-output statistics (and the worker
        # report MapStatus rows) without a device sync per sub-batch
        sub.known_rows = cnt
        out.append((p, sub))
        off += cnt
    return out
