"""Shuffle transport SPI: control plane, staging buffers, loopback fake.

TPU-native analogue of the reference's transport stack
(rapids/shuffle/RapidsShuffleTransport.scala:38-500 — client/server SPI,
bounce-buffer pools, inflight-bytes throttle, Transaction lifecycle;
RapidsShuffleClient.scala:350-770 — metadata request -> throttled buffer
receives; RapidsShuffleServer.scala:67-671 — serve buffers from any tier
through send bounce buffers).  The flatbuffers control messages become plain
dataclasses; UCX tag-matched RDMA becomes: LOOPBACK (in-memory, for tests —
the unit-testable fake the reference snapshot lacks, SURVEY.md §4) and the
ICI all-to-all path in ici.py for mesh-resident SPMD plans.

Data still moves through a bounded staging (bounce-buffer) pool with an
inflight-bytes throttle, so the flow control logic is real even when the
wire is memcpy.
"""
from __future__ import annotations

import enum
import threading
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from ..mem.address_space import AddressSpaceAllocator
from ..mem.buffer import BatchMeta
from ..mem.integrity import (BufferGone, ChecksumPolicy, CorruptBuffer,
                             CorruptShuffleBlock)
from ..utils import faults
from .catalog import ShuffleBlockId


# ---- transaction lifecycle (RapidsShuffleTransport.scala:311-376) ----------

class TransactionStatus(enum.Enum):
    NOT_STARTED = 0
    IN_PROGRESS = 1
    SUCCESS = 2
    ERROR = 3
    CANCELLED = 4


class TransactionCancelled(RuntimeError):
    """A shuffle transaction blew its overall deadline and was cancelled.
    Deliberately NOT an OSError: the per-op retry loop must not retry a
    cancelled transaction (the deadline already covered the retries)."""


@dataclass
class Transaction:
    txn_id: int
    status: TransactionStatus = TransactionStatus.NOT_STARTED
    bytes_transferred: int = 0
    error_message: Optional[str] = None

    def complete(self, nbytes: int) -> None:
        self.bytes_transferred += nbytes
        self.status = TransactionStatus.SUCCESS

    def fail(self, msg: str) -> None:
        self.status = TransactionStatus.ERROR
        self.error_message = msg

    def cancel(self, msg: str) -> "TransactionCancelled":
        """Mark cancelled and build the error to raise (the caller
        raises, so tracebacks point at the cancelling site)."""
        self.status = TransactionStatus.CANCELLED
        self.error_message = msg
        return TransactionCancelled(
            f"shuffle transaction {self.txn_id} cancelled: {msg}")


# ---- control messages (the .fbs schemas, as dataclasses) -------------------

@dataclass
class MetadataRequest:
    """Either an explicit block list, or a (shuffle_id, reduce_id) wildcard
    asking the peer to enumerate every block it holds for that reduce
    partition (the discovery the reference gets from MapStatus)."""
    blocks: Optional[List[ShuffleBlockId]] = None
    shuffle_id: Optional[int] = None
    reduce_id: Optional[int] = None
    # wildcard restricted to map ids [map_lo, map_hi) — the skew-join
    # slice fetch (adaptive/stats.py PartialReducerPartitionSpec)
    map_lo: Optional[int] = None
    map_hi: Optional[int] = None


@dataclass
class BlockMeta:
    block: ShuffleBlockId
    buffer_ids: List[int]
    metas: List[BatchMeta]
    sizes: List[int]
    # per-buffer (algorithm, per-leaf digests) records, aligned with
    # buffer_ids — the digests KNOWN at metadata time, for diagnostics
    # and external consumers of the control plane.  None for buffers not
    # yet host-materialized (still HBM-resident).  Fetch verification
    # does NOT read these: the OP_LAYOUT/buffer_checksums response at
    # fetch time is the authoritative source (it exists by then, the
    # server's _leaves call having just established it).
    checksums: Optional[List[Optional[tuple]]] = None


@dataclass
class MetadataResponse:
    block_metas: List[BlockMeta]


@dataclass
class TransferRequest:
    buffer_ids: List[int]


# ---- bounce buffers (BounceBufferManager.scala + AddressSpaceAllocator) ----

class BounceBufferPool:
    """One pre-allocated host staging area sub-allocated into per-transfer
    slices; acquire blocks until space frees (backpressure)."""

    def __init__(self, pool_size: int, buffer_size: int = 1 << 20):
        self.buffer_size = buffer_size
        self._backing = np.zeros(pool_size, dtype=np.uint8)
        from ..native import NativeAddressSpaceAllocator, native_available
        if native_available():
            self._alloc = NativeAddressSpaceAllocator(pool_size)
        else:
            self._alloc = AddressSpaceAllocator(pool_size)
        self._cond = threading.Condition()

    def acquire(self, length: int, timeout: float = 30.0) -> int:
        """Returns the slice start address.  Blocks until available."""
        assert length <= self._alloc.size, \
            f"transfer slice {length} exceeds pool {self._alloc.size}"
        with self._cond:
            deadline = None
            while True:
                addr = self._alloc.allocate(length)
                if addr is not None:
                    return addr
                import time
                if deadline is None:
                    deadline = time.monotonic() + timeout
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise TimeoutError("bounce buffer pool exhausted")
                self._cond.wait(remaining)

    def release(self, addr: int) -> None:
        with self._cond:
            self._alloc.free(addr)
            self._cond.notify_all()

    def view(self, addr: int, length: int) -> np.ndarray:
        return self._backing[addr:addr + length]


class InflightThrottle:
    """Caps bytes of shuffle data in flight to a receiving task
    (spark.rapids.shuffle.maxReceiveInflightBytes;
    UCXShuffleTransport.scala:363-471 queuePending)."""

    def __init__(self, max_bytes: int):
        self.max_bytes = max_bytes
        self._inflight = 0
        self.peak = 0
        self._cond = threading.Condition()

    def acquire(self, nbytes: int) -> None:
        take = min(nbytes, self.max_bytes)  # a single huge buffer still flows
        with self._cond:
            while self._inflight + take > self.max_bytes:
                self._cond.wait()
            self._inflight += take
            self.peak = max(self.peak, self._inflight)

    def release(self, nbytes: int) -> None:
        take = min(nbytes, self.max_bytes)
        with self._cond:
            self._inflight -= take
            self._cond.notify_all()


# ---- integrity helpers ------------------------------------------------------

def verify_fetched_leaf(policy: ChecksumPolicy, arr: np.ndarray,
                        expected: int, buffer_id: int, leaf_idx: int,
                        path: str) -> None:
    """Verify one fully-received leaf against the writer's digest.

    On mismatch the leaf is hashed a SECOND time before raising: two
    different digests of the same bytes mean the reader's own memory is
    flaky (`site="reader"`), a stable wrong digest means the bytes were
    corrupted in transit (`site=path`) — the reader half of the
    SPARK-36206 corruption-site diagnosis (the writer half is the
    diagnose_buffer RPC)."""
    got = policy.checksum_one(arr)
    want = int(expected)
    if got == want:
        return
    second = policy.checksum_one(arr)
    site = "reader" if second != got else path
    raise CorruptShuffleBlock(
        f"buffer {buffer_id} leaf {leaf_idx} failed {policy.algorithm} "
        f"verification on the {path} path: expected {want:#x}, "
        f"computed {got:#x}", buffer_id=buffer_id, leaf=leaf_idx,
        site=site, expected=want, computed=got)


class AsyncLeafVerifier:
    """Pipelined wire verification: received chunks are hashed on a side
    thread while the socket keeps receiving the next ones, so checksum
    cost overlaps with wire time instead of adding to it (the serial
    variant measured ~10% of a ~1 GB/s loopback stream; overlapped it is
    noise — the bench `integrity` stage tracks this).

    Protocol: `feed(leaf_idx, chunk)` in arrival order, `leaf_done(idx,
    leaf)` after each complete leaf, then ONE `finish()` — which joins the
    hasher and raises CorruptShuffleBlock on the first digest mismatch.
    `abort()` (in a finally) tears the thread down when the stream dies
    mid-flight."""

    _END = object()

    def __init__(self, policy: ChecksumPolicy, sums, buffer_id: int,
                 path: str):
        import queue
        self._policy = policy
        self._sums = sums
        self._buffer_id = buffer_id
        self._path = path
        self._q: "queue.SimpleQueue" = queue.SimpleQueue()
        self._digests: Dict[int, int] = {}
        self._leaves: Dict[int, np.ndarray] = {}
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="shuffle-verify")
        self._thread.start()

    def _run(self) -> None:
        hashers: Dict[int, object] = {}
        while True:
            item = self._q.get()
            if item is self._END:
                for idx, h in hashers.items():
                    self._digests[idx] = h.digest()
                return
            leaf_idx, chunk = item
            h = hashers.get(leaf_idx)
            if h is None:
                h = hashers[leaf_idx] = self._policy.hasher()
            h.update(chunk)

    def feed(self, leaf_idx: int, chunk: np.ndarray) -> None:
        self._q.put((leaf_idx, chunk))

    def leaf_done(self, leaf_idx: int, leaf: np.ndarray) -> None:
        # kept only for the mismatch path: a full re-hash distinguishes
        # flaky reader memory from transit corruption
        self._leaves[leaf_idx] = leaf

    def abort(self) -> None:
        self._q.put(self._END)

    def finish(self) -> None:
        self._q.put(self._END)
        self._thread.join(timeout=60)
        if self._thread.is_alive():
            # the hasher fell hopelessly behind (starved CPU, slow zlib
            # fallback): NEVER skip verification — re-hash the retained
            # leaves synchronously instead, and stop reading the digest
            # dict the thread still mutates
            for leaf_idx, leaf in sorted(self._leaves.items()):
                verify_fetched_leaf(self._policy, leaf,
                                    self._sums[leaf_idx],
                                    self._buffer_id, leaf_idx,
                                    self._path)
            return
        for leaf_idx in sorted(self._digests):
            got = self._digests[leaf_idx]
            want = int(self._sums[leaf_idx])
            if got == want:
                continue
            second = got
            leaf = self._leaves.get(leaf_idx)
            if leaf is not None:
                second = self._policy.checksum_one(leaf)
            site = "reader" if second != got else self._path
            raise CorruptShuffleBlock(
                f"buffer {self._buffer_id} leaf {leaf_idx} failed "
                f"{self._policy.algorithm} verification on the "
                f"{self._path} path: expected {want:#x}, computed "
                f"{got:#x}", buffer_id=self._buffer_id, leaf=leaf_idx,
                site=site, expected=want, computed=got)


# ---- SPI -------------------------------------------------------------------

class ShuffleTransportClient:
    """Fetch path to one peer (RapidsShuffleClient equivalent)."""

    def fetch_metadata(self, request: MetadataRequest) -> MetadataResponse:
        raise NotImplementedError

    def fetch_buffer(self, buffer_id: int
                     ) -> Tuple[List[np.ndarray], BatchMeta]:
        raise NotImplementedError

    def release_buffer(self, buffer_id: int) -> None:
        """Tell the peer it may drop serving state for this buffer."""

    def diagnose_buffer(self, buffer_id: int) -> Optional[dict]:
        """Ask the peer to re-hash its live copy of a buffer against the
        digests it recorded (the SPARK-36206 writer-side diagnosis after
        a reader checksum mismatch).  Returns {algorithm, recorded,
        recomputed, writer_ok} or None when the peer cannot answer."""
        return None


class ShuffleTransport:
    """Client/server factory (RapidsShuffleTransport SPI,
    RapidsShuffleTransport.scala:378-396)."""

    def make_client(self, peer_executor_id: str) -> ShuffleTransportClient:
        raise NotImplementedError

    def register_server(self, executor_id: str, server) -> None:
        raise NotImplementedError

    def shutdown(self) -> None:
        pass


# ---- loopback implementation ----------------------------------------------

class LoopbackTransport(ShuffleTransport):
    """In-process transport: peers are ShuffleServer objects in a registry.

    Every byte still flows through the bounce-buffer pool in bounded chunks
    under the inflight throttle, so flow control and reassembly are
    exercised exactly as a wire transport would."""

    def __init__(self, pool_size: int = 8 << 20, chunk_size: int = 1 << 20,
                 max_inflight_bytes: int = 4 << 20):
        self._servers: Dict[str, object] = {}
        self.pool = BounceBufferPool(pool_size, chunk_size)
        self.chunk_size = chunk_size
        self.throttle = InflightThrottle(max_inflight_bytes)
        self._txn_counter = [0]
        self._lock = threading.Lock()
        # default-on verification with the default algorithm; configure()
        # adopts the session's conf when an env constructs the transport
        self.integrity = ChecksumPolicy()
        self.counters: Dict[str, int] = {}

    def configure(self, conf) -> None:
        from ..mem.integrity import policy_from_conf
        faults.INJECTOR.configure_from_conf(conf)
        self.integrity = policy_from_conf(conf)

    def count(self, key: str, n: int = 1) -> None:
        with self._lock:
            self.counters[key] = self.counters.get(key, 0) + n

    def register_server(self, executor_id: str, server) -> None:
        with self._lock:
            self._servers[executor_id] = server

    def make_client(self, peer_executor_id: str) -> "LoopbackClient":
        with self._lock:
            server = self._servers.get(peer_executor_id)
        if server is None:
            raise KeyError(f"no shuffle server for peer {peer_executor_id}")
        return LoopbackClient(self, server)

    def next_txn(self) -> Transaction:
        with self._lock:
            self._txn_counter[0] += 1
            return Transaction(self._txn_counter[0],
                               TransactionStatus.IN_PROGRESS)


class LoopbackClient(ShuffleTransportClient):
    def __init__(self, transport: LoopbackTransport, server):
        self.transport = transport
        self.server = server

    def fetch_metadata(self, request: MetadataRequest) -> MetadataResponse:
        txn = self.transport.next_txn()
        try:
            resp = self.server.handle_metadata_request(request)
            txn.complete(0)
            return resp
        except Exception as e:  # noqa: BLE001 — transaction records it
            txn.fail(str(e))
            raise

    def release_buffer(self, buffer_id: int) -> None:
        self.server.done_serving(buffer_id)

    def diagnose_buffer(self, buffer_id: int) -> Optional[dict]:
        diag = getattr(self.server, "diagnose_buffer", None)
        if diag is None:
            return None
        try:
            return diag(buffer_id)
        except KeyError:
            return None
        except CorruptBuffer:
            # the re-hash path itself tripped the serve-time verify:
            # conclusive writer-side evidence
            return {"writer_ok": False}

    def fetch_buffer(self, buffer_id: int
                     ) -> Tuple[List[np.ndarray], BatchMeta]:
        """Pull one buffer's leaves through bounce-buffer chunks."""
        txn = self.transport.next_txn()
        pool = self.transport.pool
        chunk = self.transport.chunk_size
        try:
            leaves_meta = self.server.buffer_layout(buffer_id)
        except KeyError as e:
            # fetch raced a remove_shuffle: typed, not a KeyError crash
            txn.fail(str(e))
            raise BufferGone(f"buffer {buffer_id} gone at the peer "
                             f"(shuffle removed mid-fetch): {e}") from e
        except CorruptShuffleBlock:
            raise
        except CorruptBuffer as e:
            # the PEER's serve-time verify found its own stored copy
            # rotted: writer-site corruption, refetching cannot help —
            # same translation the socket server's OP_GONE(corrupt) frame
            # performs, so the recovery ladder escalates identically
            txn.fail(str(e))
            raise CorruptShuffleBlock(
                f"buffer {buffer_id} corrupt at the peer: {e}",
                buffer_id=buffer_id, site="writer") from e
        sums = None
        policy = self.transport.integrity
        if policy is not None and policy.enabled:
            get_sums = getattr(self.server, "buffer_checksums", None)
            rec = get_sums(buffer_id) if get_sums is not None else None
            if rec is not None and rec[0] == policy.algorithm:
                sums = rec[1]
        total = sum(nb for _, _, nb in leaves_meta[0])
        self.transport.throttle.acquire(total)
        try:
            out: List[np.ndarray] = []
            for leaf_idx, (shape, dtype_str, nbytes) \
                    in enumerate(leaves_meta[0]):
                dest = np.empty(nbytes, dtype=np.uint8)
                off = 0
                while off < nbytes:
                    length = min(chunk, nbytes - off)
                    addr = pool.acquire(length)
                    try:
                        # "send": server copies into the bounce slice
                        try:
                            self.server.copy_leaf_chunk(
                                buffer_id, leaf_idx, off, length,
                                pool.view(addr, length))
                        except KeyError as e:
                            raise BufferGone(
                                f"buffer {buffer_id} vanished mid-fetch "
                                f"at leaf {leaf_idx}+{off}: {e}") from e
                        except CorruptShuffleBlock:
                            raise
                        except CorruptBuffer as e:
                            raise CorruptShuffleBlock(
                                f"buffer {buffer_id} corrupt at the "
                                f"peer mid-fetch: {e}",
                                buffer_id=buffer_id, leaf=leaf_idx,
                                site="writer") from e
                        # corruption injection point: the staged chunk is
                        # the loopback "wire"
                        faults.INJECTOR.on_corruptible(
                            "loopback", pool.view(addr, length))
                        # "recv": copy out of the bounce slice
                        dest[off:off + length] = pool.view(addr, length)
                    finally:
                        pool.release(addr)
                    off += length
                    txn.bytes_transferred += length
                if sums is not None:
                    try:
                        verify_fetched_leaf(policy, dest, sums[leaf_idx],
                                            buffer_id, leaf_idx,
                                            "loopback")
                    except CorruptShuffleBlock:
                        self.transport.count("checksum_mismatches")
                        raise
                out.append(dest.view(np.dtype(dtype_str)).reshape(shape))
            txn.status = TransactionStatus.SUCCESS
            return out, leaves_meta[1]
        except Exception as e:  # noqa: BLE001
            txn.fail(str(e))
            raise
        finally:
            self.transport.throttle.release(total)
