"""Shuffle transport SPI: control plane, staging buffers, loopback fake.

TPU-native analogue of the reference's transport stack
(rapids/shuffle/RapidsShuffleTransport.scala:38-500 — client/server SPI,
bounce-buffer pools, inflight-bytes throttle, Transaction lifecycle;
RapidsShuffleClient.scala:350-770 — metadata request -> throttled buffer
receives; RapidsShuffleServer.scala:67-671 — serve buffers from any tier
through send bounce buffers).  The flatbuffers control messages become plain
dataclasses; UCX tag-matched RDMA becomes: LOOPBACK (in-memory, for tests —
the unit-testable fake the reference snapshot lacks, SURVEY.md §4) and the
ICI all-to-all path in ici.py for mesh-resident SPMD plans.

Data still moves through a bounded staging (bounce-buffer) pool with an
inflight-bytes throttle, so the flow control logic is real even when the
wire is memcpy.
"""
from __future__ import annotations

import enum
import threading
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from ..compress import CodecError, CompressionPolicy, frame_decompress
from ..mem.address_space import AddressSpaceAllocator
from ..mem.buffer import BatchMeta
from ..mem.integrity import (BufferGone, ChecksumPolicy, CorruptBuffer,
                             CorruptShuffleBlock)
from ..utils import faults
from .catalog import ShuffleBlockId


# ---- transaction lifecycle (RapidsShuffleTransport.scala:311-376) ----------

class TransactionStatus(enum.Enum):
    NOT_STARTED = 0
    IN_PROGRESS = 1
    SUCCESS = 2
    ERROR = 3
    CANCELLED = 4


class TransactionCancelled(RuntimeError):
    """A shuffle transaction blew its overall deadline and was cancelled.
    Deliberately NOT an OSError: the per-op retry loop must not retry a
    cancelled transaction (the deadline already covered the retries)."""


@dataclass
class Transaction:
    txn_id: int
    status: TransactionStatus = TransactionStatus.NOT_STARTED
    bytes_transferred: int = 0
    error_message: Optional[str] = None

    def complete(self, nbytes: int) -> None:
        self.bytes_transferred += nbytes
        self.status = TransactionStatus.SUCCESS

    def fail(self, msg: str) -> None:
        self.status = TransactionStatus.ERROR
        self.error_message = msg

    def cancel(self, msg: str) -> "TransactionCancelled":
        """Mark cancelled and build the error to raise (the caller
        raises, so tracebacks point at the cancelling site)."""
        self.status = TransactionStatus.CANCELLED
        self.error_message = msg
        return TransactionCancelled(
            f"shuffle transaction {self.txn_id} cancelled: {msg}")


# ---- control messages (the .fbs schemas, as dataclasses) -------------------

@dataclass
class MetadataRequest:
    """Either an explicit block list, or a (shuffle_id, reduce_id) wildcard
    asking the peer to enumerate every block it holds for that reduce
    partition (the discovery the reference gets from MapStatus)."""
    blocks: Optional[List[ShuffleBlockId]] = None
    shuffle_id: Optional[int] = None
    reduce_id: Optional[int] = None
    # wildcard restricted to map ids [map_lo, map_hi) — the skew-join
    # slice fetch (adaptive/stats.py PartialReducerPartitionSpec)
    map_lo: Optional[int] = None
    map_hi: Optional[int] = None
    # the codec this reader wants buffers framed with (compress/) — the
    # negotiation opener; the peer answers with what it can actually
    # serve per block (BlockMeta.codec) and confirms per fetch in the
    # layout response.  None/"none" = raw.
    codec: Optional[str] = None
    # distributed-trace context of the requesting task, (query, stage,
    # span, executor) — the serving side journals it on its serve record
    # so the merged timeline links this reader's fetch span to the
    # mapper's serve span (metrics/timeline.py).  Back-compat: a peer
    # running pre-trace code simply never reads it (dataclass default).
    trace: Optional[tuple] = None


@dataclass
class BlockMeta:
    block: ShuffleBlockId
    buffer_ids: List[int]
    metas: List[BatchMeta]
    sizes: List[int]
    # per-buffer (algorithm, per-leaf digests) records, aligned with
    # buffer_ids — the digests KNOWN at metadata time, for diagnostics
    # and external consumers of the control plane.  None for buffers not
    # yet host-materialized (still HBM-resident).  Fetch verification
    # does NOT read these: the OP_LAYOUT/buffer_checksums response at
    # fetch time is the authoritative source (it exists by then, the
    # server's _leaves call having just established it).
    checksums: Optional[List[Optional[tuple]]] = None
    # negotiated compression: the codec the SERVER will frame these
    # buffers with for this reader (None/"none" = raw — either nobody
    # asked or the server cannot encode the requested codec), plus the
    # per-buffer framed sizes where already known (compressed forms are
    # built lazily at first serve, so sizes may be None until then).
    # Like `checksums`, informational: the layout response at fetch time
    # is the authoritative wire contract.
    codec: Optional[str] = None
    compressed_sizes: Optional[List[Optional[List[int]]]] = None


@dataclass
class MetadataResponse:
    block_metas: List[BlockMeta]
    # trace capability advertisement: servers running trace-aware code set
    # this True, and ONLY then does the client stamp its trace context on
    # the per-buffer wire ops (layout/fetch/shm/diag) — a pre-trace peer
    # would crash unpacking the pickled triple, so like PR 5's codec the
    # capability is negotiated through the metadata handshake (pre-trace
    # servers leave the dataclass default False; pre-trace clients simply
    # never read it).
    traced: bool = False


@dataclass
class TransferRequest:
    buffer_ids: List[int]


# ---- bounce buffers (BounceBufferManager.scala + AddressSpaceAllocator) ----

class BounceBufferPool:
    """One pre-allocated host staging area sub-allocated into per-transfer
    slices; acquire blocks until space frees (backpressure)."""

    def __init__(self, pool_size: int, buffer_size: int = 1 << 20):
        self.buffer_size = buffer_size
        self._backing = np.zeros(pool_size, dtype=np.uint8)
        from ..native import NativeAddressSpaceAllocator, native_available
        if native_available():
            self._alloc = NativeAddressSpaceAllocator(pool_size)
        else:
            self._alloc = AddressSpaceAllocator(pool_size)
        self._cond = threading.Condition()

    def acquire(self, length: int, timeout: float = 30.0) -> int:
        """Returns the slice start address.  Blocks until available."""
        assert length <= self._alloc.size, \
            f"transfer slice {length} exceeds pool {self._alloc.size}"
        with self._cond:
            deadline = None
            while True:
                addr = self._alloc.allocate(length)
                if addr is not None:
                    return addr
                import time
                if deadline is None:
                    deadline = time.monotonic() + timeout
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise TimeoutError("bounce buffer pool exhausted")
                self._cond.wait(remaining)

    def release(self, addr: int) -> None:
        with self._cond:
            self._alloc.free(addr)
            self._cond.notify_all()

    def view(self, addr: int, length: int) -> np.ndarray:
        return self._backing[addr:addr + length]


class InflightThrottle:
    """Caps bytes of shuffle data in flight to a receiving task
    (spark.rapids.shuffle.maxReceiveInflightBytes;
    UCXShuffleTransport.scala:363-471 queuePending)."""

    def __init__(self, max_bytes: int):
        self.max_bytes = max_bytes
        self._inflight = 0
        self.peak = 0
        self._cond = threading.Condition()

    def acquire(self, nbytes: int) -> None:
        take = min(nbytes, self.max_bytes)  # a single huge buffer still flows
        with self._cond:
            while self._inflight + take > self.max_bytes:
                self._cond.wait()
            self._inflight += take
            self.peak = max(self.peak, self._inflight)

    def release(self, nbytes: int) -> None:
        take = min(nbytes, self.max_bytes)
        with self._cond:
            self._inflight -= take
            self._cond.notify_all()


# ---- integrity helpers ------------------------------------------------------

def verify_fetched_leaf(policy: ChecksumPolicy, arr: np.ndarray,
                        expected: int, buffer_id: int, leaf_idx: int,
                        path: str) -> None:
    """Verify one fully-received leaf against the writer's digest.

    On mismatch the leaf is hashed a SECOND time before raising: two
    different digests of the same bytes mean the reader's own memory is
    flaky (`site="reader"`), a stable wrong digest means the bytes were
    corrupted in transit (`site=path`) — the reader half of the
    SPARK-36206 corruption-site diagnosis (the writer half is the
    diagnose_buffer RPC)."""
    got = policy.checksum_one(arr)
    want = int(expected)
    if got == want:
        return
    second = policy.checksum_one(arr)
    site = "reader" if second != got else path
    raise CorruptShuffleBlock(
        f"buffer {buffer_id} leaf {leaf_idx} failed {policy.algorithm} "
        f"verification on the {path} path: expected {want:#x}, "
        f"computed {got:#x}", buffer_id=buffer_id, leaf=leaf_idx,
        site=site, expected=want, computed=got)


def decompress_verified_leaf(cpol, codec, frame: np.ndarray,
                             policy: Optional[ChecksumPolicy], raw_sum,
                             buffer_id: int, leaf_idx: int,
                             path: str, frame_verified: bool
                             ) -> np.ndarray:
    """Decompress one ALREADY-digest-checked frame and verify the result
    against the canonical (uncompressed) digest — the shared tail of the
    compressed-fetch ladder (socket stream, shm fill, loopback chunks).

    Error typing is the point: a frame that verified clean but will not
    decode (or decodes to the wrong bytes) is conclusive WRITER-side rot
    — the corruption predates the compression boundary, refetching
    cannot help.  An UNVERIFIED frame (integrity off / algorithm
    mismatch) that fails to decode gets the transit classification so a
    refetch is at least attempted; either way the error is a typed
    CorruptShuffleBlock the recovery ladder owns, never a bare
    CodecError crash."""
    try:
        flat = (cpol.decompress_leaves([frame], codec)[0]
                if cpol is not None else frame_decompress(codec, frame))
    except CodecError as e:
        raise CorruptShuffleBlock(
            f"buffer {buffer_id} leaf {leaf_idx} failed to decompress: "
            f"{e}", buffer_id=buffer_id, leaf=leaf_idx,
            site="writer" if frame_verified else path) from e
    if policy is not None and policy.enabled and raw_sum is not None:
        got = policy.checksum_one(flat)
        want = int(raw_sum)
        if got != want:
            # verified frame + wrong payload = rot predates compression
            # (writer).  Unverified frame = the flip may have happened in
            # transit and still decoded — transit classification, so the
            # ladder refetches before escalating.
            raise CorruptShuffleBlock(
                f"buffer {buffer_id} leaf {leaf_idx} decompressed to "
                f"bytes failing {policy.algorithm} verification"
                + (" (frame was clean): writer-side corruption "
                   "predating compression" if frame_verified else ""),
                buffer_id=buffer_id, leaf=leaf_idx,
                site="writer" if frame_verified else path,
                expected=want, computed=got)
    return flat


def decode_compressed_leaves(frames, layout, codec, comp_sums, sums,
                             policy: Optional[ChecksumPolicy], cpol,
                             buffer_id: int, path: str
                             ) -> List[np.ndarray]:
    """Verify + decompress + reshape a fetched buffer's framed leaves —
    the shared synchronous tail of the shm and loopback compressed fetch
    paths (the socket stream runs the identical ladder asynchronously in
    AsyncFramedReader).  Frame digests are checked BEFORE decompression,
    so a corrupt frame never reaches a decompressor.  Byte/mismatch
    counters stay at the call sites: the socket client counts mismatches
    in its outer fetch handler, the loopback client locally."""
    import time as _time

    from ..metrics.journal import journal_event
    out: List[np.ndarray] = []
    t0 = _time.perf_counter()
    nbytes = 0
    for leaf_idx, (shape, dtype_str, _raw_nbytes) in enumerate(layout):
        frame = frames[leaf_idx]
        if comp_sums is not None:
            verify_fetched_leaf(policy, frame, comp_sums[leaf_idx],
                                buffer_id, leaf_idx, path)
        flat = decompress_verified_leaf(
            cpol, codec, frame, policy,
            sums[leaf_idx] if sums is not None else None,
            buffer_id, leaf_idx, path,
            frame_verified=comp_sums is not None)
        nbytes += int(flat.nbytes)
        out.append(flat.view(np.dtype(dtype_str)).reshape(shape))
    # decode-side codec time for the timeline's per-task overlap
    # breakdown (metrics/timeline.py task_breakdown: decompress_s)
    journal_event("compress", "decompress", buffer=buffer_id, path=path,
                  bytes=nbytes, seconds=_time.perf_counter() - t0)
    return out


class AsyncLeafVerifier:
    """Pipelined wire verification: received chunks are hashed on a side
    thread while the socket keeps receiving the next ones, so checksum
    cost overlaps with wire time instead of adding to it (the serial
    variant measured ~10% of a ~1 GB/s loopback stream; overlapped it is
    noise — the bench `integrity` stage tracks this).

    Protocol: `feed(leaf_idx, chunk)` in arrival order, `leaf_done(idx,
    leaf)` after each complete leaf, then ONE `finish()` — which joins the
    hasher and raises CorruptShuffleBlock on the first digest mismatch.
    `abort()` (in a finally) tears the thread down when the stream dies
    mid-flight."""

    _END = object()

    def __init__(self, policy: ChecksumPolicy, sums, buffer_id: int,
                 path: str):
        import queue
        self._policy = policy
        self._sums = sums
        self._buffer_id = buffer_id
        self._path = path
        self._q: "queue.SimpleQueue" = queue.SimpleQueue()
        self._digests: Dict[int, int] = {}
        self._leaves: Dict[int, np.ndarray] = {}
        # tpulint: disable=TPU009 helper thread journals on the query's behalf BY DESIGN: active_journal() routes helper threads to the process trace shard (metrics/journal.py thread-routing note)
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="shuffle-verify")
        self._thread.start()

    def _run(self) -> None:
        hashers: Dict[int, object] = {}
        while True:
            item = self._q.get()
            if item is self._END:
                for idx, h in hashers.items():
                    self._digests[idx] = h.digest()
                return
            leaf_idx, chunk = item
            h = hashers.get(leaf_idx)
            if h is None:
                h = hashers[leaf_idx] = self._policy.hasher()
            h.update(chunk)

    def feed(self, leaf_idx: int, chunk: np.ndarray) -> None:
        self._q.put((leaf_idx, chunk))

    def leaf_done(self, leaf_idx: int, leaf: np.ndarray) -> None:
        # kept only for the mismatch path: a full re-hash distinguishes
        # flaky reader memory from transit corruption
        self._leaves[leaf_idx] = leaf

    def abort(self) -> None:
        self._q.put(self._END)

    def finish(self) -> None:
        self._q.put(self._END)
        self._thread.join(timeout=60)
        if self._thread.is_alive():
            # the hasher fell hopelessly behind (starved CPU, slow zlib
            # fallback): NEVER skip verification — re-hash the retained
            # leaves synchronously instead, and stop reading the digest
            # dict the thread still mutates
            for leaf_idx, leaf in sorted(self._leaves.items()):
                verify_fetched_leaf(self._policy, leaf,
                                    self._sums[leaf_idx],
                                    self._buffer_id, leaf_idx,
                                    self._path)
            return
        for leaf_idx in sorted(self._digests):
            got = self._digests[leaf_idx]
            want = int(self._sums[leaf_idx])
            if got == want:
                continue
            second = got
            leaf = self._leaves.get(leaf_idx)
            if leaf is not None:
                second = self._policy.checksum_one(leaf)
            site = "reader" if second != got else self._path
            raise CorruptShuffleBlock(
                f"buffer {self._buffer_id} leaf {leaf_idx} failed "
                f"{self._policy.algorithm} verification on the "
                f"{self._path} path: expected {want:#x}, computed "
                f"{got:#x}", buffer_id=self._buffer_id, leaf=leaf_idx,
                site=site, expected=want, computed=got)


class AsyncFramedReader:
    """Pipelined reader for COMPRESSED leaf streams: the same
    feed/leaf_done/finish/abort protocol as AsyncLeafVerifier, but over
    framed compressed bytes (compress/framed.py).  The side thread

      1. hashes compressed chunks as they arrive (overlapped with the
         recv loop),
      2. verifies each leaf's COMPRESSED digest the moment the leaf
         completes — a corrupt frame is recorded as CorruptShuffleBlock
         and NEVER reaches the decompressor (the acceptance contract of
         the integrity ladder),
      3. decompresses the verified frame (chunks parallel on the shared
         codec pool, overlapped with the next leaf's recv), and
      4. verifies the decompressed bytes against the CANONICAL
         (uncompressed) digests — frames that verify clean but decode to
         the wrong bytes mean the corruption predates compression, i.e.
         writer-side rot (classified `writer`, so the recovery ladder
         recomputes instead of refetching forever).

    `finish()` joins the pipeline, raises the first recorded mismatch,
    and returns {leaf_idx: flat uint8 decompressed leaf}."""

    _END = object()

    def __init__(self, policy: Optional[ChecksumPolicy], comp_sums,
                 raw_sums, codec, buffer_id: int, path: str):
        import queue
        self._policy = policy if policy is not None and policy.enabled \
            else None
        self._comp_sums = comp_sums if self._policy is not None else None
        self._raw_sums = raw_sums if self._policy is not None else None
        self._codec = codec
        self._buffer_id = buffer_id
        self._path = path
        self._q: "queue.SimpleQueue" = queue.SimpleQueue()
        self._frames: Dict[int, np.ndarray] = {}   # retained for fallback
        self._out: Dict[int, np.ndarray] = {}
        self._error: Optional[BaseException] = None
        # tpulint: disable=TPU009 helper thread journals on the query's behalf BY DESIGN: active_journal() routes helper threads to the process trace shard (metrics/journal.py thread-routing note)
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="shuffle-decompress")
        self._thread.start()

    # -- protocol ------------------------------------------------------------

    def feed(self, leaf_idx: int, chunk: np.ndarray) -> None:
        self._q.put(("chunk", leaf_idx, chunk))

    def leaf_done(self, leaf_idx: int, frame: np.ndarray) -> None:
        self._frames[leaf_idx] = frame
        self._q.put(("done", leaf_idx, frame))

    def abort(self) -> None:
        self._q.put(self._END)

    def finish(self) -> Dict[int, np.ndarray]:
        self._q.put(self._END)
        self._thread.join(timeout=120)
        if self._thread.is_alive():
            # pipeline starved (single busy core, slow codec): NEVER skip
            # verification — run the whole ladder synchronously over the
            # retained frames, and stop reading state the thread still
            # mutates
            out: Dict[int, np.ndarray] = {}
            for leaf_idx, frame in sorted(self._frames.items()):
                out[leaf_idx] = self._one_leaf(leaf_idx, frame,
                                               hasher_digest=None)
            return out
        if self._error is not None:
            raise self._error
        return self._out

    # -- side thread ---------------------------------------------------------

    def _run(self) -> None:
        hashers: Dict[int, object] = {}
        while True:
            item = self._q.get()
            if item is self._END:
                return
            tag, leaf_idx = item[0], item[1]
            if tag == "chunk":
                if self._comp_sums is not None:
                    h = hashers.get(leaf_idx)
                    if h is None:
                        h = hashers[leaf_idx] = self._policy.hasher()
                    h.update(item[2])
                continue
            # "done": verify this frame, then decompress it
            if self._error is not None:
                continue  # drain; first error wins
            h = hashers.pop(leaf_idx, None)
            try:
                self._out[leaf_idx] = self._one_leaf(
                    leaf_idx, item[2],
                    hasher_digest=h.digest() if h is not None else None)
            except BaseException as e:  # noqa: BLE001 — finish() raises it
                self._error = e

    def _one_leaf(self, leaf_idx: int, frame: np.ndarray,
                  hasher_digest: Optional[int]) -> np.ndarray:
        verified = False
        if self._comp_sums is not None:
            got = hasher_digest if hasher_digest is not None \
                else self._policy.checksum_one(frame)
            want = int(self._comp_sums[leaf_idx])
            if got != want:
                # double-hash classification, as verify_fetched_leaf: an
                # unstable re-digest means the reader's own memory flaked
                second = self._policy.checksum_one(frame)
                site = "reader" if second != got else self._path
                raise CorruptShuffleBlock(
                    f"buffer {self._buffer_id} leaf {leaf_idx} compressed "
                    f"frame failed {self._policy.algorithm} verification "
                    f"on the {self._path} path: expected {want:#x}, "
                    f"computed {got:#x}", buffer_id=self._buffer_id,
                    leaf=leaf_idx, site=site, expected=want, computed=got)
            verified = True
        return decompress_verified_leaf(
            None, self._codec, frame, self._policy,
            self._raw_sums[leaf_idx] if self._raw_sums is not None
            else None, self._buffer_id, leaf_idx, self._path, verified)


# ---- SPI -------------------------------------------------------------------

class ShuffleTransportClient:
    """Fetch path to one peer (RapidsShuffleClient equivalent)."""

    # per-client wire-compression override (policy/codec.py): clients
    # are per-fetch objects, so the policy engine attaches its advised
    # reader CompressionPolicy here without touching the transport's
    # session-configured one; None = use the transport's.
    compression_override = None

    def _wire_compression(self):
        if self.compression_override is not None:
            return self.compression_override
        return getattr(self.transport, "compression", None)

    def fetch_metadata(self, request: MetadataRequest) -> MetadataResponse:
        raise NotImplementedError

    def fetch_buffer(self, buffer_id: int
                     ) -> Tuple[List[np.ndarray], BatchMeta]:
        raise NotImplementedError

    def release_buffer(self, buffer_id: int) -> None:
        """Tell the peer it may drop serving state for this buffer."""

    def diagnose_buffer(self, buffer_id: int) -> Optional[dict]:
        """Ask the peer to re-hash its live copy of a buffer against the
        digests it recorded (the SPARK-36206 writer-side diagnosis after
        a reader checksum mismatch).  Returns {algorithm, recorded,
        recomputed, writer_ok} or None when the peer cannot answer."""
        return None


class ShuffleTransport:
    """Client/server factory (RapidsShuffleTransport SPI,
    RapidsShuffleTransport.scala:378-396)."""

    def make_client(self, peer_executor_id: str) -> ShuffleTransportClient:
        raise NotImplementedError

    def register_server(self, executor_id: str, server) -> None:
        raise NotImplementedError

    def shutdown(self) -> None:
        pass


# ---- loopback implementation ----------------------------------------------

class LoopbackTransport(ShuffleTransport):
    """In-process transport: peers are ShuffleServer objects in a registry.

    Every byte still flows through the bounce-buffer pool in bounded chunks
    under the inflight throttle, so flow control and reassembly are
    exercised exactly as a wire transport would."""

    def __init__(self, pool_size: Optional[int] = None,
                 chunk_size: Optional[int] = None,
                 max_inflight_bytes: int = 4 << 20):
        # bounce-pool geometry defaults live in ONE place — the conf
        # registry (spark.rapids.shuffle.bounce.*); explicit arguments
        # still win for tests that shrink the pool
        from .. import config as C
        if pool_size is None:
            pool_size = int(C.SHUFFLE_BOUNCE_POOL_SIZE.default)
        if chunk_size is None:
            chunk_size = int(C.SHUFFLE_BOUNCE_CHUNK_SIZE.default)
        self._servers: Dict[str, object] = {}
        self.pool = BounceBufferPool(pool_size, chunk_size)
        self.chunk_size = chunk_size
        self.throttle = InflightThrottle(max_inflight_bytes)
        self._txn_counter = [0]
        self._lock = threading.Lock()
        # default-on verification with the default algorithm; configure()
        # adopts the session's conf when an env constructs the transport
        self.integrity = ChecksumPolicy()
        # wire compression (compress/): default none; configure() adopts
        # spark.rapids.shuffle.compression.codec
        self.compression = CompressionPolicy()
        self.counters: Dict[str, int] = {}

    def configure(self, conf) -> None:
        from ..compress import compression_from_conf
        from ..mem.integrity import policy_from_conf
        faults.INJECTOR.configure_from_conf(conf)
        self.integrity = policy_from_conf(conf)
        self.compression = compression_from_conf(
            conf, metrics=self.compression.metrics)

    def count(self, key: str, n: int = 1) -> None:
        with self._lock:
            self.counters[key] = self.counters.get(key, 0) + n

    def register_server(self, executor_id: str, server) -> None:
        with self._lock:
            self._servers[executor_id] = server

    def make_client(self, peer_executor_id: str) -> "LoopbackClient":
        with self._lock:
            server = self._servers.get(peer_executor_id)
        if server is None:
            raise KeyError(f"no shuffle server for peer {peer_executor_id}")
        return LoopbackClient(self, server)

    def next_txn(self) -> Transaction:
        with self._lock:
            self._txn_counter[0] += 1
            return Transaction(self._txn_counter[0],
                               TransactionStatus.IN_PROGRESS)


class LoopbackClient(ShuffleTransportClient):
    def __init__(self, transport: LoopbackTransport, server):
        self.transport = transport
        self.server = server

    def _server_executor(self) -> str:
        env = getattr(self.server, "env", None)
        return getattr(env, "executor_id", "?")

    def fetch_metadata(self, request: MetadataRequest) -> MetadataResponse:
        txn = self.transport.next_txn()
        try:
            resp = self.server.handle_metadata_request(request)
            txn.complete(0)
            return resp
        except Exception as e:  # noqa: BLE001 — transaction records it
            txn.fail(str(e))
            raise

    def release_buffer(self, buffer_id: int) -> None:
        self.server.done_serving(buffer_id)

    def diagnose_buffer(self, buffer_id: int) -> Optional[dict]:
        diag = getattr(self.server, "diagnose_buffer", None)
        if diag is None:
            return None
        try:
            return diag(buffer_id)
        except KeyError:
            return None
        except CorruptBuffer:
            # the re-hash path itself tripped the serve-time verify:
            # conclusive writer-side evidence
            return {"writer_ok": False}

    def _pull_leaf(self, buffer_id: int, leaf_idx: int, nbytes: int,
                   txn: Transaction, copy_chunk) -> np.ndarray:
        """One leaf (raw or framed) through bounce-buffer chunks:
        `copy_chunk(leaf_idx, off, length, view)` is the server-side
        'send', the copy out of the bounce slice is the 'recv', and the
        staged slice is the corruption-injection point (the loopback
        'wire')."""
        pool = self.transport.pool
        chunk = self.transport.chunk_size
        dest = np.empty(nbytes, dtype=np.uint8)
        off = 0
        while off < nbytes:
            length = min(chunk, nbytes - off)
            addr = pool.acquire(length)
            try:
                try:
                    copy_chunk(leaf_idx, off, length,
                               pool.view(addr, length))
                except KeyError as e:
                    raise BufferGone(
                        f"buffer {buffer_id} vanished mid-fetch "
                        f"at leaf {leaf_idx}+{off}: {e}") from e
                except CorruptShuffleBlock:
                    raise
                except CorruptBuffer as e:
                    raise CorruptShuffleBlock(
                        f"buffer {buffer_id} corrupt at the "
                        f"peer mid-fetch: {e}",
                        buffer_id=buffer_id, leaf=leaf_idx,
                        site="writer") from e
                faults.INJECTOR.on_corruptible(
                    "loopback", pool.view(addr, length))
                dest[off:off + length] = pool.view(addr, length)
            finally:
                pool.release(addr)
            off += length
            txn.bytes_transferred += length
        return dest

    def _fetch_buffer_compressed(self, buffer_id: int, layout, meta,
                                 sums, comp: dict, txn: Transaction
                                 ) -> Tuple[List[np.ndarray], BatchMeta]:
        """Negotiated-codec fetch: framed compressed leaves cross the
        bounce pool, frames verify BEFORE decompression (transit faults),
        decompressed bytes verify against the canonical digests after
        (writer rot) — the same ladder the socket stream runs."""
        from ..compress import resolve_codec
        policy = self.transport.integrity
        cpol = self._wire_compression()
        codec = resolve_codec(comp["codec"])
        sizes = comp["sizes"]
        comp_sums = None
        if policy is not None and policy.enabled \
                and comp.get("checksums") is not None \
                and comp.get("algorithm") == policy.algorithm:
            comp_sums = comp["checksums"]
        total = sum(sizes)
        self.transport.throttle.acquire(total)
        try:
            frames = [
                self._pull_leaf(
                    buffer_id, leaf_idx, sizes[leaf_idx], txn,
                    lambda li, off, length, view: self.server
                    .copy_compressed_chunk(buffer_id, li, off, length,
                                           view, comp["codec"]))
                for leaf_idx in range(len(layout))]
            try:
                out = decode_compressed_leaves(
                    frames, layout, codec, comp_sums, sums, policy,
                    cpol, buffer_id, "loopback")
            except CorruptShuffleBlock:
                self.transport.count("checksum_mismatches")
                raise
            self.transport.count("compressed_bytes_received", total)
            if cpol.metrics is not None:
                from ..metrics import names as MN
                cpol.metrics.add(MN.COMPRESSED_SHUFFLE_BYTES_READ, total)
            txn.status = TransactionStatus.SUCCESS
            self._journal_serve(buffer_id, total)
            return out, meta
        except Exception as e:  # noqa: BLE001
            txn.fail(str(e))
            raise
        finally:
            self.transport.throttle.release(total)

    def fetch_buffer(self, buffer_id: int
                     ) -> Tuple[List[np.ndarray], BatchMeta]:
        """Pull one buffer's leaves through bounce-buffer chunks."""
        txn = self.transport.next_txn()
        try:
            leaves_meta = self.server.buffer_layout(buffer_id)
        except KeyError as e:
            # fetch raced a remove_shuffle: typed, not a KeyError crash
            txn.fail(str(e))
            raise BufferGone(f"buffer {buffer_id} gone at the peer "
                             f"(shuffle removed mid-fetch): {e}") from e
        except CorruptShuffleBlock:
            raise
        except CorruptBuffer as e:
            # the PEER's serve-time verify found its own stored copy
            # rotted: writer-site corruption, refetching cannot help —
            # same translation the socket server's OP_GONE(corrupt) frame
            # performs, so the recovery ladder escalates identically
            txn.fail(str(e))
            raise CorruptShuffleBlock(
                f"buffer {buffer_id} corrupt at the peer: {e}",
                buffer_id=buffer_id, site="writer") from e
        sums = None
        policy = self.transport.integrity
        if policy is not None and policy.enabled:
            get_sums = getattr(self.server, "buffer_checksums", None)
            rec = get_sums(buffer_id) if get_sums is not None else None
            if rec is not None and rec[0] == policy.algorithm:
                sums = rec[1]
        # codec negotiation: ask the peer to frame the leaves with our
        # configured codec; a peer without compression support (or the
        # codec library) answers None and we fall back to the raw wire
        # format, counted — never an error (typed graceful degradation)
        cpol = self._wire_compression()
        if cpol is not None and cpol.enabled:
            get_comp = getattr(self.server, "compressed_layout", None)
            comp = None
            if get_comp is not None:
                try:
                    comp = get_comp(buffer_id, cpol.codec_name)
                except KeyError as e:
                    txn.fail(str(e))
                    raise BufferGone(
                        f"buffer {buffer_id} gone at the peer "
                        f"(shuffle removed mid-fetch): {e}") from e
                except CorruptShuffleBlock:
                    raise
                except CorruptBuffer as e:
                    # the peer's serve-time verify tripped while
                    # re-reading the buffer to compress it: writer-site
                    # rot, same translation the raw path performs
                    txn.fail(str(e))
                    raise CorruptShuffleBlock(
                        f"buffer {buffer_id} corrupt at the peer: {e}",
                        buffer_id=buffer_id, site="writer") from e
            if comp is not None:
                return self._fetch_buffer_compressed(
                    buffer_id, leaves_meta[0], leaves_meta[1], sums,
                    comp, txn)
            self.transport.count("compression_fallbacks")
            if cpol.metrics is not None:
                from ..metrics import names as MN
                cpol.metrics.add(MN.NUM_COMPRESSION_FALLBACKS, 1)
        total = sum(nb for _, _, nb in leaves_meta[0])
        self.transport.throttle.acquire(total)
        try:
            out: List[np.ndarray] = []
            for leaf_idx, (shape, dtype_str, nbytes) \
                    in enumerate(leaves_meta[0]):
                dest = self._pull_leaf(
                    buffer_id, leaf_idx, nbytes, txn,
                    lambda li, off, length, view: self.server
                    .copy_leaf_chunk(buffer_id, li, off, length, view))
                if sums is not None:
                    try:
                        verify_fetched_leaf(policy, dest, sums[leaf_idx],
                                            buffer_id, leaf_idx,
                                            "loopback")
                    except CorruptShuffleBlock:
                        self.transport.count("checksum_mismatches")
                        raise
                out.append(dest.view(np.dtype(dtype_str)).reshape(shape))
            txn.status = TransactionStatus.SUCCESS
            self._journal_serve(buffer_id, total)
            return out, leaves_meta[1]
        except Exception as e:  # noqa: BLE001
            txn.fail(str(e))
            raise
        finally:
            self.transport.throttle.release(total)

    def _journal_serve(self, buffer_id: int, nbytes: int) -> None:
        """Serve record for an in-process fetch: reader and server share
        one thread, so the reader's CURRENT trace context is exactly what
        a socket peer would have carried on the wire — journaled with the
        same o_* attrs so the merged timeline links it identically."""
        from ..metrics.journal import (current_trace, journal_event,
                                      trace_attrs)
        journal_event("serve", "serveBuffer",
                      executor=self._server_executor(), buffer=buffer_id,
                      bytes=nbytes, **trace_attrs(current_trace()))
