"""Shuffle transport SPI: control plane, staging buffers, loopback fake.

TPU-native analogue of the reference's transport stack
(rapids/shuffle/RapidsShuffleTransport.scala:38-500 — client/server SPI,
bounce-buffer pools, inflight-bytes throttle, Transaction lifecycle;
RapidsShuffleClient.scala:350-770 — metadata request -> throttled buffer
receives; RapidsShuffleServer.scala:67-671 — serve buffers from any tier
through send bounce buffers).  The flatbuffers control messages become plain
dataclasses; UCX tag-matched RDMA becomes: LOOPBACK (in-memory, for tests —
the unit-testable fake the reference snapshot lacks, SURVEY.md §4) and the
ICI all-to-all path in ici.py for mesh-resident SPMD plans.

Data still moves through a bounded staging (bounce-buffer) pool with an
inflight-bytes throttle, so the flow control logic is real even when the
wire is memcpy.
"""
from __future__ import annotations

import enum
import threading
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from ..mem.address_space import AddressSpaceAllocator
from ..mem.buffer import BatchMeta
from .catalog import ShuffleBlockId


# ---- transaction lifecycle (RapidsShuffleTransport.scala:311-376) ----------

class TransactionStatus(enum.Enum):
    NOT_STARTED = 0
    IN_PROGRESS = 1
    SUCCESS = 2
    ERROR = 3
    CANCELLED = 4


class TransactionCancelled(RuntimeError):
    """A shuffle transaction blew its overall deadline and was cancelled.
    Deliberately NOT an OSError: the per-op retry loop must not retry a
    cancelled transaction (the deadline already covered the retries)."""


@dataclass
class Transaction:
    txn_id: int
    status: TransactionStatus = TransactionStatus.NOT_STARTED
    bytes_transferred: int = 0
    error_message: Optional[str] = None

    def complete(self, nbytes: int) -> None:
        self.bytes_transferred += nbytes
        self.status = TransactionStatus.SUCCESS

    def fail(self, msg: str) -> None:
        self.status = TransactionStatus.ERROR
        self.error_message = msg

    def cancel(self, msg: str) -> "TransactionCancelled":
        """Mark cancelled and build the error to raise (the caller
        raises, so tracebacks point at the cancelling site)."""
        self.status = TransactionStatus.CANCELLED
        self.error_message = msg
        return TransactionCancelled(
            f"shuffle transaction {self.txn_id} cancelled: {msg}")


# ---- control messages (the .fbs schemas, as dataclasses) -------------------

@dataclass
class MetadataRequest:
    """Either an explicit block list, or a (shuffle_id, reduce_id) wildcard
    asking the peer to enumerate every block it holds for that reduce
    partition (the discovery the reference gets from MapStatus)."""
    blocks: Optional[List[ShuffleBlockId]] = None
    shuffle_id: Optional[int] = None
    reduce_id: Optional[int] = None
    # wildcard restricted to map ids [map_lo, map_hi) — the skew-join
    # slice fetch (adaptive/stats.py PartialReducerPartitionSpec)
    map_lo: Optional[int] = None
    map_hi: Optional[int] = None


@dataclass
class BlockMeta:
    block: ShuffleBlockId
    buffer_ids: List[int]
    metas: List[BatchMeta]
    sizes: List[int]


@dataclass
class MetadataResponse:
    block_metas: List[BlockMeta]


@dataclass
class TransferRequest:
    buffer_ids: List[int]


# ---- bounce buffers (BounceBufferManager.scala + AddressSpaceAllocator) ----

class BounceBufferPool:
    """One pre-allocated host staging area sub-allocated into per-transfer
    slices; acquire blocks until space frees (backpressure)."""

    def __init__(self, pool_size: int, buffer_size: int = 1 << 20):
        self.buffer_size = buffer_size
        self._backing = np.zeros(pool_size, dtype=np.uint8)
        from ..native import NativeAddressSpaceAllocator, native_available
        if native_available():
            self._alloc = NativeAddressSpaceAllocator(pool_size)
        else:
            self._alloc = AddressSpaceAllocator(pool_size)
        self._cond = threading.Condition()

    def acquire(self, length: int, timeout: float = 30.0) -> int:
        """Returns the slice start address.  Blocks until available."""
        assert length <= self._alloc.size, \
            f"transfer slice {length} exceeds pool {self._alloc.size}"
        with self._cond:
            deadline = None
            while True:
                addr = self._alloc.allocate(length)
                if addr is not None:
                    return addr
                import time
                if deadline is None:
                    deadline = time.monotonic() + timeout
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise TimeoutError("bounce buffer pool exhausted")
                self._cond.wait(remaining)

    def release(self, addr: int) -> None:
        with self._cond:
            self._alloc.free(addr)
            self._cond.notify_all()

    def view(self, addr: int, length: int) -> np.ndarray:
        return self._backing[addr:addr + length]


class InflightThrottle:
    """Caps bytes of shuffle data in flight to a receiving task
    (spark.rapids.shuffle.maxReceiveInflightBytes;
    UCXShuffleTransport.scala:363-471 queuePending)."""

    def __init__(self, max_bytes: int):
        self.max_bytes = max_bytes
        self._inflight = 0
        self.peak = 0
        self._cond = threading.Condition()

    def acquire(self, nbytes: int) -> None:
        take = min(nbytes, self.max_bytes)  # a single huge buffer still flows
        with self._cond:
            while self._inflight + take > self.max_bytes:
                self._cond.wait()
            self._inflight += take
            self.peak = max(self.peak, self._inflight)

    def release(self, nbytes: int) -> None:
        take = min(nbytes, self.max_bytes)
        with self._cond:
            self._inflight -= take
            self._cond.notify_all()


# ---- SPI -------------------------------------------------------------------

class ShuffleTransportClient:
    """Fetch path to one peer (RapidsShuffleClient equivalent)."""

    def fetch_metadata(self, request: MetadataRequest) -> MetadataResponse:
        raise NotImplementedError

    def fetch_buffer(self, buffer_id: int
                     ) -> Tuple[List[np.ndarray], BatchMeta]:
        raise NotImplementedError

    def release_buffer(self, buffer_id: int) -> None:
        """Tell the peer it may drop serving state for this buffer."""


class ShuffleTransport:
    """Client/server factory (RapidsShuffleTransport SPI,
    RapidsShuffleTransport.scala:378-396)."""

    def make_client(self, peer_executor_id: str) -> ShuffleTransportClient:
        raise NotImplementedError

    def register_server(self, executor_id: str, server) -> None:
        raise NotImplementedError

    def shutdown(self) -> None:
        pass


# ---- loopback implementation ----------------------------------------------

class LoopbackTransport(ShuffleTransport):
    """In-process transport: peers are ShuffleServer objects in a registry.

    Every byte still flows through the bounce-buffer pool in bounded chunks
    under the inflight throttle, so flow control and reassembly are
    exercised exactly as a wire transport would."""

    def __init__(self, pool_size: int = 8 << 20, chunk_size: int = 1 << 20,
                 max_inflight_bytes: int = 4 << 20):
        self._servers: Dict[str, object] = {}
        self.pool = BounceBufferPool(pool_size, chunk_size)
        self.chunk_size = chunk_size
        self.throttle = InflightThrottle(max_inflight_bytes)
        self._txn_counter = [0]
        self._lock = threading.Lock()

    def register_server(self, executor_id: str, server) -> None:
        with self._lock:
            self._servers[executor_id] = server

    def make_client(self, peer_executor_id: str) -> "LoopbackClient":
        with self._lock:
            server = self._servers.get(peer_executor_id)
        if server is None:
            raise KeyError(f"no shuffle server for peer {peer_executor_id}")
        return LoopbackClient(self, server)

    def next_txn(self) -> Transaction:
        with self._lock:
            self._txn_counter[0] += 1
            return Transaction(self._txn_counter[0],
                               TransactionStatus.IN_PROGRESS)


class LoopbackClient(ShuffleTransportClient):
    def __init__(self, transport: LoopbackTransport, server):
        self.transport = transport
        self.server = server

    def fetch_metadata(self, request: MetadataRequest) -> MetadataResponse:
        txn = self.transport.next_txn()
        try:
            resp = self.server.handle_metadata_request(request)
            txn.complete(0)
            return resp
        except Exception as e:  # noqa: BLE001 — transaction records it
            txn.fail(str(e))
            raise

    def release_buffer(self, buffer_id: int) -> None:
        self.server.done_serving(buffer_id)

    def fetch_buffer(self, buffer_id: int
                     ) -> Tuple[List[np.ndarray], BatchMeta]:
        """Pull one buffer's leaves through bounce-buffer chunks."""
        txn = self.transport.next_txn()
        pool = self.transport.pool
        chunk = self.transport.chunk_size
        leaves_meta = self.server.buffer_layout(buffer_id)
        total = sum(nb for _, _, nb in leaves_meta[0])
        self.transport.throttle.acquire(total)
        try:
            out: List[np.ndarray] = []
            for (shape, dtype_str, nbytes) in leaves_meta[0]:
                dest = np.empty(nbytes, dtype=np.uint8)
                off = 0
                while off < nbytes:
                    length = min(chunk, nbytes - off)
                    addr = pool.acquire(length)
                    try:
                        # "send": server copies into the bounce slice
                        self.server.copy_leaf_chunk(
                            buffer_id, len(out), off, length,
                            pool.view(addr, length))
                        # "recv": copy out of the bounce slice
                        dest[off:off + length] = pool.view(addr, length)
                    finally:
                        pool.release(addr)
                    off += length
                    txn.bytes_transferred += length
                out.append(dest.view(np.dtype(dtype_str)).reshape(shape))
            txn.status = TransactionStatus.SUCCESS
            return out, leaves_meta[1]
        except Exception as e:  # noqa: BLE001
            txn.fail(str(e))
            raise
        finally:
            self.transport.throttle.release(total)
