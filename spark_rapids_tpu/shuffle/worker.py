"""Executor worker process: shuffle server + task runner over the socket
wire.

The multi-process deployment model (cluster.ProcCluster spawns N of these):
each worker owns a full executor bring-up — TpuSession/runtime (HBM pool,
semaphore, spill stores) and a ShuffleEnv registered on a SocketTransport —
and executes serialized plan fragments sent over the control RPC:

  * run_map: execute a pickled logical fragment (typically scan slice +
    row-local work), hash-partition the output batches on device, write
    every partition to the LOCAL shuffle catalog (RapidsCachingWriter
    analogue — data stays put until fetched);
  * run_reduce: for each owned partition, serve local blocks from the
    catalog and pull the rest from PEER WORKER PROCESSES over TCP
    (metadata round trip + chunked buffer streams through bounce buffers),
    then run the pickled reduce fragment over the fetched rows and return
    the result as arrow IPC bytes.

Reference analogue: the executor side of RapidsShuffleInternalManager with
UCX transport (shuffle-plugin/.../RapidsShuffleInternalManager.scala:73-337
+ ucx/UCXShuffleTransport.scala:47-507); the control RPC plays the role of
Spark's task dispatch + the UCX management-port handshake.
"""
from __future__ import annotations

import contextlib
import copy
import json
import os
import sys
import threading
import time
from typing import Dict, List, Optional

from ..plan import logical as L


def attach_stage_input(plan: "L.LogicalPlan", table) -> "L.LogicalPlan":
    """Swap every LogicalPlaceholder for an in-memory scan of `table`."""
    if isinstance(plan, L.LogicalPlaceholder):
        return L.LogicalScan(table, plan.schema, "memory")
    if not plan.children:
        return plan
    new = copy.copy(plan)
    new.children = tuple(attach_stage_input(c, table)
                         for c in plan.children)
    return new


class WorkerHandler:
    """RPC dispatch target; owns the executor-side session/runtime/env."""

    def __init__(self, executor_id: str, conf_dict: Dict):
        from ..engine import TpuSession
        from ..config import (PINNED_POOL_SIZE, SHUFFLE_BOUNCE_CHUNK_SIZE,
                              SHUFFLE_BOUNCE_POOL_SIZE,
                              SHUFFLE_MAX_RECV_INFLIGHT)
        from .manager import ShuffleEnv
        from .net import SocketTransport
        self.executor_id = executor_id
        # bootstrap hygiene: reap spill dirs leaked by DEAD predecessors
        # (a replaced worker's shuffle files on disk — the fresh process
        # never knew the sid, so remove_shuffle can never reach them)
        from ..mem.stores import sweep_stale_spill_dirs
        swept = sweep_stale_spill_dirs()
        if swept:
            import logging
            logging.getLogger("spark_rapids_tpu.shuffle").info(
                "worker %s bootstrap swept %d stale spill dir(s) left by "
                "dead processes", executor_id, swept)
        # worker bootstrap shares the engine's persistent-compile-cache
        # setup (utils/compile_cache.py): every executor process replays
        # the same on-disk XLA cache instead of re-paying compile time
        from ..config import COMPILATION_CACHE_DIR, TpuConf
        from ..utils.compile_cache import enable_compilation_cache
        enable_compilation_cache(
            TpuConf(conf_dict).get(COMPILATION_CACHE_DIR))
        self.session = TpuSession(conf_dict)
        self.runtime = self.session.runtime
        # bounce geometry from the conf registry (single source of truth,
        # spark.rapids.shuffle.bounce.*); pinned pool still overrides
        kwargs = {"max_inflight_bytes":
                  int(self.session.conf.get(SHUFFLE_MAX_RECV_INFLIGHT)),
                  "pool_size":
                  int(self.session.conf.get(SHUFFLE_BOUNCE_POOL_SIZE)),
                  "chunk_size":
                  int(self.session.conf.get(SHUFFLE_BOUNCE_CHUNK_SIZE)),
                  "rpc_handler": self.dispatch}
        pinned = int(self.session.conf.get(PINNED_POOL_SIZE))
        if pinned > 0:
            kwargs["pool_size"] = pinned
        self.transport = SocketTransport(**kwargs)
        self.transport.configure(self.session.conf)
        self.env = ShuffleEnv(self.runtime, self.session.conf, executor_id,
                              self.transport)
        # exchange execs resolve the env through the runtime singleton
        self.runtime._shuffle_env = self.env
        self.peers: List[str] = []
        self.shutdown_event = threading.Event()
        # distributed tracing: one process-lifetime journal shard (task/
        # fetch/serve spans + a wall-clock anchor) the driver drains over
        # rpc_drain_journal; file-backed under the journal dir when one is
        # configured so offline --timeline analysis works too
        from ..config import (METRICS_JOURNAL_DIR, TRACE_ENABLED,
                              TRACE_SHARD_MAX_EVENTS)
        from ..metrics import journal as J
        self.shard = None
        if bool(self.session.conf.get(TRACE_ENABLED)):
            jdir = str(self.session.conf.get(METRICS_JOURNAL_DIR) or "")
            path = (os.path.join(jdir, f"shard-{executor_id}.jsonl")
                    if jdir else None)
            self.shard = J.open_shard(
                executor_id, path,
                max_events=int(self.session.conf.get(
                    TRACE_SHARD_MAX_EVENTS)))
        # slowdown injection scope: 'exec-1/reduce:500' delay specs match
        # only the worker whose executor id equals the scope
        from ..utils import faults
        faults.INJECTOR.set_scope(executor_id)
        # per-(sid, fragment) attempt serialization: a re-run, a
        # speculative copy's cleanup, and a still-running prior attempt
        # of the SAME fragment must never interleave their registration
        # surgery — remove_map_range waits for the in-flight writer, so
        # once it returns nothing re-registers behind it
        self._frag_locks: Dict[tuple, threading.Lock] = {}
        self._frag_locks_guard = threading.Lock()
        # live-progress bookkeeping the heartbeat reports
        self._hb_lock = threading.Lock()
        self._hb_seq = 0
        self._active_tasks: Dict[int, dict] = {}
        self._task_counter = 0
        self.tasks_completed = 0
        self.tasks_failed = 0
        self.rows_written = 0
        # flight recorder + gauge sampler + /metrics endpoint (the
        # always-on telemetry plane, docs/monitoring.md): the ring taps
        # every journal in this process, the sampler snapshots the gauge
        # sources below, and the loopback HTTP server is announced in
        # the ready line so the driver (or a human with curl) can scrape
        # a live worker
        from ..config import TELEMETRY_HTTP_ENABLED
        from ..metrics import ring as R
        self.telemetry = R.init_telemetry(self.session.conf,
                                          role="worker")
        if self.telemetry is not None:
            self.telemetry.sampler.add_source("pool", self._pool_gauges)
            self.telemetry.sampler.add_source(
                "transport", lambda: dict(self.transport.counters))
            self.telemetry.sampler.add_source("tasks", self._task_gauges)
            self.telemetry.sampler.add_source("policy", self._policy_gauges)
            self.telemetry.sampler.start()
            if bool(self.session.conf.get(TELEMETRY_HTTP_ENABLED)):
                from ..metrics.http import serve_telemetry
                serve_telemetry(self.telemetry,
                                {"executor": executor_id},
                                healthz=self._healthz)

    def _pool_gauges(self) -> Dict[str, float]:
        stats = self.runtime.pool_stats()
        out = {k: float(v) for k, v in stats.items()
               if isinstance(v, (int, float))}
        out["spill_bytes"] = float(stats.get("host_used", 0)
                                   + stats.get("disk_used", 0))
        return out

    def _policy_gauges(self) -> Dict[str, float]:
        pol = getattr(self.runtime, "policy", None)
        return pol.gauges() if pol is not None else {}

    def _task_gauges(self) -> Dict[str, float]:
        with self._hb_lock:
            return {"in_flight_tasks": float(len(self._active_tasks))}

    def _healthz(self):
        with self._hb_lock:
            payload = {"ok": True, "role": "worker",
                       "executor_id": self.executor_id,
                       "pid": os.getpid(),
                       "active_tasks": len(self._active_tasks),
                       "tasks_completed": self.tasks_completed,
                       "tasks_failed": self.tasks_failed,
                       "shutting_down": self.shutdown_event.is_set()}
        if payload["shutting_down"]:
            payload["ok"] = False
        return (200 if payload["ok"] else 503), payload

    # ---- rpc methods -------------------------------------------------------

    def dispatch(self, method: str, kwargs: Dict):
        fn = getattr(self, f"rpc_{method}", None)
        if fn is None:
            raise ValueError(f"unknown rpc method {method!r}")
        return fn(**kwargs)

    def rpc_ping(self):
        return {"executor_id": self.executor_id,
                "platform": self._platform()}

    def _platform(self) -> str:
        import jax
        return jax.devices()[0].platform

    def rpc_set_peers(self, peers: Dict[str, tuple],
                      replace: bool = False):
        self.transport.set_peers(peers, replace=replace)
        self.peers = [p for p in peers if p != self.executor_id]
        return sorted(peers)

    def rpc_get_peers(self):
        """This worker's CURRENT peer address map — what its next remote
        fetch will actually dial (test observability for the
        replacement-republish path)."""
        return {k: list(v) for k, v in self.transport._peers.items()}

    @contextlib.contextmanager
    def _task(self, name: str, trace: Optional[Dict], sid: int,
              attempt: int = 0):
        """Task scope: a `task` span in the trace shard (attempt-stamped,
        so speculative copies are distinguishable on the timeline), the
        DRIVER's trace context installed on this thread (so every wire
        request the task issues carries it), the task registered for
        heartbeat active-task snapshots, the straggler-test delay hook,
        and the chaos tier's crash point (os._exit mid-task)."""
        from ..metrics import journal as J
        from ..utils import faults
        query = (trace or {}).get("query")
        stage = (trace or {}).get("stage") or f"s{sid}.{name}"
        span = None
        if self.shard is not None:
            span = self.shard.begin("task", name, query=query,
                                    stage=stage, shuffle=sid,
                                    executor=self.executor_id,
                                    attempt=attempt)
        with self._hb_lock:
            self._task_counter += 1
            tid = self._task_counter
            self._active_tasks[tid] = {
                "name": name, "stage": stage, "query": query,
                "span": span, "start_mono": time.monotonic()}
        ok = False
        try:
            with J.trace_context(query=query, stage=stage, span=span,
                                 executor=self.executor_id):
                faults.INJECTOR.on_delay(name)
                # chaos crash point AFTER the delay hook: injectDelay +
                # injectCrash compose into "die N ms INTO the task" —
                # the rpc is in flight, partial side effects may exist
                faults.INJECTOR.on_crash(name)
                yield
            ok = True
        finally:
            with self._hb_lock:
                self._active_tasks.pop(tid, None)
                # a raised task is NOT completed work — a fail/retry loop
                # must not look like advancing progress to the driver
                if ok:
                    self.tasks_completed += 1
                else:
                    self.tasks_failed += 1
            if self.shard is not None:
                self.shard.end(span, ok=ok)

    def rpc_run_map(self, sid: int, plan_blob: bytes,
                    key_names: List[str], n_parts: int,
                    trace: Optional[Dict] = None, map_id_base: int = 0,
                    attempt: int = 0):
        """Execute the fragment, hash-partition on the keys, write all
        partitions to the local catalog.  Returns per-partition row
        counts (the MapStatus analogue).

        `map_id_base` namespaces this fragment's block map-ids
        ([base, base + MAP_ID_STRIDE), catalog.MAP_ID_STRIDE), and the
        ATTEMPT-ID GUARD below makes registration atomic per attempt:
        any prior attempt's registrations for this fragment on THIS
        worker (a retried rpc that half-ran, a superseded speculative
        copy) are dropped before the new attempt writes its first block,
        so the reduce side can never read a mix of attempts."""
        with self._task("map", trace, sid, attempt=attempt):
            return self._run_map(sid, plan_blob, key_names, n_parts,
                                 map_id_base)

    def _fragment_lock(self, sid: int, map_id_base: int):
        key = (sid, map_id_base)
        with self._frag_locks_guard:
            lock = self._frag_locks.get(key)
            if lock is None:
                lock = self._frag_locks[key] = threading.Lock()
            return lock

    def _run_map(self, sid: int, plan_blob: bytes,
                 key_names: List[str], n_parts: int,
                 map_id_base: int = 0):
        with self._fragment_lock(sid, map_id_base):
            return self._run_map_locked(sid, plan_blob, key_names,
                                        n_parts, map_id_base)

    def _run_map_locked(self, sid: int, plan_blob: bytes,
                        key_names: List[str], n_parts: int,
                        map_id_base: int = 0):
        import pickle

        from ..columnar import ColumnarBatch
        from ..exec.base import ExecContext, TpuExec
        from ..ops import expressions as E
        from .catalog import MAP_ID_STRIDE
        from .partition import hash_partition_ids, split_by_partition

        # attempt-id guard: supersede any earlier attempt of THIS
        # fragment before registering anything (idempotent re-runs; the
        # fragment lock guarantees no prior attempt is still writing)
        self.env.remove_map_outputs(sid, map_id_base,
                                    map_id_base + MAP_ID_STRIDE)
        logical = pickle.loads(plan_blob)
        physical = self.session.plan(logical)
        schema = physical.schema
        names = schema.names
        refs = [E.BoundReference(names.index(k), schema.field(k).dtype, k)
                for k in key_names]
        ctx = ExecContext(self.session.conf, runtime=self.runtime)
        written: Dict[int, int] = {}
        on_tpu = isinstance(physical, TpuExec)

        def batches():
            if on_tpu:
                yield from physical.execute(ctx)
            else:
                for t in physical.execute_cpu(ctx):
                    yield ColumnarBatch.from_arrow(t)

        try:
            if on_tpu:
                self.runtime.semaphore.acquire_if_necessary()
            try:
                for map_id, batch in enumerate(batches()):
                    if refs:
                        pids = hash_partition_ids(
                            [r.eval(batch) for r in refs], n_parts)
                    else:
                        from .partition import round_robin_partition_ids
                        pids = round_robin_partition_ids(
                            batch.capacity, n_parts, map_id)
                    for p, sub in split_by_partition(batch, pids, n_parts):
                        self.env.write_partition(sid, map_id_base + map_id,
                                                 p, sub)
                        written[p] = written.get(p, 0) + sub.num_rows_host()
            finally:
                if on_tpu:
                    self.runtime.semaphore.task_done()
        finally:
            ctx.run_cleanups()
        with self._hb_lock:
            self.rows_written += sum(written.values())
        return {"written_rows": written}

    def rpc_run_reduce(self, sid: int, partitions: List[int],
                       plan_blob: bytes, trace: Optional[Dict] = None,
                       attempt: int = 0):
        """Fetch owned partitions (local + every peer over the wire), run
        the reduce fragment per partition, return arrow IPC bytes."""
        with self._task("reduce", trace, sid, attempt=attempt):
            return self._run_reduce(sid, partitions, plan_blob)

    def _run_reduce(self, sid: int, partitions: List[int],
                    plan_blob: bytes):
        import pickle

        import pyarrow as pa

        from ..engine import DataFrame

        logical = pickle.loads(plan_blob)
        outs: List[pa.Table] = []

        def reduce_one(batches: list) -> None:
            tabs = [b.to_arrow() for b in batches]
            tabs = [t for t in tabs if t.num_rows]
            if not tabs:
                return
            table = pa.concat_tables(tabs)
            df = DataFrame(self.session,
                           attach_stage_input(logical, table))
            outs.append(df.to_arrow())

        from ..config import SHUFFLE_ASYNC_FETCH
        if self.session.conf.get(SHUFFLE_ASYNC_FETCH):
            # pipelined read: the producer thread fetches partition k+1
            # from peer workers over the wire (bounded by
            # maxReceiveInflightBytes) while the reduce fragment computes
            # on partition k
            from .fetch import iter_partition_groups
            for _rid, batches in iter_partition_groups(
                    self.env.fetch_partitions_async(
                        sid, partitions, remote_peers=self.peers)):
                reduce_one(batches)
        else:  # conf kill-switch: synchronous per-partition fetch
            for p in partitions:
                reduce_one(list(self.env.fetch_partition(
                    sid, p, remote_peers=self.peers)))
        if not outs:
            return None
        result = pa.concat_tables(outs)
        sink = pa.BufferOutputStream()
        with pa.ipc.new_stream(sink, result.schema) as w:
            w.write_table(result)
        return sink.getvalue().to_pybytes()

    def rpc_transport_counters(self):
        return dict(self.transport.counters)

    def rpc_pool_stats(self):
        """Runtime pool/retry/spill figures for cluster-wide observability
        (metrics/export.cluster_snapshot pulls this from every worker)."""
        return dict(self.runtime.pool_stats())

    def rpc_heartbeat(self):
        """Live progress snapshot for the driver's heartbeat monitor
        (cluster.HeartbeatMonitor, polled over a DEDICATED connection so
        a long-running task rpc never blocks it): monotonic counters,
        pool stats, and the active-task snapshot the hung-task watchdog
        inspects.  Also a clock probe — wall_ns against the driver's
        send/receive times estimates this worker's clock offset for the
        merged timeline."""
        with self._hb_lock:
            self._hb_seq += 1
            seq = self._hb_seq
            now = time.monotonic()
            active = [{"name": t["name"], "stage": t["stage"],
                       "query": t["query"], "span": t["span"],
                       "elapsed_s": now - t["start_mono"]}
                      for t in self._active_tasks.values()]
            completed = self.tasks_completed
            failed = self.tasks_failed
            rows = self.rows_written
        try:
            pool = dict(self.runtime.pool_stats())
        except Exception:  # noqa: BLE001 — a heartbeat must never fail
            pool = {}
        if self.shard is not None:
            self.shard.instant("heartbeat", "heartbeat", seq=seq,
                               active=len(active))
        return {"executor_id": self.executor_id, "seq": seq,
                "pid": os.getpid(), "wall_ns": time.time_ns(),
                "mono_ns": time.monotonic_ns(),
                "tasks_completed": completed, "tasks_failed": failed,
                "rows_written": rows, "active_tasks": active,
                "counters": dict(self.transport.counters), "pool": pool}

    def rpc_clock_probe(self):
        """Bare wall/monotonic clock sample (NTP-style offset estimation
        without the heartbeat payload)."""
        return {"wall_ns": time.time_ns(), "mono_ns": time.monotonic_ns()}

    def rpc_drain_journal(self):
        """Incremental trace-shard drain: events journaled since the last
        drain plus the shard's wall-clock anchor (metrics/timeline.py
        merges every worker's drains into ONE query timeline).  None when
        tracing is disabled."""
        if self.shard is None:
            return None
        out = self.shard.drain()
        out["executor_id"] = self.executor_id
        return out

    def rpc_map_output_stats(self, sid: int):
        """This worker's observed map-output sizes for one shuffle
        ({reduce_id: {bytes, rows, maps}}) — the driver merges every
        worker's snapshot into cluster-wide MapOutputStatistics for
        adaptive re-planning (adaptive/stats.merge_cluster_stats)."""
        return self.env.map_stats.snapshot(sid)

    def rpc_remove_shuffle(self, sid: int):
        self.env.remove_shuffle(sid)
        with self._frag_locks_guard:  # the locks die with the shuffle
            for key in [k for k in self._frag_locks if k[0] == sid]:
                del self._frag_locks[key]
        return True

    def rpc_remove_map_range(self, sid: int, lo: int, hi: int):
        """Drop one map fragment's registered outputs (speculation-loser
        cleanup / the driver-side half of the attempt-id guard).  Takes
        the fragment lock, so a still-running attempt of the fragment is
        WAITED OUT first — after this returns, nothing re-registers the
        superseded attempt's blocks (the caller's rpc deadline bounds
        the wait; a wedge past it escalates to eviction driver-side)."""
        with self._fragment_lock(sid, lo):
            return self.env.remove_map_outputs(sid, lo, hi)

    def rpc_inject_faults(self, oom: str = "", net: str = "",
                          corruption: str = "", delay: str = "",
                          crash: str = "", seed: int = 0):
        """(Re)arm this worker's process-global fault injector — the
        chaos soak's per-round control plane: one long-lived cluster
        cycles through kill/delay/corrupt plans without respawning
        workers (replacements spawn from the base conf, i.e. healthy)."""
        from ..utils import faults
        faults.INJECTOR.configure(oom_spec=oom, net_spec=net, seed=seed,
                                  corrupt_spec=corruption,
                                  delay_spec=delay, crash_spec=crash)
        return True

    def rpc_ring_dump(self):
        """This worker's flight-recorder ring (the last-N journal lines,
        metrics/ring.py) — what a post-mortem bundle fetches from every
        SURVIVING worker (metrics/bundle.dump_diagnostics).  Unlike
        rpc_drain_journal this is a non-consuming snapshot: it can be
        read at any moment without perturbing the driver's incremental
        drain accounting.  None when telemetry is disabled."""
        if self.telemetry is None:
            return None
        lines, dropped = self.telemetry.recorder.dump_lines()
        return {"executor_id": self.executor_id, "pid": os.getpid(),
                "dropped": dropped, "lines": lines}

    def rpc_shutdown(self):
        self.shutdown_event.set()
        return True


def main(argv: Optional[List[str]] = None) -> int:
    import argparse
    parser = argparse.ArgumentParser()
    parser.add_argument("--executor-id", required=True)
    args = parser.parse_args(argv)

    if os.environ.get("SPARK_RAPIDS_TPU_WORKER_CPU") == "1":
        from ..utils.cpu_backend import force_cpu_backend
        force_cpu_backend()

    conf = json.loads(os.environ.get("SPARK_RAPIDS_TPU_CONF", "{}"))
    # mark this process as an executor BEFORE the session exists: the
    # engine's driver-side postmortem arming (SIGUSR1, auto-dump
    # triggers) must stay off in workers — the driver owns the bundle
    from ..metrics import ring as R
    R.PROCESS_ROLE[0] = "worker"
    handler = WorkerHandler(args.executor_id, conf)
    # announce the data/control port (and the telemetry endpoint's, when
    # one is listening) on stdout for the driver
    http = handler.telemetry.http if handler.telemetry is not None \
        else None
    print(json.dumps({"ready": True,
                      "executor_id": args.executor_id,
                      "host": handler.transport.address[0],
                      "port": handler.transport.address[1],
                      "http_port": http.port if http else None}),
          flush=True)

    # exit when the driver asks, or when it dies (stdin EOF)
    def stdin_watch():
        try:
            sys.stdin.read()
        except Exception:  # noqa: BLE001
            pass  # tpulint: disable=TPU006 any stdin error IS the driver-death signal; the next line delivers it
        handler.shutdown_event.set()

    threading.Thread(target=stdin_watch, daemon=True).start()
    handler.shutdown_event.wait()
    handler.transport.shutdown()
    return 0


if __name__ == "__main__":
    sys.exit(main())
