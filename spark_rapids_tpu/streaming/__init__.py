"""Streaming micro-batch engine: incremental execution of grouped
aggregations over append-only sources, with device-resident partial
state, atomic epoch checkpoints, and compiled-stage replay.

The module map mirrors the epoch's life:

  source.py      append-only sources + the epoch planner (monotonic
                 offsets, micro-batch slicing, identity-stamped scans)
  query.py       StreamingQuery: the trigger loop, each epoch a
                 scheduler query with a lifecycle token
  state.py       device-resident partial-aggregate state (owner-stamped
                 spillable buffers, folded via the aggregate's own
                 merge kernel)
  checkpoint.py  atomic epoch commit + restart recovery

See docs/tuning-guide.md, "Streaming micro-batch execution".
"""
from .checkpoint import EpochCheckpoint
from .query import StreamingQuery, StreamingUnsupported, stream_query
from .source import DirectoryTailSource, EpochSlice, MemoryStream, \
    StreamingSource
from .state import StreamState

__all__ = [
    "DirectoryTailSource", "EpochCheckpoint", "EpochSlice", "MemoryStream",
    "StreamState", "StreamingQuery", "StreamingSource",
    "StreamingUnsupported", "stream_query",
]
