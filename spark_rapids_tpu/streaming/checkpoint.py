"""Atomic epoch checkpoints: source offsets + state snapshot, commit last.

Layout under the checkpoint directory:

    epoch-<n>/meta.json    offsets, row counts, the state BatchMeta
    epoch-<n>/state.bin    flat leaf image (mem/buffer.write_leaves —
                           the same serde the disk spill tier uses)
    LATEST                 {"epoch": n}, written via temp + os.replace

The commit marker is written LAST and atomically: a query killed
mid-commit leaves a complete previous epoch behind and a partial
epoch-<n>/ directory that recovery never looks at (and the next commit
of epoch n overwrites).  Recovery therefore always resumes from a
consistent (offsets, state) pair — the state snapshot is the exact
device bits at commit time, so a restarted query's next fold continues
bit-for-bit where the killed one committed (tests/test_streaming.py
kills mid-stream and asserts equality with the uninterrupted run).

Old epochs are pruned down to
`spark.rapids.sql.tpu.streaming.checkpoint.keepEpochs` AFTER the marker
moves, so the previous recovery point survives until the new one is
durable.
"""
from __future__ import annotations

import json
import os
import shutil
from typing import Dict, List, Optional, Tuple

from ..types import Schema, StructField, _TYPES_BY_NAME


def _meta_to_json(meta) -> dict:
    return {
        "schema": [(f.name, f.dtype.name) for f in meta.schema],
        "capacity": meta.capacity,
        "leaf_meta": [{"dtype_name": lm.dtype_name,
                       "shapes": [list(s) for s in lm.shapes],
                       "np_dtypes": list(lm.np_dtypes)}
                      for lm in meta.leaf_meta],
        "sel_shape": list(meta.sel_shape),
        "size_bytes": meta.size_bytes,
    }


def _meta_from_json(d: dict):
    from ..mem.buffer import BatchMeta, ColumnLeafMeta
    schema = Schema([StructField(n, _TYPES_BY_NAME[t])
                     for n, t in d["schema"]])
    leaf_meta = [ColumnLeafMeta(lm["dtype_name"],
                                [tuple(s) for s in lm["shapes"]],
                                list(lm["np_dtypes"]))
                 for lm in d["leaf_meta"]]
    return BatchMeta(schema, int(d["capacity"]), leaf_meta,
                     tuple(d["sel_shape"]), int(d["size_bytes"]))


class EpochCheckpoint:
    """Checkpoint store for one streaming query's epochs."""

    def __init__(self, directory: str, keep: int = 2):
        self.directory = os.path.abspath(directory)
        self.keep = max(1, int(keep))
        os.makedirs(self.directory, exist_ok=True)

    def _epoch_dir(self, n: int) -> str:
        return os.path.join(self.directory, f"epoch-{n}")

    def latest_epoch(self) -> Optional[int]:
        path = os.path.join(self.directory, "LATEST")
        try:
            with open(path) as f:
                return int(json.load(f)["epoch"])
        except (FileNotFoundError, ValueError, KeyError,
                json.JSONDecodeError):
            return None

    # -- commit --------------------------------------------------------------

    def commit(self, epoch: int, offsets: Dict[str, int],
               snapshot: Optional[Tuple[List, object]],
               rows_total: int = 0) -> None:
        """Write epoch-<epoch>/ fully, then move the LATEST marker."""
        from ..mem.buffer import write_leaves
        edir = self._epoch_dir(epoch)
        if os.path.isdir(edir):  # partial leftovers from a killed commit
            shutil.rmtree(edir)
        os.makedirs(edir)
        meta: dict = {"epoch": epoch, "offsets": dict(offsets),
                      "rows_total": int(rows_total), "state": None}
        if snapshot is not None:
            leaves, bmeta = snapshot
            write_leaves(os.path.join(edir, "state.bin"), leaves)
            meta["state"] = _meta_to_json(bmeta)
        with open(os.path.join(edir, "meta.json"), "w") as f:
            json.dump(meta, f)
            f.flush()
            os.fsync(f.fileno())
        # the commit point: LATEST flips atomically to the new epoch
        marker = os.path.join(self.directory, "LATEST")
        tmp = f"{marker}.{os.getpid()}.tmp"
        with open(tmp, "w") as f:
            json.dump({"epoch": epoch}, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, marker)
        self._prune(epoch)

    def _prune(self, latest: int) -> None:
        for name in os.listdir(self.directory):
            if not name.startswith("epoch-"):
                continue
            try:
                n = int(name.split("-", 1)[1])
            except ValueError:
                continue  # tpulint: disable=TPU006 foreign file in the checkpoint dir; pruning only ever touches epoch-<n> directories
            if n <= latest - self.keep:
                shutil.rmtree(os.path.join(self.directory, name),
                              ignore_errors=True)

    # -- recovery ------------------------------------------------------------

    def load_latest(self) -> Optional[dict]:
        """The last committed epoch's payload, or None when no commit
        exists: {"epoch", "offsets", "rows_total", "state": None |
        (leaves, BatchMeta)}."""
        n = self.latest_epoch()
        if n is None:
            return None
        edir = self._epoch_dir(n)
        with open(os.path.join(edir, "meta.json")) as f:
            meta = json.load(f)
        out = {"epoch": int(meta["epoch"]),
               "offsets": {k: int(v) for k, v in meta["offsets"].items()},
               "rows_total": int(meta.get("rows_total", 0)),
               "state": None}
        if meta.get("state") is not None:
            from ..mem.buffer import read_leaves
            bmeta = _meta_from_json(meta["state"])
            leaves = read_leaves(os.path.join(edir, "state.bin"), bmeta)
            out["state"] = (leaves, bmeta)
        return out
