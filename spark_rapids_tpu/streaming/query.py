"""StreamingQuery: the micro-batch trigger loop.

One StreamingQuery = one append-only source + one grouped aggregation,
executed incrementally.  Each epoch:

  1. The epoch planner slices unread data into a micro-batch scan
     (source.py).
  2. A DELTA query — the same aggregation rewritten so its output IS a
     partial state (`_delta_aggregates`: Sum/Count/Min/Max unchanged,
     Average split into Sum(Cast(x, double)) + Count(x)) — runs over
     just that slice THROUGH `TpuSession.submit`.  Riding the scheduler
     buys the whole serving tier per epoch: a lifecycle token (so
     `stop()` cancels the in-flight epoch at its next checkpoint and
     `epochDeadlineMs` bounds it end to end), fair-share admission, SLO
     accounting, and the parameterized plan cache — whose fingerprint
     keys the stamped streaming scan by source identity + schema
     (serve/plan_cache.py), so every epoch after the first is a plan-
     cache hit replaying the already-compiled stages: warm epochs
     perform ZERO stage compiles (asserted in tests/test_streaming.py
     and recorded in BENCH_STREAM.json).
  3. The delta's output is renamed positionally onto the aggregate's
     partial-state schema and folded into the device-resident state
     with the aggregate's own merge kernel (state.py).
  4. The epoch commits atomically: source offsets + state snapshot,
     marker last (checkpoint.py).  A killed-and-restarted query resumes
     from the last committed epoch bit-for-bit.

Observability: every epoch journals `epoch` events (slice/commit, plus
recover on restart), bumps numEpochs/epochTime/streamStateBytes/
numStateRecoveries, and lands its wall time in the `epoch` SLO phase
histogram for its priority class.

What stays incremental-safe is deliberately narrow (everything else
raises StreamingUnsupported up front, not mid-stream): grouped
Sum/Count/Min/Max/Average (rollup/cube included — the grouping-id is
just another key), no distinct, no First/Last/Percentile, no compound
result projections, exactly one streaming scan under the aggregate.
docs/tuning-guide.md ("Streaming micro-batch execution") walks through
why each exclusion breaks incremental folding.
"""
from __future__ import annotations

import copy
import itertools
import threading
import time
from typing import Dict, List, Optional, Tuple

from ..metrics import names as MN
from ..metrics.journal import EventJournal, journal_event, pop_active, \
    push_active
from ..plan import logical as L
from ..types import DoubleType
from .checkpoint import EpochCheckpoint
from .source import StreamingSource
from .state import StreamState

_SUPPORTED_AGGS = ("Sum", "Count", "Min", "Max", "Average")
_query_seq = itertools.count(1)


class StreamingUnsupported(ValueError):
    """The query shape cannot be folded incrementally."""


# ---------------------------------------------------------------------------
# plan surgery
# ---------------------------------------------------------------------------

def _find_stream_scans(node: L.LogicalPlan, identity: str, acc: list):
    if getattr(node, "source_identity", None) == identity:
        acc.append(node)
    for c in node.children:
        _find_stream_scans(c, identity, acc)


def _swap_scan(node: L.LogicalPlan, identity: str,
               new_scan: L.LogicalScan) -> L.LogicalPlan:
    """Rebuild the path to the stamped scan with the epoch's slice in
    its place (copy-on-write, like plan_cache._copy_node — DataFrames
    share logical nodes, so the original tree is never mutated)."""
    if getattr(node, "source_identity", None) == identity:
        return new_scan
    new_children = tuple(_swap_scan(c, identity, new_scan)
                         for c in node.children)
    if all(n is o for n, o in zip(new_children, node.children)):
        return node
    new = copy.copy(node)
    new.children = new_children
    new.__dict__.pop("_cached_schema", None)
    return new


def _delta_aggregates(aggregates: List[L.ColumnExpr]) -> List[L.ColumnExpr]:
    """Rewrite the aggregate list so the delta query's FINALIZED output
    is, column for column, the aggregate's partial state
    (TpuHashAggregateExec._make_state_schema / _AggState.fields):

      Sum/Count/Min/Max — already their own partial (same value, same
        dtype, same validity bit).
      Average — two columns: Sum(Cast(x, double)) + Count(x), exactly
        the (sum, count) pair the update kernel accumulates (both cast
        to f64 before the masked segment sum, both with the same
        any-valid validity), so the fold's division-free merge and the
        single finalize division see identical raw bits.

    The positional rename onto the state schema happens in fold()."""
    out: List[L.ColumnExpr] = []
    for ai, a in enumerate(aggregates):
        child = a.args[0]
        if a.op == "Average":
            cast = L.ColumnExpr("Cast", (child, DoubleType))
            out.append(L.ColumnExpr("Sum", (cast, False),
                                    alias=f"_a{ai}_sum"))
            out.append(L.ColumnExpr("Count", (child, False),
                                    alias=f"_a{ai}_count"))
        else:
            out.append(L.ColumnExpr(a.op, (child, False),
                                    alias=f"_a{ai}_{a.op.lower()}"))
    return out


def _decompose(plan: L.LogicalPlan, identity: str
               ) -> Tuple[L.LogicalAggregate,
                          Optional[List[Tuple[str, str]]]]:
    """Validate + split the built query into (the aggregate node, the
    optional pure-column result projection as (source, output) name
    pairs).  GroupedData.agg wraps rollup/compound results in a
    LogicalProject; only the pure column-select form (rollup's
    grouping-id drop) is incremental-safe — compound projections
    (sum(a)/sum(b)) would need re-finalization arithmetic the state
    store does not model."""
    proj: Optional[List[Tuple[str, str]]] = None
    node = plan
    if isinstance(node, L.LogicalProject):
        if not all(isinstance(e, L.ColumnExpr) and e.op == "col"
                   for e in node.exprs):
            raise StreamingUnsupported(
                "compound aggregate result projections (e.g. "
                "sum(a)/sum(b)) are not incremental-safe; compute them "
                "from the streaming result table instead")
        proj = [(e.args[0], e.output_name) for e in node.exprs]
        node = node.children[0]
    if not isinstance(node, L.LogicalAggregate):
        raise StreamingUnsupported(
            "a streaming query must end in a grouped aggregation "
            f"(got {type(node).__name__})")
    if not node.grouping:
        raise StreamingUnsupported(
            "global (ungrouped) streaming aggregation is not supported; "
            "group by a constant to emulate it")
    for a in node.aggregates:
        if not isinstance(a, L.ColumnExpr) or a.op not in _SUPPORTED_AGGS:
            raise StreamingUnsupported(
                f"aggregate {a!r} cannot be folded incrementally "
                f"(supported: {', '.join(_SUPPORTED_AGGS)})")
        if a.args[1]:  # distinct
            raise StreamingUnsupported(
                f"distinct aggregate {a!r} is not incremental-safe: "
                "partial distinct states are not mergeable across "
                "epochs")
    scans: list = []
    _find_stream_scans(node, identity, scans)
    if len(scans) != 1:
        raise StreamingUnsupported(
            f"expected exactly one scan of streaming source "
            f"{identity!r} under the aggregate, found {len(scans)} "
            "(joins between two streams are not supported)")
    return node, proj


# ---------------------------------------------------------------------------
# the query
# ---------------------------------------------------------------------------

class StreamingQuery:
    """Incremental micro-batch execution of one grouped aggregation over
    one append-only source.  Build with `stream_query(...)` or directly:

        src = MemoryStream(schema, name="events")
        q = StreamingQuery(session, src,
                           lambda df: df.group_by(col("k"))
                                        .agg(F.sum(col("v"))),
                           checkpoint_dir="/path/ckpt")
        src.append(batch1); q.process_available(); q.result()
    """

    def __init__(self, session, source: StreamingSource, build, *,
                 name: str = "stream", output_mode: str = "complete",
                 checkpoint_dir: Optional[str] = None, priority: int = 0,
                 epoch_deadline_ms: Optional[float] = None,
                 budget_bytes: Optional[int] = None):
        from .. import config as C
        if output_mode not in ("complete", "update"):
            raise ValueError(
                f"output_mode must be 'complete' or 'update', got "
                f"{output_mode!r}")
        self.session = session
        self.source = source
        self.name = name
        self.output_mode = output_mode
        self.priority = int(priority)
        conf = session.conf
        if epoch_deadline_ms is None:
            epoch_deadline_ms = float(conf.get(C.STREAM_EPOCH_DEADLINE_MS))
        self.epoch_deadline_ms = epoch_deadline_ms or None
        if budget_bytes is None:
            budget_bytes = int(conf.get(C.SERVE_QUERY_BUDGET))
        # the owner stamp every state buffer carries (unique per query
        # INSTANCE: release() must never free a namesake's state)
        self.owner = f"stream:{name}#{next(_query_seq)}"
        self.journal = EventJournal(label=f"stream-{name}")

        # -- analyze the built query ------------------------------------
        from ..engine import DataFrame
        df = DataFrame(session, source.placeholder_scan())
        built = build(df)
        plan = built.plan if hasattr(built, "plan") else built
        self._agg_plan, self._proj = _decompose(plan, source.identity)
        self._delta_aggs = _delta_aggregates(self._agg_plan.aggregates)
        self._agg_exec = self._find_agg_exec()
        self._state = StreamState(session, self._agg_exec, self.owner,
                                  budget_bytes=budget_bytes)

        # -- epoch bookkeeping ------------------------------------------
        self.epochs_committed = 0
        self.rows_folded = 0
        self.recovered = False
        self._offsets: Dict[str, int] = {source.identity: 0}
        self._last_output = None
        self._error: Optional[BaseException] = None
        self._stopped = False
        self._stop_event = threading.Event()
        self._inflight = None
        self._thread: Optional[threading.Thread] = None
        self._lock = threading.RLock()
        self._ckpt = (EpochCheckpoint(checkpoint_dir,
                                      keep=int(conf.get(
                                          C.STREAM_CHECKPOINT_KEEP)))
                      if checkpoint_dir else None)
        if self._ckpt is not None:
            self._recover()

    # -- setup ---------------------------------------------------------------

    def _find_agg_exec(self):
        """Plan the BATCH-shaped aggregate (over the empty placeholder
        scan) and pull out its physical TpuHashAggregateExec: the state
        store borrows its state schema and its merge/finalize kernels —
        by the exec's exact kernel-cache key, so streaming folds and
        batch oracle runs share the same compiled programs."""
        from ..exec.aggregate import TpuHashAggregateExec
        physical = self.session.plan(self._agg_plan)
        stack = [physical]
        while stack:
            node = stack.pop()
            if isinstance(node, TpuHashAggregateExec):
                return node
            stack.extend(getattr(node, "children", ()))
        raise StreamingUnsupported(
            "the aggregation did not plan onto the device "
            "(TpuHashAggregateExec not found — check explain() for CPU "
            "fallbacks); streaming state requires the device aggregate")

    def _recover(self) -> None:
        with self._lock:
            payload = self._ckpt.load_latest()
            if payload is None:
                return
            self.epochs_committed = payload["epoch"]
            self.rows_folded = payload["rows_total"]
            self._offsets.update(payload["offsets"])
            if payload["state"] is not None:
                self._state.restore(*payload["state"])
            self.recovered = True
            self.session.runtime.metrics.add(MN.NUM_STATE_RECOVERIES, 1)
            push_active(self.journal)
            try:
                journal_event("epoch", "recover",
                              epoch=self.epochs_committed,
                              offsets=dict(self._offsets),
                              state_bytes=self._state.device_bytes())
            finally:
                pop_active(self.journal)

    # -- triggers ------------------------------------------------------------

    def trigger_once(self) -> bool:
        """Run AT MOST one epoch over currently-unread data; returns
        whether an epoch committed."""
        with self._lock:
            self._check_usable()
            push_active(self.journal)
            try:
                return self._run_epoch()
            finally:
                pop_active(self.journal)

    def process_available(self, max_epochs: Optional[int] = None) -> int:
        """Drain-available trigger: run epochs until no unread data
        remains (or `max_epochs`); returns the number committed."""
        n = 0
        while max_epochs is None or n < max_epochs:
            if self._stopped or not self.trigger_once():
                break
            n += 1
        return n

    def start(self, interval_s: float = 0.1) -> "StreamingQuery":
        """Interval trigger: a background thread drains available data
        every `interval_s` until stop()."""
        with self._lock:
            self._check_usable()
            if self._thread is not None:
                return self
            self._thread = threading.Thread(
                target=self._interval_loop, args=(float(interval_s),),
                name=f"stream-{self.name}", daemon=True)
            self._thread.start()
        return self

    def _interval_loop(self, interval_s: float) -> None:
        while not self._stop_event.is_set():
            try:
                self.process_available()
            except BaseException as e:  # noqa: BLE001 — surfaced via error
                with self._lock:
                    self._error = e
                return
            self._stop_event.wait(interval_s)

    def _check_usable(self) -> None:
        if self._stopped:
            raise RuntimeError("streaming query is stopped")
        if self._error is not None:
            raise self._error

    # -- the epoch -----------------------------------------------------------

    def _run_epoch(self) -> bool:
        # the RLock is already held by trigger_once; re-entering keeps
        # every epoch-state write statically inside the lock
        with self._lock:
            sl = self.source.plan_epoch(
                self._offsets[self.source.identity], self.session.conf)
            if sl is None:
                return False
            metrics = self.session.runtime.metrics
            t0 = time.perf_counter()
            with metrics.timer(MN.EPOCH_TIME):
                journal_event("epoch", "slice",
                              source=self.source.identity,
                              start=sl.start, end=sl.end,
                              rows=sl.rows if sl.rows is not None else -1)
                delta_plan = L.LogicalAggregate(
                    self._agg_plan.grouping, self._delta_aggs,
                    _swap_scan(self._agg_plan.children[0],
                               self.source.identity, sl.scan))
                fut = self.session.submit(
                    delta_plan, priority=self.priority,
                    deadline_ms=self.epoch_deadline_ms)
                self._inflight = fut
                try:
                    delta = fut.result()
                finally:
                    self._inflight = None
                groups = self._state.fold(delta)
                self.epochs_committed += 1
                self.rows_folded += sl.rows if sl.rows is not None else 0
                self._offsets[self.source.identity] = sl.end
                if self._ckpt is not None:
                    self._ckpt.commit(self.epochs_committed, self._offsets,
                                      self._state.snapshot(),
                                      rows_total=self.rows_folded)
                journal_event("epoch", "commit",
                              epoch=self.epochs_committed, groups=groups,
                              state_bytes=self._state.device_bytes(),
                              plan_cache=fut.plan_cache)
                metrics.add(MN.NUM_EPOCHS, 1)
                self._last_output = self._compute_output(delta)
            sched = self.session.scheduler
            if sched is not None:
                sched.slo.observe("epoch", self.priority,
                                  time.perf_counter() - t0)
            return True

    def _compute_output(self, delta_table):
        """Finalize the resident state into the epoch's result table.
        `update` mode keeps only groups touched this epoch (key match
        against the delta, host-side); the stored pure-column projection
        (rollup's grouping-id drop) applies last."""
        import pyarrow as pa
        full = self._state.finalize_table()
        if full is None:
            return None
        if self.output_mode == "update":
            nk = len(self._agg_plan.grouping)
            touched = set(zip(*(delta_table.column(i).to_pylist()
                                for i in range(nk))))
            keep = [t in touched
                    for t in zip(*(full.column(i).to_pylist()
                                   for i in range(nk)))]
            full = full.filter(pa.array(keep, type=pa.bool_()))
        if self._proj is not None:
            full = pa.Table.from_arrays(
                [full.column(src) for src, _out in self._proj],
                names=[out for _src, out in self._proj])
        return full

    # -- results + shutdown --------------------------------------------------

    def result(self):
        """The latest committed epoch's output table (complete: all
        groups; update: groups touched in that epoch).  None before the
        first data-carrying epoch."""
        self._check_usable()
        return self._last_output

    @property
    def error(self) -> Optional[BaseException]:
        return self._error

    def stop(self) -> int:
        """Stop the query: cancel the in-flight epoch at its next
        lifecycle checkpoint, join the interval thread, release every
        state buffer this query owns (all tiers).  Returns owner bytes
        freed.  Idempotent; the checkpoint (if any) survives for a
        successor query to recover from."""
        self._stopped = True  # tpulint: disable=TPU009 deliberately lock-free: stop() must interrupt an epoch that HOLDS the lock; a monotonic flag read at trigger checkpoints
        self._stop_event.set()
        fut = self._inflight
        if fut is not None:
            fut.cancel("streaming query stopped")
        t = self._thread
        if t is not None and t is not threading.current_thread():
            t.join(timeout=60)
        return self._state.release()


def stream_query(session, source: StreamingSource, build,
                 **kwargs) -> StreamingQuery:
    """Convenience constructor (the streaming package's entry point)."""
    return StreamingQuery(session, source, build, **kwargs)
