"""Append-only streaming sources and the epoch planner.

A streaming source is an append-only dataset with a MONOTONIC offset: an
integer that only grows as data arrives (rows appended for the in-memory
table, files landed for the directory tail).  The epoch planner slices
the unread range [committed_offset, latest_offset) into one micro-batch
per epoch, bounded by `spark.rapids.sql.tpu.streaming.maxBatchRows` /
`.maxFilesPerEpoch`, and hands back an ordinary LogicalScan over just
that slice — the rest of the engine never learns it is streaming.

Every epoch scan is stamped with `source_identity`, the stable string
that names this source across epochs AND process restarts.  The plan
cache fingerprints a stamped scan by that identity + schema instead of
the source payload (serve/plan_cache.py _plan_fp): the payload changes
every epoch (an appended table object, a longer file list) while the
query is the same dashboard aggregation, so keying on the payload would
miss the cache — and re-compile the stages — every epoch.  The identity
is also the checkpoint key for this source's committed offset
(streaming/checkpoint.py), which is why restart recovery requires the
caller to pick a name that survives the restart.
"""
from __future__ import annotations

import os
import threading
from typing import List, Optional, Tuple

from ..plan import logical as L
from ..types import Schema


class EpochSlice:
    """One planned micro-batch: the scan to run plus the offset range it
    covers.  `end` becomes the committed offset once the epoch commits."""

    __slots__ = ("scan", "start", "end", "rows")

    def __init__(self, scan: L.LogicalScan, start: int, end: int,
                 rows: Optional[int]):
        self.scan = scan
        self.start = start
        self.end = end
        self.rows = rows  # None when unknown before decode (file sources)


class StreamingSource:
    """Base: a named, append-only source with monotonic integer offsets."""

    identity: str
    schema: Schema

    def latest_offset(self) -> int:
        raise NotImplementedError

    def plan_epoch(self, start: int, conf) -> Optional[EpochSlice]:
        """Slice [start, latest) into the next micro-batch, or None when
        no unread data exists.  Implementations stamp `source_identity`
        on the returned scan."""
        raise NotImplementedError

    def placeholder_scan(self) -> L.LogicalScan:
        """An empty scan of this source's schema — the node the user's
        query is built over.  StreamingQuery swaps the per-epoch slice in
        at this position (located by `source_identity`)."""
        raise NotImplementedError

    def _stamp(self, scan: L.LogicalScan) -> L.LogicalScan:
        scan.source_identity = self.identity
        return scan


class MemoryStream(StreamingSource):
    """In-memory append-only table (the MemoryStream of Spark Structured
    Streaming, and the unit-test workhorse).  Offsets are ROW counts;
    append() is thread-safe; epoch slices are zero-copy pyarrow slices of
    the appended chunks."""

    def __init__(self, schema_or_table, name: str = "mem"):
        self.identity = f"mem:{name}"
        self._lock = threading.Lock()
        self._chunks: List = []       # appended pa.Table chunks, in order
        self._offsets: List[int] = [0]  # cumulative row counts
        if isinstance(schema_or_table, Schema):
            self.schema = schema_or_table
            self._empty = _empty_table(self.schema)
        else:
            table = schema_or_table
            from ..types import StructField, from_arrow
            self.schema = Schema([
                StructField(n, from_arrow(t))
                for n, t in zip(table.column_names, table.schema.types)])
            self._empty = table.slice(0, 0)
            if table.num_rows:
                self.append(table)

    def append(self, table) -> int:
        """Append a pyarrow Table; returns the new latest offset."""
        if table.column_names != [f.name for f in self.schema]:
            raise ValueError(
                f"appended columns {table.column_names} do not match "
                f"source schema {[f.name for f in self.schema]}")
        with self._lock:
            self._chunks.append(table)
            self._offsets.append(self._offsets[-1] + table.num_rows)
            return self._offsets[-1]

    def latest_offset(self) -> int:
        with self._lock:
            return self._offsets[-1]

    def rows_between(self, start: int, end: int):
        """pyarrow Table of rows [start, end) — zero-copy slices of the
        appended chunks, concatenated in append order (the order every
        bit-for-bit argument in docs/tuning-guide.md leans on)."""
        import pyarrow as pa
        with self._lock:
            chunks, offsets = list(self._chunks), list(self._offsets)
        parts = []
        for i, chunk in enumerate(chunks):
            lo, hi = offsets[i], offsets[i + 1]
            s, e = max(start, lo), min(end, hi)
            if s < e:
                parts.append(chunk.slice(s - lo, e - s))
        if not parts:
            return self._empty
        return pa.concat_tables(parts)

    def plan_epoch(self, start: int, conf) -> Optional[EpochSlice]:
        from .. import config as C
        latest = self.latest_offset()
        if latest <= start:
            return None
        end = min(latest, start + int(conf.get(C.STREAM_MAX_BATCH_ROWS)))
        table = self.rows_between(start, end)
        scan = self._stamp(L.LogicalScan(table, self.schema, "memory"))
        return EpochSlice(scan, start, end, table.num_rows)

    def placeholder_scan(self) -> L.LogicalScan:
        return self._stamp(L.LogicalScan(self._empty, self.schema,
                                         "memory"))


class DirectoryTailSource(StreamingSource):
    """Directory-tail file source: new files landing in a (flat)
    directory are the append log; the offset is the index into the
    SORTED file listing.  Epoch scans are ordinary file LogicalScans, so
    decode rides the existing io/ device decode path (parquet/csv/orc).

    Files must be immutable once visible (write-to-temp + rename, the
    same discipline the checkpoint commit uses) and the directory flat:
    Hive-partitioned layouts would make the scan options vary with the
    file list and break the epoch-stable plan fingerprint."""

    def __init__(self, directory: str, fmt: str = "parquet",
                 schema: Optional[Schema] = None,
                 options: Optional[dict] = None, name: str = ""):
        self.directory = os.path.abspath(directory)
        self.fmt = fmt
        self.identity = f"dir:{name or self.directory}|{fmt}"
        self._options = dict(options or {})
        self._schema: Optional[Schema] = schema
        self._exts = {"parquet": (".parquet", ".pq"),
                      "csv": (".csv",), "orc": (".orc",)}[fmt]

    def _listing(self) -> List[str]:
        try:
            names = os.listdir(self.directory)
        except FileNotFoundError:
            return []
        return sorted(os.path.join(self.directory, n) for n in names
                      if n.lower().endswith(self._exts)
                      and not n.startswith((".", "_")))

    @property
    def schema(self) -> Schema:  # type: ignore[override]
        if self._schema is None:
            files = self._listing()
            if not files:
                raise ValueError(
                    f"cannot infer schema: no {self.fmt} files in "
                    f"{self.directory} yet — pass schema= explicitly")
            from ..io.scan import scan_info
            _files, schema, _opts = scan_info([files[0]], self.fmt,
                                              dict(self._options))
            self._schema = schema
        return self._schema

    def latest_offset(self) -> int:
        return len(self._listing())

    def plan_epoch(self, start: int, conf) -> Optional[EpochSlice]:
        from .. import config as C
        files = self._listing()
        if len(files) <= start:
            return None
        end = min(len(files),
                  start + max(1, int(conf.get(C.STREAM_MAX_FILES_PER_EPOCH))))
        scan = self._stamp(L.LogicalScan(files[start:end], self.schema,
                                         self.fmt, dict(self._options)))
        return EpochSlice(scan, start, end, None)

    def placeholder_scan(self) -> L.LogicalScan:
        return self._stamp(L.LogicalScan([], self.schema, self.fmt,
                                         dict(self._options)))


def _empty_table(schema: Schema):
    import pyarrow as pa
    from ..types import to_arrow
    return pa.table({f.name: pa.array([], type=to_arrow(f.dtype))
                     for f in schema})
