"""Device-resident streaming aggregation state.

The state is ONE ColumnarBatch in the aggregate's partial-state layout
(`TpuHashAggregateExec._state_schema`: the grouping keys as `_k{i}`
columns plus each aggregate's partial columns), registered with the
memory runtime as an owner-stamped SPILLABLE buffer.  That registration
is the whole point: between epochs the state is first-class managed
memory — per-query budgets count it, the policy engine can pick it as a
spill victim under pressure, the ledger journals its movements, and
`StreamingQuery.stop()` releases it with the same owner-confined cleanup
a cancelled query uses.  A state batch that was spilled to host/disk
between epochs unspills transparently on the next fold (get_batch's
`materialize` path).

fold() is the incremental heart: the epoch's delta — the SAME
aggregation run over just the new rows, rewritten so its output IS a
partial state (query.py `_delta_aggregates`) — is concatenated BEHIND
the resident state and pushed through the aggregate's own merge kernel,
borrowed via the exec's exact kernel-cache key so warm streaming folds
share the compiled program with the batch path.  State-first concat
order is a correctness load-bearing detail: the merge's stable key sort
keeps state rows ahead of delta rows within each group, so float partial
sums accumulate in chronological left-deep order — the same order the
batch oracle's prefix-fold merge uses — which is what makes incremental
results bit-for-bit equal to a full re-query (docs/tuning-guide.md,
Streaming micro-batch execution).

Both allocation paths are retry blocks with their own reserve sites
(`stream.fold` / `stream.restore`, swept by the injectOom tests): an OOM
mid-fold spills, retries, and never corrupts the state — the old buffer
is freed only after the new one is registered.
"""
from __future__ import annotations

from typing import Optional, Tuple

from ..columnar import ColumnarBatch
from ..columnar.batch import concat_batches
from ..metrics import names as MN


class StreamState:
    """One streaming query's device-resident partial-aggregate state."""

    def __init__(self, session, agg_exec, owner: str,
                 budget_bytes: int = 0):
        self.runtime = session.runtime
        self.agg = agg_exec
        self.owner = owner
        self.budget = int(budget_bytes)
        self._bid: Optional[int] = None
        self._size_bytes = 0
        self._rows = 0

    # -- kernels (shared with the batch aggregate via its cache key) --------

    def _kernel(self, suffix: str, builder):
        from ..utils.kernel_cache import cached_kernel
        return cached_kernel(self.agg.kernel_key() + (suffix,), builder)

    # -- introspection -------------------------------------------------------

    @property
    def state_schema(self):
        return self.agg._state_schema

    def device_bytes(self) -> int:
        return self._size_bytes if self._bid is not None else 0

    def num_groups(self) -> int:
        return self._rows

    # -- fold ----------------------------------------------------------------

    def fold(self, delta_table) -> int:
        """Fold one epoch's delta (a pyarrow table already renamed to the
        state schema) into the resident state; returns resident group
        count.  Retryable: `stream.fold` reserves the H2D + concat +
        merge working set up front so the spill cascade (and the fault
        injector) see the allocation boundary."""
        from ..mem.retry import with_retry
        from ..utils.kernel_cache import record_dispatch

        names = [f.name for f in self.state_schema]
        if delta_table.column_names != names:
            delta_table = delta_table.rename_columns(names)

        def attempt(table) -> ColumnarBatch:
            # working set: the delta lands on device, concat copies
            # state + delta once, the merge writes one output of the
            # same footprint
            est = max(1, int(table.nbytes)) * 2 + self._size_bytes * 3
            self.runtime.reserve(est, site="stream.fold")
            delta = ColumnarBatch.from_arrow(table)
            parts = [delta]
            if self._bid is not None:
                # unspills transparently if the policy engine evicted
                # the state between epochs
                parts = [self.runtime.get_batch(self._bid), delta]
            merged_in = parts[0] if len(parts) == 1 \
                else concat_batches(parts)
            merge = self._kernel("merge", lambda: self.agg._merge_kernel)
            record_dispatch()
            return merge(merged_in)

        with self.runtime.ledger.query_scope(self.owner, self.budget):
            merged = with_retry(attempt, [delta_table],
                                runtime=self.runtime,
                                metrics=self.runtime.metrics,
                                name="streamFold")[0]
            n = merged.num_rows_host()
            merged = merged.maybe_shrink(n)
            new_bid = self.runtime.add_batch(merged)
        old_bid, self._bid = self._bid, new_bid
        self._rows = n
        self._size_bytes = merged.device_size_bytes()
        if old_bid is not None:
            self.runtime.free_batch(old_bid)
        self.runtime.metrics.set_max(MN.STREAM_STATE_BYTES,
                                     self._size_bytes)
        return n

    # -- finalize ------------------------------------------------------------

    def finalize_table(self):
        """Finalized result of the resident state as a pyarrow table
        (group columns + aggregate outputs), through the aggregate's own
        finalize kernel.  None before the first fold."""
        if self._bid is None:
            return None
        from ..utils.kernel_cache import record_dispatch
        state = self.runtime.get_batch(self._bid)
        finalize = self._kernel("finalize",
                                lambda: self.agg._finalize_kernel)
        record_dispatch()
        return finalize(state).to_arrow()

    # -- checkpoint + recovery ----------------------------------------------

    def snapshot(self) -> Optional[Tuple[list, object]]:
        """(host leaves, BatchMeta) of the resident state — the exact
        device bits pulled down through the spill serde, so a restore
        reproduces the state bit-for-bit."""
        if self._bid is None:
            return None
        from ..mem.buffer import batch_to_host
        return batch_to_host(self.runtime.get_batch(self._bid))

    def restore(self, leaves, meta) -> None:
        """Re-admit a checkpointed state snapshot onto the device
        (restart recovery).  Retryable at `stream.restore`."""
        from ..mem.buffer import host_to_batch
        from ..mem.retry import with_retry

        def attempt(_):
            self.runtime.reserve(max(1, int(meta.size_bytes)),
                                 site="stream.restore")
            return host_to_batch(leaves, meta)

        with self.runtime.ledger.query_scope(self.owner, self.budget):
            batch = with_retry(attempt, [None], runtime=self.runtime,
                               metrics=self.runtime.metrics,
                               name="streamRestore")[0]
            new_bid = self.runtime.add_batch(batch)
        old_bid, self._bid = self._bid, new_bid
        self._rows = batch.num_rows_host()
        self._size_bytes = batch.device_size_bytes()
        if old_bid is not None:
            self.runtime.free_batch(old_bid)
        self.runtime.metrics.set_max(MN.STREAM_STATE_BYTES,
                                     self._size_bytes)

    # -- release -------------------------------------------------------------

    def release(self) -> int:
        """Owner-confined cleanup (the stop() path): free every buffer
        stamped with this stream's owner across all tiers.  Returns
        bytes freed; idempotent."""
        self._bid = None
        self._rows = 0
        self._size_bytes = 0
        return self.runtime.release_owner(self.owner)
