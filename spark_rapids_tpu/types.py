"""Data type system for the TPU columnar engine.

Mirrors the supported-type gate of the reference plugin
(reference: sql-plugin/.../rapids/GpuOverrides.scala:375-387 — bool/byte/short/int/
long/float/double/date/timestamp-UTC/string), mapped onto JAX device dtypes.

Device representation decisions (TPU-first, not a cuDF port):
  * numeric/bool/date/timestamp columns -> a single jnp array [capacity]
  * DateType   -> int32 days since epoch
  * TimestampType -> int64 microseconds since epoch, UTC only
  * StringType -> fixed-width padded UTF-8 byte matrix uint8[capacity, max_len]
    plus an int32 length column.  XLA wants static shapes; a byte matrix keeps
    string kernels vectorizable on the VPU (8x128 lanes) instead of the
    offset+heap layout cuDF uses, which needs scatter/gather per row.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Optional

import jax.numpy as jnp
import numpy as np


@dataclasses.dataclass(frozen=True)
class DataType:
    """A SQL-level column type."""

    name: str
    # dtype of the device data buffer (None for types with special layout)
    np_dtype: Optional[np.dtype]

    def __repr__(self) -> str:  # pragma: no cover - cosmetic
        return self.name

    @property
    def is_numeric(self) -> bool:
        return self in (ByteType, ShortType, IntegerType, LongType,
                        FloatType, DoubleType)

    @property
    def is_integral(self) -> bool:
        return self in (ByteType, ShortType, IntegerType, LongType)

    @property
    def is_floating(self) -> bool:
        return self in (FloatType, DoubleType)

    @property
    def is_string(self) -> bool:
        return self is StringType

    @property
    def is_datetime(self) -> bool:
        return self in (DateType, TimestampType)

    @property
    def jnp_dtype(self):
        if self.np_dtype is None:
            raise TypeError(f"{self.name} has no single-buffer device dtype")
        return jnp.dtype(self.np_dtype)

    def __reduce__(self):
        # identity checks (`dtype is StringType`) are used on hot paths;
        # unpickling must return the module singleton, not a copy —
        # metadata crosses process boundaries in the socket shuffle
        # (shuffle/net.py) and in shipped plan fragments (cluster.py)
        return (_canonical_type, (self.name,))


BooleanType = DataType("boolean", np.dtype(np.bool_))
ByteType = DataType("byte", np.dtype(np.int8))
ShortType = DataType("short", np.dtype(np.int16))
IntegerType = DataType("int", np.dtype(np.int32))
LongType = DataType("long", np.dtype(np.int64))
FloatType = DataType("float", np.dtype(np.float32))
DoubleType = DataType("double", np.dtype(np.float64))
DateType = DataType("date", np.dtype(np.int32))          # days since 1970-01-01
TimestampType = DataType("timestamp", np.dtype(np.int64))  # micros since epoch, UTC
StringType = DataType("string", None)
NullType = DataType("null", None)

ALL_TYPES = (BooleanType, ByteType, ShortType, IntegerType, LongType, FloatType,
             DoubleType, DateType, TimestampType, StringType)

_TYPES_BY_NAME = {t.name: t for t in ALL_TYPES + (NullType,)}


def _canonical_type(name: str) -> DataType:
    return _TYPES_BY_NAME[name]

# The type gate: what the engine supports on device at all
# (reference: GpuOverrides.isSupportedType).
SUPPORTED_TYPES = frozenset(ALL_TYPES)

_NUMERIC_ORDER = [ByteType, ShortType, IntegerType, LongType, FloatType, DoubleType]


def promote(a: DataType, b: DataType) -> DataType:
    """Numeric type promotion for binary arithmetic (Spark semantics-ish)."""
    if a is b:
        return a
    if a.is_numeric and b.is_numeric:
        ia, ib = _NUMERIC_ORDER.index(a), _NUMERIC_ORDER.index(b)
        winner = _NUMERIC_ORDER[max(ia, ib)]
        # int64 + float32 -> float64 like Spark (avoid precision cliff)
        if winner.is_floating and (a is LongType or b is LongType):
            return DoubleType
        return winner
    raise TypeError(f"cannot promote {a} and {b}")


_ARROW_NAME = {
    "boolean": "bool", "byte": "int8", "short": "int16", "int": "int32",
    "long": "int64", "float": "float32", "double": "float64",
    "date": "date32", "timestamp": "timestamp[us, tz=UTC]", "string": "string",
}


def to_arrow(dt: DataType):
    import pyarrow as pa
    return {
        "boolean": pa.bool_(), "byte": pa.int8(), "short": pa.int16(),
        "int": pa.int32(), "long": pa.int64(), "float": pa.float32(),
        "double": pa.float64(), "date": pa.date32(),
        "timestamp": pa.timestamp("us", tz="UTC"), "string": pa.string(),
    }[dt.name]


def from_arrow(at) -> DataType:
    import pyarrow as pa
    if pa.types.is_boolean(at):
        return BooleanType
    if pa.types.is_int8(at):
        return ByteType
    if pa.types.is_int16(at):
        return ShortType
    if pa.types.is_int32(at):
        return IntegerType
    if pa.types.is_int64(at):
        return LongType
    if pa.types.is_float32(at):
        return FloatType
    if pa.types.is_float64(at):
        return DoubleType
    if pa.types.is_date32(at):
        return DateType
    if pa.types.is_timestamp(at):
        return TimestampType
    if pa.types.is_string(at) or pa.types.is_large_string(at):
        return StringType
    if pa.types.is_dictionary(at):
        return from_arrow(at.value_type)
    if pa.types.is_decimal(at):
        # decimals are not in the supported-type gate; scans cast to double
        return DoubleType
    raise TypeError(f"unsupported arrow type {at}")


@dataclasses.dataclass(frozen=True)
class StructField:
    name: str
    dtype: DataType
    nullable: bool = True


@dataclasses.dataclass(frozen=True)
class Schema:
    fields: tuple[StructField, ...]

    def __init__(self, fields):
        object.__setattr__(self, "fields", tuple(fields))

    def __len__(self):
        return len(self.fields)

    def __iter__(self):
        return iter(self.fields)

    def __getitem__(self, i):
        return self.fields[i]

    @property
    def names(self):
        return [f.name for f in self.fields]

    def index_of(self, name: str) -> int:
        for i, f in enumerate(self.fields):
            if f.name == name:
                return i
        raise KeyError(name)

    def field(self, name: str) -> StructField:
        return self.fields[self.index_of(name)]

    def __repr__(self):
        inner = ", ".join(f"{f.name}:{f.dtype.name}" for f in self.fields)
        return f"Schema({inner})"


def schema_of(**kwargs: DataType) -> Schema:
    return Schema([StructField(k, v) for k, v in kwargs.items()])
