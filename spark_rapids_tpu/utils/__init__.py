"""Shared utilities."""
from __future__ import annotations


def pow2_bucket(n: int, minimum: int = 1) -> int:
    """Round a dynamic count up to a power-of-two bucket (>= minimum).

    The framework's standard answer to data-dependent integers that become
    static kernel shapes or kernel-cache keys: bucketing bounds the set of
    compiled programs (log2 many) instead of one per distinct value.
    n <= 0 stays 0."""
    if n <= 0:
        return 0
    b = max(1, minimum)
    while b < n:
        b <<= 1
    return b
