"""Shared persistent-XLA-compilation-cache setup.

ONE idempotent helper owns the `jax_compilation_cache_dir` /
`jax_persistent_cache_*` config dance so the knobs cannot drift between
call sites: `engine.TpuSession` (platform-gated), the serving tier's
QueryScheduler (a restarted server replays kernels from disk), the
executor worker bootstrap (shuffle/worker.py), and bench.py's children
(force=True — the bench explicitly wants warm compiles on every backend
it measures, including its CPU oracle).

Platform gate rationale (force=False): compiles on a TPU backend cost
tens of seconds and replay byte-identically, but XLA:CPU AOT replay
warns about machine-feature mismatches (SIGILL risk) and the CPU test
environment already fights compile-cache memory pressure — so on a
CPU-only process the cache stays off unless the caller forces it.

Re-pointing: the active directory is re-pointable within a process — a
server picking up a conf change (or a test pointing at a tmpdir) calls
enable_compilation_cache with the new path and jax follows.  The old
module-global latch made the first path sticky forever, which silently
kept a stale directory; `active_cache_dir()` reports what is actually in
effect and `reset_for_tests()` restores the pristine state.
"""
from __future__ import annotations

import threading
from typing import Optional

# the path this process's jax config currently points at (None = cache
# never enabled by this helper); the lock serializes concurrent enables
# from scheduler construction vs. worker first-touch (TPU009)
_STATE = {"path": None}
_STATE_LOCK = threading.Lock()


def enable_compilation_cache(path: str, force: bool = False) -> bool:
    """Point jax's persistent compilation cache at `path` (idempotent
    per path, best-effort; returns True when THIS call enabled or
    re-pointed the cache).  Keyed by HLO hash, shared across processes:
    a second session replays every kernel this one compiled."""
    if not path:
        return False
    if _STATE["path"] == path:
        return False  # already in effect — idempotent fast path
    try:
        import os

        import jax
        if not force:
            platforms = jax.config.jax_platforms \
                or os.environ.get("JAX_PLATFORMS", "")
            if not platforms or platforms == "cpu":
                # NOT latched: a later force=True call (bench child) may
                # still enable the cache in this process
                return False
        with _STATE_LOCK:
            if _STATE["path"] == path:
                return False  # a concurrent enabler won the race
            jax.config.update("jax_compilation_cache_dir", path)
            jax.config.update(
                "jax_persistent_cache_min_entry_size_bytes", 0)
            jax.config.update(
                "jax_persistent_cache_min_compile_time_secs", 1)
            _STATE["path"] = path
        return True
    except Exception:
        return False  # an optimization, never a dependency


def active_cache_dir() -> Optional[str]:
    """The directory this helper last pointed jax at, or None."""
    return _STATE["path"]


def reset_for_tests() -> None:
    """Test-only: forget the active path and detach jax from it, so the
    next enable_compilation_cache() call can re-point cleanly from a
    known state."""
    _STATE["path"] = None
    try:
        import jax
        jax.config.update("jax_compilation_cache_dir", None)
    except Exception:  # pragma: no cover — jax may be torn down
        pass  # tpulint: disable=TPU006 best-effort detach in test teardown; the latch above is already cleared
