"""Shared persistent-XLA-compilation-cache setup.

ONE idempotent helper owns the `jax_compilation_cache_dir` /
`jax_persistent_cache_*` config dance so the knobs cannot drift between
call sites: `engine.TpuSession` (platform-gated), the executor worker
bootstrap (shuffle/worker.py), and bench.py's children (force=True —
the bench explicitly wants warm compiles on every backend it measures,
including its CPU oracle).

Platform gate rationale (force=False): compiles on a TPU backend cost
tens of seconds and replay byte-identically, but XLA:CPU AOT replay
warns about machine-feature mismatches (SIGILL risk) and the CPU test
environment already fights compile-cache memory pressure — so on a
CPU-only process the cache stays off unless the caller forces it.
"""
from __future__ import annotations

_CACHE_SET = [False]


def enable_compilation_cache(path: str, force: bool = False) -> bool:
    """Point jax's persistent compilation cache at `path` (idempotent,
    best-effort; returns True when the cache was enabled by THIS call).
    Keyed by HLO hash, shared across processes: a second session replays
    every kernel this one compiled."""
    if _CACHE_SET[0] or not path:
        return False
    try:
        import os

        import jax
        if not force:
            platforms = jax.config.jax_platforms \
                or os.environ.get("JAX_PLATFORMS", "")
            if not platforms or platforms == "cpu":
                # NOT latched: a later force=True call (bench child) may
                # still enable the cache in this process
                return False
        jax.config.update("jax_compilation_cache_dir", path)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1)
        _CACHE_SET[0] = True
        return True
    except Exception:
        return False  # an optimization, never a dependency
