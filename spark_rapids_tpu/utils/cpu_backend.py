"""Force the JAX CPU backend before any backend initializes.

The container's sitecustomize registers the axon TPU PJRT plugin in every
interpreter and the ambient env pins JAX_PLATFORMS=axon; there is ONE
exclusive TPU chip behind a machine-wide lease, and merely enumerating
backends can block on that lease indefinitely (round-1 postmortem: the
driver's bench/dryrun runs died rc=124 exactly this way).  Anything that
wants CPU execution — the test suite, the multichip dryrun, bench fallback —
must (a) drop the axon/tpu backend factories and (b) update the latched
jax config, BEFORE first backend use.  This is the one shared copy of that
dance; jax._src.xla_bridge is a private API, so when a jax upgrade moves it,
fix it here only.
"""
from __future__ import annotations

import os
from typing import Optional


def force_cpu_backend(n_devices: Optional[int] = None) -> None:
    """Pin this process to the CPU backend; optionally provision `n_devices`
    virtual devices (only effective before the CPU backend initializes)."""
    if n_devices is not None:
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + f" --xla_force_host_platform_device_count={n_devices}"
            ).strip()
    os.environ["JAX_PLATFORMS"] = "cpu"

    import jax._src.xla_bridge as xb
    for plat in ("axon", "tpu"):
        xb._backend_factories.pop(plat, None)
    # keep "tpu" a KNOWN platform name (identity alias, no factory): pallas
    # registers tpu lowering rules at import time and refuses unknown
    # platforms; an alias satisfies the check without any lease-touching
    # backend factory
    xb._platform_aliases.setdefault("tpu", "tpu")

    import jax
    jax.config.update("jax_platforms", "cpu")
