"""Deterministic fault injection for retry-path testing.

TPU-native analogue of RmmSpark's OOM injection points (the reference
forces `RetryOOM`/`SplitAndRetryOOM` at the Nth allocation from test
hooks, spark-rapids-jni RmmSpark.forceRetryOOM/forceSplitAndRetryOOM) plus
a network-side twin for the shuffle wire.  Everything is conf-driven so
tier-1 tests exercise every retry path on CPU with zero real pressure:

  spark.rapids.tpu.test.injectOom       fail the Nth `reserve()` call
  spark.rapids.tpu.test.injectNetFault  fail the Nth client socket op
  spark.rapids.tpu.test.injectSeed      seed for the probabilistic mode

Spec grammar (comma-separated items, 1-based ordinals over the process-wide
op counter of that category):

  "3"          fail op #3 once (RetryOOM / ConnectionError)
  "3x2"        fail ops #3 and #4 (a window: exhausts same-size retries)
  "split@5"    fail op #5 with SplitAndRetryOOM (OOM category only)
  "p=0.05"     fail each op with probability 0.05, seeded by injectSeed

The injector is process-global, thread-safe, and counts every observed op
per site label, so a test can run fault-free once to DISCOVER the reserve
sites of a query and then replay with each ordinal forced to fail.
"""
from __future__ import annotations

import random
import threading
from typing import Dict, List, Optional, Tuple


class InjectedNetFault(ConnectionError):
    """A network fault forced by the injector (distinguishable from real
    socket errors in tests)."""


class _Plan:
    """Parsed failure plan for one fault category."""

    def __init__(self, spec: str = "", seed: int = 0):
        self.spec = spec
        self.ordinals: Dict[int, str] = {}  # ordinal -> kind
        self.prob = 0.0
        self.rng = random.Random(seed)
        for raw in (spec or "").split(","):
            item = raw.strip()
            if not item:
                continue
            if item.startswith("p="):
                self.prob = float(item[2:])
                continue
            kind = "retry"
            if "@" in item:
                kind, item = item.split("@", 1)
            if "x" in item:
                start_s, rep_s = item.split("x", 1)
                start, rep = int(start_s), int(rep_s)
            else:
                start, rep = int(item), 1
            for o in range(start, start + rep):
                self.ordinals[o] = kind

    def check(self, n: int) -> Optional[str]:
        """Kind of fault to force at op #n, or None."""
        kind = self.ordinals.get(n)
        if kind is not None:
            return kind
        if self.prob > 0 and self.rng.random() < self.prob:
            return "retry"
        return None


class FaultInjector:
    """Process-global deterministic fault source (thread-safe)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._configured: Optional[Tuple[str, str, int]] = None
        self.reset()

    def reset(self) -> None:
        with self._lock:
            self._oom = _Plan()
            self._net = _Plan()
            self._oom_count = 0
            self._net_count = 0
            self._configured = None
            self.site_counts: Dict[str, int] = {}
            self.injected_log: List[Tuple[str, int, str]] = []

    def configure(self, oom_spec: str = "", net_spec: str = "",
                  seed: int = 0) -> None:
        """(Re)arm the injector.  Counters reset only when the spec actually
        changes, so every runtime/transport bring-up in one query can call
        this without restarting the op count mid-flight."""
        key = (oom_spec or "", net_spec or "", int(seed))
        with self._lock:
            if self._configured == key:
                return
            self._configured = key
            self._oom = _Plan(key[0], seed=key[2])
            self._net = _Plan(key[1], seed=key[2] + 1)
            self._oom_count = 0
            self._net_count = 0
            self.site_counts = {}
            self.injected_log = []

    def configure_from_conf(self, conf) -> None:
        from .. import config as C
        self.configure(str(conf.get(C.TEST_INJECT_OOM) or ""),
                       str(conf.get(C.TEST_INJECT_NET) or ""),
                       int(conf.get(C.TEST_INJECT_SEED) or 0))

    # ---- stats (test observability) ----------------------------------------

    @property
    def oom_ops(self) -> int:
        with self._lock:
            return self._oom_count

    @property
    def net_ops(self) -> int:
        with self._lock:
            return self._net_count

    # ---- hooks -------------------------------------------------------------

    def on_reserve(self, site: str, nbytes: int) -> None:
        """Called at the top of every `TpuRuntime.reserve()`.  Raises the
        planned OOM kind for this ordinal.

        Counting stays on even when no spec is armed: tests DISCOVER a
        query's reserve sites from a fault-free baseline run before
        replaying with each ordinal forced.  The cost is one uncontended
        lock + two dict ops per reserve(), which guards whole-batch
        device work — never a per-row path."""
        with self._lock:
            self._oom_count += 1
            n = self._oom_count
            self.site_counts[site] = self.site_counts.get(site, 0) + 1
            kind = self._oom.check(n)
            if kind is not None:
                self.injected_log.append(("oom", n, site))
        if kind is not None:
            from ..mem.retry import RetryOOM, SplitAndRetryOOM
            cls = SplitAndRetryOOM if kind == "split" else RetryOOM
            raise cls(f"[fault-injection] forced OOM at reserve #{n} "
                      f"(site={site}, {nbytes}B)", nbytes=nbytes,
                      injected=True)

    def on_net_op(self, site: str) -> None:
        """Called before every client-side shuffle socket operation."""
        with self._lock:
            self._net_count += 1
            n = self._net_count
            key = f"net:{site}"
            self.site_counts[key] = self.site_counts.get(key, 0) + 1
            kind = self._net.check(n)
            if kind is not None:
                self.injected_log.append(("net", n, site))
        if kind is not None:
            raise InjectedNetFault(
                f"[fault-injection] forced net fault at op #{n} "
                f"(site={site})")


INJECTOR = FaultInjector()
