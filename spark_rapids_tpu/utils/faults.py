"""Deterministic fault injection for retry-path testing.

TPU-native analogue of RmmSpark's OOM injection points (the reference
forces `RetryOOM`/`SplitAndRetryOOM` at the Nth allocation from test
hooks, spark-rapids-jni RmmSpark.forceRetryOOM/forceSplitAndRetryOOM) plus
a network-side twin for the shuffle wire.  Everything is conf-driven so
tier-1 tests exercise every retry path on CPU with zero real pressure:

  spark.rapids.tpu.test.injectOom         fail the Nth `reserve()` call
  spark.rapids.tpu.test.injectNetFault    fail the Nth client socket op
  spark.rapids.tpu.test.injectCorruption  flip a bit in the Nth
                                          transferred chunk / spilled leaf
  spark.rapids.tpu.test.injectCrash       os._exit the worker process at
                                          the Nth crash point (chaos tier)
  spark.rapids.tpu.test.injectSeed        seed for the probabilistic mode

Spec grammar (comma-separated items, 1-based ordinals over the process-wide
op counter of that category):

  "3"          fail op #3 once (RetryOOM / ConnectionError)
  "3x2"        fail ops #3 and #4 (a window: exhausts same-size retries)
  "split@5"    fail op #5 with SplitAndRetryOOM (OOM category only)
  "p=0.05"     fail each op with probability 0.05, seeded by injectSeed

The corruption category reads the @-prefix as a SITE instead of a kind:
"wire@3" flips a bit in the 3rd corruptible op AT SITE `wire` (per-site
ordinals, because the interesting question is always "the Nth chunk of
THIS path"); a bare "3" counts across all sites.  Sites instrumented:
wire (socket send staging), shm (shared-memory leaf fill), loopback
(loopback bounce chunk), spill (device->host spill leaves), disk
(host->disk flat image), writer (the shuffle server's served leaves —
corrupting these after their checksum is recorded models writer-side rot
that refetching can never fix).

The injector is process-global, thread-safe, and counts every observed op
per site label, so a test can run fault-free once to DISCOVER the reserve
sites of a query and then replay with each ordinal forced to fail.
"""
from __future__ import annotations

import random
import threading
from collections import deque
from typing import Dict, List, Optional, Tuple


class InjectedNetFault(ConnectionError):
    """A network fault forced by the injector (distinguishable from real
    socket errors in tests)."""


class _Plan:
    """Parsed failure plan for one fault category."""

    def __init__(self, spec: str = "", seed: int = 0):
        self.spec = spec
        self.ordinals: Dict[int, str] = {}  # ordinal -> kind
        self.prob = 0.0
        self.rng = random.Random(seed)
        for raw in (spec or "").split(","):
            item = raw.strip()
            if not item:
                continue
            if item.startswith("p="):
                self.prob = float(item[2:])
                continue
            kind = "retry"
            if "@" in item:
                kind, item = item.split("@", 1)
            if "x" in item:
                start_s, rep_s = item.split("x", 1)
                start, rep = int(start_s), int(rep_s)
            else:
                start, rep = int(item), 1
            for o in range(start, start + rep):
                self.ordinals[o] = kind

    def check(self, n: int) -> Optional[str]:
        """Kind of fault to force at op #n, or None."""
        kind = self.ordinals.get(n)
        if kind is not None:
            return kind
        if self.prob > 0 and self.rng.random() < self.prob:
            return "retry"
        return None


class _CorruptPlan:
    """Parsed site-addressed fault plan, shared by the corruption, net
    and crash categories: @-prefixes are SITE names with per-site
    ordinals ('wire@3' = 3rd corruptible op at site wire,
    'rpc:run_reduce@1' = 1st run_reduce control rpc, 'map@2' = this
    process's 2nd map task); bare ordinals count across every site;
    'p=' fires probabilistically; an optional 'scope/' prefix (delay
    grammar) restricts the item to the process whose injector scope
    matches ('exec-1/map@1' — worker executor ids)."""

    def __init__(self, spec: str = "", seed: int = 0):
        self.spec = spec
        self.global_ordinals: Dict[int, bool] = {}
        self.site_ordinals: Dict[str, Dict[int, bool]] = {}
        # scoped items: (scope, site or None, first ordinal, repeat)
        self.scoped: List[Tuple[str, Optional[str], int, int]] = []
        self.prob = 0.0
        self.rng = random.Random(seed)
        for raw in (spec or "").split(","):
            item = raw.strip()
            if not item:
                continue
            if item.startswith("p="):
                self.prob = float(item[2:])
                continue
            scope = None
            if "/" in item:
                scope, item = item.split("/", 1)
            site = None
            if "@" in item:
                site, item = item.split("@", 1)
            if "x" in item:
                start_s, rep_s = item.split("x", 1)
                start, rep = int(start_s), int(rep_s)
            else:
                start, rep = int(item), 1
            if scope is not None:
                self.scoped.append((scope, site, start, rep))
                continue
            dest = (self.global_ordinals if site is None
                    else self.site_ordinals.setdefault(site, {}))
            for o in range(start, start + rep):
                dest[o] = True

    def check(self, n_global: int, site: str, n_site: int,
              scope: Optional[str] = None) -> bool:
        if self.global_ordinals.get(n_global):
            return True
        if self.site_ordinals.get(site, {}).get(n_site):
            return True
        for sc, st, start, rep in self.scoped:
            if sc != scope:
                continue
            if st is not None and st != site:
                continue
            n = n_global if st is None else n_site
            if start <= n < start + rep:
                return True
        return self.prob > 0 and self.rng.random() < self.prob


#: hard cap on the injected-events log: probabilistic specs on long runs
#: would otherwise append one tuple per injected fault forever (a real
#: leak in exactly the soak-test regime that uses p= specs); overflow is
#: counted in `injected_log_dropped` instead of silently truncated
INJECTED_LOG_CAP = 4096


class _DelayPlan:
    """Parsed slowdown plan: comma items 'site:ms' or 'scope/site:ms'.
    A scoped item applies only in the process whose injector scope (set
    via `set_scope`, e.g. the worker's executor id) matches — so a
    cluster-wide conf can slow exactly ONE worker's reduce tasks
    ('exec-1/reduce:1500', the straggler-flagging test)."""

    def __init__(self, spec: str = ""):
        self.spec = spec
        self.items: List[Tuple[Optional[str], str, float]] = []
        for raw in (spec or "").split(","):
            item = raw.strip()
            if not item:
                continue
            scope = None
            if "/" in item:
                scope, item = item.split("/", 1)
            site, ms = item.rsplit(":", 1)
            self.items.append((scope, site, float(ms) / 1e3))

    def seconds_for(self, site: str, scope: Optional[str]) -> float:
        return sum(s for sc, st, s in self.items
                   if st == site and (sc is None or sc == scope))


class FaultInjector:
    """Process-global deterministic fault source (thread-safe)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._configured: Optional[Tuple[str, str, str, int, str]] = None
        self.scope: Optional[str] = None
        self.reset()

    def reset(self) -> None:
        with self._lock:
            self._oom = _Plan()
            self._net = _CorruptPlan()
            self._corrupt = _CorruptPlan()
            self._delay = _DelayPlan()
            self._crash = _CorruptPlan()
            self._oom_count = 0
            self._net_count = 0
            self._corrupt_count = 0
            self._crash_count = 0
            self._configured = None
            self.site_counts: Dict[str, int] = {}
            self.injected_log: "deque" = deque(maxlen=INJECTED_LOG_CAP)
            self.injected_log_dropped = 0

    def set_scope(self, scope: Optional[str]) -> None:
        """Name this process for scoped delay specs (worker executor id).
        Deliberately survives reset(): identity is not a fault plan."""
        self.scope = scope

    def _log_injected_locked(self, rec: Tuple[str, int, str]) -> None:
        # caller holds self._lock; the deque evicts the OLDEST entry at
        # cap (recent faults matter most for post-mortems) and the drop
        # counter keeps the loss visible
        if len(self.injected_log) >= INJECTED_LOG_CAP:
            self.injected_log_dropped += 1
        self.injected_log.append(rec)

    def configure(self, oom_spec: str = "", net_spec: str = "",
                  seed: int = 0, corrupt_spec: str = "",
                  delay_spec: str = "", crash_spec: str = "") -> None:
        """(Re)arm the injector.  Counters reset only when the spec actually
        changes, so every runtime/transport bring-up in one query can call
        this without restarting the op count mid-flight."""
        key = (oom_spec or "", net_spec or "", corrupt_spec or "",
               int(seed), delay_spec or "", crash_spec or "")
        with self._lock:
            if self._configured == key:
                return
            # parse every plan BEFORE committing anything: a malformed
            # spec must raise with the injector fully in its previous
            # state, not half-replaced with `_configured` already stamped
            # (the next identical configure() would early-exit and leave
            # it armed wrong forever)
            oom = _Plan(key[0], seed=key[3])
            # net faults ride the corruption-plan grammar: bare/windowed
            # ordinals over the global socket-op counter plus @-prefixed
            # per-SITE ordinals ('rpc:run_reduce@1'), so the cluster-rpc
            # fault sweep can aim at one rpc method deterministically.
            # Legacy compat: the pre-site grammar spelled the (only) net
            # kind explicitly ('retry@2' = fail op #2) — strip it so an
            # old spec keeps firing instead of parsing as an unknown
            # site named 'retry' that never matches
            net_spec = ",".join(
                it.strip()[len("retry@"):]
                if it.strip().startswith("retry@") else it.strip()
                for it in key[1].split(","))
            net = _CorruptPlan(net_spec, seed=key[3] + 1)
            corrupt = _CorruptPlan(key[2], seed=key[3] + 2)
            delay = _DelayPlan(key[4])
            crash = _CorruptPlan(key[5], seed=key[3] + 3)
            self._configured = key
            self._oom = oom
            self._net = net
            self._corrupt = corrupt
            self._delay = delay
            self._crash = crash
            self._oom_count = 0
            self._net_count = 0
            self._corrupt_count = 0
            self._crash_count = 0
            self.site_counts = {}
            self.injected_log = deque(maxlen=INJECTED_LOG_CAP)
            self.injected_log_dropped = 0

    def configure_from_conf(self, conf) -> None:
        from .. import config as C
        self.configure(str(conf.get(C.TEST_INJECT_OOM) or ""),
                       str(conf.get(C.TEST_INJECT_NET) or ""),
                       int(conf.get(C.TEST_INJECT_SEED) or 0),
                       str(conf.get(C.TEST_INJECT_CORRUPTION) or ""),
                       str(conf.get(C.TEST_INJECT_DELAY) or ""),
                       str(conf.get(C.TEST_INJECT_CRASH) or ""))

    # ---- stats (test observability) ----------------------------------------

    @property
    def oom_ops(self) -> int:
        with self._lock:
            return self._oom_count

    @property
    def net_ops(self) -> int:
        with self._lock:
            return self._net_count

    # ---- hooks -------------------------------------------------------------

    def on_reserve(self, site: str, nbytes: int) -> None:
        """Called at the top of every `TpuRuntime.reserve()`.  Raises the
        planned OOM kind for this ordinal.

        Counting stays on even when no spec is armed: tests DISCOVER a
        query's reserve sites from a fault-free baseline run before
        replaying with each ordinal forced.  The cost is one uncontended
        lock + two dict ops per reserve(), which guards whole-batch
        device work — never a per-row path."""
        with self._lock:
            self._oom_count += 1
            n = self._oom_count
            self.site_counts[site] = self.site_counts.get(site, 0) + 1
            kind = self._oom.check(n)
            if kind is not None:
                self._log_injected_locked(("oom", n, site))
        if kind is not None:
            from ..mem.retry import RetryOOM, SplitAndRetryOOM
            cls = SplitAndRetryOOM if kind == "split" else RetryOOM
            raise cls(f"[fault-injection] forced OOM at reserve #{n} "
                      f"(site={site}, {nbytes}B)", nbytes=nbytes,
                      injected=True)

    def on_net_op(self, site: str) -> None:
        """Called before every client-side shuffle socket operation.
        Matches both global ordinals and per-site ordinals ('site@N' in
        the spec fails the Nth op at THAT site only)."""
        with self._lock:
            self._net_count += 1
            n = self._net_count
            key = f"net:{site}"
            n_site = self.site_counts.get(key, 0) + 1
            self.site_counts[key] = n_site
            hit = self._net.check(n, site, n_site, self.scope)
            if hit:
                self._log_injected_locked(("net", n, site))
        if hit:
            raise InjectedNetFault(
                f"[fault-injection] forced net fault at op #{n} "
                f"(site={site})")

    @property
    def crash_ops(self) -> int:
        with self._lock:
            return self._crash_count

    def on_crash(self, site: str) -> None:
        """Called at worker crash points (task entry, after any injected
        delay — 'mid-task' from the driver's perspective: the task rpc is
        in flight and partial side effects may exist).  When the armed
        plan selects this op the PROCESS DIES via os._exit — no cleanup,
        no exception propagation: the honest analogue of a worker box
        losing power, which is exactly what the chaos tier recovers
        from."""
        with self._lock:
            self._crash_count += 1
            n = self._crash_count
            key = f"crash:{site}"
            n_site = self.site_counts.get(key, 0) + 1
            self.site_counts[key] = n_site
            hit = self._crash.check(n, site, n_site, self.scope)
            if hit:
                self._log_injected_locked(("crash", n, site))
        if hit:
            import logging
            import os
            import sys
            logging.getLogger("spark_rapids_tpu.faults").warning(
                "[fault-injection] forced crash at op #%d (site=%s, "
                "scope=%s): os._exit(17)", n, site, self.scope)
            try:
                sys.stderr.flush()
            except Exception:  # noqa: BLE001 — dying anyway
                pass  # tpulint: disable=TPU006 the process exits on the next line; a flush failure changes nothing
            os._exit(17)

    def on_delay(self, site: str) -> float:
        """Called at conf-declared slowdown points (worker task entry,
        sites 'map'/'reduce').  Sleeps the summed matching delay and
        returns the seconds slept (0.0 when nothing matched) — the
        deterministic straggler for timeline/watchdog tests."""
        with self._lock:
            seconds = self._delay.seconds_for(site, self.scope)
            if seconds > 0:
                key = f"delay:{site}"
                self.site_counts[key] = self.site_counts.get(key, 0) + 1
                self._log_injected_locked(("delay", int(seconds * 1e3), site))
        if seconds > 0:
            import time
            time.sleep(seconds)
        return seconds

    @property
    def corrupt_ops(self) -> int:
        with self._lock:
            return self._corrupt_count

    def on_corruptible(self, site: str, view=None) -> bool:
        """Called wherever columnar bytes sit in a host staging form (a
        bounce-buffer slice, a spilled leaf, a disk image).  When the
        armed plan selects this op, ONE bit of the middle byte is flipped
        in place — the minimal corruption the checksum layer must catch.

        `view` must be a writable 1-D uint8 ndarray/memoryview, or None
        when the caller's bytes are read-only (host leaves pulled from the
        device are immutable numpy views): then the flip is the CALLER's
        job via `flip_bit` on a True return."""
        with self._lock:
            self._corrupt_count += 1
            n = self._corrupt_count
            key = f"corrupt:{site}"
            n_site = self.site_counts.get(key, 0) + 1
            self.site_counts[key] = n_site
            hit = self._corrupt.check(n, site, n_site, self.scope)
            if hit:
                self._log_injected_locked(("corrupt", n, site))
        if hit and view is not None and len(view):
            view[len(view) // 2] ^= 0x01
        return hit


def flip_bit(arr):
    """Copy of `arr` with one bit of its middle byte flipped — the
    injected corruption for sites whose storage is a read-only numpy view
    (the caller swaps the copy in where the original lived)."""
    import numpy as np
    flat = np.array(arr, copy=True)
    u8 = flat.reshape(-1).view(np.uint8)
    if len(u8):
        u8[len(u8) // 2] ^= 0x01
    return flat


INJECTOR = FaultInjector()
