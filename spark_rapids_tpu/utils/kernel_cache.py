"""Process-wide compiled-kernel cache.

jax.jit's own cache is keyed by function identity, but the execs build fresh
closures every plan/execute, so without this layer each collect() re-traces
and re-compiles every kernel (the reference has no analogue — cuDF kernels
are precompiled; for us compilation IS the kernel-build step, so caching it
is what makes repeated/streaming queries cheap).

Keys are structural: (kernel kind, expression-tree signature, schema
signature).  Shape/dtype differences of the incoming batches are handled by
jit itself underneath one cache entry.
"""
from __future__ import annotations

from typing import Callable, Dict, Tuple

import jax


_CACHE: Dict[tuple, Callable] = {}


def expr_key(e) -> tuple:
    """Structural signature of an expression tree: class + every non-child
    constructor attribute + children, recursively.  Safer than repr (an
    expression whose repr omits a parameter would under-key the cache)."""
    from ..ops.expressions import Expression
    attrs = []
    d = getattr(e, "__dict__", None)
    items = sorted(d.items()) if d else \
        [(s, getattr(e, s)) for s in getattr(e, "__slots__", ())]
    for k, v in items:
        if k == "children" or isinstance(v, Expression):
            continue
        if isinstance(v, (list, tuple)) and any(
                isinstance(x, Expression) for x in v):
            continue
        attrs.append((k, _val_key(v)))
    kids = tuple(expr_key(c) for c in e.children)
    return (type(e).__name__, tuple(attrs), kids)


def _val_key(v):
    from ..types import DataType
    if isinstance(v, DataType):
        return v.name
    if isinstance(v, (list, tuple)):
        return tuple(_val_key(x) for x in v)
    if isinstance(v, (set, frozenset)):
        return tuple(sorted(map(repr, v)))
    if isinstance(v, dict):
        return tuple(sorted((k, _val_key(x)) for k, x in v.items()))
    return repr(v)


def schema_key(schema) -> tuple:
    return tuple((f.name, f.dtype.name) for f in schema)


def cached_kernel(key: tuple, builder: Callable[[], Callable],
                  **jit_kw) -> Callable:
    """Return the jitted kernel for `key`, building it on first use."""
    fn = _CACHE.get(key)
    if fn is None:
        fn = jax.jit(builder(), **jit_kw)
        _CACHE[key] = fn
    return fn


def cache_info() -> Tuple[int, list]:
    return len(_CACHE), [k[0] for k in _CACHE]


def clear():
    _CACHE.clear()
