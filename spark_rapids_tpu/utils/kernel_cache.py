"""Process-wide compiled-kernel cache.

jax.jit's own cache is keyed by function identity, but the execs build fresh
closures every plan/execute, so without this layer each collect() re-traces
and re-compiles every kernel (the reference has no analogue — cuDF kernels
are precompiled; for us compilation IS the kernel-build step, so caching it
is what makes repeated/streaming queries cheap).

Keys are structural: (kernel kind, expression-tree signature, schema
signature).  Shape/dtype differences of the incoming batches are handled by
jit itself underneath one cache entry.
"""
from __future__ import annotations

import contextlib
import threading
import time
from collections import OrderedDict
from typing import Callable, Dict, Tuple

import jax


_CACHE: Dict[tuple, Callable] = {}
# guards the LRU bookkeeping below and _CACHE build races: the serving
# tier dispatches kernels from several query threads at once, and
# OrderedDict.move_to_end is not safe under concurrent mutation
_CACHE_LOCK = threading.Lock()

# whole-stage AOT executables, keyed (stage key, input signature): the
# fused-stage path compiles per exact shape bucket so compile COUNT and
# trace-vs-compile time are first-class observables (exec/whole_stage.py).
# Bounded LRU: compiled executables are NOT dropped by jax.clear_caches(),
# so an unbounded dict would defeat the conftest's periodic cache clears
# that keep XLA:CPU's live-executable count under its segfault threshold.
_STAGE_EXECUTABLES: "OrderedDict[tuple, Callable]" = OrderedDict()
_STAGE_EXECUTABLES_MAX = 512

# XLA cost analysis of each compiled whole-stage program, keyed like
# _STAGE_EXECUTABLES (pruned with it): {"flops": float, "bytes": float,
# "source": "hlo"} — the roofline ledger's per-stage cost declaration
# (metrics/roofline.py).  Empty dict when the AOT path (and therefore
# Compiled.cost_analysis) was unavailable for the program.
_STAGE_COSTS: Dict[tuple, dict] = {}

# process-wide counters bench.py's fusion/serve stages read (stats()):
# builds = distinct jitted programs constructed through cached_kernel,
# stage_compiles = AOT whole-stage programs compiled,
# dispatches = per-batch device program invocations through this layer,
# kernel_hits/stage_hits = cache hits (a parameterized plan-cache hit
# shows up here as stage/kernel hits instead of fresh builds)
_COUNTERS = {"builds": 0, "stage_compiles": 0, "dispatches": 0,
             "kernel_hits": 0, "stage_hits": 0, "donated_buffers": 0}


def record_dispatch(n: int = 1) -> None:
    # dict[k] += n is a read-modify-write: under concurrent serving the
    # scheduler's worker threads dispatch simultaneously and an unlocked
    # fold silently loses counts (bench reads these as accept gates)
    with _CACHE_LOCK:
        _COUNTERS["dispatches"] += n


def record_donated(n_buffers: int) -> None:
    """Count input buffers donated to a compiled program (the HBM copies
    a warm dispatch did not pay); bench.py reads this around warm runs
    (donated_copies_warm_run) like it reads dispatches."""
    with _CACHE_LOCK:
        _COUNTERS["donated_buffers"] += n_buffers


def stats() -> Dict[str, int]:
    with _CACHE_LOCK:
        return dict(_COUNTERS, cached_kernels=len(_CACHE),
                    stage_executables=len(_STAGE_EXECUTABLES))


def input_signature(args) -> tuple:
    """Static (shape, dtype) signature of a pytree of arguments — the
    shape-bucket key of a whole-stage executable."""
    leaves = jax.tree_util.tree_flatten(args)[0]
    return tuple((tuple(getattr(x, "shape", ())),
                  str(getattr(x, "dtype", type(x).__name__)))
                 for x in leaves)


def stage_executable(key: tuple, builder: Callable[[], Callable],
                     args: tuple, metrics=None, name: str = "stage",
                     donate_argnums: tuple = ()):
    """AOT-compiled whole-stage program for (key, signature-of-args).

    On a cache miss the program is traced, lowered and compiled EXPLICITLY
    (jax AOT API) so the build is observable: numStageCompiles /
    stageCompileTime on `metrics` and a `compile` journal event with the
    trace-vs-compile time split.  Falls back to a plain jitted function if
    the AOT API is unavailable.  Returns a callable taking *args.

    `donate_argnums` lowers the program with input/output buffer aliasing
    on those argument positions (mem/donation.py owns the safety proof —
    a donated executable ALWAYS deletes those inputs, so donated and
    non-donated dispatches must resolve to distinct cache entries: the
    argnums are part of the key)."""
    if donate_argnums:
        key = key + ("donate", tuple(donate_argnums))
    k = (key, input_signature(args))
    with _CACHE_LOCK:
        fn = _STAGE_EXECUTABLES.get(k)
        if fn is not None:
            _STAGE_EXECUTABLES.move_to_end(k)
            _COUNTERS["stage_hits"] += 1
            return fn
    aot = True
    from ..metrics import names as MN
    from ..metrics.journal import journal_event
    timer = (metrics.timer(MN.STAGE_COMPILE_TIME) if metrics is not None
             else None)
    jfn = jax.jit(builder(), donate_argnums=donate_argnums)
    t0 = time.perf_counter()
    if timer is not None:
        timer.__enter__()
    try:
        try:
            traced = jfn.trace(*args)
            t_traced = time.perf_counter()
            lowered = traced.lower()
        except AttributeError:  # older jax: lower() traces internally
            lowered = jfn.lower(*args)
            t_traced = time.perf_counter()
        t_lowered = time.perf_counter()
        fn = lowered.compile()
        t_compiled = time.perf_counter()
    except Exception:
        # AOT path unavailable for this program/backend: the jitted
        # function is the executable (compile happens on first call,
        # folded into the timer by the caller's first dispatch)
        fn = jfn
        aot = False
        t_traced = t_lowered = t_compiled = time.perf_counter()
    finally:
        if timer is not None:
            timer.__exit__(None, None, None)
    cost = _extract_cost_analysis(fn) if aot else {}
    with _CACHE_LOCK:
        _COUNTERS["stage_compiles"] += 1
    if metrics is not None:
        metrics.add(MN.NUM_STAGE_COMPILES, 1)
    journal_event("compile", name,
                  trace_s=round(t_lowered - t0, 6),
                  compile_s=round(t_compiled - t_lowered, 6),
                  trace_only_s=round(t_traced - t0, 6),
                  signature_leaves=len(k[1]),
                  **({"hlo_flops": cost["flops"],
                      "hlo_bytes": cost["bytes"]} if cost else {}))
    with _CACHE_LOCK:
        _STAGE_EXECUTABLES[k] = fn
        _STAGE_COSTS[k] = cost
        while len(_STAGE_EXECUTABLES) > _STAGE_EXECUTABLES_MAX:
            old, _ = _STAGE_EXECUTABLES.popitem(last=False)
            _STAGE_COSTS.pop(old, None)
    return fn


def _extract_cost_analysis(compiled) -> dict:
    """XLA's cost analysis of a Compiled program, normalized to
    {"flops", "bytes", "source"} (metrics/roofline.py consumes this as
    the whole-stage cost declaration).  Returns {} when the backend does
    not expose the analysis — callers fall back to the declared
    batch-footprint estimate."""
    try:
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        if not ca:
            return {}
        flops = float(ca.get("flops", 0.0) or 0.0)
        nbytes = float(ca.get("bytes accessed", 0.0) or 0.0)
        if flops <= 0.0 and nbytes <= 0.0:
            return {}
        return {"flops": flops, "bytes": nbytes, "source": "hlo"}
    except Exception:  # noqa: BLE001 — cost analysis is best-effort
        return {}


def stage_cost(key: tuple, args: tuple,
               donate_argnums: tuple = ()) -> dict:
    """The XLA cost analysis recorded when stage_executable compiled the
    program for (key, signature-of-args) — same key mangling, so a caller
    that just dispatched can attribute the dispatch's HLO-derived cost.
    {} when unknown (evicted, AOT-less backend, never compiled)."""
    if donate_argnums:
        key = key + ("donate", tuple(donate_argnums))
    k = (key, input_signature(args))
    with _CACHE_LOCK:
        return _STAGE_COSTS.get(k, {})


def clear_stage_executables() -> None:
    with _CACHE_LOCK:
        _STAGE_EXECUTABLES.clear()
        _STAGE_COSTS.clear()


# --- plan-cache parameter keying --------------------------------------------
# Default: a Parameter keys like the Literal it replaced (value INCLUDED),
# so any dispatch site that does not thread parameter values as runtime
# arguments recompiles per value — always correct, merely slower.  The
# threaded sites (RowLocalExec.execute, TpuWholeStageExec, the aggregate
# whole-stage absorption, the exchange bucketing fusion) compute their keys
# under `param_free_keys()` so literal-variant queries share ONE compiled
# program and re-bind values per dispatch.

_KEY_MODE = threading.local()


@contextlib.contextmanager
def param_free_keys():
    """Within this scope, expr_key() omits Parameter VALUES (slot + dtype
    only).  Use ONLY around key computation for a dispatch site that
    passes the parameter values as traced runtime arguments."""
    prev = getattr(_KEY_MODE, "free", False)
    _KEY_MODE.free = True
    try:
        yield
    finally:
        _KEY_MODE.free = prev


def expr_key(e) -> tuple:
    """Structural signature of an expression tree: class + every non-child
    constructor attribute + children, recursively.  Safer than repr (an
    expression whose repr omits a parameter would under-key the cache)."""
    from ..ops.expressions import Expression, Parameter
    if isinstance(e, Parameter):
        key = ("Parameter", e.slot, e._dtype.name)
        if not getattr(_KEY_MODE, "free", False):
            key += (repr(e.value),)
        return key
    attrs = []
    d = getattr(e, "__dict__", None)
    items = sorted(d.items()) if d else \
        [(s, getattr(e, s)) for s in getattr(e, "__slots__", ())]
    for k, v in items:
        if k == "children" or isinstance(v, Expression):
            continue
        if isinstance(v, (list, tuple)) and any(
                isinstance(x, Expression) for x in v):
            continue
        attrs.append((k, _val_key(v)))
    kids = tuple(expr_key(c) for c in e.children)
    return (type(e).__name__, tuple(attrs), kids)


def _val_key(v):
    from ..types import DataType
    if isinstance(v, DataType):
        return v.name
    if isinstance(v, (list, tuple)):
        return tuple(_val_key(x) for x in v)
    if isinstance(v, (set, frozenset)):
        return tuple(sorted(map(repr, v)))
    if isinstance(v, dict):
        return tuple(sorted((k, _val_key(x)) for k, x in v.items()))
    return repr(v)


def schema_key(schema) -> tuple:
    return tuple((f.name, f.dtype.name) for f in schema)


def cached_kernel(key: tuple, builder: Callable[[], Callable],
                  **jit_kw) -> Callable:
    """Return the jitted kernel for `key`, building it on first use.
    Concurrent misses on the same key may both build; last registration
    wins — a benign duplicate trace, never a wrong program (the key fully
    determines the closure).  jit keywords (donate_argnums etc.) must be
    reflected in the key by the caller: a donated kernel always deletes
    its donated inputs, so it can never share an entry with the
    non-donated variant."""
    if jit_kw.get("donate_argnums"):
        key = key + ("donate", tuple(jit_kw["donate_argnums"]))
    fn = _CACHE.get(key)
    if fn is None:
        fn = jax.jit(builder(), **jit_kw)
        with _CACHE_LOCK:
            if key in _CACHE:
                return _CACHE[key]
            _CACHE[key] = fn
            _COUNTERS["builds"] += 1
    else:
        with _CACHE_LOCK:
            _COUNTERS["kernel_hits"] += 1
    return fn


def cache_info() -> Tuple[int, list]:
    return len(_CACHE), [k[0] for k in _CACHE]


def clear():
    with _CACHE_LOCK:
        _CACHE.clear()
        _STAGE_EXECUTABLES.clear()
        _STAGE_COSTS.clear()
