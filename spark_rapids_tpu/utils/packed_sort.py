"""One-shot packed-key argsort.

XLA:CPU (and the TPU sort HLO) pay a steep premium for VARIADIC sorts:
on the build host a single-operand 2M-row u64 sort runs ~180 ms while
the same rows through a 2-operand key/value sort cost ~1060 ms and a
5-key lexsort ~2260 ms (BENCH_PALLAS `argsort_*` rows) — the generic
multi-operand comparator loop defeats the specialized single-key path.
`jnp.lexsort`/`jnp.argsort` are ALWAYS variadic (they append an iota
operand), so every sort in the engine was paying it.

This module sorts with SINGLE-operand `jax.lax.sort` calls only:

  * the caller's order-preserving integer key components (each a uint64
    array holding values < 2^width) concatenate — conceptually — into
    one big-endian bit string;
  * the ROW ID is embedded in the low `r = log2(capacity)` bits of every
    sort word, so one unstable single-operand sort yields both the order
    and the permutation, and ties break by original index — which is
    exactly `lexsort` stability;
  * when the total key width fits `64 - r` bits, ONE sort call does the
    whole job (the one-shot packed-key path);
  * wider keys run a stable LSD radix: sort by the LEAST significant
    `64 - r` key bits first, gather, repeat toward the most significant
    chunk — each pass a single-operand sort, `ceil(total_bits/(64-r))`
    passes in all.

The permutation returned is BIT-IDENTICAL to
`jnp.lexsort(tuple(reversed(keys)))` over the same components (stable,
same comparison order), so callers may switch freely per the
`spark.rapids.sql.tpu.sort.packed.enabled` kill switch without changing
results.  All ops are jit-safe (pure jnp/lax; widths and pass structure
are static).

A Pallas tiled bitonic variant (`ops/pallas_kernels.bitonic_sort_u64`)
can take the single-pass sort when `spark.rapids.sql.tpu.pallas.enabled`
is on; any pallas failure (64-bit emulation on current chips, CPU
backend) falls back to `lax.sort` per call, like the cumsum kernel.
"""
from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

# latched from conf by the sort/aggregate execs (mirrors
# aggregate._PALLAS_CUMSUM): [0] = packed path enabled, [1] = pallas
# bitonic wanted for the single-pass sort
_PACKED = [True]
_PALLAS_SORT = [False]


def set_packed_enabled(enabled: bool) -> None:
    _PACKED[0] = bool(enabled)  # tpulint: disable=TPU009 per-session conf latch: an atomic boolean store, and every concurrent query of one session writes the same session-conf value


def packed_enabled() -> bool:
    return _PACKED[0]


def set_pallas_sort(enabled: bool) -> None:
    _PALLAS_SORT[0] = bool(enabled)  # tpulint: disable=TPU009 per-session conf latch: atomic boolean store, same-value writers under one session conf


def _u64(x: int):
    return jnp.uint64(x)


def _mask(bits: int):
    return _u64((1 << bits) - 1 if bits < 64 else 0xFFFFFFFFFFFFFFFF)


def plan_passes(total_bits: int, cap: int) -> int:
    """Number of single-operand sort passes a packed argsort of
    `total_bits` key bits over `cap` rows needs (cap a power of two)."""
    r = cap.bit_length() - 1
    chunk = 64 - r
    return max(1, -(-total_bits // chunk))


def _sort_words(keys):
    """Single-operand u64 sort, optionally through the Pallas tiled
    bitonic network (gated; any failure falls back to lax.sort)."""
    if _PALLAS_SORT[0] and jax.default_backend() == "tpu":
        from ..ops.pallas_kernels import bitonic_sort_u64
        try:
            return bitonic_sort_u64(keys)
        except Exception as e:  # noqa: BLE001 — any pallas failure falls back
            from ..metrics.registry import count_swallowed
            count_swallowed("numPallasFallbacks", "spark_rapids_tpu.pallas",
                            "pallas bitonic_sort_u64 failed (%r); using "
                            "lax.sort", e)
    return jax.lax.sort(keys, dimension=0, is_stable=False)


def packed_argsort(components: Sequence[Tuple[jnp.ndarray, int]],
                   cap: int) -> jnp.ndarray:
    """Stable argsort by `components` (MSB-first `(uint64 array, width)`
    pairs, every value < 2^width) — returns the int32 permutation equal
    to `jnp.lexsort` over the same keys (ties keep original order)."""
    assert cap and (cap & (cap - 1)) == 0, f"capacity {cap} not a power of 2"
    r = cap.bit_length() - 1
    chunk = 64 - r
    iota = jnp.arange(cap, dtype=jnp.uint64)
    mask_r = _mask(r)
    total = sum(w for _, w in components)
    if total == 0:
        return jnp.arange(cap, dtype=jnp.int32)

    # pack the components into 64-bit words, LSB-first: bit 0 of the
    # conceptual key is the LSB of the LAST component
    nwords = (total + 63) // 64
    words: List[Optional[jnp.ndarray]] = [None] * nwords
    pos = 0
    for arr, w in reversed(list(components)):
        a = arr.astype(jnp.uint64)
        lo, sh = pos // 64, pos % 64
        part = (a << _u64(sh)) if sh else a
        words[lo] = part if words[lo] is None else words[lo] | part
        if sh + w > 64:
            hi = a >> _u64(64 - sh)
            words[lo + 1] = (hi if words[lo + 1] is None
                             else words[lo + 1] | hi)
        pos += w
    zeros = jnp.zeros(cap, dtype=jnp.uint64)
    words = [w if w is not None else zeros for w in words]

    def extract(p: int):
        """Key bits [p*chunk, (p+1)*chunk) of the conceptual key,
        counted from the LSB."""
        start = p * chunk
        cw = min(chunk, total - start)
        lo, sh = start // 64, start % 64
        v = words[lo] >> _u64(sh) if sh else words[lo]
        if sh + cw > 64 and lo + 1 < nwords:
            v = v | (words[lo + 1] << _u64(64 - sh))
        return v & _mask(cw)

    npasses = plan_passes(total, cap)
    perm = None
    for p in range(npasses):  # LSD radix: least-significant chunk first
        bits = extract(p)
        if perm is not None:
            bits = jnp.take(bits, perm)
        s = _sort_words((bits << _u64(r)) | iota)
        step = (s & mask_r).astype(jnp.int32)
        perm = step if perm is None else jnp.take(perm, step)
    return perm
