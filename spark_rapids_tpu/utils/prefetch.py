"""Background-thread iterator prefetch (the reference's multithreaded
reader, GpuParquetScan's MULTITHREADED/COALESCING reader modes, reduced
to its TPU-relevant core): produce the NEXT chunk's host-side decode
while the device consumes the current one.  On a tunneled chip the H2D
transfer dominates the scan — overlapping it with the next chunk's
control-plane work pipelines the two instead of summing them.

jax is thread-compatible for this use: device_put/eager dispatches from
the producer thread enqueue on the same stream the consumer later
blocks on."""
from __future__ import annotations

import queue
import threading
from typing import Iterator, TypeVar

T = TypeVar("T")

_STOP = object()


class PrefetchIterator:
    """Wraps an iterator; a daemon thread keeps up to `depth` items
    decoded ahead.  Exceptions re-raise at the consumer in order.

    `close()` MUST be called when the consumer stops early (LIMIT,
    exception): it unblocks the pump thread (otherwise parked forever in
    a full-queue put, pinning the buffered batches and the source
    generator) and runs the wrapped generator's finally blocks."""

    def __init__(self, it: Iterator[T], depth: int = 1):
        self._q: "queue.Queue" = queue.Queue(maxsize=max(depth, 1))
        self._consumed = False
        self._closed = False
        self._it = it

        def offer(entry) -> bool:
            """put() that gives up once close() is called (a plain put
            can park forever on a queue the consumer stopped draining)."""
            while not self._closed:
                try:
                    self._q.put(entry, timeout=0.25)
                    return True
                except queue.Full:
                    continue  # tpulint: disable=TPU006 bounded-put retry loop; the timeout exists to re-check _closed
            return False

        def pump():
            try:
                for item in it:
                    if not offer((item, None)):
                        break
            except BaseException as e:  # noqa: BLE001 — re-raised below
                offer((None, e))
                return
            finally:
                if self._closed and hasattr(it, "close"):
                    try:
                        it.close()
                    except Exception:  # noqa: BLE001 — teardown
                        pass  # tpulint: disable=TPU006 close() of an abandoned source iterator after the consumer left
            offer((_STOP, None))

        self._thread = threading.Thread(target=pump, daemon=True,
                                        name="scan-prefetch")
        self._thread.start()

    def __iter__(self):
        return self

    def __next__(self) -> T:
        if self._consumed:
            raise StopIteration
        item, err = self._q.get()
        if err is not None:
            self._consumed = True
            raise err
        if item is _STOP:
            self._consumed = True
            raise StopIteration
        return item

    def close(self) -> None:
        self._closed = True
        try:  # drop buffered items so a parked put() finds space
            while True:
                self._q.get_nowait()
        except queue.Empty:
            pass  # tpulint: disable=TPU006 Empty is the drain loop's termination condition
