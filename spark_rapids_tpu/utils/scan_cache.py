"""Device-resident cache for in-memory table scans.

Spark keeps hot tables in the storage layer (`df.cache()` /
`CachedBatchSerializer`; the reference adds a GPU-aware columnar cache
serializer in later versions).  The TPU-native equivalent keeps the decoded
device batches HBM-resident: HBM is large (16 GiB on v5e) relative to the
host->device link, so re-uploading an immutable table on every query wastes
the slowest resource in the system.  On tunneled dev TPUs the link can be
~10 MB/s, which made repeated-query benchmarks H2D-bound (round-2 postmortem:
16 s/run for a 192 MB table).

Keys are (table identity, pruned column names, reader row limit).  A strong
reference to the source table is held so `id()` can never be recycled to a
different live table; pyarrow Tables are immutable, so identity implies
content equality.  The cache is LRU-bounded by
`spark.rapids.sql.tpu.memoryScanCache.maxSize` device bytes.
"""
from __future__ import annotations

from collections import OrderedDict
from typing import List, Optional, Tuple


class _Entry:
    __slots__ = ("table", "batches", "nbytes")

    def __init__(self, table, batches, nbytes: int):
        self.table = table
        self.batches = batches
        self.nbytes = nbytes


class MemoryScanCache:
    def __init__(self):
        self._entries: "OrderedDict[tuple, _Entry]" = OrderedDict()
        self._bytes = 0
        self.hits = 0
        self.misses = 0

    @staticmethod
    def _key(table, names: Tuple[str, ...], limit: int) -> tuple:
        return (id(table), names, limit)

    def get(self, table, names: Tuple[str, ...], limit: int
            ) -> Optional[List]:
        key = self._key(table, names, limit)
        e = self._entries.get(key)
        if e is None or e.table is not table:
            self.misses += 1
            return None
        self._entries.move_to_end(key)
        self.hits += 1
        return e.batches

    def put(self, table, names: Tuple[str, ...], limit: int,
            batches: List, max_bytes: int, nbytes: int) -> None:
        """`batches` is a list of (ColumnarBatch, live_row_count) pairs; the
        count is cached host-side so serving a hit costs no device sync.
        `nbytes` is the caller-accumulated device size of `batches` (one
        computation shared with the caller's streaming cutoff)."""
        if nbytes > max_bytes:
            return  # too big to ever fit; don't thrash the cache
        key = self._key(table, names, limit)
        old = self._entries.pop(key, None)
        if old is not None:
            self._bytes -= old.nbytes
        self._entries[key] = _Entry(table, batches, nbytes)
        self._bytes += nbytes
        while self._bytes > max_bytes and len(self._entries) > 1:
            _, evicted = self._entries.popitem(last=False)
            self._bytes -= evicted.nbytes

    def clear(self) -> None:
        self._entries.clear()
        self._bytes = 0
        self.hits = 0
        self.misses = 0

    @property
    def device_bytes(self) -> int:
        return self._bytes


MEMORY_SCAN_CACHE = MemoryScanCache()
