"""Tracing/profiling ranges.

TPU-native analogue of the reference's NVTX integration
(rapids/NvtxWithMetrics.scala:44 — a profiler range that also accumulates a
SQLMetric; docs/dev/nvtx_profiling.md): ranges show up in the XLA/JAX trace
viewer instead of Nsight.  `profile_trace` wraps jax.profiler for capturing
a trace directory viewable in TensorBoard/XProf.
"""
from __future__ import annotations

import contextlib
import time


@contextlib.contextmanager
def named_range(name: str, metrics=None, metric_name: str = None):
    """A profiler range; optionally accumulates elapsed seconds into a
    Metrics object (NvtxWithMetrics equivalent)."""
    import jax
    t0 = time.perf_counter()
    try:
        with jax.profiler.TraceAnnotation(name):
            with jax.named_scope(name):
                yield
    finally:
        if metrics is not None:
            metrics.add(metric_name or name, time.perf_counter() - t0)


@contextlib.contextmanager
def profile_trace(log_dir: str):
    """Capture a device trace for the enclosed block (the Nsight-capture
    equivalent; open with TensorBoard's profile plugin)."""
    import jax
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
