"""Tracing/profiling ranges.

TPU-native analogue of the reference's NVTX integration
(rapids/NvtxWithMetrics.scala:44 — a profiler range that also accumulates a
SQLMetric; docs/dev/nvtx_profiling.md): ranges show up in the XLA/JAX trace
viewer instead of Nsight.  `profile_trace` wraps jax.profiler for capturing
a trace directory viewable in TensorBoard/XProf.
"""
from __future__ import annotations

import contextlib
import time


@contextlib.contextmanager
def named_range(name: str, metrics=None, metric_name: str = None):
    """A profiler range; optionally accumulates elapsed seconds into a
    Metrics object (NvtxWithMetrics equivalent)."""
    import jax
    t0 = time.perf_counter()
    try:
        with jax.profiler.TraceAnnotation(name):
            with jax.named_scope(name):
                yield
    finally:
        if metrics is not None:
            metrics.add(metric_name or name, time.perf_counter() - t0)


@contextlib.contextmanager
def profile_trace(log_dir: str, journal=None):
    """Capture a device trace for the enclosed block (the Nsight-capture
    equivalent; open with TensorBoard's profile plugin).  Pass a query
    `journal` (metrics.journal.EventJournal) to also emit its spans as a
    Chrome trace-event file in `log_dir`, so the engine's
    operator/retry/spill/fetch timeline sits next to the XLA device
    timeline in the same viewer."""
    import jax
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
        if journal is not None:
            import os
            write_chrome_trace(journal.events(),
                               os.path.join(log_dir, "journal_trace.json"))


def journal_to_trace_events(events) -> list:
    """metrics.journal event records -> Chrome trace-event format (the
    XLA trace viewer / Perfetto / chrome://tracing input format).  B/E
    spans map to ph B/E duration events on a per-kind 'thread'; instant
    events map to ph i."""
    kinds = sorted({e.get("kind", "?") for e in events})
    tid_of = {k: i + 1 for i, k in enumerate(kinds)}
    out = [{"name": "process_name", "ph": "M", "pid": 1,
            "args": {"name": "spark_rapids_tpu journal"}}]
    for k, tid in tid_of.items():
        out.append({"name": "thread_name", "ph": "M", "pid": 1, "tid": tid,
                    "args": {"name": k}})
    for e in events:
        ts_us = e.get("ts", 0) / 1e3  # monotonic ns -> us
        if e.get("kind") == "mem" and e.get("name") == "pressure":
            # memory lane: sampled per-tier pool usage renders as a
            # Chrome COUNTER track (stacked area) instead of an instant
            out.append({"name": "memory", "ph": "C", "pid": 1,
                        "ts": ts_us, "cat": "mem",
                        "args": {"device": e.get("device", 0),
                                 "host": e.get("host", 0),
                                 "disk": e.get("disk", 0)}})
            continue
        if e.get("kind") == "metric" and e.get("name") == "gaugeSample":
            # telemetry counter lanes: one counter track per sampled lane
            for lane in ("device_used", "in_flight_tasks", "spill_bytes"):
                if lane in e:
                    out.append({"name": lane, "ph": "C", "pid": 1,
                                "ts": ts_us, "cat": "telemetry",
                                "args": {lane: e[lane]}})
            continue
        rec = {"name": e.get("name", "?"), "pid": 1,
               "tid": tid_of.get(e.get("kind", "?"), 0), "ts": ts_us,
               "cat": e.get("kind", "?")}
        args = {k: v for k, v in e.items()
                if k not in ("ts", "ev", "kind", "name")}
        if args:
            rec["args"] = args
        ev = e.get("ev")
        if ev == "B":
            rec["ph"] = "B"
        elif ev == "E":
            rec["ph"] = "E"
        elif ev == "I":
            rec["ph"] = "i"
            rec["s"] = "t"  # thread-scoped instant
        else:
            continue
        out.append(rec)
    return out


def write_chrome_trace(events, path: str) -> str:
    """Write journal events as a Chrome trace-event JSON file."""
    import json
    import os
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with open(path, "w") as f:
        json.dump({"traceEvents": journal_to_trace_events(events),
                   "displayTimeUnit": "ms"}, f)
    return path


def timeline_to_trace_events(timeline) -> list:
    """Merged cluster timeline (metrics.timeline.Timeline) -> Chrome
    trace events: ONE PID LANE PER WORKER (process_name = executor id),
    a thread per span kind inside each lane, wall-clock-aligned
    timestamps, and FLOW events (ph s/f) tying every reducer fetch span
    to the mapper's serve record — so a multi-process shuffle reads as
    one picture in Perfetto / chrome://tracing / the XLA trace viewer."""
    executors = sorted(timeline.executors())
    pid_of = {ex: i + 1 for i, ex in enumerate(executors)}
    kinds = sorted({s.kind for s in timeline.spans}
                   | {i["kind"] for i in timeline.instants})
    tid_of = {k: i + 1 for i, k in enumerate(kinds)}
    out = []
    for ex, pid in pid_of.items():
        out.append({"name": "process_name", "ph": "M", "pid": pid,
                    "args": {"name": ex}})
        for k, tid in tid_of.items():
            out.append({"name": "thread_name", "ph": "M", "pid": pid,
                        "tid": tid, "args": {"name": k}})
    for sp in timeline.spans:
        rec = {"name": sp.name, "cat": sp.kind, "ph": "X",
               "pid": pid_of[sp.executor], "tid": tid_of[sp.kind],
               "ts": sp.t0_ns / 1e3,
               "dur": ((sp.t1_ns - sp.t0_ns) / 1e3
                       if sp.t1_ns is not None else 0)}
        if sp.attrs:
            rec["args"] = dict(sp.attrs)
        out.append(rec)
    for i in timeline.instants:
        if i["kind"] == "mem" and i["name"] == "pressure":
            # per-worker memory lane: one counter track per executor pid
            # so each worker's pool pressure renders as its own stacked
            # area under its span lanes
            out.append({"name": "memory", "ph": "C", "cat": "mem",
                        "pid": pid_of[i["executor"]],
                        "ts": i["wall_ns"] / 1e3,
                        "args": {
                            "device": i["attrs"].get("device", 0),
                            "host": i["attrs"].get("host", 0),
                            "disk": i["attrs"].get("disk", 0)}})
            continue
        if i["kind"] == "metric" and i["name"] == "gaugeSample":
            # telemetry counter lanes (metrics/ring.GaugeSampler ticks):
            # one counter track per worker per lane key, so pool bytes /
            # in-flight tasks / spill bytes render as per-executor area
            # charts alongside the span lanes
            for lane, val in i["attrs"].items():
                out.append({"name": lane, "ph": "C", "cat": "telemetry",
                            "pid": pid_of[i["executor"]],
                            "ts": i["wall_ns"] / 1e3,
                            "args": {lane: val}})
            continue
        rec = {"name": i["name"], "cat": i["kind"], "ph": "i", "s": "t",
               "pid": pid_of[i["executor"]], "tid": tid_of[i["kind"]],
               "ts": i["wall_ns"] / 1e3}
        if i["attrs"]:
            rec["args"] = dict(i["attrs"])
        out.append(rec)
    for idx, link in enumerate(timeline.links()):
        fetch, serve = link["fetch"], link["serve"]
        common = {"name": "shuffleFetch", "cat": "fetch-serve",
                  "id": idx}
        out.append({**common, "ph": "s",
                    "pid": pid_of[fetch.executor],
                    "tid": tid_of[fetch.kind], "ts": fetch.t0_ns / 1e3})
        out.append({**common, "ph": "f", "bp": "e",
                    "pid": pid_of[serve["executor"]]
                    if serve["executor"] in pid_of
                    else pid_of[fetch.executor],
                    "tid": tid_of.get("serve", 1),
                    "ts": serve["wall_ns"] / 1e3})
    return out


def write_cluster_chrome_trace(timeline, path: str) -> str:
    """Write a merged cluster timeline as a multi-pid Chrome trace."""
    import json
    import os
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with open(path, "w") as f:
        json.dump({"traceEvents": timeline_to_trace_events(timeline),
                   "displayTimeUnit": "ms"}, f)
    return path
