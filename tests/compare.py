"""CPU-vs-TPU comparison harness.

Mirrors the reference's universal oracle (tests/.../SparkQueryCompareTestSuite
.scala:132-300): run the same query once with TPU acceleration enabled and
once with spark.rapids.sql.enabled=false (pure CPU executors), then deep-
compare row sets with float tolerance and optional sort-insensitivity.
"""
import math

from spark_rapids_tpu.engine import TpuSession


def run_both(build_query, conf=None, cpu_conf_extra=None):
    tpu_conf = dict(conf or {})
    cpu_conf = dict(conf or {})
    cpu_conf.update(cpu_conf_extra or {})
    cpu_conf["spark.rapids.sql.enabled"] = "false"
    tpu = build_query(TpuSession(tpu_conf)).collect()
    cpu = build_query(TpuSession(cpu_conf)).collect()
    return cpu, tpu


def normalize_row(row, approx):
    out = []
    for v in row:
        if isinstance(v, float):
            if math.isnan(v):
                out.append("NaN")
            elif approx:
                out.append(round(v, 9) if abs(v) < 1e12 else v)
            else:
                out.append(v)
        else:
            out.append(v)
    return tuple(out)


def _sort_key(row):
    out = []
    for v in row:
        if v is None:
            out.append((0, 0, ""))
        elif isinstance(v, float) and math.isnan(v):
            out.append((2, 0, ""))
        elif isinstance(v, str):
            out.append((1, 0, v))
        elif isinstance(v, bool):
            out.append((1, int(v), ""))
        elif isinstance(v, (int, float)):
            out.append((1, v, ""))
        else:
            out.append((1, 0, str(v)))
    return tuple(out)


def assert_rows_equal(cpu, tpu, ignore_order=True, approx_float=True):
    assert len(cpu) == len(tpu), \
        f"row count differs: cpu={len(cpu)} tpu={len(tpu)}\n" \
        f"cpu={cpu[:10]}\ntpu={tpu[:10]}"
    c = [normalize_row(r, approx_float) for r in cpu]
    t = [normalize_row(r, approx_float) for r in tpu]
    if ignore_order:
        c = sorted(c, key=_sort_key)
        t = sorted(t, key=_sort_key)
    for i, (cr, tr) in enumerate(zip(c, t)):
        if cr != tr:
            ok = len(cr) == len(tr)
            if ok:
                for cv, tv in zip(cr, tr):
                    if isinstance(cv, float) and isinstance(tv, float):
                        if not math.isclose(cv, tv, rel_tol=1e-9,
                                            abs_tol=1e-9):
                            ok = False
                            break
                    elif cv != tv:
                        ok = False
                        break
            assert ok, f"row {i} differs:\n  cpu={cr}\n  tpu={tr}"


def assert_tpu_and_cpu_are_equal(build_query, conf=None, ignore_order=True,
                                 approx_float=True):
    cpu, tpu = run_both(build_query, conf)
    assert_rows_equal(cpu, tpu, ignore_order, approx_float)
    return cpu
