"""Test harness setup: run everything on a virtual 8-device CPU mesh so
multi-chip sharding is exercised without TPU hardware (the driver separately
dry-runs the multichip path)."""
import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()
# float64 columns are part of the supported type surface
os.environ.setdefault("JAX_ENABLE_X64", "1")

import jax  # noqa: E402

jax.config.update("jax_enable_x64", True)
