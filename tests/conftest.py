"""Test harness setup: run everything on a virtual 8-device CPU mesh so
multi-chip sharding is exercised without TPU hardware (the driver separately
dry-runs the multichip path)."""
import os

# float64 columns are part of the supported type surface.  Env vars are read
# when jax first imports (sitecustomize already imported it), so the latched
# configs are ALSO set below — the env vars only help subprocesses.
os.environ.setdefault("JAX_ENABLE_X64", "1")

# force CPU + 8 virtual devices: the ambient environment pins
# JAX_PLATFORMS=axon (one exclusive real TPU chip behind a machine-wide
# lease) — tests must not contend for it
from spark_rapids_tpu.utils.cpu_backend import force_cpu_backend  # noqa: E402

force_cpu_backend(n_devices=8)

import jax  # noqa: E402

jax.config.update("jax_enable_x64", True)

import pytest  # noqa: E402

# XLA:CPU segfaults inside backend_compile after a few thousand compiled
# executables accumulate in one process (observed deterministically around
# ~80% of this suite, always inside a jit compile, regardless of which
# test compiles there; the same tests pass in a fresh process).  Dropping
# the compilation caches periodically bounds live executable count; the
# handful of retraces that follow cost seconds, a crashed suite costs
# everything.
_TESTS_PER_CACHE_CLEAR = 40
_test_count = {"n": 0}


@pytest.fixture(autouse=True)
def _bound_xla_code_memory():
    yield
    _test_count["n"] += 1
    if _test_count["n"] % _TESTS_PER_CACHE_CLEAR == 0:
        jax.clear_caches()
        # whole-stage AOT executables live OUTSIDE jax's caches (they
        # would survive clear_caches and defeat this bound)
        from spark_rapids_tpu.utils import kernel_cache
        kernel_cache.clear_stage_executables()


@pytest.fixture(autouse=True)
def _reset_fault_injector():
    """Disarm + zero the process-global fault injector around every test so
    the `faultinject` tier's ordinals are deterministic and no armed spec
    leaks into unrelated tests (the `adaptive` tier's discover-then-replay
    OOM tests rely on the same reset)."""
    from spark_rapids_tpu.utils import faults
    faults.INJECTOR.reset()
    yield
    faults.INJECTOR.reset()


# capability gate (known seed failure): the distributed join lowering
# marks fori_loop carries as varying over shard_map manual axes via
# jax.lax.pcast (exec/join.py _pvary), which some jax versions (e.g. the
# env's 0.4.37) predate — tests that lower a distributed join skip with
# a reason instead of hard-failing.  Shared here so the gate cannot
# drift between test files (test_parallel / test_distributed_*).
needs_pcast = pytest.mark.skipif(
    not hasattr(jax.lax, "pcast"),
    reason="jax.lax.pcast unavailable in jax "
           f"{jax.__version__}; distributed join lowering "
           "(spark_rapids_tpu/exec/join.py _pvary) requires it")
