"""Test harness setup: run everything on a virtual 8-device CPU mesh so
multi-chip sharding is exercised without TPU hardware (the driver separately
dry-runs the multichip path)."""
import os

# force CPU: the ambient environment pins JAX_PLATFORMS=axon (one exclusive
# real TPU chip behind a machine-wide lease) — tests must not contend for it,
# and need 8 virtual devices for the multi-chip sharding tests
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()
# float64 columns are part of the supported type surface
os.environ.setdefault("JAX_ENABLE_X64", "1")

# The container's sitecustomize registers the axon TPU PJRT plugin in every
# interpreter; merely enumerating backends then blocks on the TPU lease even
# under JAX_PLATFORMS=cpu.  Drop the factory before any backend initializes.
import jax._src.xla_bridge as _xb  # noqa: E402

for _plat in ("axon", "tpu"):
    _xb._backend_factories.pop(_plat, None)

import jax  # noqa: E402

# sitecustomize already imported jax, so the env vars above were read before
# this file ran; set the latched configs directly too
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)
