"""Test harness setup: run everything on a virtual 8-device CPU mesh so
multi-chip sharding is exercised without TPU hardware (the driver separately
dry-runs the multichip path)."""
import os

# float64 columns are part of the supported type surface.  Env vars are read
# when jax first imports (sitecustomize already imported it), so the latched
# configs are ALSO set below — the env vars only help subprocesses.
os.environ.setdefault("JAX_ENABLE_X64", "1")

# force CPU + 8 virtual devices: the ambient environment pins
# JAX_PLATFORMS=axon (one exclusive real TPU chip behind a machine-wide
# lease) — tests must not contend for it
from spark_rapids_tpu.utils.cpu_backend import force_cpu_backend  # noqa: E402

force_cpu_backend(n_devices=8)

import jax  # noqa: E402

jax.config.update("jax_enable_x64", True)
