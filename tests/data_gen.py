"""Typed random data generators with special-case injection.

Mirrors integration_tests/src/main/python/data_gen.py from the reference:
every generator seeds deterministically and injects the nasty corner values
(None, NaN, +-0.0, min/max, empty strings) at a fixed ratio.
"""
import random
import string

from spark_rapids_tpu import types as T

SPECIALS = {
    T.IntegerType: [None, 0, 1, -1, 2**31 - 1, -(2**31)],
    T.LongType: [None, 0, 1, -1, 2**63 - 1, -(2**63)],
    T.ShortType: [None, 0, -1, 2**15 - 1, -(2**15)],
    T.ByteType: [None, 0, -1, 127, -128],
    T.DoubleType: [None, 0.0, -0.0, 1.0, -1.0, float("nan"), float("inf"),
                   float("-inf"), 1e300, -1e300, 5e-324],
    T.FloatType: [None, 0.0, -0.0, float("nan"), float("inf"), 3.4e38],
    T.BooleanType: [None, True, False],
    T.StringType: [None, "", " ", "a", "A", "0", "nan", "null",
                   "\tx ", "longer string value"],
    # keep |days| within python datetime range with slack for date arithmetic
    T.DateType: [None, 0, -1, 18262, -719000, 2932800],
    T.TimestampType: [None, 0, -1, 1_600_000_000_000_000,
                      -62_135_596_800_000_000],
}


def gen_value(rng: random.Random, dtype, nullable=True):
    specials = SPECIALS[dtype]
    if rng.random() < 0.15:
        v = rng.choice(specials)
        if v is None and not nullable:
            return _random_value(rng, dtype)
        return v
    return _random_value(rng, dtype)


def _random_value(rng, dtype):
    if dtype is T.IntegerType:
        return rng.randint(-(2**31), 2**31 - 1)
    if dtype is T.LongType:
        return rng.randint(-(2**63), 2**63 - 1)
    if dtype is T.ShortType:
        return rng.randint(-(2**15), 2**15 - 1)
    if dtype is T.ByteType:
        return rng.randint(-128, 127)
    if dtype is T.DoubleType:
        return rng.uniform(-1e6, 1e6)
    if dtype is T.FloatType:
        import struct
        return struct.unpack("f", struct.pack("f",
                                              rng.uniform(-1e6, 1e6)))[0]
    if dtype is T.BooleanType:
        return rng.random() < 0.5
    if dtype is T.StringType:
        n = rng.randint(0, 20)
        return "".join(rng.choice(string.ascii_letters + string.digits + " _")
                       for _ in range(n))
    if dtype is T.DateType:
        return rng.randint(-100_000, 100_000)
    if dtype is T.TimestampType:
        return rng.randint(-10**15, 4 * 10**15)
    raise TypeError(dtype)


def gen_table(seed: int, n: int, **cols):
    """cols: name=dtype (or name=(dtype, nullable)).  Returns dict + Schema."""
    rng = random.Random(seed)
    data = {}
    fields = []
    for name, spec in cols.items():
        dtype, nullable = spec if isinstance(spec, tuple) else (spec, True)
        data[name] = [gen_value(rng, dtype, nullable) for _ in range(n)]
        fields.append(T.StructField(name, dtype, nullable))
    return data, T.Schema(fields)


def gen_df(session, seed: int, n: int, **cols):
    data, schema = gen_table(seed, n, **cols)
    return session.from_pydict(data, schema)
