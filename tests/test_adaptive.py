"""Adaptive query execution tier (ISSUE 3): runtime re-planning from
shuffle map statistics.

Covers the three rules (coalesce small partitions, skew-join split,
dynamic join strategy switch) end to end — AQE-on must match AQE-off
bit-for-bit while the adaptive counters fire and every decision lands in
the journal/Prometheus surfaces — plus the stats plumbing (MapOutputTracker
lifecycle, cluster-wide merge) and composition with OOM fault injection.
"""
import numpy as np
import pytest

from spark_rapids_tpu.adaptive.rules import (coalesce_specs, detect_skew,
                                             map_range_slices)
from spark_rapids_tpu.adaptive.stats import (CoalescedPartitionSpec,
                                             MapOutputTracker,
                                             PartialReducerPartitionSpec,
                                             identity_specs, is_identity,
                                             merge_cluster_stats)
from spark_rapids_tpu.engine import TpuSession
from spark_rapids_tpu.plan.logical import col, functions as F, lit
from spark_rapids_tpu.utils import faults

pytestmark = pytest.mark.adaptive


# --------------------------------------------------------------------------
# rule unit tests
# --------------------------------------------------------------------------

def test_coalesce_specs_merges_under_bound():
    specs = coalesce_specs(6, [[10, 10, 10, 100, 10, 10]], [35])
    assert specs == [CoalescedPartitionSpec(0, 3),
                     CoalescedPartitionSpec(3, 4),
                     CoalescedPartitionSpec(4, 6)]
    # every partition covered exactly once
    assert [p for s in specs for p in range(s.start, s.end)] == list(range(6))


def test_coalesce_specs_second_bound_caps_build_side():
    # combined bytes would merge everything; the build-side bound splits
    specs = coalesce_specs(4, [[1, 1, 1, 1], [30, 30, 30, 30]], [1000, 60])
    assert specs == [CoalescedPartitionSpec(0, 2),
                     CoalescedPartitionSpec(2, 4)]


def test_coalesce_specs_identity_detection():
    assert is_identity(identity_specs(5), 5)
    assert not is_identity([CoalescedPartitionSpec(0, 2)], 2)


def test_detect_skew_uses_median_and_floor():
    sizes = [10, 12, 11, 500, 0, 9]
    assert detect_skew(sizes, factor=3.0, threshold=1) == {3}
    # the floor suppresses skew below it whatever the factor says
    assert detect_skew(sizes, factor=3.0, threshold=10_000) == set()
    assert detect_skew([0, 0], 3.0, 1) == set()


def test_map_range_slices_split_and_unsplittable():
    slices = map_range_slices({0: 40, 1: 40, 2: 40, 3: 40}, target=90)
    assert len(slices) >= 2
    # contiguous cover of [0, max_map+1)
    assert slices[0][0] == 0 and slices[-1][1] == 4
    for (a, b), (c, _d) in zip(slices, slices[1:]):
        assert b == c and a < b
    # a single map block cannot be split
    assert map_range_slices({2: 1000}, target=10) == [(0, 3)]
    assert map_range_slices({}, target=10) == []


# --------------------------------------------------------------------------
# map-output statistics plumbing
# --------------------------------------------------------------------------

def test_map_output_tracker_record_and_remove():
    t = MapOutputTracker()
    t.record(1, map_id=0, reduce_id=2, nbytes=100, nrows=10)
    t.record(1, map_id=1, reduce_id=2, nbytes=50, nrows=5)
    t.record(1, map_id=0, reduce_id=0, nbytes=7, nrows=1)
    st = t.stats(1, num_partitions=4)
    assert st.bytes_by_partition == [7, 0, 150, 0]
    assert st.rows_by_partition == [1, 0, 15, 0]
    assert st.map_bytes_by_partition[2] == {0: 100, 1: 50}
    assert st.num_map_tasks == 2
    assert st.total_bytes == 157 and st.total_rows == 16
    t.remove_shuffle(1)
    assert t.tracked_shuffles() == []
    assert t.stats(1, 4).total_bytes == 0


def test_merge_cluster_stats_sums_executor_snapshots():
    a, b = MapOutputTracker(), MapOutputTracker()
    a.record(5, 0, 1, 100, 10)
    b.record(5, 1, 1, 40, 4)
    b.record(5, 1, 3, 8, 2)
    st = merge_cluster_stats(5, 4, [a.snapshot(5), b.snapshot(5), None])
    assert st.bytes_by_partition == [0, 140, 0, 8]
    assert st.map_bytes_by_partition[1] == {0: 100, 1: 40}
    assert st.num_map_tasks == 2


def test_tpu_cluster_map_output_stats_merges_executors():
    from spark_rapids_tpu.config import TpuConf
    from spark_rapids_tpu.plugin import TpuCluster
    from spark_rapids_tpu.columnar import ColumnarBatch
    from spark_rapids_tpu.types import LongType, Schema, StructField
    conf = TpuConf({"spark.rapids.sql.tpu.cluster.executors": 2})
    cluster = TpuCluster(conf, 2)
    try:
        schema = Schema([StructField("x", LongType)])
        batch = ColumnarBatch.from_pydict({"x": [1, 2, 3]}, schema)
        sid = cluster.new_shuffle_id()
        cluster.env_for(0).write_partition(sid, 0, 1, batch)
        cluster.env_for(1).write_partition(sid, 1, 1, batch)
        st = cluster.map_output_stats(sid, 4)
        assert st.rows_by_partition == [0, 6, 0, 0]
        assert st.num_map_tasks == 2
        assert set(st.map_bytes_by_partition[1]) == {0, 1}
        cluster.remove_shuffle(sid)
        assert cluster.map_output_stats(sid, 4).total_bytes == 0
    finally:
        cluster.shutdown()


def test_map_stats_do_not_accumulate_across_queries():
    """Shuffle lifecycle regression (satellite): remove_shuffle must drop
    the shuffle's statistics, so a long-lived session's tracker stays
    empty between queries."""
    s = TpuSession({
        "spark.sql.autoBroadcastJoinThreshold": "-1",
        "spark.rapids.sql.tpu.join.partitioned.threshold": "0",
        "spark.rapids.sql.tpu.shuffle.partitions": "4",
    })
    left = s.from_pydict({"k": [i % 5 for i in range(200)],
                          "v": [float(i) for i in range(200)]})
    right = s.from_pydict({"k": list(range(5)),
                           "w": [float(i) for i in range(5)]})
    for _ in range(2):
        left.join(right, on="k").agg(F.count(lit(1)).alias("c")).collect()
    env = getattr(s.runtime, "_shuffle_env", None)
    assert env is not None
    assert env.map_stats.tracked_shuffles() == [], \
        "map-output statistics leaked across queries"


# --------------------------------------------------------------------------
# end-to-end: AQE-on == AQE-off while the rules demonstrably fire
# --------------------------------------------------------------------------

_SKEW_CONF = {
    # force the partitioned-join path (no static broadcast) so the
    # coalesce/skew rules own the join
    "spark.sql.autoBroadcastJoinThreshold": "-1",
    "spark.rapids.sql.tpu.join.partitioned.threshold": "0",
    "spark.rapids.sql.tpu.shuffle.partitions": "8",
    "spark.rapids.sql.tpu.adaptive.advisoryPartitionSizeBytes": "16k",
    "spark.rapids.sql.tpu.adaptive.skewJoin.skewedPartitionFactor": "3",
    "spark.rapids.sql.tpu.adaptive.skewJoin."
    "skewedPartitionThresholdInBytes": "1k",
    "spark.rapids.sql.tpu.metrics.level": "DEBUG",  # in-memory journal
}


def _skewed_query(session):
    """join + agg + sort slice over a hot-key dataset; repartition(4)
    upstream gives the join's map side multiple map tasks, which is what
    the skew rule slices on."""
    rng = np.random.RandomState(0)
    keys = [7] * 3000 + [int(k) for k in rng.randint(0, 10, 3000)
                         if k != 7]
    left = session.from_pydict(
        {"k": keys, "v": [float(i % 13) for i in range(len(keys))]})
    right = session.from_pydict(
        {"k": list(range(10)), "name": [f"dim{i}" for i in range(10)]})
    return (left.repartition(4)
            .join(right, on="k")
            .group_by("name")
            .agg(F.sum(col("v")).alias("sv"),
                 F.count(col("v")).alias("cv"))
            .order_by("name"))


def _run_skewed(adaptive, extra=None):
    conf = dict(_SKEW_CONF)
    conf["spark.rapids.sql.tpu.adaptive.enabled"] = str(adaptive).lower()
    conf.update(extra or {})
    s = TpuSession(conf)
    return s, _skewed_query(s).to_arrow()


def test_aqe_on_off_identical_on_skewed_join():
    _s_off, t_off = _run_skewed(False)
    s_on, t_on = _run_skewed(True)
    # bit-for-bit: same arrow table (schema, order, values)
    assert t_on.equals(t_off)

    tot = s_on.query_metrics_total
    assert tot.get("numSkewSplits", 0) > 0
    assert tot.get("numCoalescedPartitions", 0) > 0
    assert tot.get("mapOutputBytes", 0) > 0

    qe = s_on.last_execution
    # the counters appear in the Prometheus export (acceptance criterion)
    prom = qe.prometheus()
    assert "spark_rapids_tpu_num_skew_splits" in prom
    assert "spark_rapids_tpu_num_coalesced_partitions" in prom
    # every adaptive decision journaled with the replan kind
    names = [e["name"] for e in qe.journal.events()
             if e["kind"] == "replan"]
    assert "skewSplit" in names and "coalescePartitions" in names
    # map stages journaled with observed sizes
    stages = [e for e in qe.journal.events() if e["kind"] == "stage"]
    assert stages and all(e["bytes"] >= 0 for e in stages)
    # EXPLAIN METRICS shows the FINAL (re-planned) stage plan
    text = qe.explain_with_metrics()
    assert "TpuAdaptivePlanExec[final]" in text
    assert "TpuCoalescedShuffleReaderExec" in text


def test_aqe_off_plans_contain_no_adaptive_nodes():
    s_off, _ = _run_skewed(False)
    text = s_off.last_execution.explain_with_metrics()
    assert "TpuAdaptivePlanExec" not in text
    assert "TpuCoalescedShuffleReaderExec" not in text


def test_coalesce_rule_fires_on_many_tiny_partitions():
    def q(session):
        df = session.from_pydict(
            {"k": [i % 50 for i in range(2000)],
             "v": [float(i) for i in range(2000)]})
        return (df.repartition(32)
                .group_by("k").agg(F.sum(col("v")).alias("sv"))
                .order_by("k"))

    def run(adaptive):
        s = TpuSession({
            "spark.rapids.sql.tpu.adaptive.enabled": str(adaptive).lower(),
            "spark.rapids.sql.tpu.adaptive.advisoryPartitionSizeBytes":
                "1m",
            "spark.rapids.sql.tpu.metrics.level": "DEBUG",
        })
        return s, q(s).to_arrow()

    _s_off, t_off = run(False)
    s_on, t_on = run(True)
    assert t_on.equals(t_off)
    assert s_on.query_metrics_total.get("numCoalescedPartitions", 0) > 0
    names = [e["name"] for e in s_on.last_execution.journal.events()
             if e["kind"] == "replan"]
    assert "coalescePartitions" in names


def test_promote_partitioned_join_to_broadcast():
    """Observed build side tiny though the static estimate said big (the
    filter keeps its child's upper-bound estimate): the strategy rule
    promotes to a single-build join."""
    def q(session):
        big = session.from_pydict(
            {"k": list(range(50000)),
             "v": [float(i % 7) for i in range(50000)]})
        dim = big.filter(col("k") < 100).select(
            col("k"), (col("v") * 2).alias("w"))
        return (big.join(dim, on="k")
                .group_by().agg(F.count(col("w")).alias("c")))

    def run(adaptive):
        s = TpuSession({
            "spark.sql.autoBroadcastJoinThreshold": "20k",
            "spark.rapids.sql.tpu.join.partitioned.threshold": "0",
            "spark.rapids.sql.tpu.shuffle.partitions": "4",
            "spark.rapids.sql.tpu.metrics.level": "DEBUG",
            "spark.rapids.sql.tpu.adaptive.enabled": str(adaptive).lower(),
        })
        return s, q(s).to_arrow()

    _s_off, t_off = run(False)
    s_on, t_on = run(True)
    assert t_on.equals(t_off)
    assert s_on.query_metrics_total.get("numJoinStrategyChanges", 0) == 1
    names = [e["name"] for e in s_on.last_execution.journal.events()
             if e["kind"] == "replan"]
    assert "promoteToBroadcast" in names


def test_demote_broadcast_join_when_static_estimate_forced_wrong():
    """Acceptance criterion: the static estimate is forced wrong via
    config — a self-join fan-out keeps the max(l, r) row estimate, so the
    threshold sits between estimated and observed size; the planner picks
    broadcast, adaptive demotes it, and the demotion is journaled."""
    def q(session):
        t1 = session.from_pydict(
            {"k": [i % 100 for i in range(1000)],
             "v": [float(i) for i in range(1000)]})
        fan = t1.join(t1.select(col("k"), col("v").alias("w")), on="k")
        probe = session.from_pydict(
            {"k": [i % 100 for i in range(2000)],
             "z": [float(i % 5) for i in range(2000)]})
        return (probe.join(fan, on="k")
                .group_by().agg(F.count(col("w")).alias("c")))

    def run(adaptive):
        s = TpuSession({
            "spark.sql.autoBroadcastJoinThreshold": "64k",
            "spark.rapids.sql.tpu.shuffle.partitions": "4",
            "spark.rapids.sql.tpu.metrics.level": "DEBUG",
            "spark.rapids.sql.tpu.adaptive.enabled": str(adaptive).lower(),
        })
        return s, q(s).to_arrow()

    s_off, t_off = run(False)
    s_on, t_on = run(True)
    assert t_on.equals(t_off)
    # the STATIC plan chose broadcast for the fan-out build on both runs
    assert "TpuBroadcastHashJoinExec" in \
        s_off.last_execution.explain_with_metrics()
    assert s_on.query_metrics_total.get("numJoinStrategyChanges", 0) >= 1
    events = [e for e in s_on.last_execution.journal.events()
              if e["kind"] == "replan"]
    demotes = [e for e in events if e["name"] == "demoteBroadcastJoin"]
    assert demotes, events
    assert demotes[0]["observed_bytes"] > demotes[0]["threshold"]
    # the final plan runs the partitioned replacement join
    assert "TpuShuffledHashJoinExec" in \
        s_on.last_execution.explain_with_metrics()


def test_demote_with_already_coalesced_probe_subtree():
    """Regression (code review): a demoted broadcast's replacement join
    re-walks its ALREADY-ADAPTED probe subtree.  When that subtree holds
    an exchange the first pass coalesced into a reader, the re-walk must
    not nest a second reader around it (which crashed at execution) nor
    double-count numCoalescedPartitions."""
    def q(session):
        t1 = session.from_pydict(
            {"k": [i % 100 for i in range(1000)],
             "v": [float(i) for i in range(1000)]})
        fan = t1.join(t1.select(col("k"), col("v").alias("w")), on="k")
        probe = session.from_pydict(
            {"k": [i % 100 for i in range(2000)],
             "z": [float(i % 5) for i in range(2000)]})
        # the repartition exchange under the probe side coalesces in the
        # first adaptive pass; the demotion re-walk must leave it alone
        return (probe.repartition(8, col("k"))
                .join(fan, on="k")
                .group_by().agg(F.count(col("w")).alias("c")))

    def run(adaptive):
        s = TpuSession({
            "spark.sql.autoBroadcastJoinThreshold": "64k",
            "spark.rapids.sql.tpu.shuffle.partitions": "4",
            "spark.rapids.sql.tpu.metrics.level": "DEBUG",
            "spark.rapids.sql.tpu.adaptive.enabled": str(adaptive).lower(),
        })
        return s, q(s).to_arrow()

    _s_off, t_off = run(False)
    s_on, t_on = run(True)
    assert t_on.equals(t_off)
    events = [e["name"] for e in s_on.last_execution.journal.events()
              if e["kind"] == "replan"]
    assert "demoteBroadcastJoin" in events
    # the probe's coalesce decision fired exactly once, not per walk
    text = s_on.last_execution.explain_with_metrics()
    assert "TpuCoalescedShuffleReaderExec[coalesced" in text


# --------------------------------------------------------------------------
# composition with OOM fault injection (utils/faults.py)
# --------------------------------------------------------------------------

def test_oom_injection_composes_with_skew_split():
    """Deterministic OOM at reserve sites of the skewed join must retry
    inside the skew-split read blocks and still produce identical
    results (the discover-then-replay pattern from tests/test_retry.py,
    sampled to keep the tier fast)."""
    faults.INJECTOR.reset()
    s0, baseline = _run_skewed(True)
    assert s0.query_metrics_total.get("numSkewSplits", 0) > 0
    n_ops = faults.INJECTOR.oom_ops
    assert n_ops > 5, dict(faults.INJECTOR.site_counts)
    # sample ordinals across the whole query (first, the fetch-heavy
    # middle, last) instead of all of them — each run re-executes the
    # full slice
    ordinals = sorted({1, n_ops // 3, n_ops // 2, 2 * n_ops // 3, n_ops})
    for ordinal in ordinals:
        faults.INJECTOR.reset()
        s, out = _run_skewed(True, {
            "spark.rapids.tpu.test.injectOom": str(ordinal)})
        assert faults.INJECTOR.injected_log, \
            f"ordinal {ordinal} never fired"
        assert out.equals(baseline), \
            f"ordinal {ordinal} changed the result"
        assert s.query_metrics_total.get("numSkewSplits", 0) > 0, \
            f"ordinal {ordinal} suppressed the skew split"
