"""TPU hash aggregate vs CPU oracle."""
import pytest

from spark_rapids_tpu import types as T
from spark_rapids_tpu.plan.logical import col, functions as f, lit

from compare import assert_tpu_and_cpu_are_equal, run_both, assert_rows_equal
from data_gen import gen_df

FLOAT_AGG = {"spark.rapids.sql.variableFloatAgg.enabled": "true"}


def _assert_on_tpu(build, conf=None):
    """The TPU side must actually plan the agg on device."""
    from spark_rapids_tpu.engine import TpuSession
    c = dict(conf or {})
    s = TpuSession(c)
    text = build(s).explain()
    assert "!HashAggregateExec" not in text, text


def test_groupby_sum_count_long():
    def q(s):
        df = gen_df(s, seed=20, n=800, k=T.IntegerType, v=T.LongType)
        return df.group_by("k").agg(f.sum(col("v")).alias("sv"),
                                    f.count(col("v")).alias("cv"),
                                    f.count(lit(1)).alias("cstar"))
    _assert_on_tpu(q)
    assert_tpu_and_cpu_are_equal(q)


def test_groupby_min_max():
    def q(s):
        df = gen_df(s, seed=21, n=600, k=T.IntegerType, v=T.IntegerType,
                    d=T.DoubleType)
        return df.group_by("k").agg(f.min(col("v")).alias("mnv"),
                                    f.max(col("v")).alias("mxv"),
                                    f.min(col("d")).alias("mnd"),
                                    f.max(col("d")).alias("mxd"))
    _assert_on_tpu(q)
    assert_tpu_and_cpu_are_equal(q)


def test_groupby_avg_float_conf_gated():
    def q(s):
        df = gen_df(s, seed=22, n=500, k=T.IntegerType, v=T.IntegerType)
        return df.group_by("k").agg(f.avg(col("v")).alias("av"),
                                    f.sum(col("v")).alias("sv"))
    _assert_on_tpu(q)
    assert_tpu_and_cpu_are_equal(q)


def test_float_agg_requires_conf():
    from spark_rapids_tpu.engine import TpuSession

    def q(s):
        df = gen_df(s, seed=23, n=100, k=T.IntegerType, v=T.DoubleType)
        return df.group_by("k").agg(f.sum(col("v")).alias("sv"))
    # without the conf, falls back (explain shows reason)
    text = q(TpuSession()).explain()
    assert "variableFloatAgg" in text
    # with the conf, runs on TPU and matches
    _assert_on_tpu(q, FLOAT_AGG)
    assert_tpu_and_cpu_are_equal(q, conf=FLOAT_AGG)


def test_groupby_string_keys():
    def q(s):
        df = gen_df(s, seed=24, n=600, k=T.StringType, v=T.LongType)
        return df.group_by("k").agg(f.sum(col("v")).alias("sv"),
                                    f.count(col("v")).alias("cv"))
    _assert_on_tpu(q)
    assert_tpu_and_cpu_are_equal(q)


def test_groupby_multi_keys_with_nulls_nans():
    def q(s):
        df = gen_df(s, seed=25, n=700, k1=T.IntegerType, k2=T.DoubleType,
                    v=T.LongType)
        return df.group_by("k1", "k2").agg(f.count(lit(1)).alias("c"),
                                           f.sum(col("v")).alias("sv"))
    _assert_on_tpu(q)
    assert_tpu_and_cpu_are_equal(q)


def test_groupby_first_last():
    # first/last depend on row order; use a key-sorted deterministic frame
    def q(s):
        df = s.from_pydict({"k": [1, 1, 2, 2, 2, 3],
                            "v": [10, None, 30, 40, None, 60]},
                           T.schema_of(k=T.IntegerType, v=T.IntegerType))
        return df.group_by("k").agg(f.first(col("v")).alias("fv"),
                                    f.last(col("v")).alias("lv"))
    _assert_on_tpu(q)
    assert_tpu_and_cpu_are_equal(q)


def test_global_agg():
    def q(s):
        df = gen_df(s, seed=26, n=500, v=T.LongType, d=T.DoubleType)
        return df.agg(f.sum(col("v")).alias("sv"),
                      f.count(col("v")).alias("cv"),
                      f.min(col("d")).alias("mnd"),
                      f.max(col("d")).alias("mxd"))
    _assert_on_tpu(q)
    assert_tpu_and_cpu_are_equal(q)


def test_global_agg_empty_input():
    def q(s):
        df = s.from_pydict({"v": []}, T.schema_of(v=T.LongType))
        return df.agg(f.sum(col("v")).alias("sv"),
                      f.count(col("v")).alias("cv"))
    cpu, tpu = run_both(q)
    assert tpu == [(None, 0)]
    assert_rows_equal(cpu, tpu)


def test_groupby_empty_input():
    def q(s):
        df = s.from_pydict({"k": [], "v": []},
                           T.schema_of(k=T.IntegerType, v=T.LongType))
        return df.group_by("k").agg(f.sum(col("v")).alias("sv"))
    cpu, tpu = run_both(q)
    assert cpu == tpu == []


def test_agg_over_multiple_batches():
    # force multiple scan batches so the merge path runs
    conf = {"spark.rapids.sql.reader.batchSizeRows": "100"}

    def q(s):
        df = gen_df(s, seed=27, n=950, k=T.IntegerType, v=T.LongType)
        return df.group_by("k").agg(f.sum(col("v")).alias("sv"),
                                    f.count(lit(1)).alias("c"))
    assert_tpu_and_cpu_are_equal(q, conf=conf)


def test_agg_expression_keys_and_values():
    def q(s):
        df = gen_df(s, seed=28, n=400, a=T.IntegerType, b=T.IntegerType)
        return df.group_by((col("a") % 10).alias("bucket")) \
            .agg(f.sum(col("a") + col("b")).alias("sab"),
                 f.max(col("b") * 2).alias("mb2"))
    _assert_on_tpu(q)
    assert_tpu_and_cpu_are_equal(q)


def test_distinct_agg_falls_back():
    from spark_rapids_tpu.engine import TpuSession

    def q(s):
        df = gen_df(s, seed=29, n=300, k=T.IntegerType, v=T.IntegerType)
        return df.group_by("k").agg(f.count_distinct(col("v")).alias("cd"))
    text = q(TpuSession()).explain()
    assert "distinct" in text


def test_min_with_inf_and_nan_group():
    def q(s):
        df = s.from_pydict(
            {"k": [1, 1, 2, 2, 3],
             "v": [float("inf"), float("nan"), float("nan"), float("nan"),
                   1.5]},
            T.schema_of(k=T.IntegerType, v=T.DoubleType))
        return df.group_by("k").agg(f.min(col("v")).alias("mn"),
                                    f.max(col("v")).alias("mx"))
    _assert_on_tpu(q)
    assert_tpu_and_cpu_are_equal(q)


def test_first_last_across_filtered_batches():
    conf = {"spark.rapids.sql.reader.batchSizeRows": "64"}

    def q(s):
        n = 300
        df = s.from_pydict({"k": [i % 3 for i in range(n)],
                            "v": list(range(n))},
                           T.schema_of(k=T.IntegerType, v=T.IntegerType))
        # filter leaves non-compacted batches; last() must still pick the
        # globally latest surviving row per key
        return df.filter(col("v") % 7 != 0) \
                 .group_by("k").agg(f.first(col("v")).alias("fv"),
                                    f.last(col("v")).alias("lv"))
    assert_tpu_and_cpu_are_equal(q, conf=conf)


def test_global_first_last_strings():
    def q(s):
        df = s.from_pydict({"s": ["aa", None, "cc"]},
                           T.schema_of(s=T.StringType))
        return df.agg(f.first(col("s")).alias("fs"),
                      f.last(col("s")).alias("ls"))
    _assert_on_tpu(q)
    assert_tpu_and_cpu_are_equal(q)


def test_agg_deferred_merge_fan_in_variants():
    """K-way deferred merge must equal the pairwise fold for associative
    and order-sensitive (First/Last) aggregates alike, at fan-ins that
    divide, straddle, and exceed the batch count."""
    for fan_in in ("2", "3", "8", "64"):
        conf = {"spark.rapids.sql.reader.batchSizeRows": "64",
                "spark.rapids.sql.tpu.agg.mergeFanIn": fan_in}

        def q(s):
            df = gen_df(s, seed=91, n=700, k=T.IntegerType, v=T.LongType)
            return df.group_by("k").agg(
                f.sum(col("v")).alias("sv"),
                f.min(col("v")).alias("mn"),
                f.max(col("v")).alias("mx"),
                f.count(lit(1)).alias("c"),
                f.first(col("v")).alias("fst"),
                f.last(col("v")).alias("lst"))
        assert_tpu_and_cpu_are_equal(q, conf=conf)
