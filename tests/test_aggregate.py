"""TPU hash aggregate vs CPU oracle."""
import pytest

from spark_rapids_tpu import types as T
from spark_rapids_tpu.plan.logical import col, functions as f, lit

from compare import assert_tpu_and_cpu_are_equal, run_both, assert_rows_equal
from data_gen import gen_df

FLOAT_AGG = {"spark.rapids.sql.variableFloatAgg.enabled": "true"}


def _assert_on_tpu(build, conf=None):
    """The TPU side must actually plan the agg on device."""
    from spark_rapids_tpu.engine import TpuSession
    c = dict(conf or {})
    s = TpuSession(c)
    text = build(s).explain()
    assert "!HashAggregateExec" not in text, text


def test_groupby_sum_count_long():
    def q(s):
        df = gen_df(s, seed=20, n=800, k=T.IntegerType, v=T.LongType)
        return df.group_by("k").agg(f.sum(col("v")).alias("sv"),
                                    f.count(col("v")).alias("cv"),
                                    f.count(lit(1)).alias("cstar"))
    _assert_on_tpu(q)
    assert_tpu_and_cpu_are_equal(q)


def test_groupby_min_max():
    def q(s):
        df = gen_df(s, seed=21, n=600, k=T.IntegerType, v=T.IntegerType,
                    d=T.DoubleType)
        return df.group_by("k").agg(f.min(col("v")).alias("mnv"),
                                    f.max(col("v")).alias("mxv"),
                                    f.min(col("d")).alias("mnd"),
                                    f.max(col("d")).alias("mxd"))
    _assert_on_tpu(q)
    assert_tpu_and_cpu_are_equal(q)


def test_groupby_avg_float_conf_gated():
    def q(s):
        df = gen_df(s, seed=22, n=500, k=T.IntegerType, v=T.IntegerType)
        return df.group_by("k").agg(f.avg(col("v")).alias("av"),
                                    f.sum(col("v")).alias("sv"))
    _assert_on_tpu(q)
    assert_tpu_and_cpu_are_equal(q)


def test_float_agg_requires_conf():
    from spark_rapids_tpu.engine import TpuSession

    def q(s):
        df = gen_df(s, seed=23, n=100, k=T.IntegerType, v=T.DoubleType)
        return df.group_by("k").agg(f.sum(col("v")).alias("sv"))
    # without the conf, falls back (explain shows reason)
    text = q(TpuSession()).explain()
    assert "variableFloatAgg" in text
    # with the conf, runs on TPU and matches
    _assert_on_tpu(q, FLOAT_AGG)
    assert_tpu_and_cpu_are_equal(q, conf=FLOAT_AGG)


def test_groupby_string_keys():
    def q(s):
        df = gen_df(s, seed=24, n=600, k=T.StringType, v=T.LongType)
        return df.group_by("k").agg(f.sum(col("v")).alias("sv"),
                                    f.count(col("v")).alias("cv"))
    _assert_on_tpu(q)
    assert_tpu_and_cpu_are_equal(q)


def test_groupby_multi_keys_with_nulls_nans():
    def q(s):
        df = gen_df(s, seed=25, n=700, k1=T.IntegerType, k2=T.DoubleType,
                    v=T.LongType)
        return df.group_by("k1", "k2").agg(f.count(lit(1)).alias("c"),
                                           f.sum(col("v")).alias("sv"))
    _assert_on_tpu(q)
    assert_tpu_and_cpu_are_equal(q)


def test_groupby_first_last():
    # first/last depend on row order; use a key-sorted deterministic frame
    def q(s):
        df = s.from_pydict({"k": [1, 1, 2, 2, 2, 3],
                            "v": [10, None, 30, 40, None, 60]},
                           T.schema_of(k=T.IntegerType, v=T.IntegerType))
        return df.group_by("k").agg(f.first(col("v")).alias("fv"),
                                    f.last(col("v")).alias("lv"))
    _assert_on_tpu(q)
    assert_tpu_and_cpu_are_equal(q)


def test_global_agg():
    def q(s):
        df = gen_df(s, seed=26, n=500, v=T.LongType, d=T.DoubleType)
        return df.agg(f.sum(col("v")).alias("sv"),
                      f.count(col("v")).alias("cv"),
                      f.min(col("d")).alias("mnd"),
                      f.max(col("d")).alias("mxd"))
    _assert_on_tpu(q)
    assert_tpu_and_cpu_are_equal(q)


def test_global_agg_empty_input():
    def q(s):
        df = s.from_pydict({"v": []}, T.schema_of(v=T.LongType))
        return df.agg(f.sum(col("v")).alias("sv"),
                      f.count(col("v")).alias("cv"))
    cpu, tpu = run_both(q)
    assert tpu == [(None, 0)]
    assert_rows_equal(cpu, tpu)


def test_groupby_empty_input():
    def q(s):
        df = s.from_pydict({"k": [], "v": []},
                           T.schema_of(k=T.IntegerType, v=T.LongType))
        return df.group_by("k").agg(f.sum(col("v")).alias("sv"))
    cpu, tpu = run_both(q)
    assert cpu == tpu == []


def test_agg_over_multiple_batches():
    # force multiple scan batches so the merge path runs
    conf = {"spark.rapids.sql.reader.batchSizeRows": "100"}

    def q(s):
        df = gen_df(s, seed=27, n=950, k=T.IntegerType, v=T.LongType)
        return df.group_by("k").agg(f.sum(col("v")).alias("sv"),
                                    f.count(lit(1)).alias("c"))
    assert_tpu_and_cpu_are_equal(q, conf=conf)


def test_agg_expression_keys_and_values():
    def q(s):
        df = gen_df(s, seed=28, n=400, a=T.IntegerType, b=T.IntegerType)
        return df.group_by((col("a") % 10).alias("bucket")) \
            .agg(f.sum(col("a") + col("b")).alias("sab"),
                 f.max(col("b") * 2).alias("mb2"))
    _assert_on_tpu(q)
    assert_tpu_and_cpu_are_equal(q)


def test_single_distinct_agg_on_device():
    """One distinct child dedups inside the update kernel (sorted
    (group, value) adjacency; exec/aggregate.py _distinct_child)."""
    def q(s):
        df = gen_df(s, seed=29, n=300, k=T.IntegerType, v=T.IntegerType)
        return df.group_by("k").agg(
            f.count_distinct(col("v")).alias("cd"),
            f.sum(col("v")).alias("sv"),        # mixed: non-distinct too
            f.count(col("v")).alias("c"))
    _assert_on_tpu(q)
    assert_tpu_and_cpu_are_equal(q)


def test_distinct_agg_strings_and_sum_distinct():
    def q(s):
        df = gen_df(s, seed=30, n=300, k=T.IntegerType, s_=T.StringType)
        return df.group_by("k").agg(
            f.count_distinct(col("s_")).alias("cd"))
    _assert_on_tpu(q)
    assert_tpu_and_cpu_are_equal(q)

    def q2(s):
        df = gen_df(s, seed=31, n=300, k=T.IntegerType, v=T.LongType)
        return df.group_by("k").agg(
            f._agg("Sum", col("v"), distinct=True).alias("sd"))
    _assert_on_tpu(q2)
    assert_tpu_and_cpu_are_equal(q2)


def test_multi_distinct_agg_falls_back():
    """Two DIFFERENT distinct children cannot share one sorted dedup pass;
    falls back like the reference (GpuHashAggregateMeta.tagPlanForGpu)."""
    from spark_rapids_tpu.engine import TpuSession

    def q(s):
        df = gen_df(s, seed=32, n=300, k=T.IntegerType, v=T.IntegerType,
                    w=T.IntegerType)
        return df.group_by("k").agg(
            f.count_distinct(col("v")).alias("cv"),
            f.count_distinct(col("w")).alias("cw"))
    text = q(TpuSession()).explain()
    assert "multiple distinct" in text
    assert_tpu_and_cpu_are_equal(q)


def test_min_with_inf_and_nan_group():
    def q(s):
        df = s.from_pydict(
            {"k": [1, 1, 2, 2, 3],
             "v": [float("inf"), float("nan"), float("nan"), float("nan"),
                   1.5]},
            T.schema_of(k=T.IntegerType, v=T.DoubleType))
        return df.group_by("k").agg(f.min(col("v")).alias("mn"),
                                    f.max(col("v")).alias("mx"))
    _assert_on_tpu(q)
    assert_tpu_and_cpu_are_equal(q)


def test_first_last_across_filtered_batches():
    conf = {"spark.rapids.sql.reader.batchSizeRows": "64"}

    def q(s):
        n = 300
        df = s.from_pydict({"k": [i % 3 for i in range(n)],
                            "v": list(range(n))},
                           T.schema_of(k=T.IntegerType, v=T.IntegerType))
        # filter leaves non-compacted batches; last() must still pick the
        # globally latest surviving row per key
        return df.filter(col("v") % 7 != 0) \
                 .group_by("k").agg(f.first(col("v")).alias("fv"),
                                    f.last(col("v")).alias("lv"))
    assert_tpu_and_cpu_are_equal(q, conf=conf)


def test_global_first_last_strings():
    def q(s):
        df = s.from_pydict({"s": ["aa", None, "cc"]},
                           T.schema_of(s=T.StringType))
        return df.agg(f.first(col("s")).alias("fs"),
                      f.last(col("s")).alias("ls"))
    _assert_on_tpu(q)
    assert_tpu_and_cpu_are_equal(q)


def test_agg_deferred_merge_fan_in_variants():
    """K-way deferred merge must equal the pairwise fold for associative
    and order-sensitive (First/Last) aggregates alike, at fan-ins that
    divide, straddle, and exceed the batch count."""
    for fan_in in ("2", "3", "8", "64"):
        conf = {"spark.rapids.sql.reader.batchSizeRows": "64",
                "spark.rapids.sql.tpu.agg.mergeFanIn": fan_in}

        def q(s):
            df = gen_df(s, seed=91, n=700, k=T.IntegerType, v=T.LongType)
            return df.group_by("k").agg(
                f.sum(col("v")).alias("sv"),
                f.min(col("v")).alias("mn"),
                f.max(col("v")).alias("mx"),
                f.count(lit(1)).alias("c"),
                f.first(col("v")).alias("fst"),
                f.last(col("v")).alias("lst"))
        assert_tpu_and_cpu_are_equal(q, conf=conf)


def test_whole_stage_single_dispatch_agg():
    """Whole-stage path: multi-batch scan -> fused filter/project ->
    aggregate matches the streaming loop (conf off) exactly."""
    conf_on = {"spark.rapids.sql.reader.batchSizeRows": "128"}
    conf_off = {**conf_on, "spark.rapids.sql.tpu.wholeStage.enabled":
                "false"}

    def q(s):
        df = gen_df(s, seed=71, n=1000, k=T.IntegerType, v=T.LongType)
        return (df.filter(col("v") % 2 == 0)
                .select(col("k"), (col("v") * 3).alias("w"))
                .group_by("k").agg(f.sum(col("w")).alias("s"),
                                   f.count(lit(1)).alias("c"),
                                   f.max(col("w")).alias("mx")))
    a = assert_tpu_and_cpu_are_equal(q, conf=conf_on)
    b = assert_tpu_and_cpu_are_equal(q, conf=conf_off)
    assert sorted(a, key=repr) == sorted(b, key=repr)


def test_whole_stage_global_agg():
    conf = {"spark.rapids.sql.reader.batchSizeRows": "64"}

    def q(s):
        df = gen_df(s, seed=72, n=700, v=T.LongType)
        return df.agg(f.sum(col("v")).alias("s"),
                      f.min(col("v")).alias("mn"))
    assert_tpu_and_cpu_are_equal(q, conf=conf)


def test_whole_stage_unequal_batches_fall_back():
    """A trailing short batch (different capacity bucket) must take the
    streaming path and still be correct."""
    conf = {"spark.rapids.sql.reader.batchSizeRows": "600"}

    def q(s):
        # 1000 rows -> batches of 600 (cap 1024) and 400 (cap 512)
        df = gen_df(s, seed=73, n=1000, k=T.IntegerType, v=T.LongType)
        return df.group_by("k").agg(f.count(lit(1)).alias("c"))
    assert_tpu_and_cpu_are_equal(q, conf=conf)


def test_whole_stage_monotonic_id_correct():
    """Row-offset expressions must NOT take the whole-stage path (vmapped
    offset-0 would repeat per-batch id streams; review regression)."""
    conf = {"spark.rapids.sql.reader.batchSizeRows": "128"}

    def q(s):
        df = gen_df(s, seed=74, n=256, v=T.LongType)
        return (df.select(f.monotonically_increasing_id().alias("id"))
                .agg(f.max(col("id")).alias("mx"),
                     f.count(col("id")).alias("c")))
    rows = assert_tpu_and_cpu_are_equal(q, conf=conf)
    assert rows[0] == (255, 256), rows


def test_whole_stage_mixed_string_widths_fall_back():
    """Equal capacities but different string width buckets must stream,
    not crash at jnp.stack (review regression)."""
    import pyarrow as pa
    from spark_rapids_tpu.engine import TpuSession
    conf = {"spark.rapids.sql.reader.batchSizeRows": "128"}

    def q(s):
        t = pa.table({"s": ["ab"] * 128 + ["x" * 40] * 128,
                      "v": list(range(256))})
        return (s.from_arrow(t).group_by("s")
                .agg(f.sum(col("v")).alias("sv")))
    assert_tpu_and_cpu_are_equal(q, conf=conf)


def test_whole_stage_fallback_does_not_rescan():
    """When the probe bails (unequal caps) the scan must not re-execute
    (review: double I/O)."""
    from spark_rapids_tpu.engine import TpuSession
    from spark_rapids_tpu.exec.base import ExecContext
    s = TpuSession({"spark.rapids.sql.reader.batchSizeRows": "600"})
    df = gen_df(s, seed=75, n=1000, k=T.IntegerType, v=T.LongType)
    q = df.group_by("k").agg(f.count(lit(1)).alias("c"))
    node = s.plan(q.plan)

    def find_scan(n):
        if type(n).__name__ == "TpuScanMemoryExec":
            return n
        for c in n.children:
            r = find_scan(c)
            if r:
                return r
    scan = find_scan(node)
    list(node.execute(ExecContext(s.conf, runtime=s.runtime)))
    # 1000 rows in 600-row batches = 2 scan output batches, counted ONCE
    assert scan.metrics.values.get("numOutputBatches") == 2, \
        scan.metrics.values


def test_rollup_grouping_sets():
    """ROLLUP = Expand fan-out + one aggregate; a data-null key must stay a
    separate output row from the rolled-up subtotal row (grouping-id
    distinguishes them, like Spark's grouping_id)."""
    def q(s):
        df = s.from_pydict(
            {"ch": ["a", "a", "b", "b", None],
             "id": ["x", "y", "x", "x", "z"],
             "v": [1.0, 2.0, 3.0, 4.0, 5.0]},
            T.schema_of(ch=T.StringType, id=T.StringType, v=T.DoubleType))
        return (df.rollup(col("ch"), col("id"))
                .agg(f.sum(col("v")).alias("sv"),
                     f.count(col("v")).alias("c")))
    _assert_on_tpu(q, FLOAT_AGG)
    rows = assert_tpu_and_cpu_are_equal(q, conf=FLOAT_AGG)
    # 4 leaf groups + 3 channel subtotals (a, b, None) + grand total
    assert len(rows) == 8
    assert (None, None, 15.0, 5) in rows       # grand total
    assert (None, None, 5.0, 1) in rows        # ch=None data group


def test_rollup_compound_agg():
    def q(s):
        df = gen_df(s, seed=33, n=200, k=T.IntegerType, g=T.IntegerType,
                    v=T.LongType)
        return df.rollup(col("k"), col("g")).agg(
            (f.sum(col("v")) / f.count(col("v"))).alias("m"))
    assert_tpu_and_cpu_are_equal(q)


def test_rollup_aggregate_over_key_column():
    """Aggregates over a grouping-key column must see REAL values in
    subtotal rows (Expand nulls only the grouping copies, not the
    originals — Spark semantics)."""
    def q(s):
        df = s.from_pydict(
            {"k": [1, 1, 2, 2], "v": [10, 20, 30, 40]},
            T.schema_of(k=T.IntegerType, v=T.LongType))
        return df.rollup(col("k")).agg(f.sum(col("k")).alias("sk"),
                                       f.sum(col("v")).alias("sv"))
    rows = assert_tpu_and_cpu_are_equal(q)
    assert (None, 6, 100) in rows  # grand total: sum(k)=6, not NULL


def test_cube_grouping_sets():
    """CUBE = every subset of the keys; 2^n grouping sets through the same
    Expand + grouping-id plan as rollup."""
    def q(s):
        df = s.from_pydict(
            {"a": [1, 1, 2, 2], "b": ["x", "y", "x", "y"],
             "v": [10, 20, 30, 40]},
            T.schema_of(a=T.IntegerType, b=T.StringType, v=T.LongType))
        return df.cube(col("a"), col("b")).agg(f.sum(col("v")).alias("sv"))
    rows = assert_tpu_and_cpu_are_equal(q)
    # 4 leaf + 2 a-subtotals + 2 b-subtotals + grand = 9
    assert len(rows) == 9
    assert (None, "x", 40) in rows   # b-only set: a rolled up
    assert (1, None, 30) in rows     # a-only set
    assert (None, None, 100) in rows
