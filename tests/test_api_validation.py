"""API validation (reference: api_validation/.../ApiValidation.scala:16-40 —
reflection over CPU exec signatures vs Gpu exec signatures to catch drift).

Here: every logical node must be convertible, every Tpu exec must have a CPU
counterpart with a compatible constructor, and the conf registry must stay
documented.
"""
import inspect

import spark_rapids_tpu.plan.logical as L
from spark_rapids_tpu import config as C


def _logical_nodes():
    return [cls for name, cls in vars(L).items()
            if isinstance(cls, type) and issubclass(cls, L.LogicalPlan)
            and cls is not L.LogicalPlan]


def test_every_logical_node_has_display_name():
    from spark_rapids_tpu.plan.overrides import _DISPLAY_NAMES
    missing = [cls.__name__ for cls in _logical_nodes()
               if cls not in _DISPLAY_NAMES]
    assert not missing, f"logical nodes without display names: {missing}"


def test_tpu_cpu_exec_pairs_signature_compatible():
    """Each Tpu*Exec/Cpu*Exec pair must accept the same leading
    constructor parameters (the ApiValidation check, adapted)."""
    pairs = [
        ("spark_rapids_tpu.exec.basic", "TpuProjectExec", "CpuProjectExec"),
        ("spark_rapids_tpu.exec.basic", "TpuFilterExec", "CpuFilterExec"),
        ("spark_rapids_tpu.exec.basic", "TpuUnionExec", "CpuUnionExec"),
        ("spark_rapids_tpu.exec.basic", "TpuExpandExec", "CpuExpandExec"),
        ("spark_rapids_tpu.exec.generate", "TpuGenerateExec",
         "CpuGenerateExec"),
        ("spark_rapids_tpu.exec.broadcast", "TpuBroadcastExchangeExec",
         "CpuBroadcastExchangeExec"),
    ]
    import importlib
    for mod_name, tpu_name, cpu_name in pairs:
        mod = importlib.import_module(mod_name)
        tpu = getattr(mod, tpu_name)
        cpu = getattr(mod, cpu_name)
        tsig = list(inspect.signature(tpu.__init__).parameters)
        csig = list(inspect.signature(cpu.__init__).parameters)
        assert tsig == csig, (
            f"{tpu_name}{tsig} != {cpu_name}{csig}: the planner swaps these "
            "based on tagging; their constructors must stay in sync")


def test_execs_declare_schema():
    """Every exec class must implement the schema property."""
    import importlib
    from spark_rapids_tpu.exec.base import ExecNode
    mods = ["basic", "aggregate", "join", "sort", "window", "generate",
            "broadcast", "exchange", "cpu_relational"]
    missing = []
    for m in mods:
        mod = importlib.import_module(f"spark_rapids_tpu.exec.{m}")
        for name, cls in vars(mod).items():
            if (isinstance(cls, type) and issubclass(cls, ExecNode)
                    and cls.__module__ == mod.__name__
                    and not name.startswith("_")
                    and name.endswith("Exec")  # skip abstract intermediates
                    and "schema" not in vars(cls)
                    and not any("schema" in vars(b) for b in cls.__mro__
                                if b is not ExecNode)):
                if name in ("RowLocalExec",):
                    continue
                missing.append(f"{m}.{name}")
    assert not missing, f"execs without schema: {missing}"


def test_all_confs_documented():
    for e in C.registered_entries():
        assert e.doc and len(e.doc) > 10, f"{e.key} lacks documentation"
        assert e.key.startswith("spark."), e.key


def test_conf_doc_generation_contains_all_public_keys():
    doc = C.help_doc()
    for e in C.registered_entries():
        if not e.internal:
            assert e.key in doc, f"{e.key} missing from generated docs"


def test_discovery_resource_information():
    """Discovery plugin analogue emits Spark's ResourceInformation shape
    (reference: ExclusiveModeGpuDiscoveryPlugin)."""
    from spark_rapids_tpu.discovery import resource_information
    info = resource_information("cpu")
    assert info["name"] == "tpu"
    assert len(info["addresses"]) == 8  # virtual mesh in the test env
    assert all(isinstance(a, str) for a in info["addresses"])
